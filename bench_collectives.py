"""Collective-scheduler A/B benchmark (ISSUE 12 acceptance): the SAME
training workloads — a bucketed data-parallel MLN and a ZeRO-sharded MLN
on the simulated 8-device mesh — driven through

  legacy     — the pre-scheduler primitives (inline copies of the old
               ``bucketed_psum`` / ``bucketed_psum_scatter`` /
               ``bucketed_all_gather`` loops, monkeypatched in), and
  scheduler  — the unified ``comms.scheduler`` route (plan-keyed AOT
               executables, densified buckets, probe-gated gather).

Per mode and workload it records: per-shard bytes moved and collective
launches (the ``dl4j_collective_*`` counters), bucket counts, host
dispatches, wall time per step, AOT-cache misses, and the scheduler's
plan-cache hits. Writes ``bench_collectives.json``; the committed A/B
record is ``BENCH_collectives_r01.json``. ``--smoke`` asserts the
scheduler route regresses NEITHER collective launches NOR bytes vs
legacy (the CPU proxy can't show the overlap win — XLA CPU runs
collectives sequentially — so the bar is "same schedule, no regression,
plans observable").

CPU-pinned like every bench that must not contend for the axon tunnel.
"""

import argparse
import json
import os
import time


def _pin_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def _legacy_primitives():
    """Inline copies of the pre-scheduler exchange loops (the PR-9/PR-2
    implementations) — the baseline the scheduler must not regress."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.comms.scheduler import bucket_partition

    def legacy_psum(tree, axis_name, bucket_bytes=None):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree
        if bucket_bytes is None or len(leaves) <= 1:
            return jax.tree_util.tree_unflatten(
                treedef, list(jax.lax.psum(tuple(leaves), axis_name)))
        sizes = [l.size * l.dtype.itemsize for l in leaves]
        out = [None] * len(leaves)
        pin = None
        for bucket in bucket_partition(sizes, int(bucket_bytes)):
            vals = tuple(leaves[i] for i in bucket)
            if pin is not None:
                pinned = jax.lax.optimization_barrier(vals + (pin,))
                vals = tuple(pinned[:-1])
            red = jax.lax.psum(vals, axis_name)
            pin = red[0]
            for i, r in zip(bucket, red):
                out[i] = r
        return jax.tree_util.tree_unflatten(treedef, out)

    def legacy_psum_scatter(tree, axis_name, bucket_bytes=None):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree

        def scatter(vals):
            return jax.lax.psum_scatter(vals, axis_name,
                                        scatter_dimension=0, tiled=True)

        if bucket_bytes is None or len(leaves) <= 1:
            return jax.tree_util.tree_unflatten(
                treedef, list(scatter(tuple(leaves))))
        sizes = [l.size * l.dtype.itemsize for l in leaves]
        out = [None] * len(leaves)
        pin = None
        for bucket in bucket_partition(sizes, int(bucket_bytes)):
            vals = tuple(leaves[i] for i in bucket)
            if pin is not None:
                pinned = jax.lax.optimization_barrier(vals + (pin,))
                vals = tuple(pinned[:-1])
            red = scatter(vals)
            pin = red[0]
            for i, r in zip(bucket, red):
                out[i] = r
        return jax.tree_util.tree_unflatten(treedef, out)

    def legacy_all_gather(tree, axis_name, index, full_sizes,
                          bucket_bytes=None):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree
        contribs = []
        for sl, full in zip(leaves, full_sizes):
            m = sl.shape[0]
            contribs.append(jax.lax.dynamic_update_slice(
                jnp.zeros((int(full),), sl.dtype), sl, (index * m,)))
        return legacy_psum(
            jax.tree_util.tree_unflatten(treedef, contribs),
            axis_name, bucket_bytes)

    return legacy_psum, legacy_psum_scatter, legacy_all_gather


def _net(seed=12345):
    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(learning_rate=0.01))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=256, activation=Activation.RELU))
            .layer(DenseLayer(n_out=256, activation=Activation.RELU))
            .layer(DenseLayer(n_out=128, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(64))
            .build())
    return MultiLayerNetwork(conf).init()


def _counters():
    from deeplearning4j_tpu import telemetry

    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    bytes_total = sum(v for k, v in snap.items()
                      if k.startswith("dl4j_collective_bytes_total")
                      and not isinstance(v, dict))
    ops_total = sum(v for k, v in snap.items()
                    if k.startswith("dl4j_collective_ops_total")
                    and not isinstance(v, dict))
    return bytes_total, ops_total


def _run_workload(mode, workload, steps, batch):
    """One (mode, workload) leg: fresh net + wrapper, warm step, timed
    steps, counter deltas."""
    import numpy as np

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.comms import scheduler
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    aot_cache.clear()
    telemetry.reset()
    telemetry.enable(sync=True)
    kw = ({"gradient_bucket_mb": 0.05} if workload == "dp_bucketed"
          else {"zero_optimizer": True, "gradient_bucket_mb": 0.05})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(steps * batch, 64)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, size=steps * batch)]
    net = _net()
    pw = ParallelWrapper(net, workers=8, prefetch_buffer=0, **kw)
    it = ArrayDataSetIterator(x[:batch], y[:batch], batch=batch)
    pw.fit(it, epochs=1)                       # warm: compile + stage
    plan_stats0 = scheduler.stats()
    b0, o0 = _counters()
    misses0 = aot_cache.stats()["misses"]
    it = ArrayDataSetIterator(x, y, batch=batch)
    t0 = time.perf_counter()
    pw.fit(it, epochs=1)
    wall = time.perf_counter() - t0
    b1, o1 = _counters()
    plan_stats1 = scheduler.stats()
    telemetry.disable()
    buckets = {
        k.split('op="')[1].rstrip('"}'): v
        for k, v in telemetry.REGISTRY.snapshot(
            run_collectors=False).items()
        if k.startswith("dl4j_collective_buckets")}
    return {
        "mode": mode,
        "workload": workload,
        "steps": steps,
        "dispatches": steps,
        "collective_bytes": b1 - b0,
        "collective_launches": o1 - o0,
        "buckets_per_exchange": buckets,
        "wall_s_per_step": round(wall / steps, 6),
        "recompiles_after_warmup": aot_cache.stats()["misses"] - misses0,
        "plan_cache_hits": (plan_stats1["plan_cache_hits"]
                            - plan_stats0["plan_cache_hits"]),
        "plans_built": plan_stats1["plans_built"],
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--out", default="bench_collectives.json")
    ap.add_argument("--smoke", action="store_true",
                    help="assert scheduler regresses neither launches "
                         "nor bytes vs legacy")
    args = ap.parse_args()
    _pin_cpu()

    from unittest import mock

    from deeplearning4j_tpu.parallel import compression, wrapper

    legacy_psum, legacy_scatter, legacy_gather = _legacy_primitives()
    rows = []
    for workload in ("dp_bucketed", "zero"):
        for mode in ("legacy", "scheduler"):
            patches = []
            if mode == "legacy":
                patches = [
                    mock.patch.object(wrapper, "bucketed_psum",
                                      legacy_psum),
                    mock.patch.object(wrapper, "bucketed_psum_scatter",
                                      legacy_scatter),
                    mock.patch.object(compression, "bucketed_psum",
                                      legacy_psum),
                    mock.patch.object(compression, "bucketed_all_gather",
                                      legacy_gather),
                ]
            for p in patches:
                p.start()
            try:
                rows.append(_run_workload(mode, workload, args.steps,
                                          args.batch))
            finally:
                for p in patches:
                    p.stop()
            print(json.dumps(rows[-1], indent=2))

    by = {(r["workload"], r["mode"]): r for r in rows}
    summary = {}
    for workload in ("dp_bucketed", "zero"):
        leg, sch = by[(workload, "legacy")], by[(workload, "scheduler")]
        summary[workload] = {
            "launches_legacy": leg["collective_launches"],
            "launches_scheduler": sch["collective_launches"],
            "bytes_legacy": leg["collective_bytes"],
            "bytes_scheduler": sch["collective_bytes"],
            "step_wall_ratio_sched_over_legacy": round(
                sch["wall_s_per_step"] / max(leg["wall_s_per_step"],
                                             1e-9), 3),
        }
    out = {"rows": rows, "summary": summary,
           "note": ("CPU proxy: XLA CPU serializes collectives, so the "
                    "overlap/densify win does not show in wall time "
                    "here; the bar is schedule parity — launches and "
                    "bytes no worse than the legacy primitives, zero "
                    "recompiles after warmup, plans observable.")}
    print(json.dumps(summary, indent=2))
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {args.out}")

    if args.smoke:
        for workload, s in summary.items():
            assert s["launches_scheduler"] <= s["launches_legacy"], \
                f"{workload}: scheduler issues more collectives"
            assert s["bytes_scheduler"] <= s["bytes_legacy"], \
                f"{workload}: scheduler moves more bytes"
        for r in rows:
            assert r["recompiles_after_warmup"] == 0, \
                f"{r['mode']}/{r['workload']}: recompiled after warmup"
        print("SMOKE OK: no regression in launches or bytes; "
              "zero recompiles after warmup")


if __name__ == "__main__":
    main()
