"""Streaming-ingest experiment (round-2 verdict item #7): characterize
the axon tunnel's post-big-program h2d collapse and test mitigations —
chunked staging sizes and double-buffered transfer-during-compute. The
results table lives in BASELINE.md; re-run this script to regenerate.

Phases:
1. h2d bandwidth BEFORE any big program: one 38 MB uint8 batch,
   then a chunk-size sweep (1/4/38 MB pieces).
2. Compile + run the ResNet-50 batch-256 bf16 train step (the "big
   program" that triggers the collapse).
3. h2d bandwidth AFTER: same sweep.
4. Fresh-batch training three ways: sequential (device_put then step),
   chunked staging, and double-buffered (a host thread device_puts
   batch k+1 while step k computes).
"""

import dataclasses
import json
import threading
import time

import numpy as np

BATCH = 256
IMG = 224
CLASSES = 1000
STEPS = 6


def _bw(ms, nbytes):
    return nbytes / 1e6 / (ms / 1e3)


def put_ms(arr, chunks=1):
    """Time device_put of arr (split into `chunks` row-chunks), synced."""
    import jax

    t0 = time.perf_counter()
    if chunks == 1:
        out = jax.device_put(arr)
        out.block_until_ready()
        np.asarray(out[0, 0, 0])  # value-force (tunnel: BUR lies)
    else:
        pieces = np.array_split(arr, chunks, axis=0)
        outs = [jax.device_put(p) for p in pieces]
        for o in outs:
            o.block_until_ready()
        np.asarray(outs[-1][0, 0, 0])
    return (time.perf_counter() - t0) * 1000.0


def sweep(rng, label, results):
    x = rng.integers(0, 256, (BATCH, IMG, IMG, 3), dtype=np.uint8)
    nb = x.nbytes
    for chunks in (1, 4, 16, 64):
        ms = min(put_ms(rng.integers(0, 256, x.shape, dtype=np.uint8),
                        chunks) for _ in range(2))
        results[f"{label}_h2d_{chunks}chunks_MBps"] = round(_bw(ms, nb), 1)


def main():
    import jax

    rng = np.random.default_rng(0)
    results = {}

    sweep(rng, "pre", results)

    # the big program
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    cfg = ResNet50(num_classes=CLASSES, height=IMG, width=IMG,
                   updater=Adam(learning_rate=1e-3)).conf()
    cfg = dataclasses.replace(cfg, compute_dtype="bfloat16")
    net = ComputationGraph(cfg).init()

    def fresh_ds():
        return DataSet(
            rng.integers(0, 256, (BATCH, IMG, IMG, 3), dtype=np.uint8),
            np.eye(CLASSES, dtype=np.float32)[
                rng.integers(0, CLASSES, BATCH)])

    warm = fresh_ds()
    for _ in range(3):
        net.fit_batch(warm)

    sweep(rng, "post", results)

    # ---- fresh-batch training, three ways ----
    def run_steps(feed):
        t0 = time.perf_counter()
        for i in range(STEPS):
            feed(i)
        _ = float(net.score_value)  # sync tail
        dt = time.perf_counter() - t0
        return STEPS * BATCH / dt

    batches = [fresh_ds() for _ in range(STEPS + 1)]

    results["fresh_seq_img_per_s"] = round(run_steps(
        lambda i: net.fit_batch(batches[i])), 1)

    # chunked staging: device_put in 16 pieces, concat on device, fit
    import jax.numpy as jnp

    def chunked(i):
        ds = batches[i]
        pieces = [jax.device_put(p)
                  for p in np.array_split(ds.features, 16, axis=0)]
        ds.features = jnp.concatenate(pieces, axis=0)
        net.fit_batch(ds)

    batches = [fresh_ds() for _ in range(STEPS + 1)]
    results["fresh_chunked_img_per_s"] = round(run_steps(chunked), 1)

    # double-buffered: a host thread device_puts batch k+1 during step k
    batches = [fresh_ds() for _ in range(STEPS + 1)]
    staged = {0: jax.device_put(batches[0].features)}
    lock = threading.Lock()

    def stage(i):
        dev = jax.device_put(batches[i].features)
        with lock:
            staged[i] = dev

    def double_buffered(i):
        t = threading.Thread(target=stage, args=(i + 1,))
        t.start()
        with lock:
            f = staged.pop(i, None)
        if f is None:
            t.join()
            with lock:
                f = staged.pop(i, None)
        ds = batches[i]
        if f is not None:
            ds.features = f
        net.fit_batch(ds)
        t.join()

    results["fresh_double_buffered_img_per_s"] = round(
        run_steps(double_buffered), 1)

    # cached reference (the bench.py regime)
    cached = batches[0]
    for _ in range(2):
        net.fit_batch(cached)  # write-back caches device arrays
    results["cached_img_per_s"] = round(run_steps(
        lambda i: net.fit_batch(cached)), 1)

    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
