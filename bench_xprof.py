"""Capture and summarize an XProf trace of the production ResNet-50 train
step (round-4: ``jax.profiler.start_trace`` WORKS through the axon tunnel —
this script regenerates BASELINE.md's per-op breakdown table).

Usage: ``python bench_xprof.py [outdir]`` on-chip. Prints per-category
device-time aggregates from the Chrome trace the profiler writes.
"""

import collections
import dataclasses
import glob
import gzip
import json
import re
import sys

import numpy as np

STEPS = 3


def main():
    import jax

    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/xprof_trace"
    model = ResNet50(num_classes=1000, height=224, width=224,
                     updater=Adam(learning_rate=1e-3))
    model.stem_space_to_depth = True
    cfg = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
    net = ComputationGraph(cfg).init()
    rng = np.random.default_rng(42)
    ds = DataSet(
        rng.integers(0, 256, (256, 224, 224, 3), dtype=np.uint8),
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, 256)])
    for _ in range(3):
        net.fit_batch(ds)

    jax.profiler.start_trace(outdir)
    for _ in range(STEPS):
        net._fit_batch_async(ds)
    _ = float(net.score_value)
    jax.profiler.stop_trace()

    traces = sorted(glob.glob(outdir + "/plugins/profile/*/*.trace.json.gz"))
    with gzip.open(traces[-1]) as f:
        tr = json.load(f)
    ev = tr["traceEvents"]
    device_pids = {e["pid"] for e in ev
                   if e.get("ph") == "M" and e.get("name") == "process_name"
                   and "TPU" in str(e["args"].get("name"))}
    agg = collections.defaultdict(float)
    cnt = collections.Counter()
    step_ms = 0.0
    for e in ev:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        name = e["name"]
        if name.startswith("jit_"):
            step_ms += e.get("dur", 0) / 1000.0
            continue
        if re.fullmatch(r"\d+", name):
            continue
        cat = re.sub(r"[.\d]+$", "", name)
        agg[cat] += e.get("dur", 0) / 1000.0
        cnt[cat] += 1
    print(f"step wall on device: {step_ms / STEPS:.2f} ms "
          f"(x{STEPS} steps traced)")
    for k in sorted(agg, key=lambda k: -agg[k])[:15]:
        print(f"{agg[k] / STEPS:8.2f} ms/step  x{cnt[k] // STEPS:5d}  {k}")


if __name__ == "__main__":
    main()
