"""Gradient-check harness — the central correctness oracle.

Reference: ``org.deeplearning4j.gradientcheck.GradientCheckUtil`` (the
backbone of the reference's test strategy, SURVEY.md §4): central-difference
numerical gradients vs backprop in double precision, exact per-parameter
comparison with relative-error thresholds.

Here the analytic side is ``jax.grad`` through the whole jitted loss; the
harness runs in f64 on CPU (``jax.enable_x64``), mirroring the reference's
double-precision-only protocol; TPU runs the same models in f32/bf16 with
tolerance tiers.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from deeplearning4j_tpu.util import params as params_util


@dataclasses.dataclass
class GradCheckResult:
    n_params: int
    n_checked: int
    n_failed: int
    max_rel_error: float
    mean_rel_error: float
    failures: list  # (flat_index, analytic, numeric, rel_error)

    @property
    def passed(self) -> bool:
        return self.n_failed == 0


def _central_diff_check(f_jit, flat0: np.ndarray, analytic: np.ndarray,
                        idx: np.ndarray, reshape, epsilon: float,
                        max_rel_error: float,
                        abs_error_threshold: float) -> GradCheckResult:
    """Shared perturb/eval/compare loop. ``reshape`` maps a flat vector back
    to the shape ``f_jit`` expects; rel_err = |a-n| / (|a|+|n|) (reference
    GradientCheckUtil convention)."""
    import jax.numpy as jnp

    failures, rel_errors = [], []
    for i in idx:
        e = np.zeros_like(flat0)
        e[i] = epsilon
        up = float(f_jit(jnp.asarray(reshape(flat0 + e))))
        dn = float(f_jit(jnp.asarray(reshape(flat0 - e))))
        numeric = (up - dn) / (2.0 * epsilon)
        a = float(analytic[i])
        denom = abs(a) + abs(numeric)
        rel = abs(a - numeric) / denom if denom > 0 else 0.0
        rel_errors.append(rel)
        if rel > max_rel_error and abs(a - numeric) > abs_error_threshold:
            failures.append((int(i), a, numeric, rel))
    return GradCheckResult(
        n_params=int(flat0.size),
        n_checked=len(idx),
        n_failed=len(failures),
        max_rel_error=float(np.max(rel_errors)) if rel_errors else 0.0,
        mean_rel_error=float(np.mean(rel_errors)) if rel_errors else 0.0,
        failures=failures[:20],
    )


def _check_net_params_gradient(conf64, net, loss_args, epsilon,
                               max_rel_error, abs_error_threshold, n_samples,
                               seed) -> GradCheckResult:
    """Shared scaffolding for the MultiLayerNetwork / ComputationGraph
    checks: flatten params, jit loss-of-flat-vector, analytic ``jax.grad``,
    optional parameter subsampling, central-difference compare."""
    import jax
    import jax.numpy as jnp

    like = net.params

    def loss_from_flat(flat):
        p = params_util.unflatten_params(conf64, flat, like)
        loss, _ = net._loss(p, net.state, *loss_args, rng=None, train=True)
        return loss

    flat0 = np.asarray(params_util.flatten_params(conf64, net.params))
    loss_jit = jax.jit(loss_from_flat)
    analytic = np.asarray(
        jax.jit(jax.grad(loss_from_flat))(jnp.asarray(flat0)))

    n = flat0.size
    if n_samples is not None and n_samples < n:
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, size=n_samples, replace=False))
    else:
        idx = np.arange(n)

    return _central_diff_check(loss_jit, flat0, analytic, idx,
                               reshape=lambda v: v, epsilon=epsilon,
                               max_rel_error=max_rel_error,
                               abs_error_threshold=abs_error_threshold)


def gradient_check(conf, ds, epsilon: float = 1e-6,
                   max_rel_error: float = 1e-5,
                   abs_error_threshold: float = 1e-9,
                   n_samples: Optional[int] = None,
                   seed: int = 0) -> GradCheckResult:
    """Check d(loss)/d(params) of a MultiLayerConfiguration against central
    differences (reference ``GradientCheckUtil#checkGradients``).

    ``n_samples``: check a random subset of parameters (None = all).
    """
    import jax

    with jax.enable_x64(True):
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf64 = dataclasses.replace(conf, dtype="float64")
        net = MultiLayerNetwork(conf64).init()

        features = jnp.asarray(np.asarray(ds.features), jnp.float64)
        labels = jnp.asarray(np.asarray(ds.labels), jnp.float64)
        fmask = (jnp.asarray(np.asarray(ds.features_mask), jnp.float64)
                 if ds.features_mask is not None else None)
        lmask = (jnp.asarray(np.asarray(ds.labels_mask), jnp.float64)
                 if ds.labels_mask is not None
                 else jnp.ones((features.shape[0],), jnp.float64))

        return _check_net_params_gradient(
            conf64, net, (features, labels, fmask, lmask), epsilon,
            max_rel_error, abs_error_threshold, n_samples, seed)


def check_layer_input_gradient(layer, input_type, x, epsilon: float = 1e-6,
                               max_rel_error: float = 1e-5,
                               abs_error_threshold: float = 1e-9,
                               seed: int = 0) -> GradCheckResult:
    """Op-level validation (reference ``OpValidation``/``TestCase``):
    d(sum(layer(x)))/dx vs central differences, f64."""
    import jax

    with jax.enable_x64(True):
        import jax.numpy as jnp

        key = jax.random.PRNGKey(seed)
        params = layer.init(key, input_type, jnp.float64)
        state = layer.init_state(input_type, jnp.float64)
        x = jnp.asarray(np.asarray(x), jnp.float64)

        def f(xx):
            y, _ = layer.forward(params, state, xx, train=False, rng=None)
            return jnp.sum(y)

        analytic = np.asarray(jax.jit(jax.grad(f))(x)).ravel()
        f_jit = jax.jit(f)
        x_np = np.asarray(x)
        flat0 = x_np.ravel()
        return _central_diff_check(
            f_jit, flat0, analytic, np.arange(flat0.size),
            reshape=lambda v: v.reshape(x_np.shape), epsilon=epsilon,
            max_rel_error=max_rel_error,
            abs_error_threshold=abs_error_threshold)


def gradient_check_graph(conf, mds, epsilon: float = 1e-6,
                         max_rel_error: float = 1e-5,
                         abs_error_threshold: float = 1e-9,
                         n_samples: Optional[int] = None,
                         seed: int = 0) -> GradCheckResult:
    """Gradient check for a ComputationGraphConfiguration against central
    differences (reference ``GradientCheckUtil#checkGradients(GraphConfig)``
    overload; same f64 protocol as :func:`gradient_check`)."""
    import jax

    with jax.enable_x64(True):
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.graph import ComputationGraph, _as_multi

        conf64 = dataclasses.replace(conf, dtype="float64")
        net = ComputationGraph(conf64).init()
        mds = _as_multi(mds)
        features = tuple(jnp.asarray(np.asarray(f), jnp.float64)
                         for f in mds.features)
        labels = tuple(jnp.asarray(np.asarray(l), jnp.float64)
                       for l in mds.labels)
        fmasks = tuple(
            jnp.asarray(np.asarray(m), jnp.float64) if m is not None else None
            for m in (mds.features_masks if mds.features_masks is not None
                      else (None,) * len(features)))
        if mds.labels_masks is not None:
            lmasks = tuple(
                jnp.asarray(np.asarray(m), jnp.float64) if m is not None
                else jnp.ones((labels[i].shape[0],), jnp.float64)
                for i, m in enumerate(mds.labels_masks))
        else:
            lmasks = tuple(jnp.ones((l.shape[0],), jnp.float64)
                           for l in labels)

        return _check_net_params_gradient(
            conf64, net, (features, labels, fmasks, lmasks), epsilon,
            max_rel_error, abs_error_threshold, n_samples, seed)
