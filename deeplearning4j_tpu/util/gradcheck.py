"""Gradient-check harness — the central correctness oracle.

Reference: ``org.deeplearning4j.gradientcheck.GradientCheckUtil`` (the
backbone of the reference's test strategy, SURVEY.md §4): central-difference
numerical gradients vs backprop in double precision, exact per-parameter
comparison with relative-error thresholds.

Here the analytic side is ``jax.grad`` through the whole jitted loss; the
harness runs in f64 on CPU (``jax.enable_x64``), mirroring the reference's
double-precision-only protocol; TPU runs the same models in f32/bf16 with
tolerance tiers.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from deeplearning4j_tpu.util import params as params_util


def _enable_x64():
    """``jax.enable_x64`` (new jax) / ``jax.experimental.enable_x64``
    (older jax) — same context-manager contract."""
    import jax

    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(True)
    from jax.experimental import enable_x64

    return enable_x64(True)


@dataclasses.dataclass
class GradCheckResult:
    n_params: int
    n_checked: int
    n_failed: int
    max_rel_error: float
    mean_rel_error: float
    failures: list  # (flat_index, analytic, numeric, rel_error)

    @property
    def passed(self) -> bool:
        return self.n_failed == 0


def _central_diff_check(f_jit, flat0: np.ndarray, analytic: np.ndarray,
                        idx: np.ndarray, reshape, epsilon: float,
                        max_rel_error: float,
                        abs_error_threshold: float) -> GradCheckResult:
    """Shared perturb/eval/compare harness. ``reshape`` maps a flat vector
    back to the shape ``f_jit`` expects; rel_err = |a-n| / (|a|+|n|)
    (reference GradientCheckUtil convention).

    The perturbations are evaluated VMAPPED in chunks — one compiled call
    per chunk of up/down pairs instead of two dispatches + a host sync per
    sampled parameter (the per-parameter loop made the f64 oracle the
    dominant cost of the whole tier-1 suite). Same evaluations, same f64
    math, identical results."""
    import jax
    import jax.numpy as jnp

    fv = jax.jit(jax.vmap(lambda v: f_jit(reshape(v))))
    chunk = 256
    numeric = np.empty(len(idx), np.float64)
    for start in range(0, len(idx), chunk):
        ii = np.asarray(idx[start:start + chunk])
        pert = np.zeros((len(ii), flat0.size), flat0.dtype)
        pert[np.arange(len(ii)), ii] = epsilon
        base = flat0[None, :]
        up = np.asarray(fv(jnp.asarray(base + pert)), np.float64)
        dn = np.asarray(fv(jnp.asarray(base - pert)), np.float64)
        numeric[start:start + len(ii)] = (up - dn) / (2.0 * epsilon)

    a = np.asarray(analytic, np.float64)[np.asarray(idx)]
    denom = np.abs(a) + np.abs(numeric)
    rel = np.where(denom > 0, np.abs(a - numeric) / np.maximum(denom, 1e-300),
                   0.0)
    bad = (rel > max_rel_error) & (np.abs(a - numeric) > abs_error_threshold)
    failures = [(int(i), float(av), float(nv), float(rv))
                for i, av, nv, rv in zip(np.asarray(idx)[bad], a[bad],
                                         numeric[bad], rel[bad])]
    return GradCheckResult(
        n_params=int(flat0.size),
        n_checked=len(idx),
        n_failed=len(failures),
        max_rel_error=float(np.max(rel)) if len(rel) else 0.0,
        mean_rel_error=float(np.mean(rel)) if len(rel) else 0.0,
        failures=failures[:20],
    )


def _check_net_params_gradient(conf64, net, loss_args, epsilon,
                               max_rel_error, abs_error_threshold, n_samples,
                               seed) -> GradCheckResult:
    """Shared scaffolding for the MultiLayerNetwork / ComputationGraph
    checks: flatten params, jit loss-of-flat-vector, analytic ``jax.grad``,
    optional parameter subsampling, central-difference compare."""
    import jax
    import jax.numpy as jnp

    like = net.params

    def loss_from_flat(flat):
        p = params_util.unflatten_params(conf64, flat, like)
        loss, _ = net._loss(p, net.state, *loss_args, rng=None, train=True)
        return loss

    flat0 = np.asarray(params_util.flatten_params(conf64, net.params))
    loss_jit = jax.jit(loss_from_flat)
    analytic = np.asarray(
        jax.jit(jax.grad(loss_from_flat))(jnp.asarray(flat0)))

    n = flat0.size
    if n_samples is not None and n_samples < n:
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, size=n_samples, replace=False))
    else:
        idx = np.arange(n)

    return _central_diff_check(loss_jit, flat0, analytic, idx,
                               reshape=lambda v: v, epsilon=epsilon,
                               max_rel_error=max_rel_error,
                               abs_error_threshold=abs_error_threshold)


def gradient_check(conf, ds, epsilon: float = 1e-6,
                   max_rel_error: float = 1e-5,
                   abs_error_threshold: float = 1e-9,
                   n_samples: Optional[int] = None,
                   seed: int = 0) -> GradCheckResult:
    """Check d(loss)/d(params) of a MultiLayerConfiguration against central
    differences (reference ``GradientCheckUtil#checkGradients``).

    ``n_samples``: check a random subset of parameters (None = all).
    """
    import jax

    with _enable_x64():
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf64 = dataclasses.replace(conf, dtype="float64")
        net = MultiLayerNetwork(conf64).init()

        features = jnp.asarray(np.asarray(ds.features), jnp.float64)
        labels = jnp.asarray(np.asarray(ds.labels), jnp.float64)
        fmask = (jnp.asarray(np.asarray(ds.features_mask), jnp.float64)
                 if ds.features_mask is not None else None)
        lmask = (jnp.asarray(np.asarray(ds.labels_mask), jnp.float64)
                 if ds.labels_mask is not None
                 else jnp.ones((features.shape[0],), jnp.float64))

        return _check_net_params_gradient(
            conf64, net, (features, labels, fmask, lmask), epsilon,
            max_rel_error, abs_error_threshold, n_samples, seed)


def check_layer_input_gradient(layer, input_type, x, epsilon: float = 1e-6,
                               max_rel_error: float = 1e-5,
                               abs_error_threshold: float = 1e-9,
                               seed: int = 0) -> GradCheckResult:
    """Op-level validation (reference ``OpValidation``/``TestCase``):
    d(sum(layer(x)))/dx vs central differences, f64."""
    import jax

    with _enable_x64():
        import jax.numpy as jnp

        key = jax.random.PRNGKey(seed)
        params = layer.init(key, input_type, jnp.float64)
        state = layer.init_state(input_type, jnp.float64)
        x = jnp.asarray(np.asarray(x), jnp.float64)

        def f(xx):
            y, _ = layer.forward(params, state, xx, train=False, rng=None)
            return jnp.sum(y)

        analytic = np.asarray(jax.jit(jax.grad(f))(x)).ravel()
        f_jit = jax.jit(f)
        x_np = np.asarray(x)
        flat0 = x_np.ravel()
        return _central_diff_check(
            f_jit, flat0, analytic, np.arange(flat0.size),
            reshape=lambda v: v.reshape(x_np.shape), epsilon=epsilon,
            max_rel_error=max_rel_error,
            abs_error_threshold=abs_error_threshold)


def gradient_check_graph(conf, mds, epsilon: float = 1e-6,
                         max_rel_error: float = 1e-5,
                         abs_error_threshold: float = 1e-9,
                         n_samples: Optional[int] = None,
                         seed: int = 0) -> GradCheckResult:
    """Gradient check for a ComputationGraphConfiguration against central
    differences (reference ``GradientCheckUtil#checkGradients(GraphConfig)``
    overload; same f64 protocol as :func:`gradient_check`)."""
    import jax

    with _enable_x64():
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.graph import ComputationGraph, _as_multi

        conf64 = dataclasses.replace(conf, dtype="float64")
        net = ComputationGraph(conf64).init()
        mds = _as_multi(mds)
        features = tuple(jnp.asarray(np.asarray(f), jnp.float64)
                         for f in mds.features)
        labels = tuple(jnp.asarray(np.asarray(l), jnp.float64)
                       for l in mds.labels)
        fmasks = tuple(
            jnp.asarray(np.asarray(m), jnp.float64) if m is not None else None
            for m in (mds.features_masks if mds.features_masks is not None
                      else (None,) * len(features)))
        if mds.labels_masks is not None:
            lmasks = tuple(
                jnp.asarray(np.asarray(m), jnp.float64) if m is not None
                else jnp.ones((labels[i].shape[0],), jnp.float64)
                for i, m in enumerate(mds.labels_masks))
        else:
            lmasks = tuple(jnp.ones((l.shape[0],), jnp.float64)
                           for l in labels)

        return _check_net_params_gradient(
            conf64, net, (features, labels, fmasks, lmasks), epsilon,
            max_rel_error, abs_error_threshold, n_samples, seed)
