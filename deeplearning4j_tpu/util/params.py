"""Flat-parameter-vector convention.

Reference: the ONE contiguous params vector of ``MultiLayerNetwork`` /
``ComputationGraph`` (``#params()``), with per-layer views — the layout
contract that ModelSerializer's ``coefficients.bin`` depends on.

Here params live as a pytree ``{"0": {"W":…, "b":…}, "1": …}`` (layer index
keys as strings); the flatten order spec is: layers in ascending index order,
within a layer the conf's ``param_order()`` (e.g. W then b), each raveled in
C order. Updater state flattens the same way with the updater's state keys
sorted alphabetically per param.
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np


def _key_sort(k: str):
    """Deterministic order for layer/vertex keys: numeric keys (MultiLayer
    layer indices) ascending first, then names lexicographically
    (ComputationGraph vertices outside conf order never hit this branch)."""
    return (0, int(k), "") if k.isdigit() else (1, 0, k)


def layer_keys(params: Dict[str, dict]) -> List[str]:
    return sorted(params.keys(), key=_key_sort)


def _conf_keys(conf, params: Dict[str, dict]) -> List[str]:
    """Canonical flatten order: the conf's own key order when it provides one
    (ComputationGraph topological order), else ascending layer index."""
    if hasattr(conf, "ordered_param_keys"):
        return [k for k in conf.ordered_param_keys() if k in params]
    return layer_keys(params)


def _conf_layer(conf, key: str):
    if hasattr(conf, "layer_for_key"):
        return conf.layer_for_key(key)
    return conf.layers[int(key)]


def flatten_params(conf, params: Dict[str, dict]) -> np.ndarray:
    """params pytree -> single 1-D numpy vector in the canonical order."""
    chunks = []
    for k in _conf_keys(conf, params):
        layer = _conf_layer(conf, k)
        for name in layer.param_order():
            if name in params[k]:
                chunks.append(np.asarray(params[k][name]).ravel())
    if not chunks:
        return np.zeros((0,), np.float32)
    return np.concatenate(chunks)


def unflatten_params(conf, flat, like: Dict[str, dict]) -> Dict[str, dict]:
    """1-D vector -> params pytree with shapes/dtypes taken from ``like``.
    jit-traceable (used inside the gradient-check loss-of-flat-vector fn)."""
    flat = jnp.asarray(flat)
    if flat.ndim != 1:
        raise ValueError(
            f"flat params vector must be 1-D, got shape {flat.shape}")
    expected = sum(
        int(np.prod(like[k][name].shape))
        for k in _conf_keys(conf, like)
        for name in _conf_layer(conf, k).param_order() if name in like[k])
    if flat.shape[0] != expected:
        raise ValueError(
            f"flat params vector has {flat.shape[0]} values but the model "
            f"expects {expected} (reference: setParams length check)")
    out: Dict[str, dict] = {}
    pos = 0
    for k in _conf_keys(conf, like):
        layer = _conf_layer(conf, k)
        out[k] = dict(like[k])
        for name in layer.param_order():
            if name in like[k]:
                ref = like[k][name]
                n = int(np.prod(ref.shape)) if ref.ndim else 1
                out[k][name] = (
                    flat[pos:pos + n].reshape(ref.shape).astype(ref.dtype))
                pos += n
    return out


def num_params(conf, params: Dict[str, dict]) -> int:
    return int(flatten_params(conf, params).size)


def flatten_state_like(nested) -> np.ndarray:
    """Flatten updater state {layer: {param: {statekey: arr}}} in canonical
    order (layers ascending, param insertion order, state keys sorted)."""
    chunks = []
    for k in sorted(nested.keys(), key=_key_sort):
        for pname in nested[k]:
            st = nested[k][pname]
            for sk in sorted(st.keys()):
                chunks.append(np.asarray(st[sk]).ravel())
    if not chunks:
        return np.zeros((0,), np.float32)
    return np.concatenate(chunks)


def unflatten_state_like(flat: np.ndarray, like) -> dict:
    flat = np.asarray(flat)
    out = {}
    pos = 0
    for k in sorted(like.keys(), key=_key_sort):
        out[k] = {}
        for pname in like[k]:
            out[k][pname] = {}
            for sk in sorted(like[k][pname].keys()):
                ref = like[k][pname][sk]
                n = int(np.prod(ref.shape)) if ref.ndim else 1
                out[k][pname][sk] = jnp.asarray(
                    flat[pos:pos + n].reshape(ref.shape), dtype=ref.dtype)
                pos += n
    if pos != flat.size:
        raise ValueError(f"flat state length {flat.size} != expected {pos}")
    return out
