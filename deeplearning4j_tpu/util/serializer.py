"""ModelSerializer — checkpoint save/restore.

Reference: ``org.deeplearning4j.util.ModelSerializer``: a zip archive of
``configuration.json`` + ``coefficients.bin`` (flat params vector) +
``updaterState.bin`` (flat updater state) + optional normalizer.

Format here (same spirit, numpy container): zip with
- ``configuration.json`` — full config DSL JSON (round-trippable)
- ``coefficients.npy`` — flat params vector (canonical order,
  :mod:`deeplearning4j_tpu.util.params`)
- ``updaterState.npy`` — flat updater state (if saved)
- ``state.npz`` — layer runtime state (BN running stats), keyed
  ``<layer>/<name>``
- ``metadata.json`` — iteration/epoch counters, format version
"""

from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import serde
from deeplearning4j_tpu.util import params as params_util

FORMAT_VERSION = 1


def file_digest(path) -> str:
    """sha256 of a file's content — the integrity check checkpoint
    manifests (``checkpoint.csv``, ``session.json``) record at save time
    and verify at load time, so a truncated/corrupted zip is detected
    BEFORE a restore starts instead of failing halfway through one."""
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def restore_newest_verified(candidates, restore_fn):
    """The digest-verified last-good restore walk shared by
    ``CheckpointListener.load_checkpoint*`` and
    ``TrainingSession.resume``: try ``candidates`` (``(path, digest)``
    pairs, oldest-first) newest-first, skipping any whose file is
    missing, whose content no longer matches its recorded digest
    (truncation, bit rot — an empty digest skips verification), or that
    ``restore_fn`` fails to open despite matching. Returns ``(restored,
    index, last_error)`` — ``(None, -1, err)`` when nothing loads, so a
    corrupted newest checkpoint costs one generation, never the whole
    restore."""
    import os

    last_err = None
    for i in range(len(candidates) - 1, -1, -1):
        path, digest = candidates[i]
        if not os.path.exists(path):
            continue
        if digest and file_digest(path) != digest:
            continue
        try:
            return restore_fn(path), i, None
        except Exception as e:  # unreadable despite matching digest
            last_err = e
    return None, -1, last_err


def write_model(net, path, save_updater: bool = True) -> None:
    """Reference ``ModelSerializer#writeModel(net, file, saveUpdater)``.

    The write is ATOMIC: the zip is assembled in a same-directory temp
    file and published with ``os.replace``, so a crash mid-save can never
    leave a truncated archive where the last-good checkpoint used to be
    (the health layer's ROLLBACK policy depends on that file being
    loadable)."""
    import os

    from deeplearning4j_tpu.resilience import faults

    # sharding-aware gather-on-save: while a parallel wrapper owns the
    # live (possibly ZeRO-scattered / TP-sharded) training trees, pull
    # them back onto the model first — the zip below is always full host
    # arrays, restorable onto ANY mesh shape (the atomic temp+replace
    # publish is unchanged; the gather happens before the temp file
    # opens, so a crash mid-gather leaves nothing behind)
    live = getattr(net, "_live_trainer", None)
    trainer = live() if live is not None else None
    if trainer is not None:
        trainer.sync_model()

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    try:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("configuration.json", net.conf.to_json())
            z.writestr("coefficients.npy", _npy_bytes(net.params_flat()))
            # mid-assembly injection site: a raise here IS a partial
            # write — some entries exist in the temp file, the publish
            # below never happens, and the finally-cleanup must erase it
            faults.fault_point("checkpoint.write")
            if save_updater and net.opt_state:
                z.writestr(
                    "updaterState.npy",
                    _npy_bytes(params_util.flatten_state_like(net.opt_state)))
            if net.state:
                buf = io.BytesIO()
                flat = {f"{k}/{name}": np.asarray(v)
                        for k, d in net.state.items()
                        for name, v in d.items()}
                np.savez(buf, **flat)
                z.writestr("state.npz", buf.getvalue())
            z.writestr("metadata.json", json.dumps({
                "format_version": FORMAT_VERSION,
                "iteration": net.iteration,
                "epoch": net.epoch,
                "model_class": type(net).__name__,
            }))
        os.replace(tmp, path)
    finally:
        if tmp.exists():
            tmp.unlink()


def restore_multi_layer_network(path, load_updater: bool = True):
    """Reference ``ModelSerializer#restoreMultiLayerNetwork`` — exact
    resume: params + updater state + counters."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(Path(path), "r") as z:
        conf = serde.from_json(z.read("configuration.json").decode())
        net = MultiLayerNetwork(conf).init()
        flat = _read_npy(z, "coefficients.npy")
        net.set_params_flat(flat)
        if load_updater and "updaterState.npy" in z.namelist():
            sflat = _read_npy(z, "updaterState.npy")
            net.opt_state = params_util.unflatten_state_like(sflat, net.opt_state)
        if "state.npz" in z.namelist():
            with z.open("state.npz") as f:
                data = np.load(io.BytesIO(f.read()))
                for key in data.files:
                    layer, name = key.split("/", 1)
                    net.state[layer][name] = jnp.asarray(data[key])
        meta = json.loads(z.read("metadata.json").decode())
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
    return net


def restore_computation_graph(path, load_updater: bool = True):
    """Reference ``ModelSerializer#restoreComputationGraph``."""
    try:
        from deeplearning4j_tpu.nn.graph import ComputationGraph
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "ComputationGraph runtime not available yet") from e

    with zipfile.ZipFile(Path(path), "r") as z:
        conf = serde.from_json(z.read("configuration.json").decode())
        net = ComputationGraph(conf).init()
        net.set_params_flat(_read_npy(z, "coefficients.npy"))
        if load_updater and "updaterState.npy" in z.namelist():
            sflat = _read_npy(z, "updaterState.npy")
            net.opt_state = params_util.unflatten_state_like(sflat, net.opt_state)
        if "state.npz" in z.namelist():
            with z.open("state.npz") as f:
                data = np.load(io.BytesIO(f.read()))
                for key in data.files:
                    layer, name = key.split("/", 1)
                    net.state[layer][name] = jnp.asarray(data[key])
        meta = json.loads(z.read("metadata.json").decode())
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
    return net


def _npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr))
    return buf.getvalue()


def _read_npy(z: zipfile.ZipFile, name: str) -> np.ndarray:
    with z.open(name) as f:
        return np.load(io.BytesIO(f.read()))


def restore_model(path, load_updater: bool = True):
    """Restore whichever model type the zip holds (dispatches on the
    serialized configuration class, reference ``ModelSerializer`` static
    restore helpers)."""
    with zipfile.ZipFile(path) as z:
        conf_js = z.read("configuration.json").decode()
    kind = json.loads(conf_js).get("@type", "")
    if "ComputationGraph" in kind:
        return restore_computation_graph(path, load_updater)
    return restore_multi_layer_network(path, load_updater)
