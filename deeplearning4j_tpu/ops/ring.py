"""Ring attention: sequence/context parallelism over a device mesh.

The reference has NO sequence parallelism (SURVEY.md §5.7 — long sequences
are handled only by truncated BPTT); this is the TPU-native strengthening the
build plan calls for: shard the time axis over a mesh ``sequence`` axis and
rotate key/value shards around the ring with ``lax.ppermute`` (XLA lowers the
rotation onto ICI neighbor links, overlapping it with the local block's
compute), accumulating the softmax online exactly as FlashAttention does
across key blocks. Math follows the blockwise-parallel-transformer /
RingAttention construction (see PAPERS.md); implementation is pure
``jnp`` + collectives, so it is differentiable (``ppermute`` has a transpose
rule) and runs on a CPU mesh for tests.

``ring_attention_local`` is the per-shard body (call it INSIDE
``shard_map``); ``ring_attention`` is the convenience wrapper that builds the
``shard_map`` over a ``Mesh`` for ``[B, H, T, D]`` inputs sharded on T.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def ring_attention_local(q, k, v, key_mask, axis_name: str, axis_size: int,
                         causal: bool = False, scale: Optional[float] = None):
    """Per-shard ring attention body. ``q, k, v: [B, H, Tl, D]`` hold this
    shard's slice of the time axis; ``key_mask: [B, Tl]`` (may be None).
    Must run inside ``shard_map`` over mesh axis ``axis_name`` with
    ``axis_size`` shards. Returns the local ``[B, H, Tl, D]`` output."""
    b, h, tl, d = q.shape
    sm = (1.0 / math.sqrt(d)) if scale is None else scale
    my = jax.lax.axis_index(axis_name)
    if key_mask is None:
        key_mask = jnp.ones((b, tl), q.dtype)
    km = jnp.asarray(key_mask, q.dtype)

    q32 = q.astype(jnp.float32)
    tloc = jnp.arange(tl)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def block(carry, kv_km_owner):
        acc, m, l = carry
        kblk, vblk, kmblk, owner = kv_km_owner
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, kblk.astype(jnp.float32)) * sm
        s = jnp.where(kmblk[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            qpos = my * tl + tloc  # global positions
            kpos = owner * tl + tloc
            s = jnp.where((kpos[None, :] <= qpos[:, None])[None, None],
                          s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32))
        return acc, m_new, l

    acc = jnp.zeros((b, h, tl, d), jnp.float32)
    m = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tl), jnp.float32)
    kr, vr, kmr = k, v, km
    # static python loop: axis_size ring steps, K/V/mask rotate one hop per
    # step so every shard sees every key block exactly once
    for step in range(axis_size):
        owner = (my - step) % axis_size  # whose shard we currently hold
        acc, m, l = block((acc, m, l), (kr, vr, kmr, owner))
        if step != axis_size - 1:
            kr = jax.lax.ppermute(kr, axis_name, perm)
            vr = jax.lax.ppermute(vr, axis_name, perm)
            kmr = jax.lax.ppermute(kmr, axis_name, perm)
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, key_mask=None, axis_name: str =
                   "sequence", causal: bool = False,
                   scale: Optional[float] = None):
    """Full-array entry point: shards ``[B, H, T, D]`` on T over
    ``mesh[axis_name]`` and runs the ring. T must divide evenly."""
    n = mesh.shape[axis_name]
    if q.shape[2] % n:
        raise ValueError(f"time axis {q.shape[2]} not divisible by "
                         f"{axis_name} axis size {n}")
    if key_mask is None:
        key_mask = jnp.ones((q.shape[0], k.shape[2]), q.dtype)
    from deeplearning4j_tpu.parallel.mesh import shard_map
    body = partial(ring_attention_local, axis_name=axis_name, axis_size=n,
                   causal=causal, scale=scale)
    spec = P(None, None, axis_name, None)
    return shard_map(
        body, mesh,
        in_specs=(spec, spec, spec, P(None, axis_name)),
        out_specs=spec,
    )(q, k, v, jnp.asarray(key_mask, q.dtype))
