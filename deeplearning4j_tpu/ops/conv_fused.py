"""Fused 1x1-conv (matmul) + batch-norm statistics in one output pass.

Reference: libnd4j's cuDNN platform helpers fuse conv+BN+activation per
op pair (``platform/cudnn/batchnorm.cu`` per SURVEY.md §2.1); here the
TPU-shaped equivalent targets the schedule XLA actually emits for a
train-mode 1x1-conv+BN: write y, read y for mean/var, read y to
normalize — three passes over the activation. The Pallas kernel below
computes the matmul AND the per-channel sum / sum-of-squares partials in
the SAME output pass (the epilogue of the K-loop), so the statistics
read disappears; the normalize+activation pass stays in XLA where it
fuses with whatever follows.

Numerics note: the per-channel sums are taken over the OUTPUT-dtype
(bf16-rounded) y, exactly like the unfused path's
``jnp.mean(y.astype(f32))``; variance is the one-pass E[y^2]-E[y]^2 form
in f32 — at batch-norm's 1e5+ elements-per-channel scale the one/two
pass difference is ~1e-6 relative (pinned by tests/test_zoo.py).

Backward: custom VJP. With y = x @ w, s_c = sum_m y[m,c],
q_c = sum_m y[m,c]^2, the cotangent into y is
g_total = gy + gs[None, :] + 2*y*gq[None, :], and dx = g_total @ w.T,
dw = x.T @ g_total — two plain MXU matmuls (XLA), no extra passes vs
the unfused backward (which also reads y for the BN-stats grad).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on CPU-only installs; interpret mode covers CI
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_BM_CANDIDATES = (512, 256, 128)
_BN = 128
_BK = 128


def _tpu_compiler_params(interpret: bool):
    if interpret or not _HAS_PLTPU:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def pick_block_m(m: int) -> Optional[int]:
    """Largest supported row-block size dividing ``m`` (None = shapes not
    blockable -> caller uses the plain XLA path)."""
    for bm in _BM_CANDIDATES:
        if m % bm == 0:
            return bm
    return None


def fusable(m: int, cin: int, cout: int) -> bool:
    """True when the kernel can run here: pallas-tpu importable (its VMEM
    scratch type is needed even in interpret mode) and the grid covers
    these shapes exactly — row count divisible by a supported block,
    channel counts either below the 128-lane block or a multiple of it.
    False -> callers (FusedConvBN1x1) take the plain XLA path."""
    return (_HAS_PLTPU
            and pick_block_m(m) is not None
            and (cin <= _BK or cin % _BK == 0)
            and (cout <= _BN or cout % _BN == 0))


def _kernel(x_ref, w_ref, y_ref, s_ref, q_ref, acc, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    acc[...] += jax.lax.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        # statistics over the OUTPUT-dtype y (matches the unfused path,
        # which rounds y to bf16 before jnp.mean/var reads it back)
        yb = acc[...].astype(y_ref.dtype)
        y_ref[...] = yb
        y32 = yb.astype(jnp.float32)
        s_ref[...] = jnp.sum(y32, axis=0).reshape(s_ref.shape)
        q_ref[...] = jnp.sum(y32 * y32, axis=0).reshape(q_ref.shape)


def _fwd_impl(x2, w2, interpret):
    m, cin = x2.shape
    cout = w2.shape[-1]
    bm = pick_block_m(m)
    assert bm is not None, (m, cin, cout)
    bn = min(_BN, cout)
    bk = min(_BK, cin)
    nbm, nbn, nbk = m // bm, cout // bn, cin // bk
    if not _HAS_PLTPU:  # pragma: no cover - interpret-only environments
        raise NotImplementedError("pallas tpu backend unavailable")
    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]
    y, ssum, sq = pl.pallas_call(
        functools.partial(_kernel, nk=nbk),
        grid=(nbm, nbn, nbk),
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                  pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
                   pl.BlockSpec((1, 1, bn), lambda i, j, k: (i, 0, j)),
                   pl.BlockSpec((1, 1, bn), lambda i, j, k: (i, 0, j))],
        out_shape=[
            jax.ShapeDtypeStruct((m, cout), x2.dtype),
            jax.ShapeDtypeStruct((nbm, 1, cout), jnp.float32),
            jax.ShapeDtypeStruct((nbm, 1, cout), jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=_tpu_compiler_params(interpret),
        interpret=interpret,
    )(x2, w2)
    # reduce the per-row-block partials (tiny [nbm, C] arrays)
    s = jnp.sum(ssum[:, 0], axis=0)
    q = jnp.sum(sq[:, 0], axis=0)
    return y, s, q


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def matmul_with_stats(x2, w2, interpret=False):
    """``y = x2 @ w2`` plus per-output-channel ``sum(y)`` / ``sum(y*y)``
    (f32), all produced in ONE pass over y by a Pallas kernel.

    x2: [M, Cin]; w2: [Cin, Cout] -> (y [M, Cout] in x2.dtype,
    s [Cout] f32, q [Cout] f32). Shapes must satisfy :func:`fusable`.
    """
    return _fwd_impl(x2, w2, interpret)


def _fwd(x2, w2, interpret):
    y, s, q = _fwd_impl(x2, w2, interpret)
    return (y, s, q), (x2, w2, y)


def _bwd(interpret, res, cts):
    x2, w2, y = res
    gy, gs, gq = cts
    # d(sum y)/dy = 1; d(sum y^2)/dy = 2y — fold into one cotangent,
    # f32 for the accumulation then back to the compute dtype for the MXU
    g = (gy.astype(jnp.float32) + gs[None, :]
         + 2.0 * y.astype(jnp.float32) * gq[None, :]).astype(x2.dtype)
    dx = jax.lax.dot(g, w2.T, preferred_element_type=jnp.float32)
    dw = jax.lax.dot(x2.T, g, preferred_element_type=jnp.float32)
    return dx.astype(x2.dtype), dw.astype(w2.dtype)


matmul_with_stats.defvjp(_fwd, _bwd)


def bn_fold_scale_shift(gamma, beta, mean, var, eps):
    """Inference-time BN folding constants (the libnd4j cuDNN-helper
    fusion, applied statically): eval-mode batch norm is the per-channel
    affine ``y*scale + shift`` with

        scale = gamma / sqrt(var + eps)
        shift = beta - mean * scale

    so a preceding linear op (conv/dense, identity activation) absorbs it
    exactly: ``W' = W * scale`` (scale over the output-channel axis),
    ``b' = b * scale + shift``. Computed in f32 regardless of the serving
    dtype — the fold happens once at engine construction, and rsqrt in
    bf16 would bake a permanent ~1e-2 error into the weights. ``gamma``/
    ``beta`` None = locked gamma/beta (1/0)."""
    var32 = jnp.asarray(var, jnp.float32)
    mean32 = jnp.asarray(mean, jnp.float32)
    scale = jax.lax.rsqrt(var32 + jnp.float32(eps))
    if gamma is not None:
        scale = scale * jnp.asarray(gamma, jnp.float32)
    shift = -mean32 * scale
    if beta is not None:
        shift = shift + jnp.asarray(beta, jnp.float32)
    return scale, shift


def conv1x1_bn_stats(x, w, stride: Tuple[int, int] = (1, 1),
                     interpret: Optional[bool] = None):
    """1x1 convolution (NHWC, HWIO weights [1, 1, Cin, Cout]) returning
    ``(y, sum, sumsq)`` with the statistics fused into the conv's output
    pass. A strided 1x1 conv is an exact spatial subsample first (both
    VALID and SAME sample positions 0, s, 2s, ...).

    ``interpret=None`` auto-enables the Pallas interpreter off-TPU so CPU
    CI exercises the same kernel (SURVEY.md §4 backend-parity oracle).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    sh, sw = stride
    if (sh, sw) != (1, 1):
        x = x[:, ::sh, ::sw, :]
    b, h, wd, cin = x.shape
    cout = w.shape[-1]
    m = b * h * wd
    y2, s, q = matmul_with_stats(x.reshape(m, cin), w.reshape(cin, cout),
                                 interpret)
    return y2.reshape(b, h, wd, cout), s, q
