"""Scaled dot-product attention: reference, blockwise (XLA), and Pallas flash.

Reference counterparts: ``sd.nn.multiHeadDotProductAttention`` /
``org.nd4j.linalg.api.ops.impl.transforms.custom.MultiHeadDotProductAttention``
and the attention layers in ``org.deeplearning4j.nn.conf.layers.{SelfAttentionLayer,
LearnedSelfAttentionLayer}`` — the reference materializes the full [Tq, Tk]
attention matrix per head on-device. TPU-native design: three tiers sharing
one semantics,

- ``reference_attention``: plain jnp, full materialization (oracle for tests).
- ``blockwise_attention``: online-softmax ``lax.scan`` over key blocks —
  O(T) memory at the XLA level, differentiable, runs on any backend. This is
  FlashAttention's math without a hand kernel; used as the CPU path and as the
  local compute inside ring attention (ops/ring.py).
- ``flash_attention``: Pallas TPU kernel (fwd + custom-VJP bwd), blocks
  streamed HBM→VMEM by the pipeline, f32 accumulators in VMEM scratch,
  log-sum-exp saved for the backward. Grid iterates key blocks in the
  innermost (sequential) dimension so scratch persists across them.

All take ``q, k, v: [batch, heads, time, head_dim]``, optional
``key_mask: [batch, time_k]`` (1.0 = valid, 0.0 = padding) and ``causal``.
``dot_product_attention`` dispatches by backend/size.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _scale(q, scale):
    return (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale


# ---------------------------------------------------------------------------
# Tier 0: reference (oracle)
# ---------------------------------------------------------------------------

def reference_attention(q, k, v, key_mask=None, causal=False, scale=None):
    """Full-materialization attention; the test oracle."""
    sm = _scale(q, scale)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :] > 0, s, NEG_INF)
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = jnp.arange(tk)[None, :] <= jnp.arange(tq)[:, None] + (tk - tq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ---------------------------------------------------------------------------
# Tier 1: blockwise online-softmax (pure XLA, any backend)
# ---------------------------------------------------------------------------

def blockwise_attention(q, k, v, key_mask=None, causal=False, scale=None,
                        block_k: int = 128):
    """Online-softmax over key blocks via ``lax.scan`` — never materializes
    the [Tq, Tk] matrix. Differentiable (scan has a transpose rule);
    ``jax.checkpoint`` on the block body keeps backward memory O(T)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    sm = _scale(q, scale)
    bk = min(block_k, tk)
    nk = -(-tk // bk)
    pad = nk * bk - tk

    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    km = jnp.ones((b, tk), q.dtype) if key_mask is None \
        else jnp.asarray(key_mask, q.dtype)
    km = jnp.pad(km, ((0, 0), (0, pad)))

    # [nk, b, h, bk, d] blocks scanned over axis 0
    kb = jnp.moveaxis(kp.reshape(b, h, nk, bk, d), 2, 0)
    vb = jnp.moveaxis(vp.reshape(b, h, nk, bk, d), 2, 0)
    mb = jnp.moveaxis(km.reshape(b, nk, bk), 1, 0)

    q32 = q.astype(jnp.float32)
    qpos = jnp.arange(tq)[:, None] + (tk - tq)  # global query positions

    @jax.checkpoint
    def body(carry, blk):
        acc, m, l = carry
        kblk, vblk, mblk, j = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, kblk.astype(jnp.float32)) * sm
        s = jnp.where(mblk[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            kpos = j * bk + jnp.arange(bk)[None, :]
            s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32))
        return (acc, m_new, l), None

    acc0 = jnp.zeros((b, h, tq, d), jnp.float32)
    m0 = jnp.full((b, h, tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    (acc, _, l), _ = jax.lax.scan(
        body, (acc0, m0, l0), (kb, vb, mb, jnp.arange(nk)))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Tier 2: Pallas flash kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm, causal, block_q, block_k, nk,
                tq, tk):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    i = pl.program_id(1)
    # causal: key block strictly above the diagonal contributes nothing
    run = True if not causal else (j * block_k <= (i + 1) * block_q - 1 + (tk - tq))

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm
        km = km_ref[0, :, 0].astype(jnp.float32)
        s = jnp.where(km[None, :] > 0, s, NEG_INF)
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (tk - tq)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_ref[...]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == nk - 1)
    def _final():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, lse_ref, delta_ref,
               dq_out, dq_acc, *, sm, causal, block_q, block_k, nk, tq, tk):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    i = pl.program_id(1)
    run = True if not causal else (j * block_k <= (i + 1) * block_q - 1 + (tk - tq))

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm
        km = km_ref[0, :, 0].astype(jnp.float32)
        s = jnp.where(km[None, :] > 0, s, NEG_INF)
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (tk - tq)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm

    @pl.when(j == nk - 1)
    def _final():
        dq_out[0] = dq_acc[...].astype(dq_out.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, lse_ref, delta_ref,
                dk_out, dv_out, dk_acc, dv_acc, *, sm, causal, block_q,
                block_k, nq, tq, tk):
    i = pl.program_id(2)  # query block index (innermost)
    j = pl.program_id(1)  # key block index

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True if not causal else (j * block_k <= (i + 1) * block_q - 1 + (tk - tq))

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm
        km = km_ref[0, :, 0].astype(jnp.float32)
        s = jnp.where(km[None, :] > 0, s, NEG_INF)
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (tk - tq)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])  # [bq, bk]
        do = do_ref[0].astype(jnp.float32)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm

    @pl.when(i == nq - 1)
    def _final():
        dk_out[0] = dk_acc[...].astype(dk_out.dtype)
        dv_out[0] = dv_acc[...].astype(dv_out.dtype)


def _tpu_compiler_params(interpret: bool):
    """Mosaic params shared by the three kernels: batch and q/k-block grid
    dims are parallel, the streamed (scratch-accumulating) dim sequential."""
    if interpret or not _HAS_PLTPU:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        vmem_limit_bytes=64 * 1024 * 1024)


def _pad_t(x, blk):
    t = x.shape[2]
    pad = (-t) % blk
    return (jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0))), t + pad) \
        if pad else (x, t)


def _flash_fwd_impl(q, k, v, km, causal, scale, block_q, block_k, interpret):
    b, h, tq0, d = q.shape
    tk0 = k.shape[2]
    sm = _scale(q, scale)
    bq = min(block_q, max(tq0, 8))
    bk = min(block_k, max(tk0, 8))
    q, tq = _pad_t(q, bq)
    k, tk = _pad_t(k, bk)
    v, _ = _pad_t(v, bk)
    km = jnp.pad(jnp.asarray(km, q.dtype), ((0, 0), (0, tk - tk0)))

    bh = b * h
    qf = q.reshape(bh, tq, d)
    kf = k.reshape(bh, tk, d)
    vf = v.reshape(bh, tk, d)
    kmf = jnp.broadcast_to(km[:, None, :], (b, h, tk)).reshape(bh, tk, 1)
    nq, nk = tq // bq, tk // bk

    kern = functools.partial(_fwd_kernel, sm=sm, causal=causal, block_q=bq,
                             block_k=bk, nk=nk, tq=tq0, tk=tk0)
    scratch = [pltpu.VMEM((bq, d), jnp.float32),
               pltpu.VMEM((bq, 1), jnp.float32),
               pltpu.VMEM((bq, 1), jnp.float32)]

    out, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, bk, 1), lambda b_, i, j: (b_, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b_, i, j: (b_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=_tpu_compiler_params(interpret),
        interpret=interpret,
    )(qf, kf, vf, kmf)
    out = out.reshape(b, h, tq, d)[:, :, :tq0]
    lse = lse.reshape(b, h, tq)[:, :, :tq0]
    return out, lse


def _flash_bwd_impl(q, k, v, km, out, lse, g, causal, scale, block_q,
                    block_k, interpret):
    b, h, tq0, d = q.shape
    tk0 = k.shape[2]
    sm = _scale(q, scale)
    bq = min(block_q, max(tq0, 8))
    bk = min(block_k, max(tk0, 8))
    qp, tq = _pad_t(q, bq)
    kp, tk = _pad_t(k, bk)
    vp, _ = _pad_t(v, bk)
    gp, _ = _pad_t(g, bq)
    op, _ = _pad_t(out, bq)
    kmf0 = jnp.pad(jnp.asarray(km, q.dtype), ((0, 0), (0, tk - tk0)))

    delta = jnp.sum(gp.astype(jnp.float32) * op.astype(jnp.float32), axis=-1)
    # padded query rows: lse = -inf would make exp() explode; clamp them
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, tq - tq0)),
                   constant_values=jnp.inf)

    bh = b * h
    qf, kf, vf = (x.reshape(bh, -1, d) for x in (qp, kp, vp))
    gf = gp.reshape(bh, tq, d)
    kmf = jnp.broadcast_to(kmf0[:, None, :], (b, h, tk)).reshape(bh, tk, 1)
    lsef = lsep.reshape(bh, tq, 1)
    deltaf = delta.reshape(bh, tq, 1)
    nq, nk = tq // bq, tk // bk

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm=sm, causal=causal, block_q=bq,
                          block_k=bk, nk=nk, tq=tq0, tk=tk0),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, bk, 1), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b_, i, j: (b_, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b_, i, j: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(interpret),
        interpret=interpret,
    )(qf, kf, vf, kmf, gf, lsef, deltaf)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm=sm, causal=causal, block_q=bq,
                          block_k=bk, nq=nq, tq=tq0, tk=tk0),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b_, j, i: (b_, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, j, i: (b_, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, j, i: (b_, j, 0)),
            pl.BlockSpec((1, bk, 1), lambda b_, j, i: (b_, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b_, j, i: (b_, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b_, j, i: (b_, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b_, j, i: (b_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b_, j, i: (b_, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, j, i: (b_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(interpret),
        interpret=interpret,
    )(qf, kf, vf, kmf, gf, lsef, deltaf)

    dq = dq.reshape(b, h, tq, d)[:, :, :tq0]
    dk = dk.reshape(b, h, tk, d)[:, :, :tk0]
    dv = dv.reshape(b, h, tk, d)[:, :, :tk0]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, km, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(q, k, v, km, causal, scale, block_q, block_k,
                             interpret)
    return out


def _flash_fwd(q, k, v, km, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, km, causal, scale, block_q, block_k,
                               interpret)
    return out, (q, k, v, km, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, km, out, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, km, out, lse, g, causal, scale,
                                 block_q, block_k, interpret)
    return dq, dk, dv, jnp.zeros_like(km)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, key_mask=None, causal=False, scale=None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """FlashAttention as a Pallas TPU kernel with a custom-VJP backward.

    ``interpret=None`` auto-enables the Pallas interpreter off-TPU so the
    same kernel code is exercised in CPU CI (SURVEY.md §4 backend-parity
    oracle)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if key_mask is None:
        key_mask = jnp.ones((q.shape[0], k.shape[2]), q.dtype)
    return _flash(q, k, v, jnp.asarray(key_mask, q.dtype), causal, scale,
                  block_q, block_k, interpret)


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------

def dot_product_attention(q, k, v, key_mask=None, causal=False, scale=None,
                          impl: str = "auto"):
    """Pick the right tier. Measured on the v5e chip (B4/H8/D64, bf16,
    causal): full materialization fails to COMPILE at T=16384 and the
    blockwise scan matches its speed everywhere it does compile (~160ms net
    at T=16k), while the hand Pallas kernel is grid-overhead-bound (~5-14x
    slower) — XLA's fusion wins this one, so `auto` never picks it. The
    Pallas kernel remains the explicitly-selectable (`impl="flash"`)
    strictly-O(T)-VMEM option and the backward-kernel reference."""
    if impl == "auto":
        impl = "reference" if q.shape[2] <= 1024 else "blockwise"
    if impl == "flash":
        return flash_attention(q, k, v, key_mask, causal, scale)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, key_mask, causal, scale)
    return reference_attention(q, k, v, key_mask, causal, scale)
