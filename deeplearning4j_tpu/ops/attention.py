"""Scaled dot-product attention: reference, blockwise (XLA), and Pallas flash.

Reference counterparts: ``sd.nn.multiHeadDotProductAttention`` /
``org.nd4j.linalg.api.ops.impl.transforms.custom.MultiHeadDotProductAttention``
and the attention layers in ``org.deeplearning4j.nn.conf.layers.{SelfAttentionLayer,
LearnedSelfAttentionLayer}`` — the reference materializes the full [Tq, Tk]
attention matrix per head on-device. TPU-native design: three tiers sharing
one semantics,

- ``reference_attention``: plain jnp, full materialization (oracle for tests).
- ``blockwise_attention``: online-softmax ``lax.scan`` over key blocks —
  O(T) memory at the XLA level, differentiable, runs on any backend. This is
  FlashAttention's math without a hand kernel; used as the CPU path and as the
  local compute inside ring attention (ops/ring.py).
- ``flash_attention``: Pallas TPU kernel (fwd + custom-VJP bwd), blocks
  streamed HBM→VMEM by the pipeline, f32 accumulators in VMEM scratch,
  softmax max/denominator saved lane-replicated for the backward. Grid
  iterates key blocks in the innermost (sequential) dimension so scratch
  persists across them; on the v5e this is the fastest trainable path at
  long T (BASELINE.md round-2 table) and the only one at T=16k.

All take ``q, k, v: [batch, heads, time, head_dim]``, optional
``key_mask: [batch, time_k]`` (1.0 = valid, 0.0 = padding) and ``causal``.
``dot_product_attention`` dispatches by backend/size.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _scale(q, scale):
    return (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale


# ---------------------------------------------------------------------------
# Tier 0: reference (oracle)
# ---------------------------------------------------------------------------

def reference_attention(q, k, v, key_mask=None, causal=False, scale=None):
    """Full-materialization attention; the test oracle."""
    sm = _scale(q, scale)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :] > 0, s, NEG_INF)
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = jnp.arange(tk)[None, :] <= jnp.arange(tq)[:, None] + (tk - tq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ---------------------------------------------------------------------------
# KV-cached single-token decode (autoregressive serving)
# ---------------------------------------------------------------------------

def decode_attention(q, k_cache, v_cache, positions, scale=None):
    """One decode step of causal attention against a preallocated KV
    cache. ``q: [batch, heads, head_dim]`` is the new token's query,
    ``k_cache/v_cache: [batch, max_len, heads, head_dim]`` hold every
    previously-written key/value (including the new token's own, written
    by the caller via ``dynamic_update_slice`` before this call), and
    ``positions: [batch]`` is the cache slot the new token occupies —
    slots ``0..positions[b]`` inclusive are attended, everything beyond
    is masked to ``NEG_INF`` exactly like the padding mask in
    :func:`reference_attention` (exp underflows to 0.0, so garbage in
    unwritten slots can never leak into the output as long as it is
    finite — zeros or stale keys from a retired sequence both qualify).

    This is ``reference_attention`` math at ``Tq=1`` — the full [S]
    score row per head, no online softmax — because a decode step's
    score row is tiny and one fused softmax is the fastest shape for it.
    """
    sm = _scale(q, scale)
    s = jnp.einsum("bhd,bshd->bhs", q, k_cache) * sm
    live = jnp.arange(k_cache.shape[1])[None, :] <= positions[:, None]
    s = jnp.where(live[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p, v_cache)


def chunk_decode_attention(q, k_cache, v_cache, positions, scale=None):
    """A ``Tq``-token window of causal attention against a preallocated
    KV cache — the speculative-verification generalization of
    :func:`decode_attention`. ``q: [batch, time, heads, head_dim]`` holds
    the window's queries; query ``i`` of row ``b`` sits at cache slot
    ``positions[b] + i`` (its own k/v already written by the caller via
    :func:`cache_update`), so it may attend slots
    ``0 .. positions[b] + i`` inclusive and everything beyond is masked
    to ``NEG_INF`` exactly like the single-token step. One wide launch
    scores the whole drafted window — ``lax.scan``-free, which is the
    entire point of ``spec_verify:s:k``: K+1 target positions for one
    dispatch instead of K+1 sequential steps."""
    sm = _scale(q, scale)
    s = jnp.einsum("bthd,bshd->bhts", q, k_cache) * sm
    slot = jnp.arange(k_cache.shape[1])[None, None, :]
    qpos = positions[:, None, None] + jnp.arange(q.shape[1])[None, :, None]
    s = jnp.where((slot <= qpos)[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v_cache)


def _paged_decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_sc, l_sc, acc_sc, *, sm, page, npages):
    """Online-softmax decode over KV pages. Grid (batch, page); the page
    dim is innermost/sequential so the [h, ·] scratch accumulates across
    pages. ``pos_ref`` is scalar-prefetched: the kernel AND the index
    maps read it before the body runs, so dead pages (wholly past
    ``positions[b]``) skip both their DMA (index-map redirect to page 0,
    same trick as the flash causal skip) and their compute
    (``pl.when``) — O(used pages) work per row, not O(max_len)."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[b]

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(j * page <= pos)
    def _compute():
        q = q_ref[0].astype(jnp.float32)           # [h, d]
        k = k_ref[0].astype(jnp.float32)           # [page, h, d]
        v = v_ref[0].astype(jnp.float32)           # [page, h, d]
        s = jnp.sum(q[None] * k, axis=2).T * sm    # [h, page]
        # boundary page: slots past positions[b] masked exactly like the
        # masked full-cache read (exp underflows to 0.0 — garbage in
        # unwritten slots can never leak)
        slot = j * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
        s = jnp.where(slot <= pos, s, NEG_INF)
        m_prev, l_prev = m_sc[...], l_sc[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
        p = jnp.exp(s - _rep(m_next, page))
        alpha = jnp.exp(m_prev - m_next)
        l_corr = alpha * l_prev
        l_next = jnp.sum(p, axis=1)[:, None] + l_corr
        m_sc[...] = m_next
        l_sc[...] = l_next
        # pre-normalized accumulator (flash-kernel convention): rescale
        # by 1/l every step so the final store is a cast
        l_inv = jnp.where(l_next == 0.0, 1.0, 1.0 / l_next)
        d = acc_sc.shape[1]
        acc_sc[...] *= _rep(l_corr * l_inv, d)
        pv = jnp.sum(p.T[:, :, None] * v, axis=0)  # [h, d]
        acc_sc[...] += pv * _rep(l_inv, d)

    @pl.when(j == npages - 1)
    def _store():
        o_ref[0] = acc_sc[...].astype(o_ref.dtype)


def paged_decode_attention(q, k_cache, v_cache, positions, scale=None,
                           page: int = 64,
                           interpret: Optional[bool] = None):
    """:func:`decode_attention` as a Pallas kernel gathering KV **pages**
    in-kernel: ``page``-slot blocks of the cache stream HBM→VMEM one DMA
    per page, pages wholly past ``positions[b]`` are skipped at the DMA
    level (scalar-prefetched positions drive the index map), and the
    boundary page masks per-slot. Same signature and semantics as the
    masked full-cache read — ``q: [batch, heads, head_dim]``,
    ``k_cache/v_cache: [batch, max_len, heads, head_dim]``,
    ``positions: [batch]`` — and bitwise the same masking rule, so the
    parity tests pin it directly against :func:`decode_attention`.

    ``page`` must divide ``max_len`` (the pow2 bucket ladder guarantees
    a divisor exists; the autotuner only proposes legal pages).
    ``interpret=None`` auto-enables the Pallas interpreter off-TPU."""
    if not _HAS_PLTPU:
        raise NotImplementedError("pallas tpu dialect unavailable")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = k_cache.shape
    page = min(int(page), s)
    if s % page:
        raise ValueError(f"page {page} must divide cache length {s}")
    npages = s // page
    sm = _scale(q, scale)
    pos = positions.astype(jnp.int32)

    def q_map(b_, j, p):
        return (b_, 0, 0)

    def kv_map(b_, j, p):
        live = j * page <= p[b_]
        return (b_, jax.lax.select(live, j, 0), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, npages),
        in_specs=[pl.BlockSpec((1, h, d), q_map),
                  pl.BlockSpec((1, page, h, d), kv_map),
                  pl.BlockSpec((1, page, h, d), kv_map)],
        out_specs=pl.BlockSpec((1, h, d), q_map),
        scratch_shapes=[pltpu.VMEM((h, _LANES), jnp.float32),
                        pltpu.VMEM((h, _LANES), jnp.float32),
                        pltpu.VMEM((h, d), jnp.float32)],
    )
    params = None
    if not interpret and _HAS_PLTPU:
        params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, sm=sm, page=page,
                          npages=npages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=params,
        interpret=interpret,
    )(pos, q, k_cache, v_cache)


def cache_update(cache, new, positions):
    """Write a token block ``new: [batch, t, heads, head_dim]`` (t = 1
    for ordinary decode, t = K+1 for a speculative verify window) into
    ``cache: [batch, max_len, heads, head_dim]`` at per-sequence slot
    ``positions: [batch]`` via a vmapped ``dynamic_update_slice`` (the
    slot index is traced, so one executable serves every position).
    Out-of-range positions clamp to the last slot (``dynamic_update_slice``
    semantics) — harmless by construction: only retired rows ever sit at
    a position that high, and their slots are never attended."""
    def write(c, n, p):
        return jax.lax.dynamic_update_slice(c, n, (p, 0, 0))

    return jax.vmap(write)(cache, new, positions)


# ---------------------------------------------------------------------------
# Tier 1: blockwise online-softmax (pure XLA, any backend)
# ---------------------------------------------------------------------------

def blockwise_attention(q, k, v, key_mask=None, causal=False, scale=None,
                        block_k: int = 128):
    """Online-softmax over key blocks via ``lax.scan`` — never materializes
    the [Tq, Tk] matrix. Differentiable (scan has a transpose rule);
    ``jax.checkpoint`` on the block body keeps backward memory O(T)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    sm = _scale(q, scale)
    bk = min(block_k, tk)
    nk = -(-tk // bk)
    pad = nk * bk - tk

    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    km = jnp.ones((b, tk), q.dtype) if key_mask is None \
        else jnp.asarray(key_mask, q.dtype)
    km = jnp.pad(km, ((0, 0), (0, pad)))

    # [nk, b, h, bk, d] blocks scanned over axis 0
    kb = jnp.moveaxis(kp.reshape(b, h, nk, bk, d), 2, 0)
    vb = jnp.moveaxis(vp.reshape(b, h, nk, bk, d), 2, 0)
    mb = jnp.moveaxis(km.reshape(b, nk, bk), 1, 0)

    q32 = q.astype(jnp.float32)
    qpos = jnp.arange(tq)[:, None] + (tk - tq)  # global query positions

    @jax.checkpoint
    def body(carry, blk):
        acc, m, l = carry
        kblk, vblk, mblk, j = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, kblk.astype(jnp.float32)) * sm
        s = jnp.where(mblk[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            kpos = j * bk + jnp.arange(bk)[None, :]
            s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32))
        return (acc, m_new, l), None

    acc0 = jnp.zeros((b, h, tq, d), jnp.float32)
    m0 = jnp.full((b, h, tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    (acc, _, l), _ = jax.lax.scan(
        body, (acc0, m0, l0), (kb, vb, mb, jnp.arange(nk)))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Tier 2: Pallas flash kernel
# ---------------------------------------------------------------------------
#
# Mosaic-friendly structure (the round-1 kernel lost 9-14x to XLA; these
# are the fixes, each a measured TPU layout/pipeline rule):
# - every ref keeps >= 128 lanes: running max/denominator live as
#   [block_q, 128] lane-replicated tiles (a [bq, 1] ref forces degenerate
#   1-lane layouts), and the key-padding mask is laid out lane-major as
#   [batch, 8, Tk] instead of [.., Tk, 1];
# - 4D grid (batch, heads, q blocks, k blocks) over the native
#   [B, H, T, D] arrays — no host-side reshape to [B*H, T, D];
# - causal skipping redirects the kv index map to block 0 for skipped
#   blocks, so the pipeline never DMAs data the kernel won't read
#   (a pl.when gate alone still pays the HBM traffic);
# - the accumulator is kept pre-normalized (rescaled by 1/l every step),
#   so the final store is a cast, and softmax residuals are saved as
#   l and m (lane-replicated) rather than one packed lse.

_LANES = 128
_SUBLANES = 8


def _below_diag(i, bq, j, bk, off):
    """True when key block j intersects the causal lower triangle of query
    block i (``off = tk - tq`` aligns the diagonal for cross-attention)."""
    return (i + 1) * bq - 1 + off >= j * bk


def _rep(x, n):
    """[bq, 128] lane-replicated tile -> [bq, n] (n % 128 == 0 on TPU;
    n < 128 happens only with the small blocks interpret-mode tests use)."""
    return jnp.tile(x, (1, n // _LANES)) if n >= _LANES else x[:, :n]


_lane_fit = _rep  # accumulator width d follows the same rule


def _block_mask(km_ref, causal, i, j, bq, bk, off):
    """Combined padding+causal mask for the current [bq, bk] tile, or None."""
    mask = None
    if km_ref is not None:
        mask = km_ref[0, :1, :] > 0  # [1, bk], broadcasts over rows
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + i * bq + off
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + j * bk
        cm = cols <= rows
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    return mask


def _scores(q_ref, k_ref, km_ref, sm, causal, i, j, off):
    """Masked, scaled [bq, bk] logits tile in f32."""
    s = jax.lax.dot_general(
        q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if sm != 1.0:
        s = s * sm
    bq, bk = s.shape
    mask = _block_mask(km_ref, causal, i, j, bq, bk, off)
    return s if mask is None else jnp.where(mask, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, l_ref, m_ref,
                m_sc, l_sc, acc_sc, *, sm, causal, nk, off):
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    i = pl.program_id(2)
    bq, d = q_ref.shape[2], q_ref.shape[3]
    bk = k_ref.shape[2]
    run = True if not causal else _below_diag(i, bq, j, bk, off)

    @pl.when(run)
    def _compute():
        s = _scores(q_ref, k_ref, km_ref, sm, causal, i, j, off)
        m_prev, l_prev = m_sc[...], l_sc[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])  # [bq,128]
        p = jnp.exp(s - _rep(m_next, bk))
        alpha = jnp.exp(m_prev - m_next)
        l_corr = alpha * l_prev
        l_next = jnp.sum(p, axis=1)[:, None] + l_corr
        m_sc[...] = m_next
        l_sc[...] = l_next
        l_inv = jnp.where(l_next == 0.0, 1.0, 1.0 / l_next)
        acc_sc[...] *= _lane_fit(l_corr * l_inv, d)
        pv = jax.lax.dot(p.astype(v_ref.dtype), v_ref[0, 0],
                         preferred_element_type=jnp.float32)
        acc_sc[...] += pv * _lane_fit(l_inv, d)

    @pl.when(j == nk - 1)
    def _store():
        o_ref[0, 0] = acc_sc[...].astype(o_ref.dtype)
        l_ref[0, 0] = l_sc[...]
        m_ref[0, 0] = m_sc[...]


def _p_tile(q_ref, k_ref, km_ref, l_ref, m_ref, sm, causal, i, j, off):
    """Recompute the normalized probability tile p = exp(s - m) / l."""
    s = _scores(q_ref, k_ref, km_ref, sm, causal, i, j, off)
    bk = s.shape[1]
    l = l_ref[0, 0]
    l_inv = jnp.where(l == 0.0, 1.0, 1.0 / l)
    return jnp.exp(s - _rep(m_ref[0, 0], bk)) * _rep(l_inv, bk)


def _di_tile(do, o_ref):
    """di = rowsum(do * o) recomputed in-kernel from the fwd output block:
    [bq, 1], broadcasts against the [bq, bk] dp tile. Passing o (bf16,
    d lanes) instead of a lane-replicated di operand saves a
    [B, H, Tq, 128] f32 HBM materialization per backward."""
    return jnp.sum(do.astype(jnp.float32) * o_ref[0, 0].astype(jnp.float32),
                   axis=1)[:, None]


def _dq_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, o_ref, l_ref, m_ref,
               dq_ref, dq_sc, *, sm, causal, nk, off):
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    i = pl.program_id(2)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    run = True if not causal else _below_diag(i, bq, j, bk, off)

    @pl.when(run)
    def _compute():
        p = _p_tile(q_ref, k_ref, km_ref, l_ref, m_ref, sm, causal, i, j, off)
        do = do_ref[0, 0]
        dp = jax.lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - _di_tile(do, o_ref))
        if sm != 1.0:
            ds = ds * sm
        dq_sc[...] += jax.lax.dot(ds.astype(k_ref.dtype), k_ref[0, 0],
                                  preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _store():
        dq_ref[0, 0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, o_ref, l_ref, m_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, sm, causal, nq, off):
    i = pl.program_id(3)  # query block (innermost, sequential)
    j = pl.program_id(2)  # key block

    @pl.when(i == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    run = True if not causal else _below_diag(i, bq, j, bk, off)

    @pl.when(run)
    def _compute():
        p = _p_tile(q_ref, k_ref, km_ref, l_ref, m_ref, sm, causal, i, j, off)
        do = do_ref[0, 0]
        dv_sc[...] += jax.lax.dot(p.astype(do.dtype).T, do,
                                  preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0, 0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - _di_tile(do, o_ref))
        if sm != 1.0:
            ds = ds * sm
        dk_sc[...] += jax.lax.dot(ds.astype(q_ref.dtype).T, q_ref[0, 0],
                                  preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _store():
        dk_ref[0, 0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[...].astype(dv_ref.dtype)


def _tpu_compiler_params(interpret: bool):
    """Batch/head/query grid dims are parallel; the innermost streamed
    (scratch-accumulating) dim is sequential."""
    if interpret or not _HAS_PLTPU:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _cost(b, h, tq, tk, d, causal, bwd: bool):
    """Rough CostEstimate so Mosaic schedules the pipeline sensibly."""
    frac = 0.5 if causal else 1.0
    matmuls = 5 if bwd else 2  # s, pv fwd; s, dp, dq, dk, dv bwd
    return pl.CostEstimate(
        flops=int(matmuls * 2 * b * h * tq * tk * d * frac),
        transcendentals=int(b * h * tq * tk * frac),
        bytes_accessed=int((4 if bwd else 2) * b * h * (tq + tk) * d * 2),
    )


def _pad_t(x, blk):
    t = x.shape[2]
    pad = (-t) % blk
    return (jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0))), t + pad) \
        if pad else (x, t)


def _mask_operand(km, b, tk0, tk):
    """Lane-major mask operand [batch, 8, tk] (sublane-tiled), or None
    when no mask is needed. Padding keys forced to 0 even without a user
    mask (the padded tail must not attend). Always f32: Mosaic's VPU has
    no bf16 compare, and the kernel tests ``> 0`` directly."""
    if km is None and tk == tk0:
        return None
    if km is None:
        km = jnp.ones((b, tk0), jnp.float32)
    km = jnp.pad(jnp.asarray(km, jnp.float32), ((0, 0), (0, tk - tk0)))
    return jnp.broadcast_to(km[:, None, :], (b, _SUBLANES, km.shape[1]))


def _blk(requested, t):
    """Effective block size: >= one lane tile, a multiple of the lane
    width (the lane-replication math requires it), padded-t divides it.
    When t sits just above a block multiple, shrink to the largest
    128-multiple keeping the padding waste <= t/8 — T=640 with 512-blocks
    would otherwise pad to 1024 and silently burn ~60% of the compute/HBM
    on masked rows (round-2 advisor finding)."""
    if requested > _LANES:
        requested -= requested % _LANES
    b = min(requested, max(_LANES, 1 << (t - 1).bit_length()))
    while b > _LANES and (-(-t // b)) * b - t > t // 8:
        b -= _LANES
    return b


def _index_maps(causal, bq, bk, off):
    """(q, kv, mask) BlockSpec index maps for grid (b, h, i_q, j_kv). The
    causal redirect points skipped kv blocks at block 0 so the pipeline
    never DMAs data the kernel won't read — shared by fwd and dq so the
    skip logic cannot diverge between them."""

    def q_map(b_, h_, i, j):
        return (b_, h_, i, 0)

    def kv_map(b_, h_, i, j):
        if causal:
            j = jax.lax.select(_below_diag(i, bq, j, bk, off), j, 0)
        return (b_, h_, j, 0)

    def km_map(b_, h_, i, j):
        if causal:
            j = jax.lax.select(_below_diag(i, bq, j, bk, off), j, 0)
        return (b_, 0, j)

    return q_map, kv_map, km_map


def _flash_fwd_impl(q, k, v, km, causal, scale, block_q, block_k, interpret):
    b, h, tq0, d = q.shape
    tk0 = k.shape[2]
    if d > _LANES and d % _LANES:
        raise NotImplementedError(
            f"head_dim {d} > {_LANES} must be a multiple of {_LANES}")
    sm = _scale(q, scale)
    bq = _blk(block_q, tq0)
    bk = _blk(block_k, tk0)
    q, tq = _pad_t(q, bq)
    k, tk = _pad_t(k, bk)
    v, _ = _pad_t(v, bk)
    kmo = _mask_operand(km, b, tk0, tk)
    nq, nk = tq // bq, tk // bk
    off = tk0 - tq0
    q_map, kv_map, km_map = _index_maps(causal, bq, bk, off)

    in_specs = [
        pl.BlockSpec((1, 1, bq, d), q_map),
        pl.BlockSpec((1, 1, bk, d), kv_map),
        pl.BlockSpec((1, 1, bk, d), kv_map),
        None if kmo is None else pl.BlockSpec((1, _SUBLANES, bk), km_map),
    ]
    out, l, m = pl.pallas_call(
        functools.partial(_fwd_kernel, sm=sm, causal=causal, nk=nk, off=off),
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), q_map),
            pl.BlockSpec((1, 1, bq, _LANES), q_map),
            pl.BlockSpec((1, 1, bq, _LANES), q_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, h, tq, _LANES), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(interpret),
        cost_estimate=_cost(b, h, tq, tk, d, causal, bwd=False),
        interpret=interpret,
    )(q, k, v, kmo)  # a None operand pairs with its None spec
    # residuals packed to one lane: the kernel writes them lane-replicated
    # (layout), but only [b, h, tq0] of information is worth keeping
    # around between forward and backward (536MB -> 4MB at T=16k B4/H8)
    return out[:, :, :tq0], l[:, :, :tq0, 0], m[:, :, :tq0, 0]


def _flash_bwd_impl(q, k, v, km, out, l, m, g, causal, scale, block_q,
                    block_k, interpret):
    b, h, tq0, d = q.shape
    tk0 = k.shape[2]
    sm = _scale(q, scale)
    bq = _blk(block_q, tq0)
    bk = _blk(block_k, tk0)
    qp, tq = _pad_t(q, bq)
    kp, tk = _pad_t(k, bk)
    vp, _ = _pad_t(v, bk)
    gp, _ = _pad_t(g, bq)
    kmo = _mask_operand(km, b, tk0, tk)
    nq, nk = tq // bq, tk // bk
    off = tk0 - tq0
    q_map, kv_map, km_map = _index_maps(causal, bq, bk, off)

    # per-row residuals arrive packed [b, h, tq0]; rebuild the
    # lane-replicated [.., tq, 128] operands the kernels read (padded q
    # rows: do = 0 zeroes their dk/dv contribution; l pads to 1.0 so the
    # recomputed p stays finite). These two transients (l, m) are the
    # only lane-replicated HBM operands — di is recomputed in-kernel
    # from the (bf16, d-lane) fwd output instead.
    def lanes(x, pad_value=0.0):
        x = jnp.broadcast_to(x[..., None], (b, h, tq0, _LANES))
        return jnp.pad(x, ((0, 0), (0, 0), (0, tq - tq0), (0, 0)),
                       constant_values=pad_value)

    lp = lanes(l, pad_value=1.0)
    mp = lanes(m)
    op, _ = _pad_t(out, bq)

    q_spec = pl.BlockSpec((1, 1, bq, d), q_map)
    kv_spec = pl.BlockSpec((1, 1, bk, d), kv_map)
    km_spec = None if kmo is None else pl.BlockSpec((1, _SUBLANES, bk), km_map)
    lm_spec = pl.BlockSpec((1, 1, bq, _LANES), q_map)
    operands = (qp, kp, vp, kmo, gp, op, lp, mp)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm=sm, causal=causal, nk=nk, off=off),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, km_spec, q_spec, q_spec,
                  lm_spec, lm_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(interpret),
        cost_estimate=_cost(b, h, tq, tk, d, causal, bwd=True),
        interpret=interpret,
    )(*operands)

    # dkv grid: kv blocks outer, q blocks inner (scratch accumulates over
    # q); skipped q blocks redirect their DMAs to the last q block, which
    # is always live under the causal gate
    def q_map_t(b_, h_, j, i):
        if causal:
            i = jax.lax.select(_below_diag(i, bq, j, bk, off), i, nq - 1)
        return (b_, h_, i, 0)

    def kv_map_t(b_, h_, j, i):
        return (b_, h_, j, 0)

    def km_map_t(b_, h_, j, i):
        return (b_, 0, j)

    q_spec_t = pl.BlockSpec((1, 1, bq, d), q_map_t)
    kv_spec_t = pl.BlockSpec((1, 1, bk, d), kv_map_t)
    km_spec_t = (None if kmo is None
                 else pl.BlockSpec((1, _SUBLANES, bk), km_map_t))
    lm_spec_t = pl.BlockSpec((1, 1, bq, _LANES), q_map_t)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm=sm, causal=causal, nq=nq, off=off),
        grid=(b, h, nk, nq),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, km_spec_t, q_spec_t,
                  q_spec_t, lm_spec_t, lm_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(interpret),
        cost_estimate=_cost(b, h, tq, tk, d, causal, bwd=True),
        interpret=interpret,
    )(*operands)

    return (dq[:, :, :tq0], dk[:, :, :tk0], dv[:, :, :tk0])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, km, causal, scale, block_q, block_k, interpret):
    out, _, _ = _flash_fwd_impl(q, k, v, km, causal, scale, block_q, block_k,
                                interpret)
    return out


def _flash_fwd(q, k, v, km, causal, scale, block_q, block_k, interpret):
    out, l, m = _flash_fwd_impl(q, k, v, km, causal, scale, block_q, block_k,
                                interpret)
    return out, (q, k, v, km, out, l, m)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, km, out, l, m = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, km, out, l, m, g, causal, scale,
                                 block_q, block_k, interpret)
    dkm = None if km is None else jnp.zeros_like(km)
    return dq, dk, dv, dkm


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, key_mask=None, causal=False, scale=None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None):
    """FlashAttention as a Pallas TPU kernel with a custom-VJP backward.

    ``interpret=None`` auto-enables the Pallas interpreter off-TPU so the
    same kernel code is exercised in CPU CI (SURVEY.md §4 backend-parity
    oracle)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    km = None if key_mask is None else jnp.asarray(key_mask)
    if km is not None and not jnp.issubdtype(km.dtype, jnp.floating):
        km = km.astype(jnp.float32)  # bool/int masks: keep the vjp float
    return _flash(q, k, v, km, causal, scale, block_q, block_k, interpret)


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------

# Measured crossover (committed bench_attention.py, v5e, B4/H8/D64 bf16
# causal, N=20 queue-timed + value-forced sync — two confirming runs):
#   T=2048: blockwise 4.4-7.7ms fwd / 6.3-6.9ms fwd+bwd vs flash
#           7.0-8.5 / 7.1-8.3 — blockwise wins or ties both modes;
#   T=4096: flash 7.1-7.6ms fwd / 10.2-12.0ms fwd+bwd vs blockwise
#           8.4 / 32.5-36.2 — flash wins both modes;
#   T=8192: flash 11.6 / 28.8 vs blockwise 42.2 / 178.4 — no contest,
#           and blockwise fwd+bwd cannot compile at all by T=16384 (the
#           scan saves one O(B*H*T*D) residual per key block > HBM).
# The crossover is the same for training and inference, so `train` does
# not change the choice today; it stays in the signature because the
# layers pass their mode and a future re-measurement may split the rule
# again (the round-2 dispatcher was wrong precisely because fwd-only was
# never measured separately).
_FLASH_MIN_T = 4096


def dot_product_attention(q, k, v, key_mask=None, causal=False, scale=None,
                          impl: str = "auto", train: bool = True):
    """Pick the right tier, from measurement (regenerate with
    ``python bench_attention.py`` on-chip; the table above and
    BASELINE.md's copy come from that script): full materialization for
    short sequences (one fused kernel), the XLA blockwise scan in the
    moderate band, the Pallas flash kernel from T=4096 up — and blockwise
    everywhere the kernel can't run (non-TPU backends, exotic head
    dims)."""
    d = q.shape[-1]
    flash_ok = (jax.default_backend() == "tpu"
                and (d <= _LANES or d % _LANES == 0))
    if impl == "auto":
        if q.shape[2] <= 1024:
            impl = "reference"
        elif flash_ok and q.shape[2] >= _FLASH_MIN_T:
            impl = "flash"
        else:
            impl = "blockwise"
    if impl == "flash":
        return flash_attention(q, k, v, key_mask, causal, scale)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, key_mask, causal, scale)
    return reference_attention(q, k, v, key_mask, causal, scale)
