"""TPU-native op kernels (Pallas + XLA) for the hot paths.

Reference: libnd4j's declarable-op library supplies fused kernels (attention
helpers, cuDNN platform helpers) — here the hot ops that XLA does not fuse
optimally get hand-written Pallas kernels (compiled to Mosaic), everything
else rides ``jax.numpy``/``lax`` + XLA fusion (SURVEY.md §2.1 equivalence
plan).
"""

from deeplearning4j_tpu.ops.attention import (  # noqa: F401
    cache_update,
    chunk_decode_attention,
    decode_attention,
    dot_product_attention,
    flash_attention,
    blockwise_attention,
    reference_attention,
)
from deeplearning4j_tpu.ops.ring import (  # noqa: F401
    ring_attention,
    ring_attention_local,
)
