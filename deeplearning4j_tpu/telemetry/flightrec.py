"""Black-box flight recorder: last-N step records + crash bundles.

A crashed or halted run should leave a self-contained diagnostic
artifact the way an aircraft leaves a flight recorder: what the last
steps looked like (score, gradient norms, LR, RNG lineage, batch
shapes), what the telemetry counters said, and where the time went.

- :class:`FlightRecorder` keeps a bounded ring of step records. Scores
  and guard vectors are stored as **device scalars** and only
  materialize at dump time, so recording costs a dict append per step —
  no host sync (same contract as the lazy score).
- :meth:`FlightRecorder.dump_bundle` writes a crash bundle::

      <dir>/
        manifest.json   # reason, policy, health report, env/config digest
        records.jsonl   # one step record per line, oldest first
        trace.json      # Chrome trace of the span ring (may be empty)
        traces.json     # retained request traces (telemetry.tracing)
        metrics.json    # registry snapshot + phase histograms

  The manifest carries the retained request-trace ids
  (``request_trace_ids``), so a failed request's end-to-end timeline
  survives post-mortem alongside the step records. Bundles are pruned
  keep-last-N on publish (``DL4J_FLIGHTREC_KEEP``, default 16): chaos
  sessions dump a bundle per induced crash, and without retention a
  long soak fills the disk with them.

- :func:`flight_recorder` is the context manager every ``fit`` wraps:
  on an uncaught exception (including :class:`health.DivergenceError`)
  it dumps the bundle and re-raises. Disabled (the default) it is a
  bare ``yield`` — one flag check.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Optional, Sequence

from deeplearning4j_tpu.telemetry import health as _health


def batch_fingerprint(*arrays) -> list:
    """Cheap, sync-free identity of a staged batch: shape + dtype per
    array (``None`` entries pass through). Enough to answer "which batch
    shape/dtype was in flight when it died" without hashing device
    memory."""
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
        elif isinstance(a, (tuple, list)):
            out.append(batch_fingerprint(*a))
        else:
            out.append([list(getattr(a, "shape", ())),
                        str(getattr(a, "dtype", "?"))])
    return out


def sanitize_json(obj):
    """Replace non-finite floats with the strings ``"NaN"`` /
    ``"Infinity"`` / ``"-Infinity"`` so every emitted artifact is
    spec-valid JSON — strict parsers (jq, JSON.parse, scrape agents)
    reject bare NaN literals, and non-finite values are exactly what a
    crash bundle exists to carry."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj == float("inf"):
            return "Infinity"
        if obj == float("-inf"):
            return "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_json(v) for v in obj]
    return obj


def _materialize(x):
    """Device scalar/vector -> plain JSON value at dump time. A buffer
    that was donated/deleted since recording reports as unavailable
    instead of failing the dump."""
    import numpy as np

    if x is None:
        return None
    try:
        arr = np.asarray(x, np.float64)
    except Exception:
        return "unavailable"
    if arr.ndim == 0:
        return float(arr)
    return [float(v) for v in arr.ravel()]


class FlightRecorder:
    """Ring buffer of step records + bundle writer."""

    def __init__(self, capacity: int = 256):
        import collections

        self._ring = collections.deque(maxlen=int(capacity))
        self._enabled = False
        self.last_bundle: Optional[str] = None
        self._conf_digest: Optional[str] = None

    # --- switches -----------------------------------------------------------
    def enable(self, capacity: Optional[int] = None) -> "FlightRecorder":
        import collections

        if capacity is not None and capacity != self._ring.maxlen:
            self._ring = collections.deque(self._ring,
                                           maxlen=int(capacity))
        self._enabled = True
        return self

    def disable(self) -> "FlightRecorder":
        self._enabled = False
        return self

    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> "FlightRecorder":
        self._ring.clear()
        self.last_bundle = None
        return self

    # --- recording (hot path: one flag check when disabled) -----------------
    def record_step(self, path: str, step: int, epoch: int, score=None,
                    guard=None, guard_keys: Sequence[str] = (),
                    lr=None, rng_seed=None, batch_fp=None) -> None:
        if not self._enabled:
            return
        self._ring.append({
            "path": path,
            "step": int(step),
            "epoch": int(epoch),
            "score": score,            # device scalar, materialized on dump
            "guard": guard,            # device guard vector (or None)
            "guard_keys": list(guard_keys),
            "lr": lr,
            "rng_seed": rng_seed,
            "batch": batch_fp,
            "wall_time": time.time(),
        })

    def set_config_digest(self, conf_json: str) -> None:
        """Register the model configuration (hashed into the manifest so
        a bundle self-identifies which network produced it)."""
        import hashlib

        self._conf_digest = hashlib.sha256(
            conf_json.encode("utf-8", "replace")).hexdigest()

    def records(self) -> list:
        """Materialized copies of the ring (oldest first)."""
        return [self._materialize_record(r) for r in list(self._ring)]

    def _materialize_record(self, r: dict) -> dict:
        out = dict(r)
        out["score"] = _materialize(r["score"])
        out["guard"] = _materialize(r["guard"])
        out["lr"] = _materialize(r["lr"])
        return out

    # --- bundles ------------------------------------------------------------
    def dump_bundle(self, directory: Optional[str] = None,
                    reason: str = "manual") -> str:
        """Write the crash bundle; returns its directory. Always succeeds
        in writing whatever it can — a flight recorder that throws during
        a crash is worse than none."""
        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.telemetry import spans, tracing

        if directory is None:
            root = os.environ.get("DL4J_FLIGHTREC_DIR", "flightrec")
            directory = os.path.join(
                root, f"bundle_{int(time.time())}_{os.getpid()}")
        os.makedirs(directory, exist_ok=True)

        try:
            trace_snap = tracing.snapshot()
        except Exception:
            trace_snap = None

        records = self.records()
        with open(os.path.join(directory, "records.jsonl"), "w") as f:
            for r in records:
                f.write(json.dumps(sanitize_json(r)) + "\n")

        try:
            health_report = _health.report()
        except Exception:
            health_report = None
        env = {k: v for k, v in os.environ.items()
               if k.startswith(("JAX_", "XLA_", "DL4J_", "TPU_"))}
        versions = {}
        try:
            import jax

            versions["jax"] = jax.__version__
            versions["backend"] = jax.default_backend()
            versions["devices"] = [str(d) for d in jax.local_devices()]
        except Exception:
            pass
        manifest = {
            "format_version": 1,
            "created_at": time.time(),
            "reason": reason,
            "n_records": len(records),
            "health": health_report,
            "config_digest": self._conf_digest,
            "env": env,
            "versions": versions,
            "request_trace_ids": (
                [t["trace_id"] for t in trace_snap["traces"]]
                if trace_snap else []),
            "files": ["manifest.json", "records.jsonl", "trace.json",
                      "traces.json", "metrics.json"],
        }
        with open(os.path.join(directory, "manifest.json"), "w") as f:
            json.dump(sanitize_json(manifest), f, indent=2)

        try:
            spans.export_chrome_trace(os.path.join(directory, "trace.json"))
        except Exception:
            pass
        try:
            if trace_snap is not None:
                with open(os.path.join(directory, "traces.json"),
                          "w") as f:
                    json.dump(sanitize_json(trace_snap), f)
        except Exception:
            pass
        try:
            with open(os.path.join(directory, "metrics.json"), "w") as f:
                json.dump(sanitize_json(telemetry.telemetry_record()), f)
        except Exception:
            pass

        self.last_bundle = directory
        try:
            self._prune_siblings(directory)
        except Exception:
            pass  # retention must never fail the dump
        return directory

    @staticmethod
    def _prune_siblings(directory: str) -> None:
        """Keep-last-N retention over sibling ``bundle_*`` directories
        (N from ``DL4J_FLIGHTREC_KEEP``, default 16; <= 0 disables).
        Runs AFTER the new bundle is fully published, newest-first by
        mtime so the bundle just written always survives."""
        keep = int(os.environ.get("DL4J_FLIGHTREC_KEEP", "16"))
        if keep <= 0:
            return
        root = os.path.dirname(os.path.abspath(directory)) or "."
        bundles = []
        for name in os.listdir(root):
            p = os.path.join(root, name)
            if name.startswith("bundle_") and os.path.isdir(p):
                bundles.append((os.path.getmtime(p), p))
        bundles.sort(reverse=True)
        import shutil

        for _, p in bundles[keep:]:
            shutil.rmtree(p, ignore_errors=True)


RECORDER = FlightRecorder()


def record_step(*args, **kw) -> None:
    """Module-level hot-path shim (one attribute + flag check when the
    recorder is disabled)."""
    rec = RECORDER
    if rec._enabled:
        rec.record_step(*args, **kw)


def enabled() -> bool:
    return RECORDER._enabled


@contextlib.contextmanager
def flight_recorder(directory: Optional[str] = None, model=None):
    """Wraps a ``fit``: any exception escaping the block dumps a crash
    bundle (once — nested fits mark the exception so outer wrappers
    don't re-dump) and re-raises. A no-op ``yield`` when the recorder is
    disabled."""
    rec = RECORDER
    if not rec._enabled:
        yield rec
        return
    if model is not None:
        # refresh per fit: the digest must identify THIS run's network,
        # not whichever model happened to train first in the process
        try:
            rec.set_config_digest(model.conf.to_json())
        except Exception:
            pass
    try:
        yield rec
    except BaseException as e:
        # BaseException: a Ctrl-C on a diverging run is the most common
        # way a bad run dies — it must still leave a bundle behind
        if not getattr(e, "_dl4j_flightrec_dumped", False):
            try:
                reason = (f"DivergenceError: {e}"
                          if isinstance(e, _health.DivergenceError)
                          else f"{type(e).__name__}: {e}")
                rec.dump_bundle(directory, reason=reason)
                e._dl4j_flightrec_dumped = True
            except Exception:
                pass  # never mask the original failure
        raise
