"""Export surfaces: StatsStorage bridge + JSONL persistence.

- ``TelemetryListener`` snapshots the registry + phase histograms into any
  ``ui.stats.StatsStorage`` every N iterations, so telemetry rides the
  same dashboard/remote-router plumbing as StatsListener records.
- ``dump_jsonl`` appends one self-contained snapshot line to a file —
  the offline-diff format for comparing bench rounds
  (``jq`` / ``FileStatsStorage`` both read it).

The HTTP surfaces (``/metrics`` Prometheus text, ``/metrics.json``) live
on ``ui.server.UIServer``.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from deeplearning4j_tpu.optimize.listeners import TrainingListener


def telemetry_record(session_id: Optional[str] = None,
                     iteration: Optional[int] = None) -> dict:
    """One combined snapshot: registry metrics + span phase histograms."""
    from deeplearning4j_tpu.telemetry import registry, spans

    rec = {
        "timestamp": time.time(),
        "telemetry": registry.REGISTRY.snapshot(),
        "phases": spans.phase_stats(),
    }
    if session_id is not None:
        rec["session"] = session_id
    if iteration is not None:
        rec["iteration"] = int(iteration)
    return rec


def dump_jsonl(path: str, extra: Optional[dict] = None) -> str:
    """Append one snapshot line to ``path`` (JSONL). ``extra`` keys merge
    into the record (e.g. ``{"round": "r07", "bench": "resnet"}``) so
    offline diffs across bench rounds can self-describe."""
    rec = telemetry_record()
    if extra:
        rec.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return path


class TelemetryListener(TrainingListener):
    """Bridge the registry + phase stats into a ``StatsStorage`` every
    ``frequency`` iterations (the reference dashboard's System-tab role,
    generalized to the whole metrics registry). Collection is a pure host
    read — no device sync — so it composes with the async fit loops."""

    def __init__(self, storage, frequency: int = 10,
                 session_id: Optional[str] = None):
        self.storage = storage
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"telemetry_{int(time.time())}"

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency:
            return
        self.storage.put(telemetry_record(self.session_id, iteration))
