"""Declarative per-tenant SLOs evaluated as multi-window burn rates.

An :class:`SLO` declares objectives (p95 latency, TTFT, error rate);
an :class:`SLOMonitor` turns a tenant's outcome stream into burn rates
over a SHORT and a LONG rolling window and drives a three-state alert
(``ok`` → ``warn`` → ``page``) with hysteresis.

Burn rate is the classic SRE ratio: observed violation fraction divided
by the objective's budget (for the error objective the budget is the
target error rate itself; for the latency/TTFT objectives the budget is
the 5% a p95 target tolerates by definition). An alert level fires only
when the burn clears its threshold in BOTH windows — the short window
makes the alert fast, the long window stops a handful of bad requests
from paging — and clears only after ``clear_after`` consecutive
evaluations below every threshold (hysteresis, so a boundary-hovering
tenant doesn't flap).

Determinism discipline (the :class:`~parallel.platform.CanaryGate`
contract): ``observe`` evaluates SYNCHRONOUSLY on the caller's thread
under the monitor lock, state is a pure function of the observation
stream, and nothing here reads wall clock or draws randomness — so a
seeded replay of the same traffic fires every transition at the SAME
observation index, which is pinned by test. Evaluation of an objective
is count-gated (``min_samples``) so cold windows can't page on the
first stray error.

Surfaces: ``resilience.status()["slo"]``, the UI ``/slo`` + ``/health``
endpoints, and ``dl4j_slo_*`` gauges via a scrape-time collector over
the live-monitor WeakSet (the fleet router's input).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import weakref
from typing import Deque, Dict, List, Optional, Tuple

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_PAGE = "page"
STATE_CODE = {STATE_OK: 0, STATE_WARN: 1, STATE_PAGE: 2}

# a p95 objective budgets 5% of requests over the target by definition
_TAIL_BUDGET = 0.05

# monitors register here; the telemetry collector walks the set at
# scrape time (same pattern as the serving/decode engine WeakSets)
_MONITORS: "weakref.WeakSet[SLOMonitor]" = weakref.WeakSet()


@dataclasses.dataclass(frozen=True)
class SLO:
    """One tenant's objectives + alerting knobs. ``None`` disables an
    objective."""

    latency_p95_ms: Optional[float] = None
    ttft_ms: Optional[float] = None
    error_rate: Optional[float] = 0.01
    short_window: int = 64
    long_window: int = 512
    warn_burn: float = 1.0
    page_burn: float = 4.0
    clear_after: int = 32
    min_samples: int = 16

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class _Windows:
    """Short + long rolling violation windows for one objective."""

    __slots__ = ("short", "long")

    def __init__(self, slo: SLO):
        self.short: Deque[bool] = collections.deque(
            maxlen=max(1, slo.short_window))
        self.long: Deque[bool] = collections.deque(
            maxlen=max(1, slo.long_window))

    def push(self, violated: bool) -> None:
        self.short.append(violated)
        self.long.append(violated)

    def burns(self, budget: float) -> Tuple[float, float]:
        b = max(budget, 1e-9)
        s = (sum(self.short) / len(self.short) / b) if self.short else 0.0
        lo = (sum(self.long) / len(self.long) / b) if self.long else 0.0
        return s, lo


class _TenantState:
    __slots__ = ("slo", "n", "state", "ok_streak", "since_index",
                 "transitions", "windows", "burns")

    def __init__(self, slo: SLO):
        self.slo = slo
        self.n = 0
        self.state = STATE_OK
        self.ok_streak = 0
        self.since_index = 0
        self.transitions: List[dict] = []
        self.windows: Dict[str, _Windows] = {}
        if slo.error_rate is not None:
            self.windows["error_rate"] = _Windows(slo)
        if slo.latency_p95_ms is not None:
            self.windows["latency_p95"] = _Windows(slo)
        if slo.ttft_ms is not None:
            self.windows["ttft"] = _Windows(slo)
        self.burns: Dict[str, Tuple[float, float]] = {}

    def _budget(self, objective: str) -> float:
        if objective == "error_rate":
            return self.slo.error_rate or 1e-9
        return _TAIL_BUDGET


class SLOMonitor:
    """Per-tenant burn-rate evaluation over an outcome stream.

    ``objectives`` is a default :class:`SLO` applied to every tenant, or
    a dict ``{tenant: SLO}`` (unlisted tenants get ``default`` when
    provided, else no objectives and no state)."""

    def __init__(self, objectives=None, default: Optional[SLO] = None,
                 seed: int = 0):
        self._lock = threading.Lock()
        self._states: Dict[str, _TenantState] = {}
        self.seed = seed
        if isinstance(objectives, SLO):
            self._default: Optional[SLO] = objectives
            self._per_tenant: Dict[str, SLO] = {}
        else:
            self._per_tenant = dict(objectives or {})
            self._default = default
        _MONITORS.add(self)

    def _slo_for(self, tenant: str) -> Optional[SLO]:
        return self._per_tenant.get(tenant, self._default)

    # -- write side -------------------------------------------------------

    def observe(self, tenant: str, ok: Optional[bool] = None,
                seconds: Optional[float] = None,
                ttft: Optional[float] = None) -> str:
        """Record one outcome and re-evaluate synchronously. Returns the
        tenant's (possibly new) alert state."""
        with self._lock:
            st = self._states.get(tenant)
            if st is None:
                slo = self._slo_for(tenant)
                if slo is None:
                    return STATE_OK
                st = self._states[tenant] = _TenantState(slo)
            st.n += 1
            if ok is not None and "error_rate" in st.windows:
                st.windows["error_rate"].push(not ok)
            if seconds is not None and "latency_p95" in st.windows:
                st.windows["latency_p95"].push(
                    seconds * 1000.0 > st.slo.latency_p95_ms)
            if ttft is not None and "ttft" in st.windows:
                st.windows["ttft"].push(ttft * 1000.0 > st.slo.ttft_ms)
            return self._evaluate_locked(tenant, st)

    def _evaluate_locked(self, tenant: str, st: _TenantState) -> str:
        desired = STATE_OK
        for objective, w in st.windows.items():
            if len(w.long) < st.slo.min_samples:
                st.burns[objective] = w.burns(st._budget(objective))
                continue
            s, lo = w.burns(st._budget(objective))
            st.burns[objective] = (s, lo)
            if s >= st.slo.page_burn and lo >= st.slo.page_burn:
                desired = STATE_PAGE
            elif s >= st.slo.warn_burn and lo >= st.slo.warn_burn \
                    and desired == STATE_OK:
                desired = STATE_WARN
        cur = st.state
        if STATE_CODE[desired] > STATE_CODE[cur]:
            self._transition_locked(tenant, st, desired)
            st.ok_streak = 0
        elif STATE_CODE[desired] < STATE_CODE[cur]:
            st.ok_streak += 1
            if st.ok_streak >= st.slo.clear_after:
                self._transition_locked(tenant, st, desired)
                st.ok_streak = 0
        else:
            st.ok_streak = 0
        return st.state

    def _transition_locked(self, tenant: str, st: _TenantState,
                           to: str) -> None:
        st.transitions.append({
            "index": st.n, "from": st.state, "to": to,
            "burns": {k: [round(s, 3), round(lo, 3)]
                      for k, (s, lo) in sorted(st.burns.items())},
        })
        st.state = to
        st.since_index = st.n
        from deeplearning4j_tpu import telemetry

        telemetry.record_slo_transition(tenant, to)

    # -- read side --------------------------------------------------------

    def state(self, tenant: str) -> str:
        with self._lock:
            st = self._states.get(tenant)
            return st.state if st is not None else STATE_OK

    def transitions(self, tenant: str) -> List[dict]:
        with self._lock:
            st = self._states.get(tenant)
            return list(st.transitions) if st is not None else []

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for tenant, st in sorted(self._states.items()):
                out[tenant] = {
                    "state": st.state,
                    "since_index": st.since_index,
                    "observations": st.n,
                    "objectives": st.slo.as_dict(),
                    "burn_rates": {
                        k: {"short": round(s, 3), "long": round(lo, 3)}
                        for k, (s, lo) in sorted(st.burns.items())},
                    "transitions": list(st.transitions),
                }
            return out

    def worst_state(self) -> str:
        with self._lock:
            worst = STATE_OK
            for st in self._states.values():
                if STATE_CODE[st.state] > STATE_CODE[worst]:
                    worst = st.state
            return worst

    def reset(self) -> None:
        with self._lock:
            self._states.clear()


def status() -> dict:
    """Merged view over every live monitor (the ``resilience.status()``
    / ``/slo`` payload)."""
    tenants: dict = {}
    worst = STATE_OK
    for mon in list(_MONITORS):
        for tenant, snap in mon.snapshot().items():
            tenants[tenant] = snap
            if STATE_CODE[snap["state"]] > STATE_CODE[worst]:
                worst = snap["state"]
    return {"state": worst, "tenants": tenants}


def monitors() -> List["SLOMonitor"]:
    return list(_MONITORS)
