"""Step-trace spans — the "where did this step's time go" primitive.

A span brackets one host-observable phase of a training step::

    with telemetry.span("ingest"):
        features, labels = stage(batch)
    with telemetry.span("compute") as sp:
        loss = sp.set_result(train_step(...))   # async dispatch

Finished spans land in a bounded ring buffer (process-wide, thread-safe
under the GIL via ``deque(maxlen=...)``) and can be exported as
Chrome-trace JSON (``chrome://tracing`` / Perfetto) or aggregated into
per-phase histograms (p50/p95/p99).

Timing is ``jax.block_until_ready``-aware: jax dispatch is asynchronous,
so a span around a jitted call measures only the enqueue (~µs) unless the
device result is forced. ``Span.set_result(x)`` registers the call's
output; when spans were enabled with ``sync=True`` the span blocks on it
before taking the end timestamp, so the recorded duration is the real
device time of the phase. With ``sync=False`` (the default) nothing ever
forces a host sync — the async fit pipeline stays fully queued and the
spans record host-side dispatch cost only.

Disabled mode is the hot-path contract: ``span(name)`` is ONE module-flag
check returning a shared no-op singleton — no allocation, no lock, no
host sync (pinned by tests/test_telemetry.py).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

# Canonical training-phase names. Every instrumented training path
# (MultiLayerNetwork, ComputationGraph, SameDiff, ParallelWrapper,
# PipelineParallelWrapper) reports this same breakdown, and
# bench_resnet_profile.py --phases derives its row keys from these so the
# bench and the framework cannot drift (tests/test_telemetry.py).
# ``host_gap`` (round 11) is the time the host spends BETWEEN step
# dispatches — the launch-latency budget the fused multi-step driver
# amortizes over K steps; see host_gap_open/close below.
PHASE_INGEST = "ingest"
PHASE_COMPUTE = "compute"
PHASE_GRAD_SYNC = "grad_sync"
PHASE_HOST_GAP = "host_gap"
PHASES = (PHASE_INGEST, PHASE_COMPUTE, PHASE_GRAD_SYNC, PHASE_HOST_GAP)

_enabled = False
_sync = False
_ring: "collections.deque" = collections.deque(maxlen=4096)
_tls = threading.local()


class _NullSpan:
    """Shared disabled-mode span: every method is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_result(self, x):
        return x

    def annotate(self, **kw):
        return self


NULL_SPAN = _NullSpan()


class Span:
    __slots__ = ("name", "t0", "t1", "depth", "parent", "_result", "attrs")

    def __init__(self, name: str):
        self.name = name
        self.t0 = self.t1 = 0
        self.depth = 0
        self.parent: Optional[str] = None
        self._result = None
        self.attrs: Optional[dict] = None

    def set_result(self, x):
        """Register the phase's device output; returned unchanged. In
        sync mode the span blocks on it before closing, so the duration
        covers the device work — in async mode it is never touched."""
        self._result = x
        return x

    def annotate(self, **kw):
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(kw)
        return self

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self.depth = len(stack)
        self.parent = stack[-1].name if stack else None
        stack.append(self)
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if _sync and self._result is not None:
            try:
                import jax

                jax.block_until_ready(self._result)
            except Exception:
                pass  # non-jax results (or deleted buffers) time as-is
        self.t1 = time.perf_counter_ns()
        self._result = None  # never pin device buffers in the ring
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        _ring.append((self.name, self.t0, self.t1 - self.t0, self.depth,
                      self.parent, threading.get_ident(), self.attrs))
        return False


def span(name: str):
    """A timing span for one phase. Disabled: one flag check, shared
    no-op singleton (zero allocation). Enabled: records into the ring."""
    if not _enabled:
        return NULL_SPAN
    return Span(name)


# --------------------------------------------------------------------------
# host-gap tracking (PHASE_HOST_GAP)
#
# jax dispatch is asynchronous, so a ``compute`` span measures only the
# enqueue — the cost the device actually SEES from the host is the gap
# between one dispatch returning and the next being issued (listener
# epilogues, health accounting, iterator work, batch staging). The fit
# loops bracket their dispatches with these helpers: ``host_gap_close(k)``
# right before a dispatch records the gap since the previous dispatch
# returned (annotated with the ``steps`` the upcoming dispatch fuses, so a
# K-step super-step's gap amortizes over K when aggregating per step) and
# ``host_gap_open()`` right after it re-arms the clock. State is
# thread-local; ``host_gap_reset()`` at fit entry re-arms from "now" so
# idle time between fits never records as a gap.
# --------------------------------------------------------------------------

def host_gap_reset() -> None:
    """Arm the gap clock at fit entry (records nothing)."""
    _tls.gap_open_ns = time.perf_counter_ns() if _enabled else None


def host_gap_open() -> None:
    """Mark a step dispatch as returned: the host gap starts now."""
    if _enabled:
        _tls.gap_open_ns = time.perf_counter_ns()


def host_gap_close(steps: int = 1) -> None:
    """About to dispatch the next step: record the elapsed host gap.
    ``steps`` = train steps the upcoming dispatch covers (K for a fused
    super-step) — consumers divide the gap by it for per-step cost."""
    if not _enabled:
        return
    t0 = getattr(_tls, "gap_open_ns", None)
    if t0 is None:
        return
    _tls.gap_open_ns = None
    t1 = time.perf_counter_ns()
    _ring.append((PHASE_HOST_GAP, t0, t1 - t0, 0, None,
                  threading.get_ident(), {"steps": int(steps)}))


def host_gap_stop() -> None:
    """Disarm the gap clock (fit exit): idle time after a fit's last
    dispatch must never surface as a gap when some later call — a
    standalone ``fit_batch``, the next fit — closes the clock."""
    _tls.gap_open_ns = None


def host_gap_pause() -> None:
    """An INTENTIONAL host block is starting (the fit pipeline's
    ``drain`` parking on queued device results): stop the gap clock so
    device-wait time is never billed as host dispatch gap."""
    if _enabled and getattr(_tls, "gap_open_ns", None) is not None:
        _tls.gap_pause_ns = time.perf_counter_ns()


def host_gap_resume() -> None:
    """The intentional block ended: shift the gap origin forward by the
    blocked interval."""
    t0 = getattr(_tls, "gap_pause_ns", None)
    if t0 is not None:
        _tls.gap_pause_ns = None
        if _enabled and getattr(_tls, "gap_open_ns", None) is not None:
            _tls.gap_open_ns += time.perf_counter_ns() - t0


def enable(sync: bool = False, ring_size: int = 4096) -> None:
    """Turn span recording on. ``sync=True`` makes spans block on their
    registered device result (``set_result``) for true per-phase device
    timing — at the cost of one host sync per span, so keep it off for
    production throughput runs."""
    global _enabled, _sync, _ring
    if ring_size != _ring.maxlen:
        _ring = collections.deque(_ring, maxlen=int(ring_size))
    _sync = bool(sync)
    _enabled = True


def disable() -> None:
    """Turn recording off (the ring is kept so traces remain exportable)."""
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def sync_mode() -> bool:
    return _enabled and _sync


def reset() -> None:
    """Drop recorded spans (flags untouched)."""
    _ring.clear()


def events() -> List[dict]:
    """Finished spans, oldest first, as dicts (ns timestamps)."""
    return [{"name": n, "start_ns": t0, "duration_ns": dur, "depth": depth,
             "parent": parent, "thread": tid,
             **({"attrs": attrs} if attrs else {})}
            for (n, t0, dur, depth, parent, tid, attrs) in list(_ring)]


def nearest_rank(sorted_vals, q: float):
    """Nearest-rank percentile (q in [0, 1]) over a sorted list — the ONE
    quantile definition shared by span phase stats and
    ``registry.Histogram`` so both /metrics surfaces agree."""
    n = len(sorted_vals)
    if n == 0:
        return float("nan")
    rank = max(1, -(-int(q * 1000 * n) // 1000))  # ceil(q*n), int math
    return sorted_vals[min(n, rank) - 1]


_percentile = nearest_rank  # internal alias


def phase_stats() -> Dict[str, dict]:
    """Aggregate the ring into per-phase duration histograms:
    ``{name: {count, total_ms, mean_ms, p50_ms, p95_ms, p99_ms, max_ms}}``
    (sorted by name — deterministic for a given ring)."""
    per: Dict[str, List[int]] = {}
    for (name, _t0, dur, _d, _p, _tid, _a) in list(_ring):
        per.setdefault(name, []).append(dur)
    out = {}
    for name in sorted(per):
        ds = sorted(per[name])
        total = sum(ds)
        out[name] = {
            "count": len(ds),
            "total_ms": total / 1e6,
            "mean_ms": total / len(ds) / 1e6,
            "p50_ms": _percentile(ds, 0.50) / 1e6,
            "p95_ms": _percentile(ds, 0.95) / 1e6,
            "p99_ms": _percentile(ds, 0.99) / 1e6,
            "max_ms": ds[-1] / 1e6,
        }
    return out


def export_chrome_trace(path: str) -> str:
    """Write the ring as Chrome-trace JSON (complete "X" events, µs),
    loadable in chrome://tracing / Perfetto / TensorBoard's trace viewer.
    Returns ``path``."""
    pid = os.getpid()
    evts = []
    for (name, t0, dur, depth, parent, tid, attrs) in list(_ring):
        args = {"depth": depth}
        if parent:
            args["parent"] = parent
        if attrs:
            args.update(attrs)
        evts.append({"name": name, "ph": "X", "ts": t0 / 1e3,
                     "dur": dur / 1e3, "pid": pid, "tid": tid,
                     "args": args})
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": evts, "displayTimeUnit": "ms"}, f)
    return path
