"""Process-wide metrics registry: counters, gauges, histograms.

The training paths (and any user code) increment named metrics; export
surfaces (``/metrics`` on the UI server, JSONL dumps, the
``TelemetryListener`` StatsStorage bridge) read one deterministic
``snapshot()``. Collectors — callbacks registered with
``register_collector`` — inject point-in-time gauges (AOT-cache counters,
device-memory watermarks, host RSS) only when a snapshot/scrape actually
happens, so a quiet registry costs nothing per step.

Thread safety: metric creation is lock-guarded, and each metric guards
its own read-modify-write updates with a per-metric lock — the serving
path increments counters/histograms from many concurrent HTTP handler
and dispatcher threads, so GIL-interleavable ``value += n`` is not
enough. Histograms keep a bounded window of recent observations for
percentiles plus exact count/sum totals.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, List, Tuple


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label(v: str) -> str:
    """Prometheus exposition-format label escaping: backslash, quote,
    newline (label values are an open API — device names come from
    ``str(device)`` of an external library)."""
    return (v.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _format_labels(label_items) -> str:
    if not label_items:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in label_items)
    return "{" + inner + "}"


class Counter:
    """Monotonic counter (steps, examples, bytes)."""

    kind = "counter"

    def __init__(self, name: str, labels, help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def snapshot_value(self):
        return self.value


class Gauge:
    """Point-in-time value (memory watermark, bubble fraction)."""

    kind = "gauge"

    def __init__(self, name: str, labels, help: str = ""):
        self.name = name
        self.labels = labels
        self.help = help
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def snapshot_value(self):
        return self.value


class Histogram:
    """count/sum totals + a bounded window of recent observations for
    p50/p95/p99 (summary-style quantiles on scrape)."""

    kind = "histogram"

    def __init__(self, name: str, labels, help: str = "",
                 window: int = 2048):
        self.name = name
        self.labels = labels
        self.help = help
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._window = collections.deque(maxlen=int(window))
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self._window.append(v)

    def quantile(self, q: float) -> float:
        from deeplearning4j_tpu.telemetry.spans import nearest_rank

        return nearest_rank(sorted(self._window), q)

    def snapshot_value(self):
        if not self.count:
            return {"count": 0, "sum": 0.0}
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.min,
            "max": self.max,
            "mean": self.total / self.count,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[tuple, object] = {}
        self._collectors: List[Callable] = []

    # -- creation (get-or-create; name+labels identify the series) ----------
    def _get(self, cls, name: str, labels: dict, help: str, **kw):
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, _label_key(labels), help=help, **kw)
                    self._metrics[key] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}")
        return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, labels, help)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, labels, help)

    def histogram(self, name: str, help: str = "", window: int = 2048,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, help, window=window)

    # -- collectors ----------------------------------------------------------
    def register_collector(self, fn: Callable) -> Callable:
        """``fn(registry)`` runs before every snapshot/render (best-effort:
        a failing collector is skipped, never raises into a scrape).
        Idempotent by function identity."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)
        return fn

    def collect(self) -> None:
        for fn in list(self._collectors):
            try:
                fn(self)
            except Exception:
                pass  # a probe must never break a scrape

    # -- export --------------------------------------------------------------
    def snapshot(self, run_collectors: bool = True) -> dict:
        """Deterministic ``{name{labels}: value}`` dict — sorted keys,
        plain-JSON values — identical for identical recorded data."""
        if run_collectors:
            self.collect()
        with self._lock:  # a scrape must not race a first-seen metric
            items = sorted(self._metrics.items())
        out = {}
        for (name, labels), m in items:
            out[name + _format_labels(labels)] = m.snapshot_value()
        return out

    def render_prometheus(self, run_collectors: bool = True) -> str:
        """Prometheus text exposition (counters/gauges natively;
        histograms as summary quantiles + _sum/_count)."""
        if run_collectors:
            self.collect()
        with self._lock:  # see snapshot(): scrape vs first-seen insert
            items = sorted(self._metrics.items())
        by_name: Dict[str, list] = {}
        for (name, _labels), m in items:
            by_name.setdefault(name, []).append(m)
        lines = []
        for name, metrics in by_name.items():
            kind = metrics[0].kind
            if metrics[0].help:
                lines.append(f"# HELP {name} {metrics[0].help}")
            lines.append(f"# TYPE {name} "
                         f"{'summary' if kind == 'histogram' else kind}")
            for m in metrics:
                lbl = _format_labels(m.labels)
                if kind == "histogram":
                    base = dict(m.labels)
                    for q in (0.5, 0.95, 0.99):
                        ql = _format_labels(
                            _label_key(dict(base, quantile=q)))
                        lines.append(f"{name}{ql} {m.quantile(q):.9g}")
                    lines.append(f"{name}_sum{lbl} {m.total:.9g}")
                    lines.append(f"{name}_count{lbl} {m.count}")
                else:
                    lines.append(f"{name}{lbl} {m.snapshot_value():.9g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (collectors stay registered)."""
        with self._lock:
            self._metrics.clear()


REGISTRY = MetricsRegistry()
