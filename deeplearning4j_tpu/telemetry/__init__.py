"""Unified telemetry: step-trace spans, metrics registry, export surfaces.

Three pillars (docs/observability.md has the guided tour):

1. **Spans** (``telemetry.span("ingest"|"compute"|"grad_sync")``): a
   low-overhead, nesting-aware span API recording into a ring buffer;
   exported as Chrome-trace JSON and aggregated into per-phase
   p50/p95/p99 histograms. ``enable(sync=True)`` makes spans
   ``jax.block_until_ready`` their registered result so durations are
   true device times; the default async mode never syncs.
2. **Registry** (``telemetry.registry.REGISTRY``): process-wide
   counters/gauges/histograms — steps, examples, collective bytes,
   ingest bytes, pipeline bubble fraction — plus scrape-time collectors
   for AOT-cache stats, device-memory watermarks and host RSS.
3. **Export**: ``/metrics`` (Prometheus text) + ``/metrics.json`` on
   ``ui.server.UIServer``, a ``TelemetryListener`` bridging into
   ``ui.stats`` storages, and ``dump_jsonl`` for offline diffing.

The master switch gates every hot-path write: with telemetry disabled
(the default) each instrumented site costs ONE flag check — no
allocation, no lock, no host sync. Scrape surfaces (collectors,
``/metrics``) work even while disabled; only per-step recording stops.
"""

from __future__ import annotations

import weakref

from deeplearning4j_tpu.telemetry import flightrec as flightrec  # noqa: F401
from deeplearning4j_tpu.telemetry import health as health  # noqa: F401
from deeplearning4j_tpu.telemetry import registry as registry  # noqa: F401
from deeplearning4j_tpu.telemetry import slo as slo  # noqa: F401
from deeplearning4j_tpu.telemetry import spans as spans  # noqa: F401
from deeplearning4j_tpu.telemetry import tracing as tracing  # noqa: F401
from deeplearning4j_tpu.telemetry.flightrec import (  # noqa: F401
    FlightRecorder,
    flight_recorder,
)
from deeplearning4j_tpu.telemetry.health import (  # noqa: F401
    AnomalyPolicy,
    DivergenceError,
    HealthMonitor,
)
from deeplearning4j_tpu.telemetry.export import (  # noqa: F401
    TelemetryListener,
    dump_jsonl,
    telemetry_record,
)
from deeplearning4j_tpu.telemetry.registry import REGISTRY  # noqa: F401
from deeplearning4j_tpu.telemetry.spans import (  # noqa: F401
    PHASE_COMPUTE,
    PHASE_GRAD_SYNC,
    PHASE_HOST_GAP,
    PHASE_INGEST,
    PHASES,
    enable,
    enabled,
    disable,
    events,
    export_chrome_trace,
    host_gap_close,
    host_gap_open,
    host_gap_pause,
    host_gap_reset,
    host_gap_resume,
    host_gap_stop,
    phase_stats,
    span,
    sync_mode,
)


def reset() -> None:
    """Clear recorded spans, request traces AND metrics
    (flags/collectors untouched) — the per-test / per-bench-round zero
    point."""
    spans.reset()
    tracing.reset()
    REGISTRY.reset()


# --------------------------------------------------------------------------
# hot-path recording helpers (each is one flag check when disabled)
# --------------------------------------------------------------------------

def record_step(path: str, examples: int = 0, steps: int = 1) -> None:
    """Count one host dispatch's optimization steps (and examples) for a
    training path: ``multilayer`` / ``graph`` / ``samediff`` /
    ``parallel`` / ``pipeline``. A fused K-step super-step passes
    ``steps=K`` so the counters keep K=1 semantics (K steps, K*B
    examples per dispatch)."""
    if not spans._enabled:
        return
    REGISTRY.counter("dl4j_training_steps_total",
                     help="optimization steps", path=path).inc(steps)
    if examples:
        REGISTRY.counter("dl4j_training_examples_total",
                         help="examples consumed", path=path).inc(examples)


def record_collective(op: str, nbytes: float, buckets: int = 1) -> None:
    """Count one cross-replica exchange: ``nbytes`` = per-shard payload
    crossing the interconnect, ``buckets`` = collectives issued for it
    (1 = single fused all-reduce)."""
    if not spans._enabled:
        return
    REGISTRY.counter("dl4j_collective_bytes_total",
                     help="per-shard bytes exchanged", op=op).inc(nbytes)
    REGISTRY.counter("dl4j_collective_ops_total",
                     help="collectives issued", op=op).inc(buckets)


def record_bucket_layout(op: str, bucket_bytes_list) -> None:
    """Record a bucketed collective's layout (once per compiled schedule):
    bucket count gauge + per-bucket byte sizes histogram."""
    if not spans._enabled:
        return
    REGISTRY.gauge("dl4j_collective_buckets",
                   help="buckets in the collective schedule", op=op).set(
        len(bucket_bytes_list))
    h = REGISTRY.histogram("dl4j_collective_bucket_bytes",
                           help="per-bucket payload bytes", op=op)
    for b in bucket_bytes_list:
        h.observe(b)


def record_collective_plan(intent: str, choice: str, nbytes: float,
                           launches: int) -> None:
    """Record one freshly planned collective schedule
    (``comms.scheduler``): the ``dl4j_collective_plan_total{intent,
    choice}`` counter plus per-plan bytes/launches gauges feeding the UI
    System tab collective panel — the scheduler's CHOICES (variadic /
    densify / native all-gather vs masked psum) made observable per fit.
    Unconditional like the control-plane events below: plans resolve at
    trace time (once per unique layout per process), never per step."""
    REGISTRY.counter("dl4j_collective_plan_total",
                     help="collective plans built by the scheduler",
                     intent=intent, choice=choice).inc()
    REGISTRY.gauge("dl4j_collective_plan_bytes",
                   help="logical per-shard payload of the newest plan",
                   intent=intent).set(nbytes)
    REGISTRY.gauge("dl4j_collective_plan_launches",
                   help="collectives issued per exchange by the newest "
                        "plan", intent=intent).set(launches)


def record_ingest(nbytes: float, batches: int = 1) -> None:
    """Count host->device batch staging (DeviceRingIterator and friends)."""
    if not spans._enabled:
        return
    REGISTRY.counter("dl4j_ingest_batches_total",
                     help="batches staged to device").inc(batches)
    REGISTRY.counter("dl4j_ingest_bytes_total",
                     help="bytes staged to device").inc(nbytes)


def record_pipeline_schedule(n_stages: int, n_micro: int,
                             schedule: str) -> None:
    """Record a pipeline wrapper's static bubble fraction
    ``(S-1)/(S+M-1)`` — the drain/fill cost both GPipe and 1F1B
    (PipeDream-flush) schedules pay."""
    if not spans._enabled:
        return
    frac = (n_stages - 1) / max(n_stages + n_micro - 1, 1)
    REGISTRY.gauge("dl4j_pipeline_bubble_fraction",
                   help="(S-1)/(S+M-1) fill/drain bubble",
                   schedule=schedule).set(frac)
    REGISTRY.gauge("dl4j_pipeline_stages", schedule=schedule).set(n_stages)
    REGISTRY.gauge("dl4j_pipeline_microbatches",
                   schedule=schedule).set(n_micro)


def record_shard_bytes(param_bytes: float, opt_bytes: float,
                       mesh=None) -> None:
    """Publish the per-device parameter / optimizer-state footprint of
    the active placement (``dl4j_shard_param_bytes`` /
    ``dl4j_shard_opt_bytes``, one series per mesh device) — the gauge
    pair that makes ZeRO's per-chip memory saving MEASURABLE instead of
    asserted. Recorded unconditionally (placement happens once per
    ``fit``/plan resolve, never per step); with ``mesh=None`` a single
    unlabeled series is set."""
    devices = (list(mesh.devices.flat) if mesh is not None else [None])
    for d in devices:
        labels = {"device": str(d)} if d is not None else {}
        REGISTRY.gauge("dl4j_shard_param_bytes",
                       help="per-device parameter bytes under the "
                            "active sharding plan", **labels).set(
            param_bytes)
        REGISTRY.gauge("dl4j_shard_opt_bytes",
                       help="per-device optimizer-state bytes under "
                            "the active sharding plan", **labels).set(
            opt_bytes)


def record_step_seconds(seconds: float, path: str = "listener") -> None:
    """Observe one step duration into the registry histogram (the
    ProfilerListener / OpProfiler routing)."""
    if not spans._enabled:
        return
    REGISTRY.histogram("dl4j_step_seconds", help="host-observed step time",
                       path=path).observe(seconds)


# --------------------------------------------------------------------------
# serving metrics (parallel.batcher / parallel.serving)
#
# Unlike the per-step training helpers above these record UNCONDITIONALLY:
# a serving process wants its request/batch counters without opting into
# span recording, and one registry update per HTTP request (~1µs) is noise
# next to the network round-trip it measures. docs/serving.md lists the
# series.
# --------------------------------------------------------------------------

def record_serving_request(status: str, seconds: float = None,
                           model: str = None) -> None:
    """Count one inference request terminal state: ``ok`` / ``error`` /
    ``bad_request`` / ``rejected`` (queue full) / ``expired`` (deadline);
    ``seconds`` = submit-to-completion latency when the request made it
    into the queue. ``model`` labels the series for named (multi-tenant
    platform) engines; unnamed engines keep the unlabeled series."""
    labels = {"model": model} if model else {}
    REGISTRY.counter("dl4j_serving_requests_total",
                     help="inference requests by terminal status",
                     status=status, **labels).inc()
    if seconds is not None:
        REGISTRY.histogram("dl4j_serving_request_seconds",
                           help="submit-to-result request latency",
                           **labels).observe(seconds)


def record_serving_batch(rows: int, padded_rows: int, requests: int,
                         seconds: float, model: str = None) -> None:
    """Record one shared device launch: fill ratio (real rows / padded
    bucket rows), rows and coalesced-request histograms, launch time.
    ``model`` labels the series for named engines (per-tenant views)."""
    labels = {"model": model} if model else {}
    REGISTRY.counter("dl4j_serving_batches_total",
                     help="shared inference launches", **labels).inc()
    REGISTRY.histogram("dl4j_serving_batch_fill_ratio",
                       help="real rows / padded bucket rows",
                       **labels).observe(rows / max(padded_rows, 1))
    REGISTRY.histogram("dl4j_serving_batch_rows",
                       help="real rows per shared launch",
                       **labels).observe(rows)
    REGISTRY.histogram("dl4j_serving_batch_requests",
                       help="requests coalesced per launch",
                       **labels).observe(requests)
    REGISTRY.histogram("dl4j_serving_batch_seconds",
                       help="shared launch wall time",
                       **labels).observe(seconds)


def record_platform_event(event: str, model: str = None) -> None:
    """Count one platform control-plane event (``parallel.platform``):
    ``swap`` / ``canary_deploy`` / ``canary_rollback`` / ``promote`` /
    ``host_rejected`` — unconditional, these are rare lifecycle events,
    never per-request hot-path work. docs/serving.md lists the series."""
    labels = {"model": model} if model else {}
    REGISTRY.counter(f"dl4j_platform_{event}_total",
                     help="multi-tenant platform lifecycle events",
                     **labels).inc()


# --------------------------------------------------------------------------
# resilience metrics (resilience/: faults, retry, breaker, session)
#
# Unconditional like the serving helpers: these record rare control-plane
# events (a retry, a breaker trip, a resume, an injected fault), never
# per-step hot-path work — an operator wants them without opting into
# span recording. docs/resilience.md lists the series.
# --------------------------------------------------------------------------

def record_retry(op: str) -> None:
    """Count one scheduled retry (first attempts are not retries)."""
    REGISTRY.counter("dl4j_retries_total",
                     help="retries scheduled by RetryPolicy", op=op).inc()


def record_resume(scope: str = "job") -> None:
    """Count one TrainingSession resume from a snapshot.
    ``scope="job"`` = whole-process failure (preemption, injected step
    fault, crash restart); ``scope="host"`` = one pod host died
    (``HostDeathError`` at the ``pod.heartbeat`` site) and the whole
    job resumed from the last distributed snapshot."""
    REGISTRY.counter("dl4j_resumes_total",
                     help="training resumes from snapshot",
                     scope=scope).inc()


def record_pod_hosts(n_hosts: int) -> None:
    """Publish the pod shape (``dl4j_pod_hosts``) — how many hosts the
    active snapshot/training topology spans (1 = single-host; an
    emulated pod reports its emulated width)."""
    REGISTRY.gauge("dl4j_pod_hosts",
                   help="hosts in the active pod topology").set(n_hosts)


def record_pod_shard(host: int, nbytes: int, seconds: float) -> None:
    """One host's pod-snapshot shard written: per-host shard bytes
    gauge + shard write-time histogram."""
    REGISTRY.gauge("dl4j_pod_snapshot_shard_bytes",
                   help="bytes in this host's newest snapshot shard",
                   host=str(host)).set(nbytes)
    REGISTRY.histogram("dl4j_pod_shard_write_seconds",
                       help="per-host shard write time").observe(seconds)


def record_pod_snapshot_seconds(seconds: float) -> None:
    """One full distributed snapshot (all shards + manifests + the
    coordinator commit) observed into ``dl4j_pod_snapshot_seconds``."""
    REGISTRY.histogram("dl4j_pod_snapshot_seconds",
                       help="distributed snapshot wall time").observe(
        seconds)


def record_pod_restore_seconds(seconds: float) -> None:
    """One pod-snapshot restore (verify + aggregate + rebuild) observed
    into ``dl4j_pod_restore_seconds``."""
    REGISTRY.histogram("dl4j_pod_restore_seconds",
                       help="distributed restore wall time").observe(
        seconds)


def record_fault_injected(site: str, action: str) -> None:
    """Count one fired fault-plan injection."""
    REGISTRY.counter("dl4j_faults_injected_total",
                     help="deterministic fault injections fired",
                     site=site, action=action).inc()


def record_analysis_finding(rule: str, severity: str) -> None:
    """Count one unwaived static-analysis finding (the program linter
    records at compile time, so a live process's ``/metrics`` shows what
    lint saw without re-running the CLI). Unconditional like the other
    control-plane events: findings are per-compile, never per-step."""
    REGISTRY.counter("dl4j_analysis_findings_total",
                     help="static-analysis findings (analysis/ linters)",
                     rule=rule, severity=severity).inc()


def record_canary_accuracy(model: str, delta: float) -> None:
    """Record one canary accuracy-arm shadow compare
    (``parallel.platform``): the max-abs output delta between the canary
    (e.g. an int8 quantized version) and its f32 incumbent on one sampled
    request. Gauge = last observed delta; the counter tracks sample
    volume. Rate-bounded by ``CanaryGate.accuracy_sample``, and only
    active while a gated canary is live — not steady-state hot-path
    work."""
    REGISTRY.gauge("dl4j_canary_accuracy_delta",
                   help="last canary-vs-incumbent output delta",
                   model=model).set(float(delta))
    REGISTRY.counter("dl4j_canary_accuracy_samples_total",
                     help="canary accuracy-arm shadow compares",
                     model=model).inc()


def record_kernel_selected(kernel: str, shape_bucket: str) -> None:
    """Count one kernel-registry routing decision (``kernels.routing``):
    a tuned Pallas kernel was selected for a concrete shape class
    inside a fresh trace. Unconditional like the other control-plane
    events: selection happens at trace time (once per executable),
    never per step."""
    REGISTRY.counter("dl4j_kernel_selected_total",
                     help="tuned kernel selections at trace time",
                     kernel=kernel, shape_bucket=shape_bucket).inc()


def record_autotune_trial(kernel: str) -> None:
    """Count one autotuner candidate benchmark (``kernels.tuner``)."""
    REGISTRY.counter("dl4j_kernel_autotune_trials_total",
                     help="autotune candidate tilings benchmarked",
                     kernel=kernel).inc()


def record_autotune_winner(kernel: str) -> None:
    """Count one autotuner winner recorded into the tuning cache."""
    REGISTRY.counter("dl4j_kernel_autotune_winners_total",
                     help="autotune winners recorded", kernel=kernel).inc()


def record_tuning_cache(hits: int, entries: int) -> None:
    """Publish the kernel tuning cache's cumulative hit count and entry
    count (control-plane cadence: selection and autotune events)."""
    REGISTRY.gauge("dl4j_kernel_tuning_cache_hits",
                   help="tuning-cache winner lookups that hit").set(hits)
    REGISTRY.gauge("dl4j_kernel_tuning_cache_entries",
                   help="tuned envelopes in the cache").set(entries)


def record_slo_transition(tenant: str, to_state: str) -> None:
    """Count one SLO alert-state transition (``telemetry.slo``):
    unconditional like the other control-plane events — transitions are
    rare by construction (hysteresis), never per-request work. The
    current state/burn gauges are scrape-time collectors."""
    REGISTRY.counter("dl4j_slo_transitions_total",
                     help="SLO alert-state transitions",
                     tenant=tenant, to=to_state).inc()


def record_circuit_state(name: str, state_code: int,
                         transition: bool = True) -> None:
    """Publish a breaker's state (0=closed, 1=half_open, 2=open); counts
    the transition too unless this is the initial publish."""
    REGISTRY.gauge("dl4j_circuit_state",
                   help="0=closed 1=half_open 2=open",
                   breaker=name).set(state_code)
    if transition:
        REGISTRY.counter("dl4j_circuit_transitions_total",
                         help="breaker state transitions",
                         breaker=name, to=str(state_code)).inc()


# --------------------------------------------------------------------------
# generation metrics (parallel.generation — iteration-level continuous
# batching for autoregressive decode). Unconditional like the serving
# helpers: one registry update per decode ITERATION (not per token),
# noise next to a device dispatch. docs/serving.md lists the series.
# --------------------------------------------------------------------------

def record_decode_request(status: str, seconds: float = None,
                          model: str = None) -> None:
    """Count one generation-request terminal state (``ok`` / ``error`` /
    ``bad_request`` / ``rejected`` / ``expired`` / ``shed``);
    ``seconds`` = submit-to-last-token latency when it ran. ``model``
    labels the series for named (multi-tenant platform) engines."""
    labels = {"model": model} if model else {}
    REGISTRY.counter("dl4j_decode_requests_total",
                     help="generation requests by terminal status",
                     status=status, **labels).inc()
    if seconds is not None:
        REGISTRY.histogram("dl4j_decode_request_seconds",
                           help="submit-to-completion generation latency",
                           **labels).observe(seconds)


def record_decode_iteration(tokens: int, active_rows: int, capacity: int,
                            rows_in_use: int, k: int,
                            seconds: float) -> None:
    """One decode window: tokens actually emitted, running-batch
    occupancy, KV-cache rows in use, per-token latency (window wall
    time / K — the iteration-granularity inter-token latency)."""
    REGISTRY.counter("dl4j_decode_tokens_total",
                     help="tokens generated (all sequences)").inc(tokens)
    REGISTRY.gauge("dl4j_decode_batch_occupancy",
                   help="active rows / max_batch in the running "
                        "decode batch").set(
        active_rows / max(capacity, 1))
    REGISTRY.gauge("dl4j_decode_kv_rows_in_use",
                   help="KV-cache rows currently owned by sequences").set(
        rows_in_use)
    if k > 0:
        REGISTRY.histogram("dl4j_decode_token_seconds",
                           help="per-token decode latency "
                                "(window time / K)").observe(seconds / k)


def record_decode_prefill(rows: int, bucket_rows: int,
                          seconds: float) -> None:
    """One prefill launch: joining sequences, padded join-bucket fill,
    prompt-ingestion wall time (the prefill side of the prefill/decode
    split bench_decode.py reports). Each joining row samples its first
    token in the prefill launch, so those count as generated tokens."""
    REGISTRY.counter("dl4j_decode_prefills_total",
                     help="prompt prefill launches").inc()
    REGISTRY.counter("dl4j_decode_tokens_total",
                     help="tokens generated (all sequences)").inc(rows)
    REGISTRY.histogram("dl4j_decode_prefill_fill_ratio",
                       help="joining rows / padded join bucket").observe(
        rows / max(bucket_rows, 1))
    REGISTRY.histogram("dl4j_decode_prefill_seconds",
                       help="prefill launch wall time").observe(seconds)


def record_decode_first_token(seconds: float) -> None:
    """Time-to-first-token for one request (submit → prefill sample)."""
    REGISTRY.histogram("dl4j_decode_first_token_seconds",
                       help="submit-to-first-token latency").observe(
        seconds)


def record_prefix_cache(hits: int = 0, misses: int = 0, evictions: int = 0,
                        pages: int = None, hit_tokens: int = 0) -> None:
    """Radix prefix-cache accounting: lookups that matched at least one
    page vs cold misses, refcount-0 pages LRU-evicted, live page count
    after the operation, and prompt tokens whose prefill was skipped."""
    if hits:
        REGISTRY.counter("dl4j_prefix_cache_hits_total",
                         help="prompt lookups matching >=1 cached "
                              "page").inc(hits)
    if misses:
        REGISTRY.counter("dl4j_prefix_cache_misses_total",
                         help="prompt lookups with no cached "
                              "prefix").inc(misses)
    if evictions:
        REGISTRY.counter("dl4j_prefix_cache_evictions_total",
                         help="refcount-0 KV pages LRU-evicted").inc(
            evictions)
    if pages is not None:
        REGISTRY.gauge("dl4j_prefix_cache_pages",
                       help="live KV pages in the radix tree").set(pages)
    if hit_tokens:
        REGISTRY.counter("dl4j_prefix_cache_hit_tokens_total",
                         help="prompt tokens served from cached KV "
                              "(prefill skipped)").inc(hit_tokens)


def record_spec_window(accepted: int, k: int, emitted: int) -> None:
    """One speculative verify window: drafted-and-accepted tokens out of
    the K proposed (the acceptance histogram the bench reports), plus
    total emitted (accepted drafts + the verifier's own bonus token)."""
    REGISTRY.histogram("dl4j_spec_accepted_tokens",
                       help="draft tokens accepted per verify "
                            "window").observe(accepted)
    REGISTRY.counter("dl4j_spec_draft_tokens_total",
                     help="draft tokens proposed to the "
                          "verifier").inc(k)
    REGISTRY.counter("dl4j_spec_accepted_tokens_total",
                     help="draft tokens accepted by the "
                          "verifier").inc(accepted)
    REGISTRY.counter("dl4j_spec_emitted_tokens_total",
                     help="tokens emitted from verify windows "
                          "(accepted + bonus)").inc(emitted)


_SERVING_ENGINES = weakref.WeakSet()


def register_serving_engine(engine) -> None:
    """Track a live ``InferenceEngine``; ``dl4j_serving_queue_depth`` is
    collected at scrape time as the SUM over live engines, so several
    engines in one process (two servers, a restart's old+new pair) are
    additive instead of overwriting each other's gauge."""
    _SERVING_ENGINES.add(engine)


def unregister_serving_engine(engine) -> None:
    _SERVING_ENGINES.discard(engine)


_GENERATION_ENGINES = weakref.WeakSet()


def register_generation_engine(engine) -> None:
    """Track a live ``GenerationEngine`` for the scrape-time queue-depth
    collector (same additive multi-engine semantics as serving)."""
    _GENERATION_ENGINES.add(engine)


def unregister_generation_engine(engine) -> None:
    _GENERATION_ENGINES.discard(engine)


# --------------------------------------------------------------------------
# scrape-time collectors (run on snapshot/render, never per step)
# --------------------------------------------------------------------------

@REGISTRY.register_collector
def _collect_serving_queue_depth(reg) -> None:
    engines = list(_SERVING_ENGINES)
    if engines:
        reg.gauge("dl4j_serving_queue_depth",
                  help="pending serving requests").set(
            sum(e.queue_depth() for e in engines))


@REGISTRY.register_collector
def _collect_decode_queue_depth(reg) -> None:
    engines = list(_GENERATION_ENGINES)
    if engines:
        reg.gauge("dl4j_decode_queue_depth",
                  help="generation requests waiting for a cache row").set(
            sum(e.queue_depth() for e in engines))


@REGISTRY.register_collector
def _collect_slo_metrics(reg) -> None:
    for mon in slo.monitors():
        for tenant, snap in mon.snapshot().items():
            reg.gauge("dl4j_slo_state",
                      help="0=ok 1=warn 2=page",
                      tenant=tenant).set(slo.STATE_CODE[snap["state"]])
            for objective, b in snap["burn_rates"].items():
                for window in ("short", "long"):
                    reg.gauge("dl4j_slo_burn_rate",
                              help="violation fraction / objective "
                                   "budget per rolling window",
                              tenant=tenant, objective=objective,
                              window=window).set(b[window])


@REGISTRY.register_collector
def _collect_aot_cache(reg) -> None:
    from deeplearning4j_tpu.optimize import aot_cache

    st = aot_cache.stats()
    for k in ("hits", "misses", "entries", "fallbacks", "overflows"):
        reg.gauge(f"dl4j_aot_cache_{k}",
                  help="AOT step-executable cache").set(st[k])
    reg.gauge("dl4j_aot_cache_compile_seconds_total").set(
        st["compile_seconds"])
    total = st["hits"] + st["misses"]
    reg.gauge("dl4j_aot_cache_hit_ratio",
              help="hits / (hits + misses)").set(
        st["hits"] / total if total else 0.0)


@REGISTRY.register_collector
def _collect_device_memory(reg) -> None:
    import jax

    for d in jax.local_devices():
        try:
            ms = d.memory_stats() or {}
        except Exception:
            ms = {}
        if "bytes_in_use" in ms:
            reg.gauge("dl4j_device_bytes_in_use", device=str(d)).set(
                ms["bytes_in_use"])
        if "peak_bytes_in_use" in ms:
            reg.gauge("dl4j_device_peak_bytes",
                      help="HBM high-watermark", device=str(d)).set(
                ms["peak_bytes_in_use"])
    try:
        live = jax.live_arrays()
        reg.gauge("dl4j_live_arrays",
                  help="process-wide live jax.Array handles").set(len(live))
        reg.gauge("dl4j_live_array_bytes").set(
            sum(getattr(a, "nbytes", 0) or 0 for a in live))
    except Exception:
        pass


@REGISTRY.register_collector
def _collect_host_memory(reg) -> None:
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        import os

        reg.gauge("dl4j_host_rss_bytes").set(
            rss_pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        pass


def prometheus_text() -> str:
    """The full ``/metrics`` payload: registry metrics + span phase
    histograms rendered as summaries."""
    text = REGISTRY.render_prometheus()
    phases = phase_stats()
    if phases:
        lines = ["# TYPE dl4j_phase_ms summary"]
        for name, st in phases.items():
            for q in ("p50", "p95", "p99"):
                lines.append(
                    f'dl4j_phase_ms{{phase="{name}",quantile='
                    f'"0.{q[1:]}"}} {st[f"{q}_ms"]:.9g}')
            lines.append(f'dl4j_phase_ms_sum{{phase="{name}"}} '
                         f'{st["total_ms"]:.9g}')
            lines.append(f'dl4j_phase_ms_count{{phase="{name}"}} '
                         f'{st["count"]}')
        text += "\n".join(lines) + "\n"
    return text
