"""End-to-end request tracing: where did this REQUEST's time go.

The phase spans (:mod:`telemetry.spans`) answer "where did this *step's*
time go"; this module answers the serving-side question — one record per
request covering submit → terminal, with lifecycle events at every
scheduler stage (batcher: queued → admitted → grouped → launched →
demuxed → done/shed/expired; generation: queued → prefix_attach/prefill
→ join → each fused decode window → retire/rollback).

Discipline (same contract as spans):

- DISABLED (default) costs ONE module-flag check at submit: callers hold
  ``None`` and every helper here no-ops on ``None``. Nothing on the
  tracing path touches device values — events are ``monotonic_ns`` reads
  plus list appends, recorded by whichever host thread owns the request
  at that stage — so greedy generation stays token-identical and the
  zero-recompiles-after-warmup invariant holds with tracing on or off.
- Trace ids are W3C ``traceparent``-shaped (32-hex trace id, 16-hex span
  id). Inbound headers are adopted; otherwise ids are minted as a pure
  function of ``(seed, submit counter)`` so two seeded replays mint
  IDENTICAL ids — which makes the tail sampler replay-deterministic too.
- Finished traces land in BOUNDED rings with deterministic tail
  sampling: abnormal terminals (anything but ok/done) are ALWAYS kept,
  the slowest-percentile traces are kept (nearest-rank threshold over a
  rolling duration window; count-gated so the rule is reproducible), and
  normal traces are head-sampled by trace-id hash (``1/sample_every``).
- ``finish_trace`` is idempotent: the FIRST terminal edge wins, so the
  dispatcher/watchdog/close races that :mod:`parallel.batcher` already
  resolves for result delivery cannot double-report a trace.

Export is ``export_chrome_trace``-compatible JSON (one ``X`` slice per
request plus ``i`` instants per lifecycle event) — the same
``chrome://tracing`` / Perfetto flow as the phase spans.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.telemetry.spans import nearest_rank

# terminal statuses that are NEVER sampled away: a failed request's
# timeline is exactly the one post-mortems need
ABNORMAL_STATUSES = frozenset({
    "error", "shed", "rejected", "bad_request", "expired", "timeout",
    "rollback", "shutdown", "cancelled",
})

_enabled = False
_lock = threading.Lock()
_seed = 0
_counter = 0
_sample_every = 16
_slow_quantile = 0.95
_min_slow_samples = 16
_started = 0
_finished = 0
_dropped = 0
_kept: collections.deque = collections.deque(maxlen=256)   # abnormal
_slow: collections.deque = collections.deque(maxlen=256)   # slow tail
_ring: collections.deque = collections.deque(maxlen=256)   # head sample
_durations: collections.deque = collections.deque(maxlen=512)


class Trace:
    """One request's timeline: identity + ordered lifecycle events.

    Created by :func:`start_trace` (``None`` when tracing is disabled),
    carried on the request object across threads (submit thread →
    dispatcher/decode thread), finished exactly once by
    :func:`finish_trace`.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "index",
                 "t0_ns", "t1_ns", "status", "events", "attrs")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, index: int,
                 attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.index = index
        self.t0_ns = time.monotonic_ns()
        self.t1_ns: Optional[int] = None
        self.status: Optional[str] = None
        self.events: List[Tuple[str, int, Optional[dict]]] = []
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}

    def event(self, name: str, attrs: Optional[dict] = None) -> None:
        self.events.append((name, time.monotonic_ns(), attrs))

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def duration_ms(self) -> Optional[float]:
        if self.t1_ns is None:
            return None
        return (self.t1_ns - self.t0_ns) / 1e6

    def event_ns(self, name: str) -> Optional[int]:
        for n, t, _ in self.events:
            if n == name:
                return t
        return None

    def as_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "index": self.index, "status": self.status,
            "duration_ms": self.duration_ms(), "attrs": dict(self.attrs),
            "events": [
                {"name": n, "ms": (t - self.t0_ns) / 1e6, "attrs": a or {}}
                for n, t, a in self.events],
        }


# --------------------------------------------------------------------------
# W3C traceparent
# --------------------------------------------------------------------------

def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """``00-<32hex>-<16hex>-<2hex>`` → ``(trace_id, parent_span_id)``;
    malformed / all-zero / version ``ff`` headers are rejected (the
    request then mints a fresh root trace)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    ver, tid, sid, flags = parts[0], parts[1], parts[2], parts[3]
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(ver, 16), int(tid, 16), int(sid, 16), int(flags, 16)
    except ValueError:
        return None
    if ver == "ff" or set(tid) == {"0"} or set(sid) == {"0"}:
        return None
    return tid, sid


# --------------------------------------------------------------------------
# lifecycle
# --------------------------------------------------------------------------

def enable(seed: int = 0, ring_size: int = 256, sample_every: int = 16,
           slow_quantile: float = 0.95, duration_window: int = 512,
           min_slow_samples: int = 16) -> None:
    """Arm request tracing. Clears the rings and resets the id counter,
    so ``enable(seed=S)`` at the top of two replays yields identical
    trace ids AND identical sampling decisions."""
    global _enabled, _seed, _counter, _sample_every, _slow_quantile
    global _min_slow_samples, _kept, _slow, _ring, _durations
    global _started, _finished, _dropped
    with _lock:
        _seed = seed
        _counter = 0
        _sample_every = max(1, int(sample_every))
        _slow_quantile = slow_quantile
        _min_slow_samples = max(1, int(min_slow_samples))
        _kept = collections.deque(maxlen=ring_size)
        _slow = collections.deque(maxlen=ring_size)
        _ring = collections.deque(maxlen=ring_size)
        _durations = collections.deque(maxlen=duration_window)
        _started = _finished = _dropped = 0
    _enabled = True


def disable() -> None:
    """Disarm tracing. The rings survive so a bench can run, disable,
    then export."""
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear rings + counters; the enabled flag is untouched."""
    global _counter, _started, _finished, _dropped
    with _lock:
        _counter = 0
        _started = _finished = _dropped = 0
        _kept.clear()
        _slow.clear()
        _ring.clear()
        _durations.clear()


def start_trace(name: str, traceparent: Optional[str] = None,
                attrs: Optional[dict] = None) -> Optional[Trace]:
    """Mint (or adopt, when ``traceparent`` parses) a request trace.
    Returns ``None`` when tracing is disabled — the one flag check the
    disabled path pays."""
    if not _enabled:
        return None
    global _counter, _started
    parent_id = None
    tid = None
    parsed = parse_traceparent(traceparent) if traceparent else None
    if parsed is not None:
        tid, parent_id = parsed
    with _lock:
        n = _counter
        _counter += 1
        _started += 1
    h = hashlib.sha256(f"{_seed}:{n}".encode()).hexdigest()
    if tid is None:
        tid = h[:32]
    return Trace(tid, h[32:48], parent_id, name, n, attrs)


def trace_event(trace: Optional[Trace], name: str,
                attrs: Optional[dict] = None) -> None:
    if trace is None:
        return
    trace.event(name, attrs)


def finish_trace(trace: Optional[Trace], status: str,
                 attrs: Optional[dict] = None) -> None:
    """Terminal edge: stamp status + end time and run the tail sampler.
    Idempotent — the first terminal edge wins, later calls no-op."""
    if trace is None:
        return
    global _finished, _dropped
    with _lock:
        if trace.status is not None:
            return
        trace.status = status
        trace.t1_ns = time.monotonic_ns()
        if attrs:
            trace.attrs.update(attrs)
        _finished += 1
        dur = trace.t1_ns - trace.t0_ns
        _durations.append(dur)
        if status not in ("ok", "done"):
            _kept.append(trace)
        elif len(_durations) >= _min_slow_samples \
                and dur >= nearest_rank(sorted(_durations),
                                        _slow_quantile):
            _slow.append(trace)
        elif int(trace.trace_id[:8], 16) % _sample_every == 0:
            _ring.append(trace)
        else:
            _dropped += 1


# --------------------------------------------------------------------------
# read side
# --------------------------------------------------------------------------

def traces() -> List[Trace]:
    """Every retained trace (abnormal + slow tail + head sample), in
    submit order."""
    with _lock:
        out = list(_kept) + list(_slow) + list(_ring)
    return sorted(out, key=lambda t: t.t0_ns)


def stats() -> dict:
    with _lock:
        return {
            "enabled": _enabled, "started": _started,
            "finished": _finished, "dropped": _dropped,
            "kept_abnormal": len(_kept), "kept_slow": len(_slow),
            "kept_sampled": len(_ring), "seed": _seed,
            "sample_every": _sample_every,
        }


def snapshot() -> dict:
    """JSON-ready view for the ``/traces`` endpoint."""
    return {"stats": stats(), "traces": [t.as_dict() for t in traces()]}


def export_chrome_trace(path: Optional[str] = None) -> dict:
    """Chrome-trace JSON: one ``X`` slice per request (tid = submit
    index, so concurrent requests get their own rows) plus an ``i``
    instant per lifecycle event. Same viewer flow as
    ``spans.export_chrome_trace``."""
    pid = os.getpid()
    evs = []
    for tr in traces():
        t1 = tr.t1_ns if tr.t1_ns is not None else tr.t0_ns
        evs.append({
            "name": f"req:{tr.name}", "ph": "X", "cat": "request",
            "ts": tr.t0_ns / 1e3, "dur": (t1 - tr.t0_ns) / 1e3,
            "pid": pid, "tid": tr.index,
            "args": {"trace_id": tr.trace_id, "status": tr.status,
                     **tr.attrs},
        })
        for name, t, attrs in tr.events:
            evs.append({
                "name": name, "ph": "i", "s": "t", "cat": "request",
                "ts": t / 1e3, "pid": pid, "tid": tr.index,
                "args": dict(attrs) if attrs else {},
            })
    doc = {"traceEvents": evs, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc


# --------------------------------------------------------------------------
# stage breakdown (the benches' trace-derived report)
# --------------------------------------------------------------------------

def _quant(vals: List[float]) -> Optional[dict]:
    if not vals:
        return None
    s = sorted(vals)
    return {"mean_ms": round(sum(s) / len(s), 4),
            "p50_ms": round(nearest_rank(s, 0.50), 4),
            "p95_ms": round(nearest_rank(s, 0.95), 4),
            "count": len(s)}


def stage_breakdown() -> dict:
    """Aggregate per-stage waits across retained traces: queue wait
    (submit → first launch/prefill activity), batch wait (grouped →
    launched), launch time (launched → demuxed), and per-window decode
    time (from ``decode_window`` event attrs). Sampling applies — this
    summarizes the RETAINED population, not every request."""
    queue_w, batch_w, launch, windows, totals = [], [], [], [], []
    for tr in traces():
        first_work = None
        for probe in ("launched", "prefill", "prefix_attach"):
            t = tr.event_ns(probe)
            if t is not None and (first_work is None or t < first_work):
                first_work = t
        if first_work is not None:
            queue_w.append((first_work - tr.t0_ns) / 1e6)
        tg, tl = tr.event_ns("grouped"), tr.event_ns("launched")
        if tg is not None and tl is not None:
            batch_w.append((tl - tg) / 1e6)
        td = tr.event_ns("demuxed")
        if tl is not None and td is not None:
            launch.append((td - tl) / 1e6)
        for name, _, attrs in tr.events:
            if name == "decode_window" and attrs and "ms" in attrs:
                windows.append(attrs["ms"])
        d = tr.duration_ms()
        if d is not None:
            totals.append(d)
    return {
        "traces": len(totals),
        "queue_wait": _quant(queue_w),
        "batch_wait": _quant(batch_w),
        "launch": _quant(launch),
        "decode_window": _quant(windows),
        "total": _quant(totals),
    }
