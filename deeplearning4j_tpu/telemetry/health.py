"""Training health: in-graph non-finite guards + host-side anomaly policy.

PR 3 gave the framework eyes for *time*; this module gives it eyes for
*numerical health*. Two halves:

1. **In-graph guards** (:func:`guard_vector`, :func:`apply_skip`): pure
   jnp reductions folded INTO the jitted train step — isfinite checks of
   the loss, a non-finite gradient element count, the global gradient
   norm, per-bucket (top-level-key) gradient norms, and the
   update:param norm ratio — packed into ONE small f32 vector returned
   alongside the loss. The vector rides the step's output, so reading it
   costs no extra device→host sync beyond the score fetch the training
   loop already performs. ``SKIP_STEP`` is applied in-graph too
   (``jnp.where(ok, new, old)`` over the params/state/opt trees), so a
   poisoned update never reaches the parameters even in fully-async
   training.
2. **Host-side policy** (:class:`HealthMonitor`): consumes guard vectors
   and applies the configured :class:`AnomalyPolicy` — ``WARN`` (count +
   registry metrics, lazily batched so nothing syncs per step),
   ``SKIP_STEP`` (the in-graph skip plus lazy counting), ``ROLLBACK``
   (restore the last-good snapshot via ``optimize.checkpoint``'s
   snapshot helpers) and ``HALT`` (raise :class:`DivergenceError`).
   ROLLBACK/HALT inherently check per step and therefore sync per step;
   WARN/SKIP_STEP never do.

The module-level mode is the build-time contract: step builders read
:func:`graph_mode` when compiling (and fold :func:`cache_tag` into their
AOT-cache step-kind key, so guarded and unguarded executables never
collide), and the fit loops rebuild their cached step when the mode
changes. Disabled (the default), every instrumented site costs one flag
check — the same contract as the span layer.
"""

from __future__ import annotations

import enum
import math
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.telemetry.registry import REGISTRY

# guard-vector layout: fixed head, then one global-norm entry per bucket
# (top-level gradient key). Aggregations across segments/replicas take
# the elementwise MAX, so every entry is oriented as "bigger = worse".
GUARD_LOSS = 0            # loss value (max across aggregated steps)
GUARD_LOSS_NONFINITE = 1  # 1.0 when the loss is NaN/Inf
GUARD_GRAD_NONFINITE = 2  # 1.0 when any gradient element is NaN/Inf
GUARD_GRAD_NORM = 3       # global L2 gradient norm
GUARD_UPDATE_NORM = 4     # L2 norm of (new_params - params)
GUARD_PARAM_NORM = 5      # L2 norm of params
GUARD_RATIO = 6           # update_norm / (param_norm + 1e-12)
GUARD_HEAD = 7


class AnomalyPolicy(enum.Enum):
    """What the monitor does on a non-finite loss/gradient step
    (reference has nothing comparable — a NaN silently reaches the score
    printout; here detection happens on the step it occurs)."""

    WARN = "warn"              # count + log, training continues
    SKIP_STEP = "skip_step"    # in-graph: discard the update, keep params
    ROLLBACK = "rollback"      # restore the last-good snapshot
    HALT = "halt"              # raise DivergenceError


class DivergenceError(RuntimeError):
    """Raised by the HALT policy (and by ROLLBACK with no snapshot to
    restore). Carries the host guard vector for post-mortem."""

    def __init__(self, msg: str, vec=None, step: Optional[int] = None,
                 path: str = ""):
        super().__init__(msg)
        self.vec = None if vec is None else list(np.asarray(vec, float))
        self.step = step
        self.path = path


# ---------------------------------------------------------------------------
# in-graph guard math (pure jnp — call INSIDE the jitted step)
# ---------------------------------------------------------------------------

def bucket_keys(grads) -> Tuple[str, ...]:
    """Static per-bucket key order for :func:`guard_vector`'s tail — the
    sorted top-level keys of a dict gradient tree, or a single synthetic
    bucket for anything else (flat vectors, lists)."""
    if isinstance(grads, dict) and grads:
        return tuple(sorted(grads))
    return ("all",)


def _float_leaves(tree):
    import jax
    import jax.numpy as jnp

    return [l for l in jax.tree_util.tree_leaves(tree)
            if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]


def guard_vector(loss, grads, params=None, new_params=None):
    """The packed health vector (f32, ``GUARD_HEAD + n_buckets`` wide).

    Hot-path cost: ONE squared-sum reduction per gradient leaf (plus
    one diff-reduce and one sum-reduce per param leaf when the
    update/param norms are requested) — the non-finite flag derives
    from the reductions themselves (NaN/Inf propagate through a sum),
    so there is no separate ``isfinite`` pass over the tensors. All
    reductions are in f32 regardless of the compute dtype, and the
    vector is just one more (tiny) step output — no host sync.
    ``params``/``new_params`` enable the update/param-norm entries;
    omitted they stay 0."""
    import jax.numpy as jnp

    f32 = jnp.float32
    keys = bucket_keys(grads)
    bucket_sq = []
    for k in keys:
        sub = grads[k] if (isinstance(grads, dict) and k in grads) else grads
        sq = f32(0.0)
        for l in _float_leaves(sub):
            l32 = l.astype(f32)
            sq = sq + jnp.sum(l32 * l32)
        bucket_sq.append(sq)
    # any NaN/Inf gradient element poisons its squared sum (an f32
    # OVERFLOW of the sum also trips this — a gradient with norm > ~2e19
    # is an anomaly by any definition); the tail assembly is shared with
    # the pre-reduced path so the two can never desynchronize
    return guard_vector_from_sq(loss, bucket_sq, params=params,
                                new_params=new_params)


def guard_vector_from_sq(loss, bucket_sq, params=None, new_params=None):
    """:func:`guard_vector` built from PRE-REDUCED per-bucket squared
    sums (an ordered list matching :func:`bucket_keys`). The ZeRO
    wrapper computes squared sums on its reduce-scattered gradient
    slices and psums them — this finishes the vector with the exact
    same layout/semantics as the dense-gradient path, so the monitor
    never needs to know which exchange produced the numbers."""
    import jax.numpy as jnp

    f32 = jnp.float32
    bucket_sq = [jnp.asarray(b, f32) for b in bucket_sq]
    total_sq = sum(bucket_sq) if bucket_sq else f32(0.0)
    grad_nf = (~jnp.isfinite(total_sq)).astype(f32)
    loss32 = jnp.asarray(loss).astype(f32)
    loss_nf = (~jnp.isfinite(loss32)).astype(f32)
    if params is not None and new_params is not None:
        upd_sq = sum(jnp.sum((n.astype(f32) - o.astype(f32)) ** 2)
                     for n, o in zip(_float_leaves(new_params),
                                     _float_leaves(params)))
        par_sq = sum(jnp.sum(l.astype(f32) ** 2)
                     for l in _float_leaves(params))
        unorm = jnp.sqrt(upd_sq)
        pnorm = jnp.sqrt(par_sq)
    else:
        unorm = pnorm = f32(0.0)
    ratio = unorm / (pnorm + 1e-12)
    return jnp.stack([loss32, loss_nf, grad_nf, jnp.sqrt(total_sq),
                      unorm, pnorm, ratio]
                     + [jnp.sqrt(sq) for sq in bucket_sq])


def loss_guard_vector(loss):
    """Loss-only guard (no gradient access) for paths whose compiled step
    cannot cheaply expose gradients (pipeline stages, expert-parallel):
    same layout, gradient entries 0."""
    import jax.numpy as jnp

    f32 = jnp.float32
    loss32 = jnp.asarray(loss).astype(f32)
    z = jnp.zeros((), f32)
    return jnp.stack([loss32, (~jnp.isfinite(loss32)).astype(f32),
                      z, z, z, z, z, z])


_loss_guard_jit = None


def loss_guard(loss):
    """Host-callable loss-only guard: one tiny jitted isfinite reduction
    dispatched on the (already queued) device loss — detection on the
    step it occurs with no extra sync (the monitor decides when to
    materialize)."""
    global _loss_guard_jit
    if _loss_guard_jit is None:
        import jax

        _loss_guard_jit = jax.jit(loss_guard_vector)
    return _loss_guard_jit(loss)


def vec_ok(vec):
    """In-graph: True scalar when the step is numerically healthy."""
    return (vec[GUARD_LOSS_NONFINITE] + vec[GUARD_GRAD_NONFINITE]) == 0


def apply_skip(vec, new_trees, old_trees):
    """In-graph SKIP_STEP: select ``new`` leaves on a healthy step, keep
    ``old`` on an anomalous one (elementwise where — composes with
    donation and sharding). ``*_trees`` are matching tuples of pytrees
    (params, state, opt, ...)."""
    import jax
    import jax.numpy as jnp

    ok = vec_ok(vec)
    return tuple(
        jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), nt, ot)
        for nt, ot in zip(new_trees, old_trees))


def combine(stacked_vecs):
    """Aggregate stacked guard vectors ([n, G], e.g. one per tBPTT
    segment) into one: elementwise max (every entry is
    bigger-is-worse)."""
    import jax.numpy as jnp

    return jnp.max(stacked_vecs, axis=0)


def combine_across(vec, axis_name):
    """Aggregate one guard vector across a shard_map/pmap axis (pmax —
    any replica's anomaly is the step's anomaly)."""
    import jax

    return jax.lax.pmax(vec, axis_name)


# ---------------------------------------------------------------------------
# module mode (build-time contract for the step builders)
# ---------------------------------------------------------------------------

_MODE = ""  # "" disabled | "observe" | "skip"


def graph_mode() -> str:
    """What the compiled step must contain: ``""`` (no guards),
    ``"observe"`` (guard vector returned), ``"skip"`` (guard vector +
    in-graph SKIP_STEP select). Step builders capture this at build time;
    fit loops rebuild when it changes."""
    return _MODE


def cache_tag() -> str:
    """AOT-cache step-kind suffix — guarded and unguarded executables
    must never share a cache entry."""
    return f"+h{_MODE}" if _MODE else ""


def enabled() -> bool:
    return bool(_MODE)


# ---------------------------------------------------------------------------
# host-side monitor
# ---------------------------------------------------------------------------

class HealthMonitor:
    """Applies the anomaly policy to guard vectors.

    WARN / SKIP_STEP are **lazy**: vectors queue as device scalars and
    materialize in one stacked transfer every ``flush_every`` steps (or
    on ``report()``/``flush()``), so the async fit pipeline never gains
    a per-step sync. ROLLBACK / HALT materialize per step — remediation
    cannot be deferred.

    Snapshots for ROLLBACK are taken every ``snapshot_every`` healthy
    steps through the owner's ``_health_snapshot``/``_health_restore``
    hooks (networks delegate to ``optimize.checkpoint``'s
    ``snapshot_training_state``/``restore_training_state``)."""

    def __init__(self, policy: AnomalyPolicy = AnomalyPolicy.WARN,
                 flush_every: int = 64, snapshot_every: int = 10):
        self.policy = policy
        self.flush_every = max(1, int(flush_every))
        self.snapshot_every = max(1, int(snapshot_every))
        self._lock = threading.RLock()
        self.reset()

    def reset(self):
        with self._lock:
            self.steps = 0
            self.nonfinite_steps = 0
            self.skipped_steps = 0
            self.rollbacks = 0
            self.halted = False
            self.last_vec: Optional[List[float]] = None
            self.last_keys: Tuple[str, ...] = ()
            self.last_anomaly_step: Optional[int] = None
            self._pending: List[tuple] = []
            self._pending_steps = 0  # entries weighted by their K

    # --- recording ----------------------------------------------------------
    def on_step(self, vec, keys: Sequence[str] = (), path: str = "",
                owner=None,
                snapshot: Optional[Callable[[], object]] = None,
                restore: Optional[Callable[[object], None]] = None,
                skipped: Optional[bool] = None) -> str:
        """Feed one step's guard vector (a device array). Returns the
        action taken: ``"none"``, ``"skip"``, ``"rollback"``; HALT
        raises. ``owner`` hosts the rollback snapshot (stored on the
        object itself, so monitor state never pins a dead model).
        ``skipped``: whether an anomalous update was actually discarded
        in-graph — paths without the in-graph select (pipeline,
        expert-parallel) pass False so ``skipped_steps`` never claims a
        discard that didn't happen; None = derived from the policy."""
        if skipped is None:
            skipped = self.policy is AnomalyPolicy.SKIP_STEP
        self.steps += 1
        lazy = self.policy in (AnomalyPolicy.WARN, AnomalyPolicy.SKIP_STEP)
        if lazy:
            self._pending.append((vec, tuple(keys), path, self.steps,
                                  skipped))
            self._pending_steps += 1
            if self._pending_steps >= self.flush_every:
                self.flush()
            return "none"
        # ROLLBACK / HALT: the decision must happen on the step it occurs
        v = np.asarray(vec, np.float64)
        anomalous = (v[GUARD_LOSS_NONFINITE] + v[GUARD_GRAD_NONFINITE]) > 0
        self._observe_host([(v, tuple(keys), path, self.steps, skipped)])
        if not anomalous:
            self._maybe_snapshot(owner, snapshot)
            return "none"
        return self._remediate(v, keys, path, self.steps, "step", owner,
                               restore)

    def _remediate(self, v, keys, path, step: int, frag: str, owner,
                   restore) -> str:
        """The shared ROLLBACK/HALT tail for the single- and fused-step
        paths: restore-or-raise with ``frag`` naming the offending step
        ("step" for K=1; "step N (step j/K of the fused super-step)"
        for a fused dispatch)."""
        if self.policy is AnomalyPolicy.ROLLBACK:
            tag = getattr(owner, "_health_last_good", None) \
                if owner is not None else None
            if tag is None or restore is None:
                self.halted = True
                raise DivergenceError(
                    f"non-finite {frag} on path {path!r} with ROLLBACK "
                    "policy but no last-good snapshot to restore "
                    f"(guard={self._describe(v, keys)})",
                    vec=v, step=step, path=path)
            restore(tag[0])
            self.rollbacks += 1
            REGISTRY.counter("dl4j_rollbacks_total",
                             help="health-policy snapshot restores",
                             path=path).inc()
            return "rollback"
        self.halted = True
        REGISTRY.counter("dl4j_halts_total",
                         help="DivergenceError raises", path=path).inc()
        raise DivergenceError(
            f"non-finite training {frag} on path {path!r} "
            f"(guard={self._describe(v, keys)})",
            vec=v, step=step, path=path)

    def _maybe_snapshot(self, owner, snapshot):
        """Healthy-step ROLLBACK snapshot cadence (shared by the single-
        and fused-step paths)."""
        if self.policy is AnomalyPolicy.ROLLBACK and owner is not None \
                and snapshot is not None:
            tag = getattr(owner, "_health_last_good", None)
            # tag[1] > steps = a leftover from before a monitor
            # reset — refresh rather than trust an ancient snapshot
            if tag is None or tag[1] > self.steps \
                    or self.steps - tag[1] >= self.snapshot_every:
                owner._health_last_good = (snapshot(), self.steps)

    def on_steps(self, vecs, k: int, keys: Sequence[str] = (),
                 path: str = "", owner=None,
                 snapshot: Optional[Callable[[], object]] = None,
                 restore: Optional[Callable[[object], None]] = None,
                 skipped: Optional[bool] = None) -> str:
        """Feed one fused super-step's stacked guard vectors (a [K, G]
        device array; row j = step j of the scan's ys). Counting
        semantics match K :meth:`on_step` calls — WARN/SKIP queue the
        stack as ONE pending entry (no extra host sync; the K rows are
        unpacked at flush time). ROLLBACK/HALT resolve at SUPER-STEP
        granularity: the compiled scan has already run all K steps when
        the vector surfaces, so remediation restores/raises for the
        whole super-step, with the first offending step's global index
        surfaced in the error/report."""
        k = int(k)
        if skipped is None:
            skipped = self.policy is AnomalyPolicy.SKIP_STEP
        first = self.steps + 1
        self.steps += k
        lazy = self.policy in (AnomalyPolicy.WARN, AnomalyPolicy.SKIP_STEP)
        if lazy:
            self._pending.append((vecs, tuple(keys), path, self.steps,
                                  skipped))
            # the cadence counts STEPS, not queue entries: a K-step
            # stack weighs K, so detection latency matches K=1
            self._pending_steps += k
            if self._pending_steps >= self.flush_every:
                self.flush()
            return "none"
        # ROLLBACK / HALT: the decision happens on the super-step it
        # occurs (one stacked transfer)
        v = np.asarray(vecs, np.float64).reshape(k, -1)
        self._observe_host([(v, tuple(keys), path, self.steps, skipped)])
        bad = np.flatnonzero((v[:, GUARD_LOSS_NONFINITE]
                              + v[:, GUARD_GRAD_NONFINITE]) > 0)
        if bad.size == 0:
            self._maybe_snapshot(owner, snapshot)
            return "none"
        j = int(bad[0])
        offending = first + j
        return self._remediate(
            v[j], tuple(keys), path, offending,
            f"step {offending} (step {j + 1}/{k} of the fused "
            "super-step)", owner, restore)

    def _describe(self, v, keys) -> str:
        parts = [f"loss={v[GUARD_LOSS]:.4g}",
                 f"loss_nonfinite={int(v[GUARD_LOSS_NONFINITE])}",
                 f"grad_nonfinite={int(v[GUARD_GRAD_NONFINITE])}",
                 f"grad_norm={v[GUARD_GRAD_NORM]:.4g}"]
        bad = [k for k, n in zip(keys, v[GUARD_HEAD:])
               if not math.isfinite(float(n))]
        if bad:
            parts.append(f"nonfinite_buckets={bad}")
        return ", ".join(parts)

    # --- lazy accounting ----------------------------------------------------
    def flush(self) -> int:
        """Materialize queued vectors (one stacked host transfer) and
        fold them into counts + registry metrics. Returns the number of
        anomalies seen in this batch."""
        with self._lock:
            pending, self._pending = self._pending, []
            self._pending_steps = 0
        if not pending:
            return 0
        host = [(np.asarray(vec, np.float64), keys, path, step, skipped)
                for vec, keys, path, step, skipped in pending]
        return self._observe_host(host)

    def _observe_host(self, entries) -> int:
        anomalies = 0
        with self._lock:
            for v, keys, path, step, skipped in entries:
                # a fused super-step queues its K per-step vectors as one
                # [K, G] stack; ``step`` records the LAST step's index
                rows = v if v.ndim == 2 else v.reshape(1, -1)
                base = step - len(rows) + 1
                for i, r in enumerate(rows):
                    self.last_vec = [float(x) for x in r]
                    self.last_keys = keys
                    bad = (r[GUARD_LOSS_NONFINITE]
                           + r[GUARD_GRAD_NONFINITE]) > 0
                    if not bad:
                        continue
                    anomalies += 1
                    self.nonfinite_steps += 1
                    self.last_anomaly_step = base + i
                    REGISTRY.counter(
                        "dl4j_nonfinite_steps_total",
                        help="steps with non-finite loss/gradients",
                        path=path).inc()
                    if skipped \
                            and self.policy is AnomalyPolicy.SKIP_STEP:
                        self.skipped_steps += 1
                        REGISTRY.counter(
                            "dl4j_skipped_steps_total",
                            help="updates discarded by SKIP_STEP",
                            path=path).inc()
            if self.last_vec is not None:
                REGISTRY.gauge("dl4j_grad_global_norm",
                               help="last observed global gradient "
                                    "norm").set(
                    self.last_vec[GUARD_GRAD_NORM])
                REGISTRY.gauge("dl4j_update_param_ratio",
                               help="last update:param norm ratio").set(
                    self.last_vec[GUARD_RATIO])
        return anomalies

    # --- reporting ----------------------------------------------------------
    def report(self) -> dict:
        """Flush + summarize (the ``/health`` endpoint payload)."""
        self.flush()
        with self._lock:
            if self.halted:
                status = "halted"
            elif self.nonfinite_steps:
                status = "anomalous"
            else:
                status = "ok"
            last = None
            if self.last_vec is not None:
                last = {
                    "loss": self.last_vec[GUARD_LOSS],
                    "grad_norm": self.last_vec[GUARD_GRAD_NORM],
                    "update_norm": self.last_vec[GUARD_UPDATE_NORM],
                    "param_norm": self.last_vec[GUARD_PARAM_NORM],
                    "update_param_ratio": self.last_vec[GUARD_RATIO],
                    "bucket_norms": dict(zip(
                        self.last_keys,
                        self.last_vec[GUARD_HEAD:])),
                }
            return {
                "enabled": enabled(),
                "policy": self.policy.value,
                "status": status,
                "steps": self.steps,
                "nonfinite_steps": self.nonfinite_steps,
                "skipped_steps": self.skipped_steps,
                "rollbacks": self.rollbacks,
                "last_anomaly_step": self.last_anomaly_step,
                "last": last,
            }


MONITOR = HealthMonitor()


def monitor() -> HealthMonitor:
    return MONITOR


def observe_step(owner, path: str, step: int, epoch: int, loss, vec,
                 keys: Sequence[str], batch=None,
                 rng_seed: Optional[int] = None,
                 snapshot: Optional[Callable[[], object]] = None,
                 restore: Optional[Callable[[object], None]] = None,
                 skipped: Optional[bool] = None) -> str:
    """The ONE per-step health epilogue every training path calls when a
    mode is active: flight-record the step (fingerprinting the batch
    only if the recorder is on), then apply the policy. ``snapshot``/
    ``restore`` default to the owner's ``_health_snapshot``/
    ``_health_restore`` hooks. Returns the monitor's action."""
    from deeplearning4j_tpu.telemetry import flightrec

    if flightrec.RECORDER._enabled:
        flightrec.RECORDER.record_step(
            path, step, epoch, score=loss, guard=vec, guard_keys=keys,
            rng_seed=rng_seed,
            batch_fp=(flightrec.batch_fingerprint(*batch)
                      if batch is not None else None))
    if snapshot is None and owner is not None:
        snapshot = getattr(owner, "_health_snapshot", None)
    if restore is None and owner is not None:
        restore = getattr(owner, "_health_restore", None)
    return MONITOR.on_step(vec, keys=keys, path=path, owner=owner,
                           snapshot=snapshot, restore=restore,
                           skipped=skipped)


def observe_fused(owner, path: str, first_step: int, epoch: int, losses,
                  vecs, keys: Sequence[str], k: int, batch=None,
                  rng_seed: Optional[int] = None,
                  snapshot: Optional[Callable[[], object]] = None,
                  restore: Optional[Callable[[object], None]] = None,
                  skipped: Optional[bool] = None) -> str:
    """The fused K-step health epilogue (the super-step counterpart of
    :func:`observe_step`): flight-record ONE entry for the super-step
    (max-combined guard, last step's loss) and feed the [K, G] stacked
    guard vectors to the monitor. WARN/SKIP stay lazy — the stack queues
    as one device array, no extra host sync per super-step;
    ROLLBACK/HALT materialize it and resolve at super-step granularity
    with the offending step's index in the report. ``first_step`` = the
    global index of the scan's first step; losses is the scan's [K] ys
    (only its last entry is touched, lazily)."""
    from deeplearning4j_tpu.telemetry import flightrec

    if flightrec.RECORDER._enabled:
        flightrec.RECORDER.record_step(
            path, first_step + k - 1, epoch, score=losses[-1],
            guard=combine(vecs), guard_keys=keys, rng_seed=rng_seed,
            batch_fp=(flightrec.batch_fingerprint(*batch)
                      if batch is not None else None))
    if snapshot is None and owner is not None:
        snapshot = getattr(owner, "_health_snapshot", None)
    if restore is None and owner is not None:
        restore = getattr(owner, "_health_restore", None)
    return MONITOR.on_steps(vecs, k, keys=keys, path=path, owner=owner,
                            snapshot=snapshot, restore=restore,
                            skipped=skipped)


def configure(policy: AnomalyPolicy = AnomalyPolicy.WARN,
              flush_every: int = 64, snapshot_every: int = 10,
              record_flights: bool = True) -> HealthMonitor:
    """Turn the health layer on: sets the in-graph mode (step builders
    pick it up on their next build), resets and reconfigures the global
    monitor, and (by default) enables the flight recorder so a HALT or
    crash leaves a bundle behind."""
    global _MODE
    if isinstance(policy, str):
        policy = AnomalyPolicy(policy)
    MONITOR.policy = policy
    MONITOR.flush_every = max(1, int(flush_every))
    MONITOR.snapshot_every = max(1, int(snapshot_every))
    MONITOR.reset()
    _MODE = "skip" if policy is AnomalyPolicy.SKIP_STEP else "observe"
    if record_flights:
        from deeplearning4j_tpu.telemetry import flightrec

        flightrec.RECORDER.enable()
    return MONITOR


def disable() -> None:
    """Back to the zero-cost fast path (recorded counts retained)."""
    global _MODE
    _MODE = ""


def report() -> dict:
    return MONITOR.report()
