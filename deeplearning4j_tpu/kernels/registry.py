"""Kernel registry: named Pallas kernels, envelopes, tuned selection.

Each registered :class:`Kernel` declares

- a **shape/dtype envelope** (``supports(env)``) — the exact set of
  concrete problems its grid can cover; anything outside routes to
  stock XLA with zero behavior change;
- a **tiling/grid parameter space** (``candidates(env)``) the autotuner
  (``kernels.tuner``) sweeps per concrete ``(shape, dtype, backend)``;
- a **reference implementation** (``reference(env)``) — the ``jax.lax``
  path it must match numerically (the parity tests pin every kernel
  against it in interpret mode);
- the **builder** (``build(env, tiling)``) producing the Pallas
  callable for one tuned layout.

Selection (:meth:`KernelRegistry.select`) is a pure tuning-cache
lookup: only a TUNED envelope gets a kernel — an untuned shape is a
recorded fallback, never a guess. The per-kernel **tuning digest**
(8-hex over the winner table + kernel version, epoch-memoized) is what
the model step keys fold in as ``kern:<id>:<digest>`` tokens, so a
retune re-keys every kernel-bearing executable (PRG207 audits the
tokens against this registry).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.kernels import impls, tuner

# candidate block sweeps (clamped per-problem, deduped by effective
# tiling): sublane-multiples for rows, lane-width favorites for
# columns/contraction — the guide's (8/16, 128) tile floors
_BM_SWEEP = (512, 256, 128, 64, 32, 16, 8)
_BN_SWEEP = (256, 128, 64, 32, 16, 8)
_BK_SWEEP = (512, 256, 128, 64, 32, 16, 8)

_SUPPORTED_DTYPES = ("float32", "bfloat16")

# attention sweeps: flash (block_q, block_k) favors the MXU-shaped big
# blocks first (ops.attention._blk clamps per-problem, so the candidate
# space is the EFFECTIVE block set — small-T problems collapse to one
# candidate); paged decode sweeps the page (KV slots per DMA) down the
# pow2 ladder the cache buckets come from
_ATTN_BQ_SWEEP = (512, 256, 128)
_ATTN_BK_SWEEP = (512, 256, 128)
_PAGE_SWEEP = (128, 64, 32, 16, 8)


@dataclasses.dataclass(frozen=True)
class MatmulEnvelope:
    """One concrete matmul-class problem: [M, K] @ [K, N] in ``dtype``
    on ``backend`` ("tpu" = real Mosaic lowering, "interpret" = the
    Pallas interpreter — this container's mode), with an optional
    elementwise activation baked in the epilogue."""

    m: int
    k: int
    n: int
    dtype: str
    backend: str
    act: str = "identity"

    @property
    def key(self) -> str:
        return (f"{self.backend}:m{self.m}:k{self.k}:n{self.n}"
                f":{self.dtype}:{self.act}")

    @property
    def shape_bucket(self) -> str:
        """The telemetry label: shape class without backend/act noise."""
        return f"m{self.m}_k{self.k}_n{self.n}"


@dataclasses.dataclass(frozen=True)
class AttentionEnvelope:
    """One concrete attention problem. ``tq`` is the query length (1 for
    single-token decode), ``tk`` the key length — for the paged decode
    kernel that is the KV cache bucket, so every hop up the pow2 ladder
    is its own tuned envelope. ``masked`` marks a key-padding mask
    operand (train prefill over ragged batches); decode masking rides
    ``positions`` and is always on."""

    b: int
    h: int
    tq: int
    tk: int
    d: int
    dtype: str
    backend: str
    causal: bool = True
    masked: bool = False

    @property
    def key(self) -> str:
        return (f"{self.backend}:b{self.b}:h{self.h}:tq{self.tq}"
                f":tk{self.tk}:d{self.d}:{self.dtype}"
                f":c{int(self.causal)}:m{int(self.masked)}")

    @property
    def shape_bucket(self) -> str:
        return f"b{self.b}_h{self.h}_tq{self.tq}_tk{self.tk}_d{self.d}"


def _sweep_candidates(env: MatmulEnvelope,
                      limit: Optional[int]) -> List[Tuple[int, int, int]]:
    seen, out = set(), []
    for bm in _BM_SWEEP:
        for bn in _BN_SWEEP:
            for bk in _BK_SWEEP:
                t = (bm, bn, bk)
                eff = impls.effective_tiling(env.m, env.k, env.n, t)
                if eff in seen or not impls.tiling_valid(
                        env.m, env.k, env.n, t):
                    continue
                seen.add(eff)
                out.append(eff)
    # prefer big MXU-shaped tiles first so a capped sweep still sees
    # the plausible winners
    out.sort(key=lambda t: (-(t[0] * t[1]), -t[2]))
    return out[:limit] if limit else out


def _matmul_supports(env) -> bool:
    return (impls.has_pallas()
            and env.dtype in _SUPPORTED_DTYPES
            and env.m > 0 and env.k > 0 and env.n > 0
            and bool(_sweep_candidates(env, limit=1)))


def _activation(name: str):
    from deeplearning4j_tpu.conf.activations import Activation

    return Activation(name)


def _rand_inputs(env: MatmulEnvelope, seed: int, with_bias: bool):
    import jax
    import jax.numpy as jnp

    kx, kw, kb = jax.random.split(jax.random.PRNGKey(seed), 3)
    dt = jnp.dtype(env.dtype)
    x = jax.random.normal(kx, (env.m, env.k), jnp.float32).astype(dt)
    w = jax.random.normal(kw, (env.k, env.n), jnp.float32).astype(dt)
    if not with_bias:
        return x, w
    b = jax.random.normal(kb, (env.n,), jnp.float32).astype(dt)
    return x, w, b


class Kernel:
    """Base registry entry. ``version`` participates in the tuning
    digest, so a kernel-body change invalidates every cached executable
    keyed on the old behavior."""

    kernel_id: str = ""
    version: int = 1

    def supports(self, env) -> bool:
        raise NotImplementedError

    def candidates(self, env, limit: Optional[int] = None):
        raise NotImplementedError

    def build(self, env, tiling):
        """-> callable over :meth:`make_inputs`-shaped args running the
        Pallas path with ``tiling``."""
        raise NotImplementedError

    def reference(self, env):
        """-> callable over the same args running the stock ``jax.lax``
        path this kernel must match."""
        raise NotImplementedError

    def make_inputs(self, env, seed: int = 0):
        raise NotImplementedError

    def tiling_ok(self, env, tiling) -> bool:
        """Whether a cached winner still legally covers ``env`` — the
        guard :meth:`KernelRegistry.select` runs before trusting a
        hand-edited / cross-version tuning-cache entry. Default: the
        winner must be one of this kernel's own candidates."""
        return tuple(tiling) in {tuple(t) for t in self.candidates(env)}


class _MatmulKernel(Kernel):
    """Shared matmul-class winner validation: a 3-tuple whose clamped
    blocks divide the problem exactly (``impls.tiling_valid``)."""

    def tiling_ok(self, env, tiling) -> bool:
        return len(tiling) == 3 and impls.tiling_valid(
            env.m, env.k, env.n, tiling)


class MatmulBiasActKernel(_MatmulKernel):
    """Tiled matmul + bias + elementwise activation in one pass — the
    dense / 1x1-conv forward class (``impls.matmul_bias_act``)."""

    kernel_id = "matmul_bias_act"
    version = 1

    def supports(self, env) -> bool:
        return _matmul_supports(env)

    def candidates(self, env, limit: Optional[int] = None):
        return _sweep_candidates(env, limit)

    def build(self, env, tiling):
        act = _activation(env.act)
        interpret = env.backend != "tpu"
        tiling = tuple(tiling)

        def fn(x, w, b):
            return impls.matmul_bias_act(x, w, b, act, tiling, interpret)

        return fn

    def reference(self, env):
        act = _activation(env.act)

        def ref(x, w, b):
            return act.apply(x @ w + b)

        return ref

    def make_inputs(self, env, seed: int = 0):
        return _rand_inputs(env, seed, with_bias=True)


class Int8MatmulBiasActKernel(_MatmulKernel):
    """Quantized-serving matmul: int8 x int8 -> int32 accumulate with the
    f32 scale/bias/activation epilogue fused in the same pass
    (``impls.matmul_bias_act_int8``). Serves ``QuantizedDenseLayer`` and —
    after the routing reshape — ``QuantizedConv1x1Layer``. The envelope
    machinery (candidates/tuner/on-disk cache/stock fallback/PRG207) is
    untouched: this is just a new dtype reaching the same sweeps."""

    kernel_id = "matmul_bias_act_int8"
    version = 1

    def supports(self, env) -> bool:
        return (impls.has_pallas() and env.dtype == "int8"
                and env.m > 0 and env.k > 0 and env.n > 0
                and bool(_sweep_candidates(env, limit=1)))

    def candidates(self, env, limit: Optional[int] = None):
        return _sweep_candidates(env, limit)

    def build(self, env, tiling):
        act = _activation(env.act)
        interpret = env.backend != "tpu"
        tiling = tuple(tiling)

        def fn(xq, wq, scale, b):
            return impls.matmul_bias_act_int8(xq, wq, scale, b, act,
                                              tiling, interpret)

        return fn

    def reference(self, env):
        import jax
        import jax.numpy as jnp

        act = _activation(env.act)

        def ref(xq, wq, scale, b):
            acc = jax.lax.dot(xq, wq, preferred_element_type=jnp.int32)
            return act.apply(acc.astype(jnp.float32) * scale + b)

        return ref

    def make_inputs(self, env, seed: int = 0):
        import jax
        import jax.numpy as jnp

        kx, kw, ks, kb = jax.random.split(jax.random.PRNGKey(seed), 4)
        xq = jax.random.randint(kx, (env.m, env.k), -127, 128, jnp.int8)
        wq = jax.random.randint(kw, (env.k, env.n), -127, 128, jnp.int8)
        scale = jax.random.uniform(ks, (env.n,), jnp.float32, 0.5, 2.0) / 127
        b = jax.random.normal(kb, (env.n,), jnp.float32)
        return xq, wq, scale, b


class ConvBnActKernel(_MatmulKernel):
    """Fused 1x1-conv + batch-norm statistics — the dominant trace
    fusion class (round-2 ``ops/conv_fused`` experiment): the matmul
    emits y AND the per-channel sum / sum-of-squares in one output
    pass, so the train-mode BN statistics re-read of the activation
    disappears (normalize + activation stay in XLA where they fuse
    with whatever follows)."""

    kernel_id = "conv_bn_act"
    version = 1

    def supports(self, env) -> bool:
        return _matmul_supports(env)

    def candidates(self, env, limit: Optional[int] = None):
        return _sweep_candidates(env, limit)

    def build(self, env, tiling):
        interpret = env.backend != "tpu"
        tiling = tuple(tiling)

        def fn(x, w):
            return impls.matmul_stats(x, w, tiling, interpret)

        return fn

    def reference(self, env):
        import jax.numpy as jnp

        def ref(x, w):
            y = x @ w
            y32 = y.astype(jnp.float32)
            return y, jnp.sum(y32, axis=0), jnp.sum(y32 * y32, axis=0)

        return ref

    def make_inputs(self, env, seed: int = 0):
        return _rand_inputs(env, seed, with_bias=False)


def _attention_supports(env) -> bool:
    return (impls.has_pallas()
            and isinstance(env, AttentionEnvelope)
            and env.dtype in _SUPPORTED_DTYPES
            and env.b > 0 and env.h > 0 and env.d > 0
            and env.tq > 0 and env.tk > 0)


def _rand_attn(env, seed: int, shapes):
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(env.dtype)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(shapes))
    return tuple(
        jax.random.normal(k, s, jnp.float32).astype(dt)
        for k, s in zip(keys, shapes))


class FlashAttentionKernel(Kernel):
    """Tiled online-softmax attention (``ops.attention.flash_attention``):
    (Bq, Bk)-blocked forward that never materializes the [Tq, Tk] score
    matrix, custom-VJP backward recomputing each probability tile from
    the saved row-max/row-sum stats. The tuned tiling is the
    ``(block_q, block_k)`` pair; ``ops.attention._blk`` clamps each to
    the effective legal block for the problem, so every candidate here
    IS its own effective tiling."""

    kernel_id = "flash_attention"
    version = 1

    def supports(self, env) -> bool:
        if not _attention_supports(env):
            return False
        # the kernel's lane-replication math needs d <= 128 or 128 | d
        return env.d <= 128 or env.d % 128 == 0

    def candidates(self, env, limit: Optional[int] = None):
        from deeplearning4j_tpu.ops import attention as A

        seen, out = set(), []
        for bq in _ATTN_BQ_SWEEP:
            for bk in _ATTN_BK_SWEEP:
                eff = (A._blk(bq, env.tq), A._blk(bk, env.tk))
                if eff in seen:
                    continue
                seen.add(eff)
                out.append(eff)
        out.sort(key=lambda t: (-(t[0] * t[1]), -t[0]))
        return out[:limit] if limit else out

    def build(self, env, tiling):
        from deeplearning4j_tpu.ops import attention as A

        bq, bk = (int(t) for t in tiling)
        causal = env.causal
        interpret = env.backend != "tpu"

        def fn(q, k, v, key_mask=None):
            return A.flash_attention(q, k, v, key_mask, causal=causal,
                                     block_q=bq, block_k=bk,
                                     interpret=interpret)

        return fn

    def reference(self, env):
        from deeplearning4j_tpu.ops import attention as A

        causal = env.causal

        def ref(q, k, v, key_mask=None):
            return A.reference_attention(q, k, v, key_mask, causal=causal)

        return ref

    def make_inputs(self, env, seed: int = 0):
        import jax
        import jax.numpy as jnp

        q, k, v = _rand_attn(env, seed, [(env.b, env.h, env.tq, env.d),
                                         (env.b, env.h, env.tk, env.d),
                                         (env.b, env.h, env.tk, env.d)])
        if not env.masked:
            return q, k, v
        # ragged key-padding mask: every row keeps at least one key
        lens = jax.random.randint(jax.random.PRNGKey(seed + 1),
                                  (env.b,), 1, env.tk + 1)
        km = (jnp.arange(env.tk)[None, :]
              < lens[:, None]).astype(jnp.float32)
        return q, k, v, km


class PagedDecodeAttentionKernel(Kernel):
    """Single-token decode against the KV cache as an in-kernel page
    gather (``ops.attention.paged_decode_attention``): the cache streams
    page-by-page, pages wholly past ``positions[b]`` skip their DMA via
    the scalar-prefetched index map, so a row's decode step costs
    O(used pages) instead of the masked full-cache read. The tuned
    tiling is the 1-tuple ``(page,)``; only divisors of the cache bucket
    are legal."""

    kernel_id = "paged_decode_attention"
    version = 1

    def supports(self, env) -> bool:
        return (_attention_supports(env) and env.tq == 1
                and bool(self.candidates(env, limit=1)))

    def candidates(self, env, limit: Optional[int] = None):
        out = [(p,) for p in _PAGE_SWEEP
               if p <= env.tk and env.tk % p == 0]
        if not out and env.tk <= max(_PAGE_SWEEP):
            out = [(env.tk,)]  # tiny caches: one page covers the bucket
        return out[:limit] if limit else out

    def build(self, env, tiling):
        from deeplearning4j_tpu.ops import attention as A

        page = int(tiling[0])
        interpret = env.backend != "tpu"

        def fn(q, k_cache, v_cache, positions):
            return A.paged_decode_attention(q, k_cache, v_cache, positions,
                                            page=page, interpret=interpret)

        return fn

    def reference(self, env):
        from deeplearning4j_tpu.ops import attention as A

        def ref(q, k_cache, v_cache, positions):
            return A.decode_attention(q, k_cache, v_cache, positions)

        return ref

    def make_inputs(self, env, seed: int = 0):
        import jax
        import jax.numpy as jnp

        q, kc, vc = _rand_attn(env, seed, [(env.b, env.h, env.d),
                                           (env.b, env.tk, env.h, env.d),
                                           (env.b, env.tk, env.h, env.d)])
        pos = jax.random.randint(jax.random.PRNGKey(seed + 1),
                                 (env.b,), 0, env.tk, jnp.int32)
        return q, kc, vc, pos


@dataclasses.dataclass(frozen=True)
class Selection:
    """One resolved routing decision."""

    kernel: Kernel
    env: object
    tiling: Tuple[int, ...]


class KernelRegistry:
    """Process-global name -> :class:`Kernel` table + tuned selection
    + epoch-memoized tuning digests."""

    def __init__(self, cache: Optional[tuner.TuningCache] = None):
        self._kernels: Dict[str, Kernel] = {}
        self._cache = cache if cache is not None else tuner.TUNING
        self._digests: Dict[str, Tuple[int, str]] = {}
        self._tag_memo: Optional[Tuple[int, Tuple[str, ...], str]] = None
        self._lock = threading.Lock()

    @property
    def tuning(self) -> tuner.TuningCache:
        return self._cache

    def register(self, kernel: Kernel) -> Kernel:
        with self._lock:
            self._kernels[kernel.kernel_id] = kernel
            self._digests.pop(kernel.kernel_id, None)
            self._tag_memo = None
        return kernel

    def get(self, kernel_id: str) -> Optional[Kernel]:
        return self._kernels.get(kernel_id)

    def ids(self) -> List[str]:
        return sorted(self._kernels)

    def select(self, kernel_id: str, env) -> Optional[Selection]:
        """The tuned kernel for one envelope, or None (untuned /
        unsupported / winner no longer legal) — None means stock XLA."""
        kernel = self._kernels.get(kernel_id)
        if kernel is None or not kernel.supports(env):
            return None
        win = self._cache.winner(kernel_id, env.key)
        if win is None:
            return None
        tiling = tuple(int(t) for t in win.get("tiling", ()))
        if not kernel.tiling_ok(env, tiling):
            # a hand-edited / cross-version winner that no longer covers
            # the problem: refuse it, fall back to stock XLA
            return None
        return Selection(kernel=kernel, env=env, tiling=tiling)

    def tuning_digest(self, kernel_id: str) -> str:
        """8-hex digest over the kernel's current winner table (+ its
        version); memoized against the tuning-cache epoch so the
        per-step re-key check stays two dict lookups."""
        epoch = self._cache.epoch
        with self._lock:
            memo = self._digests.get(kernel_id)
            if memo is not None and memo[0] == epoch:
                return memo[1]
        kernel = self._kernels.get(kernel_id)
        payload = {
            "version": getattr(kernel, "version", 0),
            "winners": self._cache.winners(kernel_id),
        }
        d = hashlib.sha256(
            json.dumps(payload, sort_keys=True,
                       separators=(",", ":")).encode()).hexdigest()[:8]
        with self._lock:
            self._digests[kernel_id] = (epoch, d)
        return d

    def cache_tag(self) -> str:
        """The ``:kern:<id>:<digest>`` token string step keys fold in —
        one token per registered kernel, so retuning ANY kernel mints
        new executables for every kernel-enabled step. Memoized against
        the tuning-cache epoch (like the per-kernel digests), so the hot
        decode loop's per-dispatch re-key check is one tuple compare
        instead of a join over every registered kernel."""
        epoch = self._cache.epoch
        ids = tuple(self.ids())
        with self._lock:
            memo = self._tag_memo
            if memo is not None and memo[0] == epoch and memo[1] == ids:
                return memo[2]
        tag = "".join(f":kern:{kid}:{self.tuning_digest(kid)}"
                      for kid in ids)
        with self._lock:
            self._tag_memo = (epoch, ids, tag)
        return tag


REGISTRY = KernelRegistry()
REGISTRY.register(MatmulBiasActKernel())
REGISTRY.register(Int8MatmulBiasActKernel())
REGISTRY.register(ConvBnActKernel())
REGISTRY.register(FlashAttentionKernel())
REGISTRY.register(PagedDecodeAttentionKernel())
