"""Layer-to-kernel routing + envelope planning + capability probe.

``maybe_forward(layer, ...)`` is the single dispatch point the model
forward passes call when ``conf.use_kernels`` is on: it inspects the
layer (exact forward, not a subclass override), derives the concrete
:class:`registry.MatmulEnvelope` from the traced shapes, and asks the
registry for a TUNED selection. Anything short of a tuned, envelope-
covered, elementwise-activation match returns ``None`` — the caller
runs the stock layer forward, bit-identical to ``use_kernels=False``.

Routed classes:

- ``DenseLayer`` (2-D input, elementwise activation) and 1x1
  ``ConvolutionLayer`` (a 1x1 conv IS a matmul over [B*H*W, Cin]) →
  ``matmul_bias_act``;
- ``FusedConvBN1x1`` in train mode → ``conv_bn_act`` (matmul + fused
  per-channel statistics; normalize/activation stay in XLA), sharing
  ``_bn_running_update`` / ``_bn_normalize`` with the layer so the
  semantics cannot diverge;
- ``SelfAttentionLayer`` → ``flash_attention``: the route re-enters the
  layer's OWN forward with ``use_kernels=True`` so only the
  softmax(QK^T)V core is swapped (dropout / projections / activation /
  mask-zeroing stay single-sourced in the layer).

The serving decode path routes through the functional twins
:func:`maybe_flash_attention` (prefill) and
:func:`maybe_decode_attention` (the paged single-token kernel), called
from inside ``SelfAttentionLayer.prefill`` / ``decode_step`` when the
decoder passes ``use_kernels=True``; :func:`decoder_envelopes` /
:func:`autotune_decoder` plan and tune the bucket-ladder envelopes
those steps bake.

Selection happens at TRACE time (shapes are static under jit), so a
routed executable bakes exactly one tuned layout — which is why the
step keys carry the registry's tuning digest: a retune means a new
trace, never a silently stale kernel.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from deeplearning4j_tpu.kernels import impls
from deeplearning4j_tpu.kernels.registry import (
    REGISTRY,
    AttentionEnvelope,
    MatmulEnvelope,
)


def backend() -> str:
    """The Pallas execution mode for this process: ``"tpu"`` (real
    Mosaic lowering), ``"interpret"`` (the Pallas interpreter — CPU
    containers), or ``"none"`` (pallas-tpu unimportable: routing is
    disabled entirely)."""
    if not impls.has_pallas():
        return "none"
    import jax

    return "tpu" if jax.default_backend() == "tpu" else "interpret"


_CAPABILITY = None


def capability() -> str:
    """Probe-once capability: like :func:`backend`, but ``"tpu"`` is
    only reported after a trivial ``pallas_call`` actually COMPILES
    without ``interpret`` (the PR-7 probe-and-skip shape — a TPU
    backend whose Mosaic pipeline is broken degrades to interpret
    rather than failing every routed trace)."""
    global _CAPABILITY
    if _CAPABILITY is not None:
        return _CAPABILITY
    mode = backend()
    if mode == "tpu":
        try:
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def _probe(x_ref, o_ref):
                o_ref[...] = x_ref[...] + 1.0

            x = jnp.zeros((8, 128), jnp.float32)
            jax.jit(lambda a: pl.pallas_call(
                _probe, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(a)).lower(x).compile()
        except Exception:
            mode = "interpret"
    _CAPABILITY = mode
    return _CAPABILITY


# every Activation is elementwise except softmax (normalizes over the
# feature axis — cannot run per-tile in the epilogue)
_NON_ELEMENTWISE = frozenset({"softmax"})


def _elementwise(act) -> bool:
    return act.value not in _NON_ELEMENTWISE


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _env(m: int, k: int, n: int, dtype, act: str = "identity",
         mode: Optional[str] = None) -> MatmulEnvelope:
    # capability(), not backend(): a TPU whose Mosaic pipeline fails the
    # probe keys (and builds) its envelopes as "interpret" instead of
    # failing every routed trace at compile time
    return MatmulEnvelope(m=int(m), k=int(k), n=int(n), dtype=str(dtype),
                          backend=mode or capability(), act=act)


def _attn_env(b: int, h: int, tq: int, tk: int, d: int, dtype,
              causal: bool, masked: bool,
              mode: Optional[str] = None) -> AttentionEnvelope:
    return AttentionEnvelope(b=int(b), h=int(h), tq=int(tq), tk=int(tk),
                             d=int(d), dtype=str(dtype),
                             backend=mode or capability(),
                             causal=bool(causal), masked=bool(masked))


# --------------------------------------------------------------------------
# per-layer routes (each returns (y, new_state) or None = stock XLA)
# --------------------------------------------------------------------------

def _record_selected(kernel_id: str, env) -> None:
    from deeplearning4j_tpu import telemetry

    telemetry.record_kernel_selected(kernel_id, env.shape_bucket)
    telemetry.record_tuning_cache(REGISTRY.tuning.hits,
                                  REGISTRY.tuning.entries())


def _route_dense(layer, params, state, x, train, rng):
    from deeplearning4j_tpu.conf.layers import DenseLayer

    if type(layer).forward is not DenseLayer.forward:
        return None  # a subclass with its own forward: never reroute it
    if x.ndim != 2 or not _elementwise(layer.activation):
        return None
    m, k = x.shape
    sel = REGISTRY.select("matmul_bias_act",
                          _env(m, k, layer.n_out, x.dtype,
                               act=layer.activation.value))
    if sel is None:
        return None
    import jax.numpy as jnp

    x = layer._dropout_input(x, train, rng)
    w = params["W"]
    b = params["b"] if layer.has_bias else jnp.zeros((layer.n_out,),
                                                     x.dtype)
    y = sel.kernel.build(sel.env, sel.tiling)(x, w, b)
    _record_selected("matmul_bias_act", sel.env)
    return y, state


def _route_conv1x1(layer, params, state, x, train, rng):
    from deeplearning4j_tpu.conf.layers_cnn import (
        ConvolutionLayer,
        ConvolutionMode,
    )

    if type(layer).forward is not ConvolutionLayer.forward:
        return None
    if x.ndim != 4 or not _elementwise(layer.activation):
        return None
    if _pair(layer.kernel_size) != (1, 1) or _pair(layer.dilation) != (1, 1):
        return None
    # a 1x1 conv reads no neighborhood, so explicit padding changes the
    # output (zero-rows appear) — only pad-free geometries are a pure
    # matmul. SAME/stride s samples positions 0, s, 2s, ... exactly.
    if (layer.convolution_mode is not ConvolutionMode.SAME
            and _pair(layer.padding) != (0, 0)):
        return None
    sh, sw = _pair(layer.stride)
    b_, h, wd, cin = x.shape
    h_o, w_o = -(-h // sh), -(-wd // sw)
    m = b_ * h_o * w_o
    sel = REGISTRY.select("matmul_bias_act",
                          _env(m, cin, layer.n_out, x.dtype,
                               act=layer.activation.value))
    if sel is None:
        return None
    import jax.numpy as jnp

    # dropout BEFORE the stride subsample — the stock forward masks the
    # FULL input, so the bernoulli draw must see the same shape (a
    # post-slice mask would be a different stream for the same rng)
    x = layer._dropout_input(x, train, rng)
    xs = x[:, ::sh, ::sw, :] if (sh, sw) != (1, 1) else x
    w2 = params["W"].reshape(cin, layer.n_out)
    b = params["b"] if layer.has_bias else jnp.zeros((layer.n_out,),
                                                     x.dtype)
    y2 = sel.kernel.build(sel.env, sel.tiling)(xs.reshape(m, cin), w2, b)
    _record_selected("matmul_bias_act", sel.env)
    return y2.reshape(b_, h_o, w_o, layer.n_out), state


def _route_quant_dense(layer, params, state, x, train, rng):
    from deeplearning4j_tpu.conf.layers_quant import (
        QuantizedDenseLayer,
        quantize_input,
    )

    if type(layer).forward is not QuantizedDenseLayer.forward:
        return None
    if x.ndim != 2 or not _elementwise(layer.activation):
        return None
    m, k = x.shape
    sel = REGISTRY.select("matmul_bias_act_int8",
                          _env(m, k, layer.n_out, "int8",
                               act=layer.activation.value))
    if sel is None:
        return None
    # the round/clip/cast stays in XLA (it fuses into the surrounding
    # program); the kernel receives the already-int8 activations
    xq = quantize_input(x, params["xs"], params["xz"])
    y = sel.kernel.build(sel.env, sel.tiling)(xq, params["Wq"],
                                              params["scale"], params["b"])
    _record_selected("matmul_bias_act_int8", sel.env)
    return y.astype(x.dtype), state


def _route_quant_conv1x1(layer, params, state, x, train, rng):
    from deeplearning4j_tpu.conf.layers_quant import (
        QuantizedConv1x1Layer,
        quantize_input,
    )

    if type(layer).forward is not QuantizedConv1x1Layer.forward:
        return None
    if x.ndim != 4 or not _elementwise(layer.activation):
        return None
    sh, sw = _pair(layer.stride)
    b_, h, wd, cin = x.shape
    h_o, w_o = -(-h // sh), -(-wd // sw)
    m = b_ * h_o * w_o
    sel = REGISTRY.select("matmul_bias_act_int8",
                          _env(m, cin, layer.n_out, "int8",
                               act=layer.activation.value))
    if sel is None:
        return None
    xs = x[:, ::sh, ::sw, :] if (sh, sw) != (1, 1) else x
    xq = quantize_input(xs.reshape(m, cin), params["xs"], params["xz"])
    y2 = sel.kernel.build(sel.env, sel.tiling)(xq, params["Wq"],
                                               params["scale"], params["b"])
    _record_selected("matmul_bias_act_int8", sel.env)
    return y2.reshape(b_, h_o, w_o, layer.n_out).astype(x.dtype), state


def _route_fused_conv_bn(layer, params, state, x, train, rng):
    from deeplearning4j_tpu.conf.layers_cnn import (
        FusedConvBN1x1,
        _bn_normalize,
        _bn_running_update,
    )

    if type(layer).forward is not FusedConvBN1x1.forward:
        return None
    if not train or x.ndim != 4:
        return None  # eval mode reads running stats: no statistics pass
    sh, sw = _pair(layer.stride)
    b_, h, wd, cin = (x[:, ::sh, ::sw, :].shape if (sh, sw) != (1, 1)
                      else x.shape)
    m = b_ * h * wd
    sel = REGISTRY.select("conv_bn_act", _env(m, cin, layer.n_out, x.dtype))
    if sel is None:
        return None
    import jax.numpy as jnp

    # EXACTLY the layer's train-mode kernel path, with the registry's
    # tuned tiling instead of ops/conv_fused's fixed one; the BN pieces
    # are the layer module's own helpers so semantics cannot diverge
    x = layer._dropout_input(x, train, rng)
    xs = x[:, ::sh, ::sw, :] if (sh, sw) != (1, 1) else x
    sdt = state["mean"].dtype
    y2, s, q = sel.kernel.build(sel.env, sel.tiling)(
        xs.reshape(m, cin), params["W"].reshape(cin, layer.n_out))
    y = y2.reshape(b_, h, wd, layer.n_out)
    mean = (s / m).astype(sdt)
    var = jnp.maximum((q / m).astype(sdt) - mean * mean, 0.0)
    new_state = _bn_running_update(state, mean, var, layer.decay)
    xhat = _bn_normalize(y.astype(sdt), mean, var, layer.eps,
                         params["gamma"].astype(sdt),
                         params["beta"].astype(sdt))
    _record_selected("conv_bn_act", sel.env)
    return layer.activation.apply(xhat).astype(x.dtype), new_state


def maybe_flash_attention(q, k, v, key_mask=None, causal=False):
    """Route head-split ``[B, H, T, D]`` attention through the tuned
    flash kernel, or return ``None`` for the stock tier (untuned
    envelope, unsupported shape, pallas unavailable). Selection happens
    at trace time, so the caller's executable bakes one tuned
    ``(block_q, block_k)`` layout."""
    if capability() == "none":
        return None
    b, h, tq, d = q.shape
    env = _attn_env(b, h, tq, k.shape[2], d, q.dtype, causal=causal,
                    masked=key_mask is not None)
    sel = REGISTRY.select("flash_attention", env)
    if sel is None:
        return None
    out = sel.kernel.build(sel.env, sel.tiling)(q, k, v, key_mask)
    _record_selected("flash_attention", sel.env)
    return out


def maybe_decode_attention(q, k_cache, v_cache, positions):
    """Route single-token decode attention (``q [B, H, D]`` against
    ``[B, S, H, D]`` caches valid through ``positions``) through the
    tuned paged-gather kernel, or return ``None`` for the stock masked
    full-cache read."""
    if capability() == "none":
        return None
    b, h, d = q.shape
    env = _attn_env(b, h, 1, k_cache.shape[1], d, q.dtype, causal=True,
                    masked=False)
    sel = REGISTRY.select("paged_decode_attention", env)
    if sel is None:
        return None
    out = sel.kernel.build(sel.env, sel.tiling)(q, k_cache, v_cache,
                                                positions)
    _record_selected("paged_decode_attention", sel.env)
    return out


def _route_self_attention(layer, params, state, x, train, rng, mask):
    from deeplearning4j_tpu.conf.layers_attention import SelfAttentionLayer

    if type(layer).forward is not SelfAttentionLayer.forward:
        return None
    if x.ndim != 3 or layer.attention_impl not in ("auto", "flash"):
        return None
    b, t, e = x.shape
    h = layer.n_heads if layer.project_input else 1
    env = _attn_env(b, h, t, t, layer._head_size(e), x.dtype,
                    causal=layer.causal, masked=mask is not None)
    if REGISTRY.select("flash_attention", env) is None:
        return None
    # the layer's OWN forward with the kernel core swapped in — the
    # dropout / projection / activation / mask-zeroing semantics stay
    # single-sourced (the inner route re-derives this same envelope)
    return layer.forward(params, state, x, train=train, rng=rng,
                         mask=mask, use_kernels=True)


def maybe_forward(layer, params, state, x, train=False, rng=None, **kw):
    """Run ``layer`` through a tuned registry kernel, or return ``None``
    for the stock path. ``kw`` beyond SelfAttentionLayer's ``mask``
    never routes."""
    if capability() == "none":
        return None
    from deeplearning4j_tpu.conf.layers import DenseLayer
    from deeplearning4j_tpu.conf.layers_attention import SelfAttentionLayer
    from deeplearning4j_tpu.conf.layers_cnn import (
        ConvolutionLayer,
        FusedConvBN1x1,
    )
    from deeplearning4j_tpu.conf.layers_quant import (
        QuantizedConv1x1Layer,
        QuantizedDenseLayer,
    )

    if isinstance(layer, SelfAttentionLayer):
        mask = kw.pop("mask", None)
        if kw:
            return None
        return _route_self_attention(layer, params, state, x, train, rng,
                                     mask)
    if kw:
        return None
    if isinstance(layer, QuantizedDenseLayer):
        return _route_quant_dense(layer, params, state, x, train, rng)
    if isinstance(layer, QuantizedConv1x1Layer):
        return _route_quant_conv1x1(layer, params, state, x, train, rng)
    if isinstance(layer, FusedConvBN1x1):
        return _route_fused_conv_bn(layer, params, state, x, train, rng)
    if isinstance(layer, ConvolutionLayer):
        return _route_conv1x1(layer, params, state, x, train, rng)
    if isinstance(layer, DenseLayer):
        return _route_dense(layer, params, state, x, train, rng)
    return None


def maybe_vertex_forward(vertex, params, state, xs, train=False, rng=None,
                         **kw):
    """Graph-side dispatch: route a single-input ``LayerVertex``'s
    wrapped layer (applying its preprocessor first, exactly as
    ``LayerVertex.forward`` does). None = run the stock vertex forward
    (an unrouted preprocessor application here is dead code XLA
    eliminates). A feature ``mask`` rides through only for
    SelfAttentionLayer (the one routed class that consumes it)."""
    mask = kw.pop("mask", None)
    if kw:
        return None
    layer = getattr(vertex, "layer", None)
    if layer is None or len(xs) != 1:
        return None
    if mask is not None:
        from deeplearning4j_tpu.conf.layers_attention import (
            SelfAttentionLayer,
        )

        if not isinstance(layer, SelfAttentionLayer):
            return None
    x = xs[0]
    pre = getattr(vertex, "preprocessor", None)
    if pre is not None:
        x, _ = pre.forward({}, {}, x, train=train, rng=None)
    mkw = {"mask": mask} if mask is not None else {}
    return maybe_forward(layer, params, state, x, train=train, rng=rng,
                         **mkw)


# --------------------------------------------------------------------------
# envelope planning + whole-model autotune
# --------------------------------------------------------------------------

def _layer_envelope(layer, itype, batch: int, dtype,
                    mode: Optional[str]) -> Optional[Tuple[str, object]]:
    """The ``(kernel_id, envelope)`` a routable layer at this input
    type/batch would select against, or None — the static-shape twin of
    the ``_route_*`` checks (same qualifiers, conf-derived geometry)."""
    from deeplearning4j_tpu.conf import inputs as it
    from deeplearning4j_tpu.conf.layers import DenseLayer
    from deeplearning4j_tpu.conf.layers_attention import SelfAttentionLayer
    from deeplearning4j_tpu.conf.layers_cnn import (
        ConvolutionLayer,
        ConvolutionMode,
        FusedConvBN1x1,
    )
    from deeplearning4j_tpu.conf.layers_quant import (
        QuantizedConv1x1Layer,
        QuantizedDenseLayer,
    )

    if isinstance(layer, QuantizedDenseLayer) \
            and type(layer).forward is QuantizedDenseLayer.forward \
            and _elementwise(layer.activation):
        try:
            from deeplearning4j_tpu.conf.layers import _as_ff_size

            k = _as_ff_size(itype)
        except ValueError:
            return None
        return ("matmul_bias_act_int8",
                _env(batch, k, layer.n_out, "int8",
                     act=layer.activation.value, mode=mode))
    if isinstance(layer, QuantizedConv1x1Layer) \
            and type(layer).forward is QuantizedConv1x1Layer.forward \
            and isinstance(itype, it.Convolutional) \
            and _elementwise(layer.activation):
        sh, sw = _pair(layer.stride)
        m = batch * (-(-itype.height // sh)) * (-(-itype.width // sw))
        return ("matmul_bias_act_int8",
                _env(m, itype.channels, layer.n_out, "int8",
                     act=layer.activation.value, mode=mode))
    if isinstance(layer, SelfAttentionLayer) \
            and type(layer).forward is SelfAttentionLayer.forward \
            and isinstance(itype, it.Recurrent) \
            and itype.timesteps and itype.timesteps > 0 \
            and layer.attention_impl in ("auto", "flash"):
        h = layer.n_heads if layer.project_input else 1
        t = itype.timesteps
        # masked=False: the planned fit envelope is the no-feature-mask
        # path; a masked fit derives its own envelope at trace time
        return ("flash_attention",
                _attn_env(batch, h, t, t, layer._head_size(itype.size),
                          dtype, causal=layer.causal, masked=False,
                          mode=mode))
    if isinstance(layer, FusedConvBN1x1) \
            and type(layer).forward is FusedConvBN1x1.forward \
            and isinstance(itype, it.Convolutional):
        sh, sw = _pair(layer.stride)
        m = batch * (-(-itype.height // sh)) * (-(-itype.width // sw))
        return ("conv_bn_act",
                _env(m, itype.channels, layer.n_out, dtype, mode=mode))
    if isinstance(layer, ConvolutionLayer) \
            and type(layer).forward is ConvolutionLayer.forward \
            and isinstance(itype, it.Convolutional) \
            and _pair(layer.kernel_size) == (1, 1) \
            and _pair(layer.dilation) == (1, 1) \
            and (layer.convolution_mode is ConvolutionMode.SAME
                 or _pair(layer.padding) == (0, 0)) \
            and _elementwise(layer.activation):
        sh, sw = _pair(layer.stride)
        m = batch * (-(-itype.height // sh)) * (-(-itype.width // sw))
        return ("matmul_bias_act",
                _env(m, itype.channels, layer.n_out, dtype,
                     act=layer.activation.value, mode=mode))
    if isinstance(layer, DenseLayer) \
            and type(layer).forward is DenseLayer.forward \
            and not isinstance(itype, it.Recurrent) \
            and _elementwise(layer.activation):
        try:
            from deeplearning4j_tpu.conf.layers import _as_ff_size

            k = _as_ff_size(itype)
        except ValueError:
            return None
        return ("matmul_bias_act",
                _env(batch, k, layer.n_out, dtype,
                     act=layer.activation.value, mode=mode))
    return None


def plan_envelopes(conf, batch: int,
                   mode: Optional[str] = None) -> List[Tuple[str, object]]:
    """The ``(kernel_id, envelope)`` list a ``use_kernels`` fit of this
    conf at ``batch`` would try to route — what :func:`autotune_model`
    tunes. Derived from the conf's static shape chain, so it needs no
    params or data. Accepts a MultiLayerConfiguration (layer chain) or
    a ComputationGraphConfiguration (DAG walk over its LayerVertex
    specs, preprocessors applied)."""
    dtype = getattr(conf, "compute_dtype", None) or conf.dtype
    out: List[Tuple[str, object]] = []
    seen = set()

    def add(pair):
        if pair is None:
            return
        kid, env = pair
        if (kid, env.key) not in seen:
            seen.add((kid, env.key))
            out.append((kid, env))

    if hasattr(conf, "vertices"):  # ComputationGraphConfiguration
        types = conf.vertex_output_types()
        vmap = conf.vertex_map()
        inputs_t = dict(zip(conf.network_inputs, conf.input_types))
        for name in conf.topo_order():
            spec = vmap[name]
            layer = getattr(spec.vertex, "layer", None)
            if layer is None or len(spec.inputs) != 1:
                continue
            src = spec.inputs[0]
            itype = inputs_t.get(src, types.get(src))
            pre = getattr(spec.vertex, "preprocessor", None)
            if pre is not None and itype is not None:
                itype = pre.output_type(itype)
            if itype is not None:
                add(_layer_envelope(layer, itype, batch, dtype, mode))
    else:
        for layer, itype in zip(conf.layers, conf.input_types()):
            add(_layer_envelope(layer, itype, batch, dtype, mode))
    return out


def autotune_model(conf, batch: int, retune: bool = False,
                   **autotune_kw) -> List[object]:
    """Autotune every routable envelope of a model conf (MLN chain or
    graph DAG) at one batch size (already-tuned envelopes are skipped
    unless ``retune``). Returns the :class:`tuner.AutotuneResult` list;
    after this, a ``use_kernels`` fit at ``batch`` routes every planned
    layer."""
    from deeplearning4j_tpu.kernels import tuner as tuner_mod

    results = []
    for kid, env in plan_envelopes(conf, batch):
        kernel = REGISTRY.get(kid)
        if kernel is None or not kernel.supports(env):
            continue
        if not retune \
                and REGISTRY.tuning.winner(kid, env.key) is not None:
            continue
        results.append(tuner_mod.autotune(kernel, env, **autotune_kw))
    return results


def decoder_envelopes(decoder,
                      mode: Optional[str] = None
                      ) -> List[Tuple[str, object]]:
    """The attention ``(kernel_id, envelope)`` list a ``use_kernels``
    :class:`nn.decoding.TransformerDecoder` routes: one paged-decode
    envelope per KV bucket (the fused decode window runs at full
    ``max_batch``) and one flash envelope per (prompt bucket, join
    width) — cold prefill always attends under the prompt-length key
    mask, so those envelopes are ``masked=True``. Derived from the
    decoder's ladders and attention geometry; needs no params or
    traffic."""
    out: List[Tuple[str, object]] = []
    seen = set()

    def add(kid, env):
        if (kid, env.key) not in seen:
            seen.add((kid, env.key))
            out.append((kid, env))

    dtype = decoder._dtype
    geoms = set()
    for name, n_in in decoder._attn.items():
        layer = decoder._layer(name)
        geoms.add((layer.n_heads, layer._head_size(n_in)))
    for h, d in sorted(geoms):
        for s in decoder.kv_ladder:
            add("paged_decode_attention",
                _attn_env(decoder.max_batch, h, 1, s, d, dtype,
                          causal=True, masked=False, mode=mode))
        for tp in decoder.prompt_ladder:
            for bp in decoder.join_ladder:
                add("flash_attention",
                    _attn_env(bp, h, tp, tp, d, dtype, causal=True,
                              masked=True, mode=mode))
    return out


def autotune_decoder(decoder, retune: bool = False,
                     **autotune_kw) -> List[object]:
    """Autotune every attention envelope a ``use_kernels`` decoder would
    route (paged decode per KV bucket, flash prefill per prompt/join
    bucket pair). Run BEFORE ``warm_all``: selection is baked at trace
    time, so executables compiled before tuning keep the stock core
    until their key's digest token changes."""
    from deeplearning4j_tpu.kernels import tuner as tuner_mod

    results = []
    for kid, env in decoder_envelopes(decoder):
        kernel = REGISTRY.get(kid)
        if kernel is None or not kernel.supports(env):
            continue
        if not retune \
                and REGISTRY.tuning.winner(kid, env.key) is not None:
            continue
        results.append(tuner_mod.autotune(kernel, env, **autotune_kw))
    return results
