"""Pallas kernel subsystem: registry, per-shape autotuner, routing.

ROADMAP item 5: training is conv-compute-bound (BASELINE.md,
bench_conv_matrix.json — sync + ingest < 0.1%), so raw speed now only
comes from better kernels than the ones XLA emits. This package makes
the hand-kernel path SYSTEMATIC instead of ad hoc (the PyGraph
compiler-integration argument, arXiv:2503.19779):

- ``registry``: named Pallas kernels (fused conv+BN statistics — the
  round-2 ``ops/conv_fused`` experiment — and a tiled
  matmul+bias+activation), each with a declared shape/dtype envelope,
  a tiling parameter space, and the ``jax.lax`` reference it must
  match;
- ``tuner``: the per-(shape, dtype, backend) autotuner and the
  digest-verified on-disk tuning cache (temp+rename; corruption is a
  named refusal + stock-XLA fallback);
- ``routing``: the forward-pass dispatch behind ``conf.use_kernels``
  (default OFF — bit-identical to no subsystem at all) plus the
  capability probe (real Mosaic lowering on TPU, the Pallas
  interpreter everywhere else so CPU containers validate the same
  kernel bodies end to end).

Selection is keyed into ``optimize/aot_cache`` via
``cache_tag(conf)``'s ``kern:<id>:<digest>`` tokens: a retuned kernel
is a NEW executable, an untuned shape is stock XLA, and the program
linter's PRG207 audits every token against this registry.

See docs/kernels.md.
"""

from __future__ import annotations

import os

from deeplearning4j_tpu.kernels import impls as impls  # noqa: F401
from deeplearning4j_tpu.kernels import registry as registry  # noqa: F401
from deeplearning4j_tpu.kernels import routing as routing  # noqa: F401
from deeplearning4j_tpu.kernels import tuner as tuner  # noqa: F401
from deeplearning4j_tpu.kernels.registry import (  # noqa: F401
    AttentionEnvelope,
    Kernel,
    KernelRegistry,
    MatmulEnvelope,
    REGISTRY,
    Selection,
)
from deeplearning4j_tpu.kernels.routing import (  # noqa: F401
    autotune_decoder,
    autotune_model,
    backend,
    capability,
    decoder_envelopes,
    maybe_decode_attention,
    maybe_flash_attention,
    maybe_forward,
    maybe_vertex_forward,
    plan_envelopes,
)
from deeplearning4j_tpu.kernels.tuner import (  # noqa: F401
    AutotuneResult,
    TUNING,
    TuningCache,
    TuningCacheCorruptError,
    autotune,
    set_tuning_cache,
)


def tuning_digest(kernel_id: str) -> str:
    """The registry's current 8-hex tuning digest for one kernel (what
    the ``kern:<id>:<digest>`` key tokens carry)."""
    return REGISTRY.tuning_digest(kernel_id)


def cache_tag(conf=None) -> str:
    """The step-key token string for a model conf: empty unless
    ``conf.use_kernels`` (so every pre-subsystem key is unchanged),
    else one ``:kern:<id>:<digest>`` token per registered kernel.
    Cheap per call — digests are memoized against the tuning-cache
    epoch — so fit loops re-check it every dispatch and rebuild their
    step on a retune."""
    if conf is not None and not getattr(conf, "use_kernels", False):
        return ""
    return REGISTRY.cache_tag()


# opt-in persistent cache via environment (bound lazily so importing
# the package never touches the filesystem unless asked)
_ENV_CACHE = "DL4J_TPU_KERNEL_CACHE"
if os.environ.get(_ENV_CACHE):
    try:
        set_tuning_cache(os.environ[_ENV_CACHE])
    except TuningCacheCorruptError:
        # refused: the named error already detached the file; selection
        # runs on stock XLA until a fresh cache is bound
        pass
