"""Per-shape kernel autotuner + persistent digest-verified tuning cache.

The autotuner benchmarks every legal tiling candidate a registry kernel
declares for one concrete ``(shape, dtype, backend)`` envelope and
records the winner into a process-global :class:`TuningCache`. The
cache persists to disk with the checkpoint discipline (canonical JSON,
sha256 content digest recorded inside the file, temp + ``os.replace``
publish), so winners tuned in one process select identically in the
next — and a hand-edited/corrupt file is REFUSED with a named error
(:class:`TuningCacheCorruptError`) while selection falls back to stock
XLA instead of running an unverified layout.

Every mutation bumps ``TuningCache.epoch``; the registry memoizes its
per-kernel tuning digests against the epoch, so the per-step "has the
winner set changed?" check the model fit paths run is two dict lookups,
not a hash pass.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

CACHE_VERSION = 1

# default trial protocol: candidates are compared by min-of-`trials`
# wall time after `warmup` discarded runs (min is the standard
# autotuner statistic: noise only ever ADDS time)
DEFAULT_WARMUP = 1
DEFAULT_TRIALS = 3
DEFAULT_MAX_CANDIDATES = 16


class TuningCacheCorruptError(RuntimeError):
    """A persisted tuning cache failed its digest/format verification.
    The cache refuses the file's winners (selection falls back to stock
    XLA); the error names the path and the reason."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"kernel tuning cache {path!r} refused: {reason}")
        self.path = path
        self.reason = reason


def _canonical(winners: dict) -> str:
    return json.dumps(winners, sort_keys=True, separators=(",", ":"))


def _digest(winners: dict) -> str:
    return hashlib.sha256(_canonical(winners).encode()).hexdigest()


class TuningCache:
    """``kernel_id -> {envelope_key -> {"tiling": [bm, bn, bk],
    "ms": float}}`` with optional disk persistence.

    Thread-safe; ``epoch`` increments on every mutation (record / load /
    clear) so digest consumers can memoize against it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._winners: Dict[str, Dict[str, dict]] = {}
        self.epoch = 0
        self.hits = 0
        self.misses = 0
        self.path: Optional[str] = None

    # --- selection --------------------------------------------------------
    def winner(self, kernel_id: str, env_key: str) -> Optional[dict]:
        """The recorded winner for one envelope (None = untuned — the
        caller falls back to stock XLA)."""
        with self._lock:
            rec = self._winners.get(kernel_id, {}).get(env_key)
            if rec is None:
                self.misses += 1
            else:
                self.hits += 1
            return dict(rec) if rec is not None else None

    def winners(self, kernel_id: str) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v)
                    for k, v in self._winners.get(kernel_id, {}).items()}

    def entries(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._winners.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": sum(len(v) for v in self._winners.values()),
                "hits": self.hits,
                "misses": self.misses,
                "epoch": self.epoch,
                "path": self.path,
            }

    # --- mutation ---------------------------------------------------------
    def record(self, kernel_id: str, env_key: str,
               tiling: Tuple[int, int, int], ms: float,
               backend: str = "") -> None:
        """Record one envelope's winning tiling (and persist when a path
        is bound)."""
        with self._lock:
            self._winners.setdefault(kernel_id, {})[env_key] = {
                "tiling": [int(t) for t in tiling],
                "ms": float(ms),
                "backend": backend,
            }
            self.epoch += 1
            if self.path is not None:
                self._save_locked()

    def clear(self) -> None:
        with self._lock:
            self._winners.clear()
            self.hits = self.misses = 0
            self.epoch += 1
            self.path = None

    # --- persistence ------------------------------------------------------
    def bind(self, path: str, load: bool = True) -> "TuningCache":
        """Attach a persistence path; an existing file is loaded (digest
        verified) and future records publish through it. A corrupt file
        raises :class:`TuningCacheCorruptError` AFTER resetting the
        in-memory winners — the process keeps running on stock XLA."""
        if load and os.path.exists(path):
            try:
                with open(path, "r") as f:
                    blob = json.load(f)
            except (OSError, ValueError) as e:
                self._refuse(path, f"unreadable JSON ({e})")
            if not isinstance(blob, dict) or "winners" not in blob \
                    or "digest" not in blob:
                self._refuse(path, "missing winners/digest fields")
            if int(blob.get("version", -1)) != CACHE_VERSION:
                self._refuse(path,
                             f"version {blob.get('version')!r} != "
                             f"{CACHE_VERSION}")
            if _digest(blob["winners"]) != blob["digest"]:
                self._refuse(path, "content digest mismatch")
            with self._lock:
                self._winners = {
                    str(k): {str(ek): dict(rec) for ek, rec in v.items()}
                    for k, v in blob["winners"].items()}
                self.epoch += 1
                self.path = path
        else:
            with self._lock:
                self.path = path
        return self

    def _refuse(self, path: str, reason: str) -> None:
        """Corruption: drop any half-loaded state, detach the path, and
        raise the NAMED error — selection falls back to stock XLA."""
        with self._lock:
            self._winners = {}
            self.epoch += 1
            self.path = None
        raise TuningCacheCorruptError(path, reason)

    def save(self) -> None:
        with self._lock:
            if self.path is None:
                raise ValueError("tuning cache has no bound path "
                                 "(call bind(path) first)")
            self._save_locked()

    def _save_locked(self) -> None:
        # checkpoint discipline: content digest recorded inside the
        # file, pid-suffixed temp + os.replace publish (a crash
        # mid-write leaves the prior complete file authoritative, and
        # two processes sharing one cache path never interleave writes
        # into the same temp file — the pod/serializer convention)
        blob = {
            "version": CACHE_VERSION,
            "winners": self._winners,
            "digest": _digest(self._winners),
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(blob, f, sort_keys=True, indent=1)
        os.replace(tmp, self.path)


# the process-global cache every selection reads
TUNING = TuningCache()


def set_tuning_cache(path: str, load: bool = True) -> TuningCache:
    """Bind the process-global tuning cache to ``path`` (loading an
    existing file, digest-verified). Raises
    :class:`TuningCacheCorruptError` on a refused file — the in-memory
    cache is left EMPTY, so kernel selection safely falls back to
    stock XLA."""
    return TUNING.bind(path, load=load)


# --------------------------------------------------------------------------
# the autotune loop
# --------------------------------------------------------------------------

class AutotuneResult:
    def __init__(self, kernel_id: str, env_key: str,
                 tiling: Tuple[int, int, int], ms: float,
                 trials: List[dict]):
        self.kernel_id = kernel_id
        self.env_key = env_key
        self.tiling = tiling
        self.ms = ms
        self.trials = trials

    def __repr__(self):
        return (f"AutotuneResult({self.kernel_id}, {self.env_key}, "
                f"tiling={self.tiling}, ms={self.ms:.3f}, "
                f"{len(self.trials)} candidates)")


def autotune(kernel, env, cache: Optional[TuningCache] = None,
             warmup: int = DEFAULT_WARMUP, trials: int = DEFAULT_TRIALS,
             max_candidates: int = DEFAULT_MAX_CANDIDATES,
             record: bool = True) -> AutotuneResult:
    """Benchmark ``kernel``'s legal tilings for one envelope and record
    the winner.

    ``kernel`` is a ``registry.Kernel``; ``env`` its envelope object.
    Each candidate compiles one jitted wrapper, runs ``warmup`` settle
    calls, then takes min-of-``trials`` wall time with the outputs
    forced. Off-TPU the kernel executes through the Pallas interpreter,
    so timings rank the interpreter, not the MXU — the machinery
    (sweep, winner record, persistence, digest re-keying) is what the
    CPU container validates; real rankings need the TPU backend
    (docs/kernels.md states the caveat).
    """
    import jax

    from deeplearning4j_tpu import telemetry

    cache = TUNING if cache is None else cache
    if not kernel.supports(env):
        raise ValueError(f"kernel {kernel.kernel_id!r} does not support "
                         f"envelope {env.key!r}")
    cands = kernel.candidates(env, limit=max_candidates)
    if not cands:
        raise ValueError(f"no legal tilings for envelope {env.key!r}")
    args = kernel.make_inputs(env, seed=0)
    results = []
    for tiling in cands:
        fn = jax.jit(kernel.build(env, tiling))
        try:
            for _ in range(max(1, warmup)):
                jax.block_until_ready(fn(*args))
            best = float("inf")
            for _ in range(max(1, trials)):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*args))
                best = min(best, time.perf_counter() - t0)
        except Exception as e:
            # a candidate the compiler rejects is a silent non-winner,
            # not an autotune failure (Mosaic tile limits vary by chip)
            results.append({"tiling": list(tiling), "error": repr(e)})
            telemetry.record_autotune_trial(kernel.kernel_id)
            continue
        results.append({"tiling": list(tiling), "ms": best * 1e3})
        telemetry.record_autotune_trial(kernel.kernel_id)
    timed = [r for r in results if "ms" in r]
    if not timed:
        raise RuntimeError(
            f"autotune: every candidate failed for {env.key!r}: {results}")
    win = min(timed, key=lambda r: r["ms"])
    if record:
        cache.record(kernel.kernel_id, env.key, tuple(win["tiling"]),
                     win["ms"], backend=env.backend)
        telemetry.record_autotune_winner(kernel.kernel_id)
        telemetry.record_tuning_cache(cache.hits, cache.entries())
    return AutotuneResult(kernel.kernel_id, env.key, tuple(win["tiling"]),
                          win["ms"], results)
