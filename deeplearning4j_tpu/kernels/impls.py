"""Tiling-parameterized Pallas kernel implementations.

These are the Mosaic-side bodies behind the kernel registry
(``kernels.registry``): each takes its tiling as an explicit
``(bm, bn, bk)`` triple so the per-shape autotuner (``kernels.tuner``)
can sweep the grid/block space instead of baking one hand-picked layout
(the ``ops/conv_fused`` experiment hard-codes 512/128/128 — the exact
"compiler-generated schedules leave tuning on the table" gap
arXiv:2207.00257 measures for high-level-construct transpilation).

Both kernels follow the ``ops/conv_fused`` discipline:

- forward is the Pallas pass (MXU matmul with a fused epilogue),
  ``interpret=True`` off-TPU so the CPU container executes the SAME
  kernel body through the Pallas interpreter (the backend-parity
  oracle);
- backward is a ``jax.custom_vjp`` built from plain XLA ops that
  recompute exactly what the stock path would have produced, so
  gradients track the ``jax.lax`` reference implementation and the
  kernel path stays drop-in for train steps (donation included —
  nothing here blocks input/output aliasing, pinned by the PRG201
  audit over kernel-bearing executables).

Tiling validity: a candidate ``(bm, bn, bk)`` is clamped per-dimension
to the problem size (``ebm = min(bm, m)`` ...) and is legal when every
clamped block divides its dimension exactly — the registry's envelope
check; shapes with no legal candidate fall back to stock XLA.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports can fail on CPU-only installs; interpret mode is
    # still available without the TPU lowering itself
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def has_pallas() -> bool:
    """Whether the Pallas TPU dialect is importable at all (its VMEM
    scratch types are needed even in interpret mode)."""
    return _HAS_PLTPU


def effective_tiling(m: int, k: int, n: int,
                     tiling: Tuple[int, int, int]) -> Tuple[int, int, int]:
    """Clamp a candidate tiling to the problem size."""
    bm, bn, bk = tiling
    return min(int(bm), m), min(int(bn), n), min(int(bk), k)


def tiling_valid(m: int, k: int, n: int,
                 tiling: Tuple[int, int, int]) -> bool:
    """True when every clamped block divides its dimension exactly (the
    grid covers the problem with no ragged tail)."""
    ebm, ebn, ebk = effective_tiling(m, k, n, tiling)
    return (ebm > 0 and ebn > 0 and ebk > 0
            and m % ebm == 0 and n % ebn == 0 and k % ebk == 0)


def _compiler_params(interpret: bool):
    if interpret or not _HAS_PLTPU:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


# --------------------------------------------------------------------------
# matmul + bias + elementwise activation (dense / 1x1-conv forward)
# --------------------------------------------------------------------------

def _mm_bias_act_kernel(x_ref, w_ref, b_ref, y_ref, acc, *, nk, act_fn):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    acc[...] += jax.lax.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _():
        z = acc[...] + b_ref[...].astype(jnp.float32)
        y_ref[...] = act_fn(z).astype(y_ref.dtype)


def _mm_bias_act_impl(x2, w2, b, act, tiling, interpret):
    m, k = x2.shape
    n = w2.shape[-1]
    ebm, ebn, ebk = effective_tiling(m, k, n, tiling)
    assert tiling_valid(m, k, n, tiling), (m, k, n, tiling)
    if not _HAS_PLTPU:  # pragma: no cover - interpret-only environments
        raise NotImplementedError("pallas tpu dialect unavailable")
    nbm, nbn, nbk = m // ebm, n // ebn, k // ebk
    return pl.pallas_call(
        functools.partial(_mm_bias_act_kernel, nk=nbk, act_fn=act.apply),
        grid=(nbm, nbn, nbk),
        in_specs=[pl.BlockSpec((ebm, ebk), lambda i, j, kk: (i, kk)),
                  pl.BlockSpec((ebk, ebn), lambda i, j, kk: (kk, j)),
                  pl.BlockSpec((1, ebn), lambda i, j, kk: (0, j))],
        out_specs=pl.BlockSpec((ebm, ebn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
        scratch_shapes=[pltpu.VMEM((ebm, ebn), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2, w2, b.reshape(1, n))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def matmul_bias_act(x2, w2, b, act, tiling, interpret):
    """``act(x2 @ w2 + b)`` as ONE tiled Pallas pass: the bias add and
    the elementwise activation run in the MXU epilogue (last K block)
    instead of as separate XLA passes over the output.

    x2: [M, K]; w2: [K, N]; b: [N]; ``act`` an elementwise
    ``conf.activations.Activation``; ``tiling`` a ``(bm, bn, bk)``
    candidate valid per :func:`tiling_valid`. Backward is plain XLA
    recomputing the pre-activation exactly as the stock dense forward
    would, so gradients match the reference path.
    """
    return _mm_bias_act_impl(x2, w2, b, act, tiling, interpret)


def _mm_bias_act_fwd(x2, w2, b, act, tiling, interpret):
    y = _mm_bias_act_impl(x2, w2, b, act, tiling, interpret)
    return y, (x2, w2, b)


def _mm_bias_act_bwd(act, tiling, interpret, res, g):
    x2, w2, b = res
    # recompute the pre-activation with the SAME ops the stock forward
    # uses (x @ W + b), then pull the cotangent through the activation —
    # the gradient is the reference path's gradient, not a kernel-shaped
    # approximation of it
    z = x2 @ w2 + b
    _, act_vjp = jax.vjp(act.apply, z)
    (dz,) = act_vjp(g.astype(z.dtype))
    dx = (dz @ w2.T).astype(x2.dtype)
    dw = (x2.T @ dz).astype(w2.dtype)
    db = jnp.sum(dz.astype(jnp.float32), axis=0).astype(b.dtype)
    return dx, dw, db


matmul_bias_act.defvjp(_mm_bias_act_fwd, _mm_bias_act_bwd)


# --------------------------------------------------------------------------
# matmul + per-channel sum / sum-of-squares (fused conv+BN statistics)
# --------------------------------------------------------------------------

def _mm_stats_kernel(x_ref, w_ref, y_ref, s_ref, q_ref, acc, *, nk):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    acc[...] += jax.lax.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _():
        # statistics over the OUTPUT-dtype y — matches the unfused path,
        # which rounds y to the storage dtype before the mean/var read
        # (same formulation as ops/conv_fused)
        yb = acc[...].astype(y_ref.dtype)
        y_ref[...] = yb
        y32 = yb.astype(jnp.float32)
        s_ref[...] = jnp.sum(y32, axis=0).reshape(s_ref.shape)
        q_ref[...] = jnp.sum(y32 * y32, axis=0).reshape(q_ref.shape)


def _mm_stats_impl(x2, w2, tiling, interpret):
    m, k = x2.shape
    n = w2.shape[-1]
    ebm, ebn, ebk = effective_tiling(m, k, n, tiling)
    assert tiling_valid(m, k, n, tiling), (m, k, n, tiling)
    if not _HAS_PLTPU:  # pragma: no cover - interpret-only environments
        raise NotImplementedError("pallas tpu dialect unavailable")
    nbm, nbn, nbk = m // ebm, n // ebn, k // ebk
    y, ssum, sq = pl.pallas_call(
        functools.partial(_mm_stats_kernel, nk=nbk),
        grid=(nbm, nbn, nbk),
        in_specs=[pl.BlockSpec((ebm, ebk), lambda i, j, kk: (i, kk)),
                  pl.BlockSpec((ebk, ebn), lambda i, j, kk: (kk, j))],
        out_specs=[pl.BlockSpec((ebm, ebn), lambda i, j, kk: (i, j)),
                   pl.BlockSpec((1, 1, ebn), lambda i, j, kk: (i, 0, j)),
                   pl.BlockSpec((1, 1, ebn), lambda i, j, kk: (i, 0, j))],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x2.dtype),
            jax.ShapeDtypeStruct((nbm, 1, n), jnp.float32),
            jax.ShapeDtypeStruct((nbm, 1, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((ebm, ebn), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2, w2)
    # reduce the per-row-block partials (tiny [nbm, N] arrays) in XLA
    return y, jnp.sum(ssum[:, 0], axis=0), jnp.sum(sq[:, 0], axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_stats(x2, w2, tiling, interpret):
    """``y = x2 @ w2`` plus per-output-channel ``sum(y)`` / ``sum(y*y)``
    (f32) in ONE output pass — the fused conv+BN statistics class
    (``ops/conv_fused``) with the tiling exposed to the autotuner.

    Returns ``(y [M, N] in x2.dtype, s [N] f32, q [N] f32)``.
    """
    return _mm_stats_impl(x2, w2, tiling, interpret)


def _mm_stats_fwd(x2, w2, tiling, interpret):
    y, s, q = _mm_stats_impl(x2, w2, tiling, interpret)
    return (y, s, q), (x2, w2, y)


def _mm_stats_bwd(tiling, interpret, res, cts):
    # identical math to ops/conv_fused._bwd: d(sum y)/dy = 1,
    # d(sum y^2)/dy = 2y — one combined cotangent, two MXU matmuls
    x2, w2, y = res
    gy, gs, gq = cts
    g = (gy.astype(jnp.float32) + gs[None, :]
         + 2.0 * y.astype(jnp.float32) * gq[None, :]).astype(x2.dtype)
    dx = jax.lax.dot(g, w2.T, preferred_element_type=jnp.float32)
    dw = jax.lax.dot(x2.T, g, preferred_element_type=jnp.float32)
    return dx.astype(x2.dtype), dw.astype(w2.dtype)


matmul_stats.defvjp(_mm_stats_fwd, _mm_stats_bwd)


# --------------------------------------------------------------------------
# int8 matmul + f32 scale/bias + activation (quantized dense / 1x1-conv)
# --------------------------------------------------------------------------

def _mm_bias_act_q8_kernel(x_ref, w_ref, s_ref, b_ref, y_ref, acc, *, nk,
                           act_fn):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    # int8 x int8 -> int32: the MXU's native int8 path (the interpreter
    # runs the same accumulate in int32 on CPU)
    acc[...] += jax.lax.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.int32)

    @pl.when(kk == nk - 1)
    def _():
        # dequant-free epilogue: the per-output-channel scale already
        # carries the folded activation scales, the effective bias carries
        # the zero-point correction (see conf.layers_quant)
        z = (acc[...].astype(jnp.float32) * s_ref[...].astype(jnp.float32)
             + b_ref[...].astype(jnp.float32))
        y_ref[...] = act_fn(z).astype(y_ref.dtype)


def matmul_bias_act_int8(xq, wq, scale, b, act, tiling, interpret):
    """``act(int32_dot(xq, wq) * scale + b)`` as ONE tiled Pallas pass —
    the quantized-serving variant of :func:`matmul_bias_act`.

    xq: [M, K] int8 (already quantized in-graph); wq: [K, N] int8;
    scale/b: [N] f32 (effective scale/bias from
    ``nn.inference_opt.quantize_for_inference``). Forward-only: quantized
    layers never train, so there is no custom VJP — differentiating
    through this is a programming error and fails loudly in JAX.
    """
    m, k = xq.shape
    n = wq.shape[-1]
    ebm, ebn, ebk = effective_tiling(m, k, n, tiling)
    assert tiling_valid(m, k, n, tiling), (m, k, n, tiling)
    if not _HAS_PLTPU:  # pragma: no cover - interpret-only environments
        raise NotImplementedError("pallas tpu dialect unavailable")
    nbm, nbn, nbk = m // ebm, n // ebn, k // ebk
    return pl.pallas_call(
        functools.partial(_mm_bias_act_q8_kernel, nk=nbk, act_fn=act.apply),
        grid=(nbm, nbn, nbk),
        in_specs=[pl.BlockSpec((ebm, ebk), lambda i, j, kk: (i, kk)),
                  pl.BlockSpec((ebk, ebn), lambda i, j, kk: (kk, j)),
                  pl.BlockSpec((1, ebn), lambda i, j, kk: (0, j)),
                  pl.BlockSpec((1, ebn), lambda i, j, kk: (0, j))],
        out_specs=pl.BlockSpec((ebm, ebn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((ebm, ebn), jnp.int32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(xq, wq, scale.reshape(1, n), b.reshape(1, n))
