"""Shared findings model for the static-analysis passes.

Both linters — the program pass (``analysis.program``, jaxpr/HLO checks
hooked into the AOT-cache compile path) and the source pass
(``analysis.source``, AST checks over the repo) — report through one
vocabulary: a :class:`Finding` carries a stable rule id, a severity, a
location, and (when the repo explicitly accepts the behavior) a waiver.

Waivers are inline comments in the flagged source (``<RULE>`` is a
placeholder here so this docstring does not itself parse as a waiver)::

    x = arr.item()  # dl4j: waive <RULE> — score() is a sync point by contract

optionally time-boxed::

    # dl4j: waive <RULE> until=2026-12-01 — kept for the pallas backport

An expired waiver stops suppressing (the finding comes back), and a
waiver that matches nothing raises ``SRC100 stale-waiver`` so dead
suppressions cannot accumulate. The program pass waives by cache-key
substring instead (no source line to annotate) — see
``analysis.program.waive_program``.

Every recorded unwaived finding increments
``dl4j_analysis_findings_total{rule,severity}`` in the telemetry
registry, so a live process's ``/metrics`` shows what compile-time lint
saw without anyone re-running the CLI.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Dict, List, Optional, Tuple

# severity ladder; make lint fails on unwaived findings >= WARN
INFO = "INFO"
WARN = "WARN"
ERROR = "ERROR"
_ORDER = {INFO: 10, WARN: 20, ERROR: 30}


def severity_at_least(sev: str, floor: str) -> bool:
    return _ORDER.get(sev, 0) >= _ORDER.get(floor, 0)


@dataclasses.dataclass
class Finding:
    rule: str          # stable id: SRC1xx (source) / PRG2xx (program)
    severity: str      # INFO / WARN / ERROR
    message: str
    location: str      # "path/to/file.py:123" or "graph=abcd kind=train_step"
    waived: bool = False
    waiver_reason: str = ""

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        w = f" [waived: {self.waiver_reason}]" if self.waived else ""
        return f"{self.severity:5s} {self.rule} {self.location}: " \
               f"{self.message}{w}"


# --------------------------------------------------------------------------
# inline waivers
# --------------------------------------------------------------------------

# matches "dl4j: waive <rule>[,<rule>...] [until=YYYY-MM-DD] — reason"
WAIVER_RE = re.compile(
    r"#\s*dl4j:\s*waive\s+(?P<rules>[A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
    r"(?:\s+until=(?P<until>\d{4}-\d{2}-\d{2}))?"
    r"\s*(?:—|--|-)\s*(?P<reason>.+?)\s*$")


@dataclasses.dataclass
class Waiver:
    rules: Tuple[str, ...]
    reason: str
    line: int                 # line the comment sits on
    until: Optional[str] = None  # ISO date; past = expired
    used: bool = False

    def expired(self, today: Optional[str] = None) -> bool:
        if self.until is None:
            return False
        if today is None:
            import datetime

            today = datetime.date.today().isoformat()
        return self.until < today

    def covers(self, rule: str, line: int) -> bool:
        # a waiver suppresses findings on its own line, or — for a
        # standalone comment line — on the next line
        return rule in self.rules and line in (self.line, self.line + 1)


def parse_waivers(text: str) -> List[Waiver]:
    out = []
    for i, line in enumerate(text.splitlines(), start=1):
        m = WAIVER_RE.search(line)
        if m:
            rules = tuple(r.strip() for r in m.group("rules").split(","))
            out.append(Waiver(rules=rules, reason=m.group("reason"),
                              line=i, until=m.group("until")))
    return out


def apply_waivers(findings: List[Finding], waivers: List[Waiver],
                  filename: str, today: Optional[str] = None
                  ) -> List[Finding]:
    """Mark findings covered by an unexpired waiver; append a
    ``SRC100 stale-waiver`` for every waiver that suppressed nothing
    (including expired ones — an expired waiver is by definition no
    longer doing its job and must be refreshed or deleted)."""
    for f in findings:
        try:
            line = int(f.location.rsplit(":", 1)[1])
        except (IndexError, ValueError):
            continue
        for w in waivers:
            if w.covers(f.rule, line):
                if w.expired(today):
                    w.used = True  # matched, but out of date
                    f.message += f" (waiver expired {w.until})"
                else:
                    w.used = True
                    f.waived = True
                    f.waiver_reason = w.reason
                break
    for w in waivers:
        if not w.used:
            findings.append(Finding(
                rule="SRC100", severity=WARN,
                message=f"stale waiver for {', '.join(w.rules)}: suppresses "
                        f"nothing (fix landed? delete the comment)",
                location=f"{filename}:{w.line}"))
    return findings


# --------------------------------------------------------------------------
# process-global findings log (the program pass records here at compile
# time; /metrics and the UI read it)
# --------------------------------------------------------------------------

class FindingsLog:
    """Bounded thread-safe sink. ``counts`` survives the ring so a
    long-lived process keeps exact totals even after old entries age
    out."""

    _MAX = 500

    def __init__(self):
        self._lock = threading.Lock()
        self._items: List[Finding] = []
        self._counts: Dict[Tuple[str, str], int] = {}

    def record(self, finding: Finding) -> None:
        from deeplearning4j_tpu import telemetry

        with self._lock:
            if len(self._items) >= self._MAX:
                del self._items[: self._MAX // 4]
            self._items.append(finding)
            k = (finding.rule, finding.severity)
            self._counts[k] = self._counts.get(k, 0) + 1
        if not finding.waived:
            telemetry.record_analysis_finding(finding.rule,
                                              finding.severity)

    def items(self) -> List[Finding]:
        with self._lock:
            return list(self._items)

    def counts(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._counts)

    def clear(self) -> None:
        with self._lock:
            self._items.clear()
            self._counts.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "findings": [f.as_dict() for f in self._items],
                "counts": {f"{r}/{s}": n
                           for (r, s), n in sorted(self._counts.items())},
            }


LOG = FindingsLog()


def summarize(findings: List[Finding], min_severity: str = WARN) -> dict:
    """Counts for CLI exit-code logic: total / waived / actionable
    (unwaived at or above ``min_severity``)."""
    actionable = [f for f in findings
                  if not f.waived and severity_at_least(f.severity,
                                                        min_severity)]
    return {
        "total": len(findings),
        "waived": sum(1 for f in findings if f.waived),
        "actionable": len(actionable),
    }
