"""Program linter: jaxpr + compiled-HLO checks on every AOT-cache miss.

The framework's memory and dispatch story rests on properties of the
COMPILED step executables that nothing used to verify: train steps must
donate their params/opt buffers (the fused/ZeRO memory claims are void
without input→output aliasing), no host callback may hide inside a step,
ZeRO steps must reduce-scatter rather than all-reduce, bucketed
collective chains must keep their ``optimization_barrier`` issue-order
pins, and closure-captured arrays must not get baked into executables as
constants (silent memory bloat + a recompile per captured object).
PyGraph (arXiv:2503.19779) makes the same argument for CUDA-graph
capture: whole-program dispatch is only safe when a compiler-side check
enforces the capture rules; arXiv:2112.01075 shows collective placement
is auditable from the lowered program alone.

``optimize.aot_cache`` calls :func:`on_compile` from its lower/compile
miss path (every executable the process ever caches passes through
here). Findings land in ``analysis.findings.LOG`` and the
``dl4j_analysis_findings_total`` metric; ``DL4J_TPU_PROGRAM_LINT=0``
disables the hook, ``=strict`` additionally raises on unwaived ERROR
findings (CI fixtures). The pass never retraces: the cache's miss path
already produces the Traced (jaxpr) and Compiled (HLO) artifacts, and
linting reads those.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.analysis.findings import (
    ERROR,
    WARN,
    Finding,
    LOG,
)

# step kinds whose executables MUST donate (alias) their buffers: the
# model train steps, the fused/tbptt scans, every ParallelWrapper SPMD
# step kind ("pw_*" — including the pod-path multi-process keys, which
# carry a ":p<N>" process-topology token so a pod executable never
# collides with a single-host one; donation + collective audit apply to
# them unchanged), and the KV-cached generation path — "decode_step*"
# consumes the whole decode state (the KV caches dominate it) every
# fused window, "prefill*" (prefill_join) scatters prompt KV into it,
# and "gen_release*" passes it through with rows masked; a non-donated
# decode-state executable silently doubles KV memory every token. The
# speculative-decoding window ("spec_verify*"), the draft's fused
# sync+window ("spec_draft*") and standalone reconciliation
# ("spec_sync*") consume the same decode state, as do the prefix-cache
# scatter ("prefix_attach*") and suffix join ("prefix_join*") — all
# donate for the same reason. The suffix PREFILL ("gen_prompt_sfx*")
# is deliberately absent: its prefix-page input is a shared refcounted
# buffer other requests attach concurrently, so it must NOT donate
# (same construction-level exemption as gen_prompt).
TRAIN_KIND_PREFIXES = ("train_step", "fused_scan", "tbptt_scan", "pw_",
                       "decode_step", "prefill", "gen_release",
                       "spec_verify", "spec_sync", "spec_draft",
                       "prefix_attach", "prefix_join")

# pod/reshard data-plane kinds (comms.reshard commit_compiled /
# recut_flat — the pod checkpoint restore-across-pod-shapes route):
# every OTHER program rule applies to them (baked consts, f64 leaks,
# callbacks, collective audit), but they are deliberately NOT in
# TRAIN_KIND_PREFIXES — exempting them from the PRG201 donation
# expectation BY CONSTRUCTION: a cross-placement recommit's source and
# target layouts have different per-device buffer sizes, which XLA
# cannot alias — demanding donation there would force a waiver on
# every pod restore (test_pod pins that they never enter the donation
# audit and compile finding-free).
RESHARD_KIND_PREFIXES = ("pod_recut", "reshard_commit")

ALL_REDUCE_PRIMS = frozenset({"psum", "psum2", "all_reduce"})
REDUCE_SCATTER_PRIMS = frozenset({"psum_scatter", "reduce_scatter"})
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed",
})

# closure-captured consts above WARN_BYTES are reported; above
# ERROR_BYTES they are treated as baked-in weights (the classic
# "jitted over self.params instead of passing them" bug)
CONST_WARN_BYTES = 1 << 20   # 1 MiB
CONST_ERROR_BYTES = 16 << 20  # 16 MiB


class ProgramLintError(RuntimeError):
    """Raised in strict mode when a compile produces an unwaived ERROR."""

    def __init__(self, findings: List[Finding]):
        super().__init__("; ".join(f.render() for f in findings))
        self.findings = findings


@dataclasses.dataclass
class ProgramArtifact:
    """Everything one compile exposes to the rules. ``jaxpr`` may be
    None (a jax without ``jit.trace``); jaxpr-based rules then skip.
    ``sibling_sigs``: signatures already cached under the same
    (graph_key, fn_key) — the recompile-hazard diff input."""

    graph_key: str
    fn_key: str
    jaxpr: object = None                  # ClosedJaxpr
    executable: object = None             # jax Compiled
    signature: object = None              # aot_cache.signature_of(args)
    sibling_sigs: Tuple = ()
    _aliases: object = dataclasses.field(default=False, repr=False)

    @property
    def location(self) -> str:
        return f"graph={str(self.graph_key)[:12]} kind={self.fn_key}"

    def is_train_kind(self) -> bool:
        return self.fn_key.startswith(TRAIN_KIND_PREFIXES)

    def alias_count(self):
        """Cached: ``executable.as_text()`` renders the whole optimized
        HLO module, so the donation rule and the audit must share one
        pass over it."""
        if self._aliases is False:
            self._aliases = (_alias_count(self.executable)
                             if self.executable is not None else None)
        return self._aliases


# --------------------------------------------------------------------------
# waivers (no source line to annotate: program waivers match on the
# cache key instead, registered next to the wrap() callsite)
# --------------------------------------------------------------------------

_WAIVERS: List[Tuple[str, str, str]] = []


def waive_program(rule: str, key_substring: str, reason: str) -> None:
    """Accept ``rule`` findings for executables whose
    ``graph_key + fn_key`` contains ``key_substring``. Register next to
    the ``aot_cache.wrap`` callsite the waiver justifies."""
    _WAIVERS.append((rule, key_substring, reason))


def _apply_waivers(art: ProgramArtifact,
                   findings: List[Finding]) -> List[Finding]:
    hay = f"{art.graph_key}{art.fn_key}"
    for f in findings:
        for rule, sub, reason in _WAIVERS:
            if f.rule == rule and sub in hay:
                f.waived = True
                f.waiver_reason = reason
                break
    return findings


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------

def iter_eqns(closed_jaxpr):
    """Yield every eqn in a ClosedJaxpr, recursing into sub-jaxprs
    (scan/while/cond bodies, pjit/shard_map call_jaxprs, custom-vjp
    branches) wherever they appear in eqn params."""
    seen = set()

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for eqn in jaxpr.eqns:
            yield eqn
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else (v,)
                for sub in vals:
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        yield from walk(inner)      # ClosedJaxpr
                    elif hasattr(sub, "eqns"):
                        yield from walk(sub)        # raw Jaxpr

    yield from walk(closed_jaxpr.jaxpr)


def _prim_counts(closed_jaxpr) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for eqn in iter_eqns(closed_jaxpr):
        n = eqn.primitive.name
        counts[n] = counts.get(n, 0) + 1
    return counts


def _alias_count(executable) -> Optional[int]:
    """Input→output alias entries in the compiled module (the HLO-level
    truth of donation: jit-side donate_argnums that XLA could not honor
    — dtype mismatch, non-donatable layout — silently drop the alias,
    which is exactly what this rule exists to surface). None = the
    backend exposed no HLO text (rule skips, intent check takes over)."""
    try:
        text = executable.as_text()
    except Exception:
        return None
    header = text.split("\n", 1)[0]
    i = header.find("input_output_alias={")
    if i < 0:
        return 0
    # balanced-brace scan: alias entries themselves contain "{}", so a
    # substring search for the closing brace picks the wrong one
    depth, start = 0, header.index("{", i)
    end = len(header)
    for j in range(start, len(header)):
        if header[j] == "{":
            depth += 1
        elif header[j] == "}":
            depth -= 1
            if depth == 0:
                end = j
                break
    seg = header[start:end + 1]
    return seg.count("may-alias") + seg.count("must-alias")


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------

def _rule_donation(art: ProgramArtifact, out: List[Finding]) -> None:
    """PRG201: a train-step executable with zero input→output aliases
    keeps TWO copies of params/opt state live across every step —
    defeats the fused-scan and ZeRO memory story and doubles peak HBM."""
    if not art.is_train_kind() or art.executable is None:
        return
    n = art.alias_count()
    if n == 0:
        out.append(Finding(
            rule="PRG201", severity=ERROR, location=art.location,
            message="train-step executable has no input/output donation "
                    "aliasing — params/opt buffers are copied, not "
                    "reused (add donate_argnums to the jit)"))


def _rule_baked_constants(art: ProgramArtifact, out: List[Finding]) -> None:
    """PRG202: large arrays captured as jaxpr consts are baked into the
    executable — silent device-memory bloat, and a fresh capture (a
    rebuilt closure) recompiles the whole program."""
    if art.jaxpr is None:
        return
    for c in getattr(art.jaxpr, "consts", ()):
        nbytes = getattr(c, "nbytes", 0) or 0
        if nbytes >= CONST_WARN_BYTES:
            sev = ERROR if nbytes >= CONST_ERROR_BYTES else WARN
            shape = getattr(c, "shape", ())
            dtype = getattr(c, "dtype", "?")
            out.append(Finding(
                rule="PRG202", severity=sev, location=art.location,
                message=f"closure-captured constant {shape} {dtype} "
                        f"({nbytes / (1 << 20):.1f} MiB) baked into the "
                        f"executable — pass it as an argument"))


def _rule_dtype_promotion(art: ProgramArtifact, out: List[Finding]) -> None:
    """PRG203: f64 values inside a graph with no f64 inputs (a python
    float / enable_x64 promotion leak — doubles the op's cost on TPU,
    where f64 emulation is catastrophic). bf16→f32 promotions are NOT
    flagged: mixed-precision steps keep f32 masters/losses by design."""
    if art.jaxpr is None:
        return
    in_dtypes = {str(getattr(a, "dtype", "")) for a in art.jaxpr.in_avals}
    if "float64" in in_dtypes:
        return  # caller asked for f64 (x64 gradcheck); nothing leaked
    f64_prims = set()
    for eqn in iter_eqns(art.jaxpr):
        for v in eqn.outvars:
            if str(getattr(v.aval, "dtype", "")) == "float64":
                f64_prims.add(eqn.primitive.name)
    if f64_prims:
        out.append(Finding(
            rule="PRG203", severity=WARN, location=art.location,
            message=f"f64 values inside a graph with no f64 inputs "
                    f"(promotion leak in: "
                    f"{', '.join(sorted(f64_prims)[:6])})"))


def _rule_host_callback(art: ProgramArtifact, out: List[Finding]) -> None:
    """PRG204: a host callback inside a compiled step serializes the
    device on the host every dispatch — the exact sync the AOT cache
    exists to eliminate."""
    if art.jaxpr is None:
        return
    hits = sorted(set(_prim_counts(art.jaxpr)) & CALLBACK_PRIMS)
    if hits:
        out.append(Finding(
            rule="PRG204", severity=ERROR, location=art.location,
            message=f"host callback/transfer inside the compiled step: "
                    f"{', '.join(hits)}"))


def _rule_collectives(art: ProgramArtifact, out: List[Finding]) -> None:
    """PRG205: collective audit. (a) a ZeRO step whose gradient exchange
    all-reduces instead of reduce-scattering moves n× the bytes and
    replicates what sharding was meant to split; (b) a bucketed schedule
    with multiple scatter collectives but no ``optimization_barrier``
    lets XLA merge/reorder the buckets — the overlap schedule silently
    degrades to one fused exchange; (c) scheduler-emitted plans: a step
    key carrying ``plan:<digest>`` tokens promised a specific collective
    sequence — op kinds, bucket count, barrier chain — and the compiled
    module must deliver it (``comms.scheduler.lookup_plan`` resolves the
    digests). Single-bucket plans and the legacy ``:b0`` fused exchange
    are variadic single collectives with legitimately no ordering chain
    — exempt."""
    if art.jaxpr is None:
        return
    counts = _prim_counts(art.jaxpr)
    n_allreduce = sum(counts.get(p, 0) for p in ALL_REDUCE_PRIMS)
    n_scatter = sum(counts.get(p, 0) for p in REDUCE_SCATTER_PRIMS)
    n_barrier = counts.get("optimization_barrier", 0)
    _audit_scheduler_plans(art, counts, n_allreduce, n_scatter,
                           n_barrier, out)
    if art.fn_key.startswith("pw_zero"):
        if n_allreduce and not n_scatter:
            out.append(Finding(
                rule="PRG205", severity=ERROR, location=art.location,
                message="ZeRO-mode step contains all-reduce collectives "
                        "but no reduce-scatter — the gradient exchange "
                        "is not sharded"))
        # barrier audit only when the key declares a bucketed schedule
        # (":b<nonzero>"): the fused b0 exchange has one variadic
        # collective (per-leaf eqns) and legitimately no ordering chain.
        # Caveat: a bucket size that swallows the whole tree also yields
        # one bucket — that WARN means "your bucket config is inert",
        # which is worth hearing too.
        m = re.search(r":b(\d+)", art.fn_key)
        if (m and int(m.group(1)) > 0 and n_scatter >= 2
                and n_barrier == 0):
            out.append(Finding(
                rule="PRG205", severity=WARN, location=art.location,
                message=f"{n_scatter} scatter collectives with no "
                        f"optimization_barrier issue-order chain — "
                        f"buckets can merge/reorder"))


def _audit_scheduler_plans(art: ProgramArtifact, counts, n_allreduce,
                           n_scatter, n_barrier,
                           out: List[Finding]) -> None:
    """PRG205(c): verify the compiled collective sequence against every
    scheduler plan whose digest the step key carries."""
    digests = re.findall(r"plan:([0-9a-f]{8,40})", art.fn_key)
    if not digests:
        return
    try:
        from deeplearning4j_tpu.comms import scheduler as comms_sched

        plans = [p for d in digests
                 if (p := comms_sched.lookup_plan(d)) is not None]
    except Exception:
        return  # keys minted elsewhere / comms unavailable: nothing to say
    if not plans:
        return
    n_gather = counts.get("all_gather", 0)
    exp_barriers = sum(max(0, p.launches() - 1) for p in plans)
    for p in plans:
        if (p.intent == "reduce_scatter" and n_scatter == 0
                and n_allreduce):
            out.append(Finding(
                rule="PRG205", severity=ERROR, location=art.location,
                message=f"plan {p.digest} promised reduce-scatter but "
                        f"the module compiled all-reduce collectives "
                        f"only — the gradient exchange is not sharded"))
        if (p.intent == "all_gather" and "all_gather" in p.choices
                and n_gather == 0 and n_allreduce == 0):
            out.append(Finding(
                rule="PRG205", severity=WARN, location=art.location,
                message=f"plan {p.digest} promised a native all-gather "
                        f"but the module contains no gather (or masked-"
                        f"psum) collective"))
    # expected scatter launches: >= one psum_scatter eqn per leaf, so at
    # least one per bucket — fewer means buckets merged despite the pins
    exp_scatter = sum(p.launches() for p in plans
                      if p.intent == "reduce_scatter")
    if exp_scatter and 0 < n_scatter < exp_scatter:
        out.append(Finding(
            rule="PRG205", severity=WARN, location=art.location,
            message=f"scheduler plans promised >= {exp_scatter} "
                    f"reduce-scatter launches; module has {n_scatter} — "
                    f"buckets merged"))
    if exp_barriers and n_barrier < exp_barriers:
        out.append(Finding(
            rule="PRG205", severity=WARN, location=art.location,
            message=f"scheduler plans promised {exp_barriers} "
                    f"optimization_barrier issue-order pins; module has "
                    f"{n_barrier} — buckets can merge/reorder"))


def _near_miss(sig_a, sig_b) -> Optional[str]:
    """Classify two cache signatures as a near-miss recompile hazard.
    Returns a human reason, or None when the recompile was legitimate
    (shape change, different arity/structure)."""
    try:
        leaves_a, tree_a = sig_a
        leaves_b, tree_b = sig_b
    except (TypeError, ValueError):
        return None
    if tree_a != tree_b or len(leaves_a) != len(leaves_b):
        return None
    reasons = []
    for i, (a, b) in enumerate(zip(leaves_a, leaves_b)):
        if a == b:
            continue
        if isinstance(a, str) or isinstance(b, str):
            # one side traced as a weak-typed python scalar
            reasons.append(f"leaf {i}: python scalar vs array "
                           f"({a!r} vs {b!r})")
        elif (isinstance(a, tuple) and isinstance(b, tuple)
                and len(a) >= 2 and len(b) >= 2 and a[0] == b[0]):
            reasons.append(f"leaf {i}: same shape {a[0]}, dtype "
                           f"{a[1]} vs {b[1]} (weak-type churn?)")
        else:
            return None  # a real shape/layout change: legitimate miss
    return "; ".join(reasons) if reasons else None


# kern:<id>:<digest> tokens minted by kernels.cache_tag() into
# use_kernels step keys — the kernel-registry audit's input
_KERNEL_TOKEN_RE = re.compile(r"kern:([A-Za-z0-9_]+):([0-9a-f]{8})")


def _rule_kernel_registry(art: ProgramArtifact,
                          out: List[Finding]) -> None:
    """PRG207: executables whose key carries ``kern:<id>:<digest>``
    tokens promised to route through the Pallas kernel registry —
    (a) an id that does not resolve in the registry means the
    executable was keyed against kernels this process cannot audit
    (ERROR); (b) a key-time tuning digest that mismatches the
    registry's CURRENT winner table means the executable bakes a
    stale/unknown tuned layout — a retune is supposed to mint a NEW
    key, so a mismatch is a dispatch of an unverified kernel (ERROR).
    PRG201 applies unchanged to kernel-bearing train kinds (the token
    is a suffix; the kind prefix still classifies)."""
    tokens = _KERNEL_TOKEN_RE.findall(art.fn_key)
    if not tokens:
        return
    try:
        from deeplearning4j_tpu import kernels as kmod
    except Exception:
        out.append(Finding(
            rule="PRG207", severity=ERROR, location=art.location,
            message="step key carries kern:<id>:<digest> tokens but the "
                    "kernel registry is unavailable — the executable "
                    "cannot be audited"))
        return
    for kid, digest in tokens:
        if kmod.REGISTRY.get(kid) is None:
            out.append(Finding(
                rule="PRG207", severity=ERROR, location=art.location,
                message=f"key token kern:{kid}:{digest} does not resolve "
                        f"through the kernel registry (known kernels: "
                        f"{', '.join(kmod.REGISTRY.ids()) or 'none'})"))
            continue
        current = kmod.tuning_digest(kid)
        if digest != current:
            out.append(Finding(
                rule="PRG207", severity=ERROR, location=art.location,
                message=f"key-time tuning digest {digest} for kernel "
                        f"{kid!r} mismatches the registry's current "
                        f"winner table ({current}) — stale executable "
                        f"vs a retune; rebuild the step so the key "
                        f"re-mints"))


# q:<scheme>:<digest8> tokens minted by MultiLayerNetwork._qtag() into
# quantized-artifact step keys — the calibration-liveness audit's input.
# The leading (^|:) anchor keeps ids like "seq:..." from aliasing.
_QUANT_TOKEN_RE = re.compile(r"(?:^|:)q:([A-Za-z0-9_]+):([0-9a-f]{8})")


def _rule_quant_calibration(art: ProgramArtifact,
                            out: List[Finding]) -> None:
    """PRG208: executables whose key carries ``q:<scheme>:<digest8>``
    tokens were traced from a quantized artifact — (a) a scheme this
    build does not implement means the executable's math cannot be
    audited (ERROR); (b) a digest with no live calibration record means
    the executable outlived a recalibration or a registry restore never
    happened — it bakes scales no record vouches for (ERROR). A
    recalibration mints a new digest and therefore a new key; the stale
    executable surviving under the old token is exactly what this rule
    catches. PRG201 applies unchanged to quantized train kinds."""
    tokens = _QUANT_TOKEN_RE.findall(art.fn_key)
    if not tokens:
        return
    try:
        from deeplearning4j_tpu.nn import inference_opt as iopt
    except Exception:
        out.append(Finding(
            rule="PRG208", severity=ERROR, location=art.location,
            message="step key carries q:<scheme>:<digest> tokens but the "
                    "quantization pass is unavailable — the executable "
                    "cannot be audited"))
        return
    for scheme, digest in tokens:
        if scheme not in iopt.QUANT_SCHEMES:
            out.append(Finding(
                rule="PRG208", severity=ERROR, location=art.location,
                message=f"key token q:{scheme}:{digest} names a "
                        f"quantization scheme this build does not "
                        f"implement (supported: "
                        f"{', '.join(iopt.QUANT_SCHEMES)})"))
            continue
        rec = iopt.lookup_calibration(digest)
        if rec is None:
            out.append(Finding(
                rule="PRG208", severity=ERROR, location=art.location,
                message=f"key token q:{scheme}:{digest} does not resolve "
                        f"to a live calibration record — stale executable "
                        f"vs a recalibration (or a quantized restore that "
                        f"skipped ModelRegistry.load); rebuild the step "
                        f"so the key re-mints"))
        elif rec.scheme != scheme:
            out.append(Finding(
                rule="PRG208", severity=ERROR, location=art.location,
                message=f"key token q:{scheme}:{digest} resolves to a "
                        f"calibration record of scheme {rec.scheme!r} — "
                        f"token/record drift"))


def _rule_recompile_hazard(art: ProgramArtifact,
                           out: List[Finding]) -> None:
    """PRG206: this miss differs from an already-cached signature only
    in python-scalar/dtype leaves — the classic silent-recompile churn
    (an int passed one call, np.int32 the next). One finding per
    compile, naming the first near-miss sibling."""
    if art.signature is None:
        return
    for sib in art.sibling_sigs:
        reason = _near_miss(art.signature, sib)
        if reason:
            out.append(Finding(
                rule="PRG206", severity=WARN, location=art.location,
                message=f"near-miss recompile — signature churn, not a "
                        f"shape change: {reason}. Pin the argument's "
                        f"dtype (np.int32/np.float32) at the callsite"))
            return


_RULES = (
    _rule_donation,
    _rule_baked_constants,
    _rule_dtype_promotion,
    _rule_host_callback,
    _rule_collectives,
    _rule_kernel_registry,
    _rule_quant_calibration,
    _rule_recompile_hazard,
)


def lint_program(art: ProgramArtifact) -> List[Finding]:
    """Run every program rule over one compile's artifacts."""
    out: List[Finding] = []
    for rule in _RULES:
        rule(art, out)
    return _apply_waivers(art, out)


# --------------------------------------------------------------------------
# the AOT-cache hook
# --------------------------------------------------------------------------

# (graph_key, fn_key) -> {"aliases": int|None, "findings": int} for every
# train-kind compile this process performed — the donation-audit record
_AUDIT: Dict[Tuple[str, str], dict] = {}
_AUDIT_LOCK = threading.Lock()
# dedup: a (rule, graph, kind) triple is reported once per process, so a
# fallback-retracing loop cannot spam the log
_REPORTED: set = set()


def on_compile(key, traced, executable, sibling_keys=()) -> None:
    """Called by ``optimize.aot_cache`` after each lower/compile miss
    (under the cache lock — everything here is host-side and fast).
    ``key`` = (graph_key, fn_key, signature); ``traced`` = the jax
    Traced (or None); ``sibling_keys`` = cached keys sharing the
    (graph_key, fn_key) prefix."""
    graph_key, fn_key, signature = key[0], key[1], key[2]
    art = ProgramArtifact(
        graph_key=graph_key, fn_key=fn_key,
        jaxpr=getattr(traced, "jaxpr", None),
        executable=executable, signature=signature,
        sibling_sigs=tuple(k[2] for k in sibling_keys))
    findings = lint_program(art)
    if art.is_train_kind():
        with _AUDIT_LOCK:
            _AUDIT[(graph_key, fn_key)] = {
                "aliases": art.alias_count(),
                "findings": len([f for f in findings if not f.waived]),
            }
    fresh = []
    for f in findings:
        k = (f.rule, graph_key, fn_key)
        if k in _REPORTED:
            continue
        _REPORTED.add(k)
        LOG.record(f)
        fresh.append(f)
    strict = [f for f in fresh if not f.waived and f.severity == ERROR]
    if strict and _strict_mode():
        raise ProgramLintError(strict)


def _strict_mode() -> bool:
    import os

    return os.environ.get("DL4J_TPU_PROGRAM_LINT", "1") == "strict"


def donation_audit() -> Dict[Tuple[str, str], dict]:
    """Per-(graph_key, fn_key) donation record for every train-kind
    executable compiled this process. An entry with ``aliases == 0``
    is a step paying double params memory — the repo-clean test asserts
    there are none."""
    with _AUDIT_LOCK:
        return dict(_AUDIT)


def reset() -> None:
    """Test hook: forget audit + dedup state (the findings LOG is owned
    by the caller; clear it separately)."""
    with _AUDIT_LOCK:
        _AUDIT.clear()
    _REPORTED.clear()


# --------------------------------------------------------------------------
# standalone entry (tests / `python -m deeplearning4j_tpu.analysis program`)
# --------------------------------------------------------------------------

def trace_artifact(jit_fn, args, graph_key: str = "adhoc",
                   fn_key: str = "adhoc", compile: bool = True,
                   sibling_sigs: Tuple = ()) -> ProgramArtifact:
    """Build a ProgramArtifact from a jitted fn outside the cache —
    fixture tests and the CLI drive rules through this without touching
    process-global cache state."""
    from deeplearning4j_tpu.optimize.aot_cache import signature_of

    traced = jit_fn.trace(*args) if hasattr(jit_fn, "trace") else None
    executable = None
    if compile:
        lowered = (traced.lower() if traced is not None
                   else jit_fn.lower(*args))
        executable = lowered.compile()
    return ProgramArtifact(
        graph_key=graph_key, fn_key=fn_key,
        jaxpr=getattr(traced, "jaxpr", None),
        executable=executable, signature=signature_of(args),
        sibling_sigs=tuple(sibling_sigs))
