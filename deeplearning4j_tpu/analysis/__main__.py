"""CLI: ``python -m deeplearning4j_tpu.analysis [source|program|all]``.

``source`` (the ``make lint`` pass) lints the package tree with the AST
rules and exits 1 when any unwaived finding at or above ``--fail-on``
remains. ``program`` (the ``make analysis-smoke`` pass) builds one
small MultiLayerNetwork, ComputationGraph, and ParallelWrapper step
each, runs them through the AOT cache with the compile-time linter
armed, and reports what the program rules saw — the repo's own steps
must come out clean. ``--json`` emits machine-readable findings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _print(findings, as_json: bool, source: str) -> None:
    from deeplearning4j_tpu.analysis import findings as fmod

    if as_json:
        print(json.dumps({
            "pass": source,
            "findings": [f.as_dict() for f in findings],
            "summary": fmod.summarize(findings),
        }, indent=2))
        return
    for f in sorted(findings, key=lambda f: (f.location, f.rule)):
        print(f.render())
    s = fmod.summarize(findings)
    print(f"[{source}] {s['total']} finding(s), {s['waived']} waived, "
          f"{s['actionable']} actionable")


def run_source(root: str, as_json: bool, fail_on: str) -> int:
    from deeplearning4j_tpu.analysis import findings as fmod
    from deeplearning4j_tpu.analysis.source import lint_paths

    findings = lint_paths(root)
    _print(findings, as_json, "source")
    if fail_on == "never":
        return 0
    bad = [f for f in findings if not f.waived
           and fmod.severity_at_least(f.severity, fail_on.upper())]
    return 1 if bad else 0


def run_program(as_json: bool, fail_on: str) -> int:
    """Drive one step of each training path through the AOT cache with
    the program linter armed, then report the accumulated LOG."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from deeplearning4j_tpu.analysis import findings as fmod
    from deeplearning4j_tpu.analysis.findings import LOG

    rng = np.random.RandomState(7)
    x = rng.randn(8, 6).astype("float32")
    y = np.eye(4, dtype="float32")[rng.randint(0, 4, 8)]

    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration

    def _out_layer():
        return OutputLayer(n_out=4, activation=Activation.SOFTMAX,
                           loss_fn=LossMCXENT())

    def mln():
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(7).list()
                .layer(DenseLayer(n_out=11, activation=Activation.TANH))
                .layer(_out_layer())
                .set_input_type(InputType.feed_forward(6)).build())
        MultiLayerNetwork(conf).init().fit(x, y, epochs=1)

    def graph():
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (NeuralNetConfiguration.builder().seed(7).graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.feed_forward(6))
                .add_layer("d", DenseLayer(n_out=11,
                                           activation=Activation.TANH),
                           "in")
                .add_layer("out", _out_layer(), "d")
                .set_outputs("out").build())
        ComputationGraph(conf).init().fit(x, y, epochs=1)

    def wrapper():
        from deeplearning4j_tpu.datasets.iterators import (
            ArrayDataSetIterator,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        conf = (NeuralNetConfiguration.builder().seed(7).list()
                .layer(DenseLayer(n_out=13, activation=Activation.TANH))
                .layer(_out_layer())
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init()
        # ZeRO mode: the one wrapper step kind that is BOTH aot_cache-
        # keyed (pw_zero → donation audit) and collective-bearing
        # (reduce-scatter/all-gather → the PRG205 audit runs for real)
        pw = ParallelWrapper(net, zero_optimizer=True)
        pw.fit(ArrayDataSetIterator(x, y, batch=8), epochs=1)

    failures = []
    for name, fn in (("multilayer", mln), ("graph", graph),
                     ("wrapper", wrapper)):
        try:
            fn()
        except Exception as e:  # a path that cannot run still reports
            failures.append(f"{name}: {type(e).__name__}: {e}")

    findings = LOG.items()
    _print(findings, as_json, "program")
    from deeplearning4j_tpu.analysis.program import donation_audit

    audit = donation_audit()
    undonated = {k: v for k, v in audit.items() if v["aliases"] == 0}
    if not as_json:
        print(f"[program] donation audit: {len(audit)} train-step "
              f"executable(s), {len(undonated)} without aliasing")
    for msg in failures:
        print(f"[program] PATH FAILED {msg}", file=sys.stderr)
    if fail_on == "never":
        return 0
    bad = [f for f in findings if not f.waived
           and fmod.severity_at_least(f.severity, fail_on.upper())]
    return 1 if bad or undonated or failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="jaxpr/HLO program lint + repo-discipline AST lint")
    ap.add_argument("which", nargs="?", default="all",
                    choices=("source", "program", "all"))
    ap.add_argument("--root", default=None,
                    help="package root for the source pass (default: the "
                         "installed deeplearning4j_tpu tree)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--fail-on", default="warn",
                    choices=("info", "warn", "error", "never"),
                    help="exit 1 on unwaived findings at/above this "
                         "severity (default: warn)")
    args = ap.parse_args(argv)

    root = args.root
    if root is None:
        root = os.path.dirname(os.path.abspath(__file__ + "/.."))
    rc = 0
    if args.which in ("source", "all"):
        rc |= run_source(root, args.json, args.fail_on)
    if args.which in ("program", "all"):
        rc |= run_program(args.json, args.fail_on)
    return rc


if __name__ == "__main__":
    sys.exit(main())
