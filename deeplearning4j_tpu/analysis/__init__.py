"""Static analysis: program (jaxpr/HLO) + source (AST) linters.

- ``analysis.program`` — hooked into ``optimize.aot_cache``'s
  lower/compile miss path: every executable the process caches is
  checked for donation aliasing, baked-in constants, dtype-promotion
  leaks, host callbacks, collective misuse, and near-miss recompile
  churn. ``DL4J_TPU_PROGRAM_LINT=0`` disables, ``=strict`` raises.
- ``analysis.source`` — AST checks over the repo: host syncs in
  compiled functions, lock discipline on shared registries, wall-clock/
  RNG in traced code, fit-loop fault/host-gap bracketing, unused
  imports.
- ``analysis.findings`` — the shared findings model (rule ids,
  severities, inline ``# dl4j: waive RULE — reason`` waivers) and the
  process-global ``LOG`` feeding
  ``dl4j_analysis_findings_total{rule,severity}``.

CLI: ``python -m deeplearning4j_tpu.analysis [source|program|all]``
(``make lint`` / ``make analysis-smoke``). docs/analysis.md has the
rule catalog.
"""

from deeplearning4j_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    WARN,
    Finding,
    LOG,
    summarize,
)
from deeplearning4j_tpu.analysis.program import (  # noqa: F401
    ProgramLintError,
    donation_audit,
    lint_program,
    trace_artifact,
    waive_program,
)
from deeplearning4j_tpu.analysis.source import (  # noqa: F401
    lint_paths,
    lint_source,
)
