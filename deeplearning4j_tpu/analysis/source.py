"""Source linter: AST checks for the repo's hand-enforced disciplines.

Five invariants this codebase previously kept by review alone:

- **SRC101 host-sync-in-compiled-fn** — no ``.item()`` /
  ``block_until_ready`` / ``np.asarray`` / ``float()`` on traced values
  inside functions that reach ``aot_cache`` (functions traced into a
  compiled step). Most such calls explode only when their branch is
  traced — a guard-mode branch no test covers ships the bug; the AST
  check catches it on every branch.
- **SRC102 unlocked-shared-mutation** — a shared registry that is
  lock-guarded *somewhere* must be lock-guarded *everywhere* (the
  batcher/registry thread rules PRs 5/6 hardened by hand). Functions
  named ``*_locked`` are exempt by convention (their caller holds the
  lock), as are ``__init__``/module-level construction.
- **SRC103 wallclock-rng-in-compiled-fn** — ``time.time()`` or
  unseeded RNG inside a compiled function executes ONCE at trace time
  and bakes its value into the executable: a silent constant that is
  also nondeterministic across processes.
- **SRC105 dispatch-bracketing** — every fit dispatch loop keeps the
  ``host_gap_close``/``host_gap_open`` pair, the
  ``host_gap_reset``/``host_gap_stop`` fit bracket, and a reachable
  ``fault_point`` kill site (the telemetry/resilience contracts from
  PRs 6/7).
- **SRC106 unused-import** — dead imports (re-exports via
  ``import x as x``, ``__all__``, ``# noqa`` and availability probes in
  ``try/except ImportError`` are exempt).
- **SRC107 request-span-finish** — a function that opens a request
  trace (``start_trace``) must live in a module that closes traces
  (``finish_trace``) at all (ERROR: the span can never finish), and a
  function that both opens a span and ``raise``s must finish the span
  on the reject edge itself (WARN: the raise leaks an open span, which
  the tail sampler then never sees — exactly the abnormal trace it
  exists to keep). Only ``tracing.``-qualified calls (or names imported
  from a ``tracing`` module) count — the XProf
  ``jax.profiler.start_trace`` pair is a different protocol.

Reachability ("reaches aot_cache") is a package-wide fixpoint: roots
are functions passed to ``jax.jit`` / ``shard_map`` / ``lax.scan`` -
family transforms (or returned by a builder whose result is), closure
over nested defs, same-class ``self.x()`` calls, same-module calls, and
imported-name calls across modules. Waive with
``# dl4j: waive SRC1xx — reason`` on the flagged line (see
``analysis.findings``).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.findings import (
    ERROR,
    WARN,
    Finding,
    apply_waivers,
    parse_waivers,
)

# jax transform entry points whose function-valued arguments are traced
# (builtin-shadowing names like `map` are deliberately absent: `map(f,
# xs)` is almost never `lax.map` and one false root taints everything f
# transitively calls)
JIT_LIKE = {
    "jit", "shard_map", "scan", "while_loop", "fori_loop", "cond",
    "switch", "vmap", "pmap", "grad", "value_and_grad", "checkpoint",
    "remat", "custom_vjp", "custom_jvp", "associative_scan",
}
# receiver methods that force a host sync on a device value
SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# converter calls that force concretization when fed a traced value.
# int() is deliberately absent: `int(key)` / `int(np.prod(shape))` on
# static config params is pervasive trace-time idiom, and a traced-int
# sync nearly always spells itself float()/.item() first.
SYNC_CONVERTERS = {"float", "bool"}
NP_SYNC_FUNCS = {"asarray", "array", "ascontiguousarray", "copyto", "save"}
# container-mutating method names (SRC102)
MUTATORS = {"append", "extend", "insert", "add", "discard", "remove",
            "pop", "popitem", "popleft", "appendleft", "clear", "update",
            "setdefault"}
RNG_DRAW_FUNCS = {"random", "rand", "randn", "randint", "uniform",
                  "normal", "choice", "shuffle", "permutation", "sample",
                  "randrange", "getrandbits"}


def _tail(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _base_name(node: ast.expr) -> str:
    """Leading name of an attribute chain: ``np.random.rand`` -> 'np'."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _dotted(node: ast.expr) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_self_attr(node: ast.expr) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class FuncInfo:
    """One function/method: identity, params, call edges, lexical
    context — the unit the reachability fixpoint runs over."""

    __slots__ = ("node", "module", "cls", "name", "params", "calls",
                 "self_calls", "imported_calls", "jit_builder_calls",
                 "returned_names", "nested", "compiled", "parent",
                 "factory_vars")

    def __init__(self, node, module: str, cls: Optional[str],
                 parent: Optional["FuncInfo"]):
        self.node = node
        self.module = module
        self.cls = cls
        self.name = node.name
        self.parent = parent
        a = node.args
        self.params = {p.arg for p in
                       a.posonlyargs + a.args + a.kwonlyargs}
        if a.vararg:
            self.params.add(a.vararg.arg)
        if a.kwarg:
            self.params.add(a.kwarg.arg)
        self.params.discard("self")
        self.calls: Set[str] = set()            # bare-name calls
        self.self_calls: Set[str] = set()       # self.X(...) calls
        self.imported_calls: Set[Tuple[str, str]] = set()  # (alias, attr)
        # factories whose RESULT went straight into a jit-like call:
        # `jax.jit(self.fused_scan_fn(k))` — their returned fns are roots
        self.jit_builder_calls: Set[str] = set()
        self.returned_names: Set[str] = set()   # names this fn returns
        self.nested: List["FuncInfo"] = []
        self.compiled = False
        # local name -> factory callee: `raw = self.train_step_fn(...)`.
        # Nested compiled fns calling `raw(...)` resolve through this
        # (the dominant builder idiom in nn/multilayer & friends).
        self.factory_vars: Dict[str, str] = {}


class ModuleAnalysis:
    """Parse + index one module; rule application happens after the
    package-wide compiled-function fixpoint."""

    def __init__(self, path: str, text: str, relpath: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.is_init = os.path.basename(path) == "__init__.py"
        self.funcs: List[FuncInfo] = []
        # name -> module dotted path, for `import x.y as z` / `from p
        # import mod` bindings used in cross-module call edges
        self.module_aliases: Dict[str, str] = {}
        # name -> (module dotted path, original name) for `from m import f`
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        # (enclosing FuncInfo or None for module level, root fn name)
        self.jit_name_roots: List[Tuple[Optional[FuncInfo], str]] = []
        self._index()

    # -- indexing ------------------------------------------------------------
    def _index(self) -> None:
        self._collect_imports()
        for node in self.tree.body:
            self._walk_scope(node, cls=None, parent=None)
        self._scan_module_level()

    def _scan_module_level(self) -> None:
        """jit-like calls outside any function (module/class level):
        their Name args are roots resolved at module scope."""

        def walk(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            if isinstance(node, ast.Call) and _tail(node.func) in JIT_LIKE:
                for arg in self._fn_args(node):
                    if isinstance(arg, ast.Name):
                        self.jit_name_roots.append((None, arg.id))
            for child in ast.iter_child_nodes(node):
                walk(child)

        for stmt in self.tree.body:
            walk(stmt)

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    self.module_aliases[al.asname or
                                        al.name.split(".")[0]] = al.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for al in node.names:
                    if al.name == "*":
                        continue
                    self.from_imports[al.asname or al.name] = (
                        node.module, al.name)

    def _walk_scope(self, node, cls, parent) -> None:
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                self._walk_scope(child, cls=node.name, parent=parent)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FuncInfo(node, self.relpath, cls, parent)
            self.funcs.append(fi)
            if parent is not None:
                parent.nested.append(fi)
            self._scan_body(fi, cls)
            for deco in node.decorator_list:
                d = deco.func if isinstance(deco, ast.Call) else deco
                if _tail(d) in JIT_LIKE:
                    fi.compiled = True
        else:
            for child in ast.iter_child_nodes(node):
                self._walk_scope(child, cls=cls, parent=parent)

    def _scan_body(self, fi: FuncInfo, cls) -> None:
        """Record fi's call edges + jit roots; recurse into nested defs
        as their own FuncInfo (their statements are NOT fi's)."""
        factory_vars = fi.factory_vars  # local name -> factory callee

        def visit(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_scope(node, cls=cls, parent=fi)
                return
            if isinstance(node, ast.Lambda):
                return  # lambdas: no statements to lint
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                callee = node.value.func
                cname = (_is_self_attr(callee) or
                         (callee.id if isinstance(callee, ast.Name)
                          else ""))
                if cname:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            factory_vars[tgt.id] = cname
            if isinstance(node, ast.Return) and node.value is not None:
                vals = (node.value.elts
                        if isinstance(node.value, ast.Tuple)
                        else [node.value])
                for v in vals:
                    if isinstance(v, ast.Name):
                        fi.returned_names.add(v.id)
            if isinstance(node, ast.Call):
                f = node.func
                sname = _is_self_attr(f)
                if sname:
                    fi.self_calls.add(sname)
                elif isinstance(f, ast.Name):
                    fi.calls.add(f.id)
                elif isinstance(f, ast.Attribute):
                    base = _base_name(f)
                    if base and base != "self":
                        fi.imported_calls.add((base, f.attr))
                if _tail(f) in JIT_LIKE:
                    for arg in self._fn_args(node):
                        if isinstance(arg, ast.Name):
                            # resolve later, in the scope that issued it
                            self.jit_name_roots.append((fi, arg.id))
                        elif isinstance(arg, ast.Call):
                            # jit(self.fused_scan_fn(k)): the builder's
                            # returned functions are the traced roots
                            cal = arg.func
                            cn = (_is_self_attr(cal) or
                                  (cal.id if isinstance(cal, ast.Name)
                                   else ""))
                            if cn:
                                fi.jit_builder_calls.add(cn)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fi.node.body:
            visit(stmt)

    @staticmethod
    def _fn_args(call: ast.Call) -> List[ast.expr]:
        """Positional args of a jit-like call that can carry a function
        (Name / Lambda / builder Call)."""
        return [a for a in call.args
                if isinstance(a, (ast.Name, ast.Lambda, ast.Call))]


class SourceLinter:
    """Package-wide pass: parse all modules, run the compiled-function
    fixpoint across module boundaries, then apply rules per module."""

    def __init__(self):
        self.modules: Dict[str, ModuleAnalysis] = {}  # dotted -> analysis

    # -- loading -------------------------------------------------------------
    def add_file(self, path: str, root: str) -> None:
        rel = os.path.relpath(path, root)
        dotted = rel[:-3].replace(os.sep, ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        with open(path, encoding="utf-8") as f:
            text = f.read()
        self.modules[dotted] = ModuleAnalysis(path, text, rel)

    def add_source(self, text: str, name: str = "<fixture>") -> None:
        self.modules[name] = ModuleAnalysis(name, text, name)

    # -- reachability fixpoint ----------------------------------------------
    def _func_index(self):
        by_module: Dict[str, Dict[str, FuncInfo]] = {}
        by_class: Dict[Tuple[str, str, str], FuncInfo] = {}
        for dotted, mod in self.modules.items():
            mfuncs = by_module.setdefault(dotted, {})
            for fi in mod.funcs:
                if fi.cls is None and fi.parent is None:
                    mfuncs[fi.name] = fi
                if fi.cls is not None:
                    by_class[(dotted, fi.cls, fi.name)] = fi
        return by_module, by_class

    def mark_compiled(self) -> None:
        by_module, by_class = self._func_index()

        def resolve(dotted: str, mod: ModuleAnalysis, fi: FuncInfo,
                    name: str) -> Optional[FuncInfo]:
            # local defs shadow module scope
            p = fi
            while p is not None:
                for n in p.nested:
                    if n.name == name:
                        return n
                p = p.parent
            if fi.cls is not None and (dotted, fi.cls, name) in by_class:
                return by_class[(dotted, fi.cls, name)]
            if name in by_module.get(dotted, {}):
                return by_module[dotted][name]
            if name in mod.from_imports:
                src_mod, orig = mod.from_imports[name]
                return by_module.get(src_mod, {}).get(orig)
            return None

        owner = {id(fi): (dotted, mod)
                 for dotted, mod in self.modules.items()
                 for fi in mod.funcs}

        # seed: jit-root expressions (resolved in their issuing scope)
        work: List[FuncInfo] = []

        def seed(fi: Optional[FuncInfo]) -> None:
            if fi is not None and not fi.compiled:
                fi.compiled = True
                work.append(fi)

        def seed_factory_returns(dotted, mod, scope, factory) -> None:
            """A factory whose result is traced (passed to jit, or
            called from compiled code): its returned local defs are
            compiled roots."""
            bf = resolve(dotted, mod, scope, factory)
            if bf is None:
                return
            for rname in bf.returned_names:
                seed(resolve(dotted, mod, bf, rname))

        for dotted, mod in self.modules.items():
            for fi in mod.funcs:
                if fi.compiled:
                    work.append(fi)
                # jit(self.builder(...)) seeds regardless of whether the
                # CALLER is compiled — fit loops are host code
                for factory in fi.jit_builder_calls:
                    seed_factory_returns(dotted, mod, fi, factory)
            for scope, name in mod.jit_name_roots:
                if scope is not None:
                    seed(resolve(dotted, mod, scope, name))
                else:
                    t = (by_module.get(dotted, {}).get(name)
                         or self._from_import_func(mod, name, by_module))
                    seed(t)

        # propagate: nested defs, same-class/self calls, bare-name and
        # cross-module calls, builder returns
        while work:
            fi = work.pop()
            dotted, mod = owner[id(fi)]
            for n in fi.nested:
                if not n.compiled:
                    n.compiled = True
                    work.append(n)
            for name in list(fi.calls) + list(fi.self_calls):
                t = resolve(dotted, mod, fi, name)
                if t is not None:
                    seed(t)
                    continue
                # unresolved bare call from compiled code: maybe a
                # factory-result variable bound here or in an enclosing
                # builder scope (`raw = self.train_step_fn(); raw(x)`)
                p = fi
                while p is not None:
                    if name in p.factory_vars:
                        seed_factory_returns(dotted, mod, p,
                                             p.factory_vars[name])
                        break
                    p = p.parent
            for base, attr in fi.imported_calls:
                target_mod = mod.module_aliases.get(base)
                if target_mod is None and base in mod.from_imports:
                    target_mod = ".".join(mod.from_imports[base])
                t = self._module_func(target_mod, attr)
                if t is not None:
                    seed(t)

    @staticmethod
    def _from_import_func(mod: ModuleAnalysis, name: str,
                          by_module) -> Optional[FuncInfo]:
        if name in mod.from_imports:
            src_mod, orig = mod.from_imports[name]
            return by_module.get(src_mod, {}).get(orig)
        return None

    def _module_func(self, dotted: Optional[str],
                     name: str) -> Optional[FuncInfo]:
        if dotted is None:
            return None
        mod = self.modules.get(dotted)
        if mod is None:
            return None
        for fi in mod.funcs:
            if fi.cls is None and fi.parent is None and fi.name == name:
                return fi
        return None

    # -- run -----------------------------------------------------------------
    def run(self, today: Optional[str] = None) -> List[Finding]:
        self.mark_compiled()
        out: List[Finding] = []
        for mod in self.modules.values():
            findings = []
            for fi in mod.funcs:
                if fi.compiled:
                    _rule_host_sync(mod, fi, findings)
                    _rule_wallclock_rng(mod, fi, findings)
            _rule_lock_discipline(mod, findings)
            _rule_dispatch_bracketing(mod, findings)
            _rule_request_span_finish(mod, findings)
            _rule_unused_imports(mod, findings)
            apply_waivers(findings, parse_waivers(mod.text), mod.relpath,
                          today=today)
            out.extend(findings)
        return out


# --------------------------------------------------------------------------
# per-function rules (compiled functions only)
# --------------------------------------------------------------------------

def _own_statements(fi: FuncInfo):
    """Walk fi's body, NOT descending into nested function defs (each
    nested def is linted as its own FuncInfo)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from walk(child)

    for stmt in fi.node.body:
        yield stmt
        yield from walk(stmt)


def _refs_param(fi: FuncInfo, node: ast.AST) -> bool:
    return bool(_names_in(node) & fi.params)


def _rule_host_sync(mod: ModuleAnalysis, fi: FuncInfo,
                    out: List[Finding]) -> None:
    loc = lambda n: f"{mod.relpath}:{n.lineno}"  # noqa: E731
    for node in _own_statements(fi):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # value.item() / value.block_until_ready() on a traced param
        if (isinstance(f, ast.Attribute) and f.attr in SYNC_METHODS
                and _refs_param(fi, f.value)):
            out.append(Finding(
                rule="SRC101", severity=ERROR, location=loc(node),
                message=f".{f.attr}() on a traced value inside compiled "
                        f"function {fi.name!r} forces a host sync"))
        # jax.device_get(anything) inside a compiled fn
        elif isinstance(f, ast.Attribute) and f.attr == "device_get":
            out.append(Finding(
                rule="SRC101", severity=ERROR, location=loc(node),
                message=f"jax.device_get inside compiled function "
                        f"{fi.name!r}"))
        # np.asarray(param-derived) and friends
        elif (isinstance(f, ast.Attribute) and f.attr in NP_SYNC_FUNCS
                and _base_name(f) in ("np", "numpy", "onp")
                and node.args and _refs_param(fi, node.args[0])):
            out.append(Finding(
                rule="SRC101", severity=ERROR, location=loc(node),
                message=f"numpy.{f.attr} on a traced value inside "
                        f"compiled function {fi.name!r} — use jnp, or "
                        f"hoist to the host side"))
        # float(x)/int(x)/bool(x) on a param-derived expression
        elif (isinstance(f, ast.Name) and f.id in SYNC_CONVERTERS
                and node.args and _refs_param(fi, node.args[0])):
            out.append(Finding(
                rule="SRC101", severity=ERROR, location=loc(node),
                message=f"{f.id}() on a traced value inside compiled "
                        f"function {fi.name!r} forces concretization"))


def _rule_wallclock_rng(mod: ModuleAnalysis, fi: FuncInfo,
                        out: List[Finding]) -> None:
    for node in _own_statements(fi):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted in ("time.time", "time.perf_counter",
                      "time.monotonic", "time.perf_counter_ns",
                      "datetime.datetime.now", "datetime.datetime.utcnow"):
            out.append(Finding(
                rule="SRC103", severity=ERROR,
                location=f"{mod.relpath}:{node.lineno}",
                message=f"{dotted}() inside compiled function "
                        f"{fi.name!r}: runs once at trace time and "
                        f"bakes a stale wall-clock constant into the "
                        f"executable"))
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr in RNG_DRAW_FUNCS
                and _dotted(node.func).split(".")[0] in
                ("np", "numpy", "random")
                and ".random" in "." + _dotted(node.func)):
            out.append(Finding(
                rule="SRC103", severity=ERROR,
                location=f"{mod.relpath}:{node.lineno}",
                message=f"unseeded host RNG ({_dotted(node.func)}) "
                        f"inside compiled function {fi.name!r}: traced "
                        f"once, baked in, nondeterministic across "
                        f"processes — use jax.random with a threaded "
                        f"key"))


# --------------------------------------------------------------------------
# module-wide rules
# --------------------------------------------------------------------------

def _lockish(expr: ast.expr) -> bool:
    name = _tail(expr).lower()
    return "lock" in name or "cond" in name or "mutex" in name


def _rule_lock_discipline(mod: ModuleAnalysis,
                          out: List[Finding]) -> None:
    """SRC102: collect every mutation of module-global containers and
    ``self.X`` targets, note which targets are EVER mutated under a
    lock-ish ``with``, then flag the unlocked mutations of those same
    targets."""
    # mutation = (target_key, lineno, locked, func_name, at_module_level)
    mutations: List[Tuple[Tuple, int, bool, Optional[str], bool]] = []

    module_globals: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    module_globals.add(tgt.id)

    class V(ast.NodeVisitor):
        def __init__(self):
            self.with_locks = 0
            self.func_stack: List[Tuple[Optional[str], str]] = []
            self.cls: Optional[str] = None

        # -- context tracking
        def visit_ClassDef(self, node):
            prev, self.cls = self.cls, node.name
            self.generic_visit(node)
            self.cls = prev

        def _visit_func(self, node):
            self.func_stack.append((self.cls, node.name))
            saved, self.with_locks = self.with_locks, 0
            self.generic_visit(node)
            self.with_locks = saved
            self.func_stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_With(self, node):
            locked = any(_lockish(item.context_expr)
                         for item in node.items)
            if locked:
                self.with_locks += 1
            self.generic_visit(node)
            if locked:
                self.with_locks -= 1

        # -- mutation collection
        def _target_key(self, expr) -> Optional[Tuple]:
            attr = _is_self_attr(expr)
            if attr is not None:
                return ("self", self.cls, attr)
            if isinstance(expr, ast.Name) and expr.id in module_globals:
                return ("global", expr.id)
            return None

        def _record(self, key, lineno):
            if key is None:
                return
            fname = self.func_stack[-1][1] if self.func_stack else None
            mutations.append((key, lineno, self.with_locks > 0, fname,
                              not self.func_stack))

        def visit_Assign(self, node):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    self._record(self._target_key(tgt.value), node.lineno)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            tgt = node.target
            if isinstance(tgt, ast.Subscript):
                self._record(self._target_key(tgt.value), node.lineno)
            else:
                self._record(self._target_key(tgt), node.lineno)
            self.generic_visit(node)

        def visit_Delete(self, node):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    self._record(self._target_key(tgt.value), node.lineno)
            self.generic_visit(node)

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
                self._record(self._target_key(f.value), node.lineno)
            self.generic_visit(node)

    V().visit(mod.tree)

    locked_targets = {m[0] for m in mutations if m[2]}
    for key, lineno, locked, fname, at_module in mutations:
        if key not in locked_targets or locked or at_module:
            continue
        if fname in ("__init__", "__new__", "__del__", "__post_init__"):
            continue  # construction: not shared yet / teardown
        if fname and fname.endswith("_locked"):
            continue  # convention: caller holds the lock
        target = (f"self.{key[2]}" if key[0] == "self" else key[1])
        out.append(Finding(
            rule="SRC102", severity=WARN,
            location=f"{mod.relpath}:{lineno}",
            message=f"{target} is lock-guarded elsewhere but mutated "
                    f"here without the lock (in {fname!r}) — take the "
                    f"lock, or rename the function *_locked if the "
                    f"caller holds it"))


def _rule_dispatch_bracketing(mod: ModuleAnalysis,
                              out: List[Finding]) -> None:
    """SRC105: (a) ``host_gap_close`` without ``host_gap_open`` in the
    same function; (b) ``host_gap_reset`` and ``host_gap_stop`` must
    travel together; (c) a dispatching function (calls host_gap_close)
    with no ``fault_point`` in itself or any same-module caller is a
    step the chaos layer cannot kill."""
    calls_by_func: Dict[int, Set[str]] = {}
    for fi in mod.funcs:
        names = set()
        for node in _own_statements(fi):
            if isinstance(node, ast.Call):
                t = _tail(node.func)
                if t:
                    names.add(t)
        calls_by_func[id(fi)] = names

    # same-module reverse call graph (bare + self + module-attr calls all
    # reduce to trailing-name matching here: good enough for "is there a
    # kill site above this dispatch loop")
    callers: Dict[str, Set[int]] = {}
    for fi in mod.funcs:
        for name in (fi.calls | fi.self_calls |
                     {a for _, a in fi.imported_calls}):
            callers.setdefault(name, set()).add(id(fi))
    by_id = {id(fi): fi for fi in mod.funcs}

    def reachable_upward(fi: FuncInfo, needle: str,
                         depth: int = 3) -> bool:
        seen, frontier = {id(fi)}, [id(fi)]
        for _ in range(depth):
            nxt = []
            for fid in frontier:
                if needle in calls_by_func.get(fid, ()):
                    return True
                for up in callers.get(by_id[fid].name, ()):
                    if up not in seen:
                        seen.add(up)
                        nxt.append(up)
            frontier = nxt
        return any(needle in calls_by_func.get(fid, ()) for fid in seen)

    for fi in mod.funcs:
        names = calls_by_func[id(fi)]
        line = fi.node.lineno
        loc = f"{mod.relpath}:{line}"
        if "host_gap_close" in names and "host_gap_open" not in names:
            out.append(Finding(
                rule="SRC105", severity=WARN, location=loc,
                message=f"{fi.name!r} calls host_gap_close but never "
                        f"host_gap_open — the gap clock stays disarmed "
                        f"and every later step's gap is lost"))
        if "host_gap_reset" in names and "host_gap_stop" not in names:
            # the reverse (stop without reset) is a legitimate disarm —
            # fit_batch-style single steps stop a clock someone else arms
            out.append(Finding(
                rule="SRC105", severity=WARN, location=loc,
                message=f"{fi.name!r} arms the gap clock "
                        f"(host_gap_reset) but never disarms it "
                        f"(host_gap_stop in a finally) — idle time "
                        f"after the last dispatch records as host gap"))
        if ("host_gap_close" in names
                and not reachable_upward(fi, "fault_point")):
            out.append(Finding(
                rule="SRC105", severity=WARN, location=loc,
                message=f"dispatch loop {fi.name!r} has no fault_point "
                        f"kill site in itself or its callers — "
                        f"resilience chaos plans cannot preempt it"))


def _rule_request_span_finish(mod: ModuleAnalysis,
                              out: List[Finding]) -> None:
    """SRC107: every opened request span must reach a terminal edge.
    (a) a function calls ``start_trace`` but NOTHING in its module ever
    calls ``finish_trace`` — the span cannot finish on any path (ERROR);
    (b) a function both opens a span and ``raise``s without calling
    ``finish_trace`` in its own body — the reject edge leaks the open
    span (WARN). Finishing is usually delegated across functions
    (submit opens, the dispatcher finishes), so (b) only fires on the
    function that raises PAST its own open span; ``tracing.py`` itself
    (the module that defines the helpers) is exempt. Only request-trace
    calls count: ``tracing.start_trace(...)`` or a bare name imported
    from a ``tracing`` module — the XProf profiler's
    ``jax.profiler.start_trace``/``stop_trace`` pair is a different
    protocol and must not trip this rule."""
    if mod.relpath.endswith("telemetry/tracing.py"):
        return

    def span_call(node: ast.Call, name: str) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr == name and _base_name(f) == "tracing"
        if isinstance(f, ast.Name) and f.id == name:
            src = mod.from_imports.get(name)
            return src is not None and "tracing" in str(src)
        return False

    calls_by_func: Dict[int, Set[str]] = {}
    raises_by_func: Dict[int, bool] = {}
    module_finishes = False
    for fi in mod.funcs:
        names = set()
        has_raise = False
        for node in _own_statements(fi):
            if isinstance(node, ast.Call):
                for t in ("start_trace", "finish_trace"):
                    if span_call(node, t):
                        names.add(t)
            elif isinstance(node, ast.Raise):
                has_raise = True
        calls_by_func[id(fi)] = names
        raises_by_func[id(fi)] = has_raise
        if "finish_trace" in names:
            module_finishes = True

    for fi in mod.funcs:
        names = calls_by_func[id(fi)]
        if "start_trace" not in names:
            continue
        loc = f"{mod.relpath}:{fi.node.lineno}"
        if not module_finishes:
            out.append(Finding(
                rule="SRC107", severity=ERROR, location=loc,
                message=f"{fi.name!r} opens a request span "
                        f"(start_trace) but nothing in this module "
                        f"ever calls finish_trace — the span cannot "
                        f"reach a terminal edge on any path"))
        elif raises_by_func[id(fi)] and "finish_trace" not in names:
            out.append(Finding(
                rule="SRC107", severity=WARN, location=loc,
                message=f"{fi.name!r} opens a request span and raises "
                        f"without finishing it — the reject edge leaks "
                        f"an open span the tail sampler never sees"))


def _rule_unused_imports(mod: ModuleAnalysis,
                         out: List[Finding]) -> None:
    """SRC106: imported names never referenced. Exemptions: explicit
    re-exports (``import x as x`` / ``__all__``), ``__future__``,
    TYPE_CHECKING blocks, availability probes (``try: import m`` with an
    ImportError handler), ``# noqa`` lines, and ``__init__.py`` files
    (a package __init__'s imports ARE its public API)."""
    if mod.is_init:
        return
    lines = mod.text.splitlines()
    dunder_all: Set[str] = set()
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            dunder_all = {e.value for e in node.value.elts
                          if isinstance(e, ast.Constant)}

    probe_lines: Set[int] = set()
    type_check_lines: Set[int] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Try):
            if any(_handles_import_error(h) for h in node.handlers):
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Import, ast.ImportFrom)):
                        probe_lines.add(sub.lineno)
        if (isinstance(node, ast.If)
                and "TYPE_CHECKING" in _names_in(node.test)):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Import, ast.ImportFrom)):
                    type_check_lines.add(sub.lineno)

    used: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            used.add(_base_name(node))

    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and \
                node.module == "__future__":
            continue
        if node.lineno in probe_lines or node.lineno in type_check_lines:
            continue
        for al in node.names:
            if al.name == "*":
                continue
            bound = al.asname or al.name.split(".")[0]
            if isinstance(node, ast.ImportFrom):
                bound = al.asname or al.name
                if al.asname == al.name:
                    continue  # PEP 484 explicit re-export
            if bound in used or bound in dunder_all:
                continue
            # multi-line froms: the name may sit lines below node.lineno
            for ln in range(node.lineno,
                            getattr(node, "end_lineno", node.lineno) + 1):
                if ln - 1 < len(lines) and "noqa" in lines[ln - 1] \
                        and (bound in lines[ln - 1]
                             or node.lineno == ln):
                    break
            else:
                out.append(Finding(
                    rule="SRC106", severity=WARN,
                    location=f"{mod.relpath}:{node.lineno}",
                    message=f"unused import {bound!r}"))


def _handles_import_error(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = {_tail(e) for e in
             (t.elts if isinstance(t, ast.Tuple) else [t])}
    return bool(names & {"ImportError", "ModuleNotFoundError",
                         "Exception"})


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

def lint_paths(root: str, today: Optional[str] = None) -> List[Finding]:
    """Lint every .py file under ``root`` as one package (cross-module
    reachability enabled)."""
    linter = SourceLinter()
    pkg_root = os.path.dirname(os.path.abspath(root).rstrip(os.sep))
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                linter.add_file(os.path.join(dirpath, fn), pkg_root)
    return linter.run(today=today)


def lint_source(text: str, name: str = "<fixture>",
                today: Optional[str] = None) -> List[Finding]:
    """Lint one module from a string (fixture tests)."""
    linter = SourceLinter()
    linter.add_source(text, name)
    return linter.run(today=today)
