"""Arbiter — hyperparameter optimization.

Reference: ``arbiter/`` (``org.deeplearning4j.arbiter.optimize``) —
parameter spaces over the config DSL, ``RandomSearchGenerator`` /
``GridSearchCandidateGenerator``, score functions, termination conditions,
``LocalOptimizationRunner`` (SURVEY.md §2.2 L7).

TPU-native shape: a ``MultiLayerSpace`` is a plain builder FUNCTION from
sampled hyperparameters to a ``MultiLayerConfiguration`` (configs are data,
so the space composes with everything else); the runner trains each
candidate with the normal jitted path and returns an ``OptimizationResult``.
"""

from __future__ import annotations

import itertools
import math
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# Parameter spaces (reference org.deeplearning4j.arbiter.optimize.parameter)
# ---------------------------------------------------------------------------

class ParameterSpace:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def grid(self, points: int) -> List:
        raise NotImplementedError


class ContinuousParameterSpace(ParameterSpace):
    """Uniform (or log-uniform) float range (reference class of the same
    name)."""

    def __init__(self, min_value: float, max_value: float,
                 log_scale: bool = False):
        self.lo, self.hi = float(min_value), float(max_value)
        self.log_scale = log_scale

    def sample(self, rng):
        if self.log_scale:
            return float(np.exp(rng.uniform(np.log(self.lo),
                                            np.log(self.hi))))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self, points):
        if self.log_scale:
            return list(np.exp(np.linspace(np.log(self.lo), np.log(self.hi),
                                           points)))
        return list(np.linspace(self.lo, self.hi, points))


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, min_value: int, max_value: int):
        self.lo, self.hi = int(min_value), int(max_value)

    def sample(self, rng):
        return int(rng.integers(self.lo, self.hi + 1))

    def grid(self, points):
        return sorted({int(round(v)) for v in
                       np.linspace(self.lo, self.hi, points)})


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, *values):
        self.values = list(values[0]) if len(values) == 1 and isinstance(
            values[0], (list, tuple)) else list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self, points):
        return list(self.values)


class BooleanSpace(DiscreteParameterSpace):
    def __init__(self):
        super().__init__(True, False)


class FixedValue(ParameterSpace):
    def __init__(self, value):
        self.value = value

    def sample(self, rng):
        return self.value

    def grid(self, points):
        return [self.value]


# ---------------------------------------------------------------------------
# Candidate generators
# ---------------------------------------------------------------------------

class CandidateGenerator:
    def __init__(self, spaces: Dict[str, ParameterSpace]):
        self.spaces = dict(spaces)

    def candidates(self):
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    """Reference ``RandomSearchGenerator``: i.i.d. samples from every
    space; infinite stream (bounded by termination conditions)."""

    def __init__(self, spaces, seed: int = 42):
        super().__init__(spaces)
        self.rng = np.random.default_rng(seed)

    def candidates(self):
        while True:
            yield {k: s.sample(self.rng) for k, s in self.spaces.items()}


class GridSearchCandidateGenerator(CandidateGenerator):
    """Reference ``GridSearchCandidateGenerator``: cartesian product with
    ``discretization_count`` points per continuous axis."""

    def __init__(self, spaces, discretization_count: int = 5):
        super().__init__(spaces)
        self.points = int(discretization_count)

    def candidates(self):
        keys = list(self.spaces)
        axes = [self.spaces[k].grid(self.points) for k in keys]
        for combo in itertools.product(*axes):
            yield dict(zip(keys, combo))


# ---------------------------------------------------------------------------
# Score functions (reference org.deeplearning4j.arbiter.scoring)
# ---------------------------------------------------------------------------

class ScoreFunction:
    minimize = True

    def score(self, net, data_provider) -> float:
        raise NotImplementedError


class DataSetLossScoreFunction(ScoreFunction):
    """Average test-set loss; lower is better."""

    minimize = True

    def score(self, net, data_provider):
        it = data_provider.test_data()
        total, n = 0.0, 0
        for ds in it:
            total += float(net.score(ds)) * ds.num_examples()
            n += ds.num_examples()
        it.reset()
        return total / max(n, 1)


class EvaluationScoreFunction(ScoreFunction):
    """Classification metric (accuracy/f1); higher is better."""

    minimize = False

    def __init__(self, metric: str = "accuracy"):
        self.metric = metric

    def score(self, net, data_provider):
        it = data_provider.test_data()
        ev = net.evaluate(it)
        it.reset()
        return float(getattr(ev, self.metric)())


class DataSetIteratorProvider:
    """Reference ``DataProvider``: train/test iterators per candidate."""

    def __init__(self, train_iterator, test_iterator):
        self._train = train_iterator
        self._test = test_iterator

    def train_data(self):
        self._train.reset()
        return self._train

    def test_data(self):
        self._test.reset()
        return self._test


# ---------------------------------------------------------------------------
# Termination + runner
# ---------------------------------------------------------------------------

class MaxCandidatesCondition:
    def __init__(self, n: int):
        self.n = int(n)

    def terminate(self, n_done: int, start_time: float) -> bool:
        return n_done >= self.n


class MaxTimeCondition:
    def __init__(self, seconds: float):
        self.seconds = float(seconds)

    def terminate(self, n_done, start_time):
        return time.monotonic() - start_time > self.seconds


class CandidateResult:
    def __init__(self, index: int, values: dict, score: float, model,
                 exception: Optional[BaseException] = None):
        self.index = index
        self.values = values
        self.score = score
        self.model = model
        self.exception = exception


class OptimizationResult:
    def __init__(self, best: CandidateResult,
                 results: List[CandidateResult], minimize: bool = True):
        self.best = best
        self.results = results
        self.minimize = minimize

    def best_score(self) -> float:
        return self.best.score

    def best_values(self) -> dict:
        return self.best.values

    def best_model(self):
        return self.best.model

    def render(self, path: str) -> str:
        """Static search report (the reference's arbiter-ui module:
        candidate scores, running best, best hyperparameters)."""
        import html as _html

        from deeplearning4j_tpu.ui.server import _chart

        # non-finite scores (diverged, no exception raised) count as
        # failed: a NaN in the series would blank the whole chart
        ok = [r for r in self.results
              if r.exception is None and math.isfinite(r.score)]
        xs = [float(r.index) for r in ok]
        ys = [float(r.score) for r in ok]
        pick = min if self.minimize else max
        running = []
        cur = None
        for r in ok:
            cur = r.score if cur is None else pick(cur, r.score)
            running.append(float(cur))
        body = _chart("Candidate score vs index",
                      {"score": (xs, ys), "running best": (xs, running)})
        failed = len(self.results) - len(ok)
        rows = "".join(
            f"<tr><td>{_html.escape(str(k))}</td>"
            f"<td>{_html.escape(repr(v))}</td></tr>"
            for k, v in sorted(self.best.values.items()))
        from deeplearning4j_tpu.ui.server import _page

        doc = _page(
            "arbiter search",
            f"<h1>Hyperparameter search</h1>"
            f"<p>{len(ok)} candidates evaluated"
            f"{f', {failed} failed' if failed else ''}; best score "
            f"{self.best.score:.6g} at candidate {self.best.index}.</p>"
            f"{body}<h3>Best hyperparameters</h3>"
            f"<table>{rows}</table>",
            style_extra="table{border-collapse:collapse}"
                        "td{border:1px solid #ccc;padding:4px 8px}")
        with open(path, "w") as f:
            f.write(doc)
        return path


class OptimizationConfiguration:
    """Reference ``OptimizationConfiguration.Builder``."""

    def __init__(self, candidate_generator: CandidateGenerator,
                 data_provider: DataSetIteratorProvider,
                 score_function: ScoreFunction,
                 termination_conditions: Sequence,
                 epochs_per_candidate: int = 1):
        if not termination_conditions:
            raise ValueError("at least one termination condition required "
                             "(e.g. MaxCandidatesCondition)")
        self.generator = candidate_generator
        self.data_provider = data_provider
        self.score_function = score_function
        self.terminations = list(termination_conditions)
        self.epochs = int(epochs_per_candidate)


class LocalOptimizationRunner:
    """Reference ``LocalOptimizationRunner``: sequential candidate training
    (each candidate is one whole-graph compile + fit on the chip; arbiter's
    thread pool would just contend for it)."""

    def __init__(self, config: OptimizationConfiguration,
                 model_builder: Callable[..., object]):
        """``model_builder(**hyperparams)`` returns an UN-initialized
        MultiLayerNetwork/ComputationGraph or a configuration with an
        ``init``-able wrapper (the reference's ``MultiLayerSpace``
        candidate)."""
        self.config = config
        self.model_builder = model_builder

    def _materialize(self, values: dict):
        from deeplearning4j_tpu.conf.graph import ComputationGraphConfiguration
        from deeplearning4j_tpu.conf.multilayer import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        built = self.model_builder(**values)
        if isinstance(built, MultiLayerConfiguration):
            built = MultiLayerNetwork(built)
        elif isinstance(built, ComputationGraphConfiguration):
            built = ComputationGraph(built)
        if getattr(built, "params", 1) is None:
            built.init()
        return built

    def execute(self) -> OptimizationResult:
        cfg = self.config
        results: List[CandidateResult] = []
        start = time.monotonic()
        best: Optional[CandidateResult] = None
        sign = 1.0 if cfg.score_function.minimize else -1.0
        for i, values in enumerate(cfg.generator.candidates()):
            if any(t.terminate(len(results), start)
                   for t in cfg.terminations):
                break
            try:
                net = self._materialize(values)
                net.fit(cfg.data_provider.train_data(), epochs=cfg.epochs)
                score = cfg.score_function.score(net, cfg.data_provider)
            except Exception as e:  # a bad candidate must not kill the run
                results.append(
                    CandidateResult(i, values, math.nan, None, exception=e))
                continue
            res = CandidateResult(i, values, score, net)
            results.append(res)
            # a NaN-scored (diverged) candidate must never be "best"
            if math.isfinite(score) and (
                    best is None or not math.isfinite(best.score)
                    or sign * score < sign * best.score):
                best = res
        if best is None:
            errs = [r.exception for r in results if r.exception is not None]
            detail = f"; first error: {errs[0]!r}" if errs else ""
            raise RuntimeError(
                f"no candidate completed with a finite score "
                f"({len(results)} attempted){detail}")
        return OptimizationResult(best, results,
                                  minimize=cfg.score_function.minimize)
