"""Policies (reference ``org.deeplearning4j.rl4j.policy``: ``Policy``,
``DQNPolicy``, ``ACPolicy``, ``EpsGreedy``): action selection decoupled
from the learner, plus ``play`` rollouts for evaluation."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .a3c import _policy_logits, _select_from_logits
from .dqn import _q_values, linear_epsilon


class Policy:
    """``nextAction(obs) -> int`` + greedy ``play`` (reference Policy)."""

    def next_action(self, obs) -> int:
        raise NotImplementedError

    def play(self, mdp, episodes: int = 1, max_steps: int = 1000) -> float:
        total = 0.0
        for _ in range(episodes):
            obs = mdp.reset()
            for _ in range(max_steps):
                obs, r, done = mdp.step(self.next_action(obs))
                total += r
                if done:
                    break
        return total / episodes


class DQNPolicy(Policy):
    """Greedy argmax over Q-values (reference ``DQNPolicy``)."""

    def __init__(self, params):
        self.params = params

    def next_action(self, obs) -> int:
        q = _q_values(self.params, jnp.asarray(np.asarray(obs)[None]))
        return int(jnp.argmax(q[0]))


class ACPolicy(Policy):
    """Samples from the actor's softmax; greedy if ``rng`` is None
    (reference ``ACPolicy``)."""

    def __init__(self, params, rng: np.random.Generator = None):
        self.params = params
        self.rng = rng

    def next_action(self, obs) -> int:
        logits = np.asarray(
            _policy_logits(self.params, jnp.asarray(np.asarray(obs)[None])))[0]
        return _select_from_logits(logits, self.rng)


class EpsGreedy(Policy):
    """Wraps a policy with annealed-epsilon random exploration (reference
    ``EpsGreedy``): linear 1.0 -> ``min_epsilon`` over ``epsilon_nb_step``
    calls."""

    def __init__(self, policy: Policy, action_size: int,
                 min_epsilon: float = 0.05, epsilon_nb_step: int = 3000,
                 rng: np.random.Generator = None):
        self.policy = policy
        self.action_size = int(action_size)
        self.min_epsilon = float(min_epsilon)
        self.epsilon_nb_step = int(epsilon_nb_step)
        self.rng = rng or np.random.default_rng(0)
        self.calls = 0

    def epsilon(self) -> float:
        return linear_epsilon(self.calls, self.min_epsilon,
                              self.epsilon_nb_step)

    def next_action(self, obs) -> int:
        eps = self.epsilon()
        self.calls += 1
        if self.rng.random() < eps:
            return int(self.rng.integers(0, self.action_size))
        return self.policy.next_action(obs)
