"""A3C and async n-step Q-learning (reference ``org.deeplearning4j.rl4j.
learning.async.a3c.discrete.A3CDiscreteDense`` and ``learning.async.
nstep.discrete.AsyncNStepQLearningDiscreteDense``).

The reference runs ``numThreads`` AsyncThreads, each holding a local copy
of the global network: roll out up to ``nstep`` transitions, compute
n-step returns, push gradients into a shared ``AsyncGlobal`` which applies
them to the global params (Hogwild-style, no barrier). Here the rollout
loop stays host-side per worker thread, but the entire gradient
computation + Adam application is ONE jitted function; workers apply it to
the shared params under a lock (exact, not lossy — the JVM version's
unsynchronized adds are an artifact of its runtime, not a feature).

Actor-critic loss matches the reference's ``ActorCriticLoss``:
policy head -log pi(a|s) * advantage with entropy bonus ``BETA``,
value head MSE on n-step returns.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .dqn import _mlp_apply, _mlp_init, _q_values, linear_epsilon


@dataclasses.dataclass
class A3CConfiguration:
    """Reference ``A3CLearningConfiguration`` fields (snake_case)."""

    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 8_000
    num_threads: int = 2
    nstep: int = 5
    gamma: float = 0.99
    reward_factor: float = 1.0
    learning_rate: float = 1e-3
    entropy_beta: float = 0.01          # ActorCriticLoss.BETA


@dataclasses.dataclass
class AsyncQLearningConfiguration:
    """Reference ``AsyncQLearningConfiguration`` (async n-step Q)."""

    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 8_000
    num_threads: int = 2
    nstep: int = 5
    gamma: float = 0.99
    reward_factor: float = 1.0
    learning_rate: float = 1e-3
    target_dqn_update_freq: int = 500
    min_epsilon: float = 0.05
    epsilon_nb_step: int = 3_000


def _ac_init(key, obs_size: int, hidden, action_size: int):
    """Shared trunk + separate policy/value heads (reference
    ``ActorCriticFactoryCompoundStdDense``)."""
    trunk = _mlp_init(key, [obs_size, *hidden])
    k_pi, k_v = jax.random.split(jax.random.fold_in(key, 1))
    n_last = hidden[-1]
    pi = {"W": jax.random.normal(k_pi, (n_last, action_size))
               * np.sqrt(1.0 / n_last).astype(np.float32),
          "b": jnp.zeros((action_size,), jnp.float32)}
    v = {"W": jax.random.normal(k_v, (n_last, 1))
              * np.sqrt(1.0 / n_last).astype(np.float32),
         "b": jnp.zeros((1,), jnp.float32)}
    return {"trunk": trunk, "pi": pi, "v": v}


def _ac_apply(params, x):
    h = x
    for layer in params["trunk"]:
        h = jax.nn.relu(h @ layer["W"] + layer["b"])
    logits = h @ params["pi"]["W"] + params["pi"]["b"]
    value = (h @ params["v"]["W"] + params["v"]["b"])[:, 0]
    return logits, value


def _adam(params, grads, m, v, step, lr):
    b1, b2, eps = 0.9, 0.999, 1e-8
    t = step + 1.0

    def upd(p, g, m_, v_):
        mk = b1 * m_ + (1 - b1) * g
        vk = b2 * v_ + (1 - b2) * g * g
        mhat = mk / (1 - b1 ** t)
        vhat = vk / (1 - b2 ** t)
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), mk, vk

    flat = jax.tree_util.tree_map(upd, params, grads, m, v)
    new_p = jax.tree_util.tree_map(lambda x: x[0], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_m = jax.tree_util.tree_map(lambda x: x[1], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_v = jax.tree_util.tree_map(lambda x: x[2], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
    return new_p, new_m, new_v


@jax.jit
def _a3c_step(params, opt_m, opt_v, batch, step, lr_beta):
    """One n-step actor-critic update over a rollout segment (returns are
    already discounted host-side)."""
    s, a, returns = batch
    lr, beta = lr_beta

    def loss_fn(params):
        logits, value = _ac_apply(params, s)
        logp = jax.nn.log_softmax(logits)
        p = jnp.exp(logp)
        adv = jax.lax.stop_gradient(returns - value)
        pi_loss = -jnp.mean(
            jnp.take_along_axis(logp, a[:, None], 1)[:, 0] * adv)
        entropy = -jnp.mean(jnp.sum(p * logp, axis=1))
        v_loss = jnp.mean((returns - value) ** 2)
        return pi_loss + 0.5 * v_loss - beta * entropy

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new_p, new_m, new_v = _adam(params, grads, opt_m, opt_v, step, lr)
    return new_p, new_m, new_v, loss


@jax.jit
def _nstepq_step(params, opt_m, opt_v, batch, step, lr):
    """Async n-step Q update: MSE of Q(s,a) against precomputed targets."""
    s, a, targets = batch

    def loss_fn(params):
        q = _mlp_apply(params, s)
        q_sa = jnp.take_along_axis(q, a[:, None], 1)[:, 0]
        return jnp.mean((q_sa - targets) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new_p, new_m, new_v = _adam(params, grads, opt_m, opt_v, step, lr)
    return new_p, new_m, new_v, loss


@jax.jit
def _policy_logits(params, obs):
    return _ac_apply(params, obs)[0]


def _select_from_logits(logits: np.ndarray,
                        rng: Optional[np.random.Generator]) -> int:
    """Categorical sample from softmax(logits); greedy argmax if ``rng``
    is None. Shared by the A3C learner and ``ACPolicy``."""
    if rng is None:
        return int(np.argmax(logits))
    z = logits - logits.max()
    p = np.exp(z) / np.exp(z).sum()
    return int(rng.choice(len(p), p=p))


class _AsyncGlobal:
    """Reference ``AsyncGlobal``: the shared params + optimizer state that
    worker threads apply their gradient steps to."""

    def __init__(self, params):
        self.lock = threading.Lock()
        self.params = params
        self.opt_m = jax.tree_util.tree_map(jnp.zeros_like, params)
        self.opt_v = jax.tree_util.tree_map(jnp.zeros_like, params)
        self.step_count = 0          # global env-step counter (T)
        self.update_count = 0


class A3CDiscreteDense:
    """Advantage actor-critic over dense observations (reference class of
    the same name). ``mdp_factory`` builds one MDP per worker thread."""

    def __init__(self, mdp_factory, config: Optional[A3CConfiguration] = None,
                 hidden: List[int] = (64, 64)):
        self.mdp_factory = mdp_factory
        self.cfg = config or A3CConfiguration()
        probe = mdp_factory(0)
        self.action_size = probe.action_size
        key = jax.random.PRNGKey(self.cfg.seed)
        params = _ac_init(key, probe.observation_size, list(hidden),
                          probe.action_size)
        self.shared = _AsyncGlobal(params)
        self.episode_rewards: List[float] = []
        self._reward_lock = threading.Lock()

    @property
    def params(self):
        return self.shared.params

    def act(self, obs, rng: np.random.Generator,
            greedy: bool = False) -> int:
        logits = np.asarray(_policy_logits(self.shared.params,
                                           jnp.asarray(obs[None])))[0]
        return _select_from_logits(logits, None if greedy else rng)

    def _worker(self, tid: int):
        cfg = self.cfg
        mdp = self.mdp_factory(tid)
        rng = np.random.default_rng(cfg.seed + 1000 * (tid + 1))
        shared = self.shared
        obs = mdp.reset()
        ep_reward, ep_steps = 0.0, 0
        while shared.step_count < cfg.max_step:
            states, actions, rewards = [], [], []
            done = False
            for _ in range(cfg.nstep):
                a = self.act(obs, rng)
                obs2, r, done = mdp.step(a)
                states.append(obs)
                actions.append(a)
                rewards.append(r * cfg.reward_factor)
                ep_reward += r
                ep_steps += 1
                obs = obs2
                if done or ep_steps >= cfg.max_epoch_step:
                    break
            # bootstrap from V(s_last) unless terminal
            if done or ep_steps >= cfg.max_epoch_step:
                boot = 0.0
            else:
                _, value = _ac_apply(shared.params, jnp.asarray(obs[None]))
                boot = float(value[0])
            returns = np.empty(len(rewards), np.float32)
            acc = boot
            for i in range(len(rewards) - 1, -1, -1):
                acc = rewards[i] + cfg.gamma * acc
                returns[i] = acc
            batch = (jnp.asarray(np.stack(states)),
                     jnp.asarray(actions, jnp.int32),
                     jnp.asarray(returns))
            with shared.lock:
                # steps accumulate once per rollout segment: a per-step
                # lock acquisition would contend with the update lock and
                # serialize collection across workers
                shared.step_count += len(rewards)
                (shared.params, shared.opt_m, shared.opt_v, _) = _a3c_step(
                    shared.params, shared.opt_m, shared.opt_v, batch,
                    jnp.asarray(float(shared.update_count), jnp.float32),
                    (jnp.asarray(cfg.learning_rate, jnp.float32),
                     jnp.asarray(cfg.entropy_beta, jnp.float32)))
                shared.update_count += 1
            if done or ep_steps >= cfg.max_epoch_step:
                with self._reward_lock:
                    self.episode_rewards.append(ep_reward)
                obs = mdp.reset()
                ep_reward, ep_steps = 0.0, 0

    def train(self) -> "A3CDiscreteDense":
        threads = [threading.Thread(target=self._worker, args=(t,),
                                    daemon=True)
                   for t in range(self.cfg.num_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self

    def play(self, episodes: int = 1) -> float:
        """Greedy rollouts via ``ACPolicy`` semantics."""
        mdp = self.mdp_factory(-1)
        rng = np.random.default_rng(0)
        total = 0.0
        for _ in range(episodes):
            obs = mdp.reset()
            for _ in range(self.cfg.max_epoch_step):
                obs, r, done = mdp.step(self.act(obs, rng, greedy=True))
                total += r
                if done:
                    break
        return total / episodes


class AsyncNStepQLearningDiscreteDense:
    """Async n-step Q-learning (reference class of the same name): worker
    threads, eps-greedy behavior, n-step targets bootstrapped from a
    periodically-synced target network."""

    def __init__(self, mdp_factory,
                 config: Optional[AsyncQLearningConfiguration] = None,
                 hidden: List[int] = (64, 64)):
        self.mdp_factory = mdp_factory
        self.cfg = config or AsyncQLearningConfiguration()
        probe = mdp_factory(0)
        self.action_size = probe.action_size
        key = jax.random.PRNGKey(self.cfg.seed)
        params = _mlp_init(key, [probe.observation_size, *hidden,
                                 probe.action_size])
        self.shared = _AsyncGlobal(params)
        self.target_params = jax.tree_util.tree_map(lambda x: x, params)
        self.episode_rewards: List[float] = []
        self._reward_lock = threading.Lock()

    @property
    def params(self):
        return self.shared.params

    def epsilon(self) -> float:
        return linear_epsilon(self.shared.step_count, self.cfg.min_epsilon,
                              self.cfg.epsilon_nb_step)

    def act(self, obs, rng: np.random.Generator,
            greedy: bool = False) -> int:
        if not greedy and rng.random() < self.epsilon():
            return int(rng.integers(0, self.action_size))
        q = _q_values(self.shared.params, jnp.asarray(obs[None]))
        return int(jnp.argmax(q[0]))

    def _worker(self, tid: int):
        cfg = self.cfg
        mdp = self.mdp_factory(tid)
        rng = np.random.default_rng(cfg.seed + 1000 * (tid + 1))
        shared = self.shared
        obs = mdp.reset()
        ep_reward, ep_steps = 0.0, 0
        while shared.step_count < cfg.max_step:
            states, actions, rewards = [], [], []
            done = False
            for _ in range(cfg.nstep):
                a = self.act(obs, rng)
                obs2, r, done = mdp.step(a)
                states.append(obs)
                actions.append(a)
                rewards.append(r * cfg.reward_factor)
                ep_reward += r
                ep_steps += 1
                obs = obs2
                if done or ep_steps >= cfg.max_epoch_step:
                    break
            if done or ep_steps >= cfg.max_epoch_step:
                boot = 0.0
            else:
                q = _q_values(self.target_params, jnp.asarray(obs[None]))
                boot = float(jnp.max(q[0]))
            targets = np.empty(len(rewards), np.float32)
            acc = boot
            for i in range(len(rewards) - 1, -1, -1):
                acc = rewards[i] + cfg.gamma * acc
                targets[i] = acc
            batch = (jnp.asarray(np.stack(states)),
                     jnp.asarray(actions, jnp.int32),
                     jnp.asarray(targets))
            with shared.lock:
                # segment-granular step accounting (see A3C worker note)
                shared.step_count += len(rewards)
                (shared.params, shared.opt_m, shared.opt_v, _) = (
                    _nstepq_step(
                        shared.params, shared.opt_m, shared.opt_v, batch,
                        jnp.asarray(float(shared.update_count), jnp.float32),
                        jnp.asarray(cfg.learning_rate, jnp.float32)))
                shared.update_count += 1
                if shared.update_count % max(
                        1, cfg.target_dqn_update_freq // cfg.nstep) == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda x: x, shared.params)
            if done or ep_steps >= cfg.max_epoch_step:
                with self._reward_lock:
                    self.episode_rewards.append(ep_reward)
                obs = mdp.reset()
                ep_reward, ep_steps = 0.0, 0

    def train(self) -> "AsyncNStepQLearningDiscreteDense":
        threads = [threading.Thread(target=self._worker, args=(t,),
                                    daemon=True)
                   for t in range(self.cfg.num_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self

    def play(self, episodes: int = 1) -> float:
        mdp = self.mdp_factory(-1)
        rng = np.random.default_rng(0)
        total = 0.0
        for _ in range(episodes):
            obs = mdp.reset()
            for _ in range(self.cfg.max_epoch_step):
                obs, r, done = mdp.step(self.act(obs, rng, greedy=True))
                total += r
                if done:
                    break
        return total / episodes
