"""RL4J-equivalent: deep reinforcement learning.

Reference: ``rl4j/`` — ``QLearningDiscreteDense`` (DQN over dense
observations: epsilon-greedy acting, experience replay, target network,
double-DQN option), the ``MDP`` interface, gym adapters (SURVEY.md §2.2
L7). TPU-native: the TD update is one jitted step (gather Q(s,a), TD
targets from the target net, MSE on the taken actions) over replay batches.
"""

from deeplearning4j_tpu.rl4j.mdp import MDP, CartPole, SimpleToyMDP  # noqa: F401
from deeplearning4j_tpu.rl4j.dqn import (  # noqa: F401
    QLearningConfiguration,
    QLearningDiscreteDense,
    ReplayMemory,
)
from deeplearning4j_tpu.rl4j.a3c import (  # noqa: F401
    A3CConfiguration,
    A3CDiscreteDense,
    AsyncNStepQLearningDiscreteDense,
    AsyncQLearningConfiguration,
)
from deeplearning4j_tpu.rl4j.policy import (  # noqa: F401
    ACPolicy,
    DQNPolicy,
    EpsGreedy,
    Policy,
)
