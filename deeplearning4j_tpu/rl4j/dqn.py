"""DQN (reference ``org.deeplearning4j.rl4j.learning.sync.qlearning.discrete.
QLearningDiscreteDense`` + ``QLearningConfiguration`` + ``ExpReplay``).

The reference builds TD targets in Java per batch and calls net.fit; here
the whole TD update — online Q gather, target-net max (or double-DQN
argmax/gather), MSE on taken actions, Adam step — is ONE jitted function
over replay batches.
"""

from __future__ import annotations

import dataclasses
import functools
from collections import deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class QLearningConfiguration:
    """Reference ``QLearningConfiguration`` fields (same names, snake_case)."""

    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 10_000
    exp_rep_max_size: int = 10_000
    batch_size: int = 64
    target_dqn_update_freq: int = 100
    update_start: int = 100
    reward_factor: float = 1.0
    gamma: float = 0.99
    error_clamp: float = 1.0
    min_epsilon: float = 0.05
    epsilon_nb_step: int = 3000
    double_dqn: bool = True
    learning_rate: float = 1e-3


class ReplayMemory:
    """Reference ``ExpReplay``: bounded FIFO of (s, a, r, s', done)."""

    def __init__(self, max_size: int, seed: int = 0):
        self._buf = deque(maxlen=int(max_size))
        self.rng = np.random.default_rng(seed)

    def store(self, s, a, r, s2, done):
        self._buf.append((s, a, r, s2, done))

    def __len__(self):
        return len(self._buf)

    def sample(self, n: int):
        idx = self.rng.integers(0, len(self._buf), n)
        s, a, r, s2, d = zip(*(self._buf[i] for i in idx))
        return (np.stack(s), np.asarray(a, np.int32),
                np.asarray(r, np.float32), np.stack(s2),
                np.asarray(d, np.float32))


def linear_epsilon(count: int, min_epsilon: float, nb_step: int) -> float:
    """Linear anneal 1.0 -> ``min_epsilon`` over ``nb_step`` counts (the
    reference ``EpsGreedy`` schedule, shared by every learner here)."""
    frac = min(1.0, count / max(nb_step, 1))
    return 1.0 + frac * (min_epsilon - 1.0)


def _mlp_init(key, sizes):
    params = []
    for i, (n_in, n_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (n_in, n_out)) * np.sqrt(2.0 / n_in)
        params.append({"W": w.astype(jnp.float32),
                       "b": jnp.zeros((n_out,), jnp.float32)})
    return params


def _mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["W"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


@functools.partial(jax.jit, static_argnums=(8,))
def _td_step(params, opt_m, opt_v, target_params, batch, step, lr_gamma,
             clamp, double_dqn):
    s, a, r, s2, done = batch
    lr, gamma = lr_gamma

    def loss_fn(params):
        q = _mlp_apply(params, s)                       # [b, A]
        q_sa = jnp.take_along_axis(q, a[:, None], 1)[:, 0]
        q2_t = _mlp_apply(target_params, s2)
        if double_dqn:
            a2 = jnp.argmax(_mlp_apply(params, s2), axis=1)
            q2 = jnp.take_along_axis(q2_t, a2[:, None], 1)[:, 0]
        else:
            q2 = jnp.max(q2_t, axis=1)
        target = r + gamma * (1.0 - done) * jax.lax.stop_gradient(q2)
        err = q_sa - target
        # Huber: quadratic inside ``error_clamp``, linear outside — a hard
        # clip would zero the gradient exactly when Q diverges (the
        # reference clamps the TD error with the same intent)
        quad = jnp.minimum(jnp.abs(err), clamp)
        lin = jnp.abs(err) - quad
        return jnp.mean(0.5 * quad * quad + clamp * lin)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    # Adam
    b1, b2, eps = 0.9, 0.999, 1e-8
    t = step + 1.0
    new_p, new_m, new_v = [], [], []
    for p, g, m, v in zip(params, grads, opt_m, opt_v):
        layer_p, layer_m, layer_v = {}, {}, {}
        for k in p:
            mk = b1 * m[k] + (1 - b1) * g[k]
            vk = b2 * v[k] + (1 - b2) * g[k] * g[k]
            mhat = mk / (1 - b1 ** t)
            vhat = vk / (1 - b2 ** t)
            layer_p[k] = p[k] - lr * mhat / (jnp.sqrt(vhat) + eps)
            layer_m[k], layer_v[k] = mk, vk
        new_p.append(layer_p)
        new_m.append(layer_m)
        new_v.append(layer_v)
    return new_p, new_m, new_v, loss


@jax.jit
def _q_values(params, obs):
    return _mlp_apply(params, obs)


class QLearningDiscreteDense:
    """DQN trainer (reference class of the same name). ``hidden``: MLP
    widths for the Q-network (the reference takes a ``DQNFactoryStdDense``
    conf)."""

    def __init__(self, mdp, config: Optional[QLearningConfiguration] = None,
                 hidden: List[int] = (64, 64)):
        self.mdp = mdp
        self.cfg = config or QLearningConfiguration()
        key = jax.random.PRNGKey(self.cfg.seed)
        sizes = [mdp.observation_size, *hidden, mdp.action_size]
        self.params = _mlp_init(key, sizes)
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self.opt_m = jax.tree_util.tree_map(jnp.zeros_like, self.params)
        self.opt_v = jax.tree_util.tree_map(jnp.zeros_like, self.params)
        self.replay = ReplayMemory(self.cfg.exp_rep_max_size, self.cfg.seed)
        self.rng = np.random.default_rng(self.cfg.seed)
        self.step_count = 0
        self.episode_rewards: List[float] = []

    # --- policy --------------------------------------------------------------
    def epsilon(self) -> float:
        return linear_epsilon(self.step_count, self.cfg.min_epsilon,
                              self.cfg.epsilon_nb_step)

    def act(self, obs, greedy: bool = False) -> int:
        if not greedy and self.rng.random() < self.epsilon():
            return int(self.rng.integers(0, self.mdp.action_size))
        q = _q_values(self.params, jnp.asarray(obs[None]))
        return int(jnp.argmax(q[0]))

    # --- training ------------------------------------------------------------
    def train(self) -> "QLearningDiscreteDense":
        cfg = self.cfg
        while self.step_count < cfg.max_step:
            obs = self.mdp.reset()
            ep_reward = 0.0
            for _ in range(cfg.max_epoch_step):
                a = self.act(obs)
                obs2, r, done = self.mdp.step(a)
                ep_reward += r
                self.replay.store(obs, a, r * cfg.reward_factor, obs2,
                                  float(done))
                obs = obs2
                self.step_count += 1
                if (self.step_count >= cfg.update_start
                        and len(self.replay) >= cfg.batch_size):
                    batch = self.replay.sample(cfg.batch_size)
                    batch = tuple(jnp.asarray(b) for b in batch)
                    (self.params, self.opt_m, self.opt_v, _) = _td_step(
                        self.params, self.opt_m, self.opt_v,
                        self.target_params, batch,
                        jnp.asarray(float(self.step_count), jnp.float32),
                        (jnp.asarray(cfg.learning_rate, jnp.float32),
                         jnp.asarray(cfg.gamma, jnp.float32)),
                        jnp.asarray(cfg.error_clamp, jnp.float32),
                        cfg.double_dqn)
                if self.step_count % cfg.target_dqn_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda x: x, self.params)
                if done or self.step_count >= cfg.max_step:
                    break
            self.episode_rewards.append(ep_reward)
        return self

    # --- evaluation ----------------------------------------------------------
    def play(self, episodes: int = 1) -> float:
        """Greedy rollouts; returns mean episode reward (reference
        ``Policy#play``)."""
        total = 0.0
        for _ in range(episodes):
            obs = self.mdp.reset()
            for _ in range(self.cfg.max_epoch_step):
                obs, r, done = self.mdp.step(self.act(obs, greedy=True))
                total += r
                if done:
                    break
        return total / episodes
