"""MDP interface + built-in environments.

Reference: ``org.deeplearning4j.rl4j.mdp.MDP`` and the gym adapters;
``CartPole`` reimplements the classic control dynamics in numpy so tests
and examples run with zero external deps (the reference reaches it through
gym-java-client)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


class MDP:
    """step/reset/is_done contract (reference MDP<O, A, AS>)."""

    observation_size: int
    action_size: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        """-> (observation, reward, done)."""
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError


class SimpleToyMDP(MDP):
    """Reference ``org.deeplearning4j.rl4j.mdp.toy.SimpleToy``: a chain of
    ``length`` states; action 1 advances and pays 1, action 0 ends the
    episode. Optimal return = length."""

    observation_size = 1
    action_size = 2

    def __init__(self, length: int = 10):
        self.length = int(length)
        self._state = 0
        self._done = False

    def reset(self):
        self._state = 0
        self._done = False
        return self._obs()

    def _obs(self):
        return np.asarray([self._state / self.length], np.float32)

    def step(self, action):
        if action == 1:
            self._state += 1
            reward = 1.0
            self._done = self._state >= self.length
        else:
            reward = 0.0
            self._done = True
        return self._obs(), reward, self._done

    def is_done(self):
        return self._done


class CartPole(MDP):
    """Classic cart-pole balance (dynamics per Barto-Sutton-Anderson, the
    same task the reference drives through gym's CartPole-v0)."""

    observation_size = 4
    action_size = 2

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LENGTH = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4

    def __init__(self, max_steps: int = 200, seed: int = 0):
        self.max_steps = int(max_steps)
        self.rng = np.random.default_rng(seed)
        self._state = np.zeros(4, np.float32)
        self._steps = 0
        self._done = False

    def reset(self):
        self._state = self.rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        self._done = False
        return self._state.copy()

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pml = self.POLE_MASS * self.POLE_HALF_LENGTH
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + pml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LENGTH
            * (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pml * theta_acc * cos_t / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self._state = np.asarray([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1
        self._done = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT
                          or self._steps >= self.max_steps)
        return self._state.copy(), 1.0, self._done

    def is_done(self):
        return self._done
