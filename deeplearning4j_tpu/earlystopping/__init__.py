"""Early stopping.

Reference: ``org.deeplearning4j.earlystopping`` — ``EarlyStoppingConfiguration``
(epoch/iteration termination conditions + score calculator + model saver),
``EarlyStoppingTrainer#fit`` returning an ``EarlyStoppingResult`` with the
best model, and savers (``LocalFileModelSaver``, ``InMemoryModelSaver``).
"""

from __future__ import annotations

import copy
import enum
import os
import time
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.util import serializer


# ---------------------------------------------------------------------------
# Termination conditions
# ---------------------------------------------------------------------------

class EpochTerminationCondition:
    """Checked after each epoch (reference interface of the same name)."""

    def initialize(self) -> None:
        """Reset state at the start of each fit (reference
        ``EpochTerminationCondition#initialize``)."""

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after ``max_epochs_without_improvement`` non-improving epochs
    (improvement = score drop greater than ``min_improvement``).

    NaN-safe: a non-finite score terminates EXPLICITLY (``last_reason``
    says why) instead of silently counting as "no improvement" — with
    float comparisons every NaN compare is False, so a diverged run
    would otherwise grind through the whole patience window on NaN."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self._best = float("inf")
        self._bad = 0
        self.last_reason: Optional[str] = None

    def initialize(self):
        self._best = float("inf")
        self._bad = 0
        self.last_reason = None

    def terminate(self, epoch, score):
        if not np.isfinite(score):
            self.last_reason = f"non-finite score {score} at epoch {epoch}"
            return True
        if score < self._best - self.min_improvement:
            self._best = score
            self._bad = 0
            return False
        self._bad += 1
        if self._bad > self.patience:
            self.last_reason = (f"no improvement in {self._bad} epochs "
                                f"(best {self._best})")
            return True
        return False


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at/below a target (reference class).
    NaN-safe: a non-finite score terminates explicitly (it will never
    reach the target; ``score <= target`` is silently False for NaN)."""

    def __init__(self, best_expected_score: float):
        self.target = float(best_expected_score)
        self.last_reason: Optional[str] = None

    def initialize(self):
        self.last_reason = None

    def terminate(self, epoch, score):
        if not np.isfinite(score):
            self.last_reason = f"non-finite score {score} at epoch {epoch}"
            return True
        if score <= self.target:
            self.last_reason = f"score {score} reached target {self.target}"
            return True
        return False


class IterationTerminationCondition:
    """Checked after each iteration (minibatch)."""

    def initialize(self) -> None:
        """Reset state at the start of each fit."""

    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def initialize(self):
        self._start = None

    def terminate(self, score):
        if self._start is None:
            self._start = time.monotonic()
        return time.monotonic() - self._start > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort on exploding loss."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, score):
        return score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, score):
        return not np.isfinite(score)


class DivergenceTerminationCondition(IterationTerminationCondition):
    """Stop the fit when the run diverges: a non-finite iteration score,
    OR the health monitor (``telemetry.health``) observed non-finite
    steps since this fit started — so an in-graph guard trip (e.g. a
    NaN gradient under ``SKIP_STEP``, where the *score* may still look
    finite) also terminates the early-stopping loop."""

    def __init__(self):
        self._baseline = 0
        self.last_reason: Optional[str] = None

    def initialize(self):
        from deeplearning4j_tpu.telemetry import health

        m = health.monitor()
        m.flush()
        self._baseline = m.nonfinite_steps
        self.last_reason = None

    def terminate(self, score):
        if not np.isfinite(score):
            self.last_reason = f"non-finite score {score}"
            return True
        from deeplearning4j_tpu.telemetry import health

        m = health.monitor()
        m.flush()
        if m.nonfinite_steps > self._baseline:
            self.last_reason = (
                f"{m.nonfinite_steps - self._baseline} non-finite step(s) "
                f"observed by the health monitor (policy {m.policy.value})")
            return True
        return False


# ---------------------------------------------------------------------------
# Score calculators
# ---------------------------------------------------------------------------

class ScoreCalculator:
    def calculate_score(self, model) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a validation iterator (reference class)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model):
        total, n = 0.0, 0
        for ds in self.iterator:
            total += float(model.score(ds)) * ds.num_examples()
            n += ds.num_examples()
        self.iterator.reset()
        if n == 0:
            return float("nan")
        return total / n if self.average else total


class ClassificationScoreCalculator(ScoreCalculator):
    """NEGATIVE accuracy/F1 so that lower = better, matching the trainer's
    minimization convention (reference ``ClassificationScoreCalculator``)."""

    def __init__(self, iterator, metric: str = "accuracy"):
        self.iterator = iterator
        self.metric = metric

    def calculate_score(self, model):
        ev = model.evaluate(self.iterator)
        self.iterator.reset()
        return -float(getattr(ev, self.metric)())


# ---------------------------------------------------------------------------
# Model savers
# ---------------------------------------------------------------------------

class ModelSaver:
    def save_best_model(self, model, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError


class InMemoryModelSaver(ModelSaver):
    def __init__(self):
        self._best = None

    def save_best_model(self, model, score):
        import jax

        host = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: np.asarray(x), t)
        self._best = (copy.deepcopy(model.conf), host(model.params),
                      host(model.state))

    def get_best_model(self):
        if self._best is None:
            return None
        conf, params, state = self._best
        if type(conf).__name__ == "ComputationGraphConfiguration":
            from deeplearning4j_tpu.nn.graph import ComputationGraph

            net = ComputationGraph(conf)
        else:
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            net = MultiLayerNetwork(conf)
        net.init()
        net.params = copy.deepcopy(params)
        net.state = copy.deepcopy(state)
        return net


class LocalFileModelSaver(ModelSaver):
    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._path = os.path.join(self.directory, "bestModel.zip")

    def save_best_model(self, model, score):
        serializer.write_model(model, self._path, save_updater=True)

    def get_best_model(self):
        if not os.path.exists(self._path):
            return None
        return serializer.restore_model(self._path)


# ---------------------------------------------------------------------------
# Configuration / trainer / result
# ---------------------------------------------------------------------------

class EarlyStoppingConfiguration:
    """Reference ``EarlyStoppingConfiguration.Builder`` (kwargs replace the
    builder chain)."""

    def __init__(self,
                 epoch_termination_conditions: Optional[List] = None,
                 iteration_termination_conditions: Optional[List] = None,
                 score_calculator: Optional[ScoreCalculator] = None,
                 model_saver: Optional[ModelSaver] = None,
                 evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False):
        self.epoch_conditions = list(epoch_termination_conditions or [])
        self.iteration_conditions = list(
            iteration_termination_conditions or [])
        self.score_calculator = score_calculator
        self.model_saver = model_saver or InMemoryModelSaver()
        self.evaluate_every_n_epochs = int(evaluate_every_n_epochs)
        self.save_last_model = save_last_model


class TerminationReason(enum.Enum):
    EPOCH = "EpochTerminationCondition"
    ITERATION = "IterationTerminationCondition"
    ERROR = "Error"


class EarlyStoppingResult:
    """Reference ``EarlyStoppingResult``."""

    def __init__(self, termination_reason, termination_details,
                 score_vs_epoch, best_model_epoch, best_model_score,
                 total_epochs, best_model):
        self.termination_reason = termination_reason
        self.termination_details = termination_details
        self.score_vs_epoch = score_vs_epoch
        self.best_model_epoch = best_model_epoch
        self.best_model_score = best_model_score
        self.total_epochs = total_epochs
        self.best_model = best_model

    def get_best_model(self):
        return self.best_model


class EarlyStoppingTrainer:
    """Reference ``EarlyStoppingTrainer`` over a MultiLayerNetwork (the
    graph variant works identically through duck typing)."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iter):
        self.config = config
        self.net = net
        self.train_iter = train_iter

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        if not cfg.epoch_conditions and not cfg.iteration_conditions:
            raise ValueError(
                "EarlyStoppingConfiguration needs at least one termination "
                "condition (e.g. MaxEpochsTerminationCondition) or fit() "
                "would never return")
        for cond in cfg.epoch_conditions + cfg.iteration_conditions:
            cond.initialize()
        if self.net.params is None:
            self.net.init()
        best_score, best_epoch = float("inf"), -1
        scores = {}
        epoch = 0
        reason, details = TerminationReason.EPOCH, "max epochs"
        stop = False
        while not stop:
            for ds in self.train_iter:
                score = self.net.fit_batch(ds)
                for cond in cfg.iteration_conditions:
                    if cond.terminate(score):
                        details = f"{type(cond).__name__} at score {score}"
                        why = getattr(cond, "last_reason", None)
                        if why:
                            details += f" ({why})"
                        reason = TerminationReason.ITERATION
                        stop = True
                        break
                if stop:
                    break
            self.train_iter.reset()
            if stop:
                break

            if epoch % cfg.evaluate_every_n_epochs == 0:
                if cfg.score_calculator is not None:
                    score = cfg.score_calculator.calculate_score(self.net)
                else:
                    score = self.net.score_value
                scores[epoch] = score
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best_model(self.net, score)

            evaluated = epoch in scores
            for cond in cfg.epoch_conditions:
                # score-driven conditions only fire on epochs that actually
                # evaluated; MaxEpochs fires regardless (reference behavior)
                if not evaluated and not isinstance(
                        cond, MaxEpochsTerminationCondition):
                    continue
                if cond.terminate(epoch, scores.get(epoch, best_score)):
                    details = type(cond).__name__
                    why = getattr(cond, "last_reason", None)
                    if why:
                        details += f" ({why})"
                    reason = TerminationReason.EPOCH
                    stop = True
                    break
            epoch += 1

        best = cfg.model_saver.get_best_model()
        if best is None:
            best = self.net
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            score_vs_epoch=scores, best_model_epoch=best_epoch,
            best_model_score=best_score, total_epochs=epoch,
            best_model=best)
