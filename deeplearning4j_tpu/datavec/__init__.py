"""DataVec-equivalent ETL pipeline (reference ``datavec/`` modules).

Record readers, input splits, schema-driven transform processes, image
loading/augmentation and the RecordReader→DataSetIterator bridge — the
TPU-side difference is that everything stays host-side numpy until the
prefetcher hands batches to the jitted step (SURVEY.md §2.2 DataVec rows).
"""

from deeplearning4j_tpu.datavec.writables import (
    Writable, IntWritable, LongWritable, FloatWritable, DoubleWritable,
    Text, BooleanWritable, NDArrayWritable, NullWritable,
)
from deeplearning4j_tpu.datavec.split import (
    InputSplit, FileSplit, CollectionInputSplit, NumberedFileInputSplit,
    StringSplit,
)
from deeplearning4j_tpu.datavec.records import (
    RecordReader, SequenceRecordReader, CSVRecordReader, LineRecordReader,
    CollectionRecordReader, CollectionSequenceRecordReader,
    CSVSequenceRecordReader, RegexLineRecordReader, JsonRecordReader,
    TransformProcessRecordReader,
)
from deeplearning4j_tpu.datavec.schema import Schema, ColumnType
from deeplearning4j_tpu.datavec.transform import TransformProcess
from deeplearning4j_tpu.datavec.join import Join, JoinType, execute_join
from deeplearning4j_tpu.datavec.bridge import (
    RecordReaderDataSetIterator, SequenceRecordReaderDataSetIterator,
)

__all__ = [
    "Writable", "IntWritable", "LongWritable", "FloatWritable",
    "DoubleWritable", "Text", "BooleanWritable", "NDArrayWritable",
    "NullWritable",
    "InputSplit", "FileSplit", "CollectionInputSplit",
    "NumberedFileInputSplit", "StringSplit",
    "RecordReader", "SequenceRecordReader", "CSVRecordReader",
    "LineRecordReader", "CollectionRecordReader",
    "CollectionSequenceRecordReader", "CSVSequenceRecordReader",
    "RegexLineRecordReader", "JsonRecordReader",
    "TransformProcessRecordReader",
    "Schema", "ColumnType", "TransformProcess", "Join", "JoinType", "execute_join",
    "RecordReaderDataSetIterator", "SequenceRecordReaderDataSetIterator",
]
