"""Record readers.

Reference: ``org.datavec.api.records.reader.impl.*`` — ``CSVRecordReader``,
``LineRecordReader``, ``CSVSequenceRecordReader``, ``RegexLineRecordReader``,
Jackson JSON readers, ``CollectionRecordReader`` and the transform-applying
wrapper ``TransformProcessRecordReader``. A record is a list of cells; a
sequence record is a list of records (one per timestep).
"""

from __future__ import annotations

import csv
import io
import json
import re
from pathlib import Path
from typing import Iterator, List, Optional, Sequence

from deeplearning4j_tpu.datavec.split import InputSplit


class RecordReader:
    """One record per ``next()`` (reference ``RecordReader``). Iterating
    yields records (lists of cell values)."""

    def initialize(self, split: InputSplit) -> "RecordReader":
        raise NotImplementedError

    def __iter__(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def labels(self) -> Optional[List[str]]:
        """Known label set, when the reader derives labels (image readers)."""
        return None


class SequenceRecordReader(RecordReader):
    """One SEQUENCE (list of timestep records) per ``next()`` (reference
    ``SequenceRecordReader``)."""


def _read_text(location: str) -> str:
    p = Path(location)
    if p.exists():
        return p.read_text()
    return location  # StringSplit hands the data itself as the location


class LineRecordReader(RecordReader):
    """Each line is a single-cell record (reference ``LineRecordReader``)."""

    def __init__(self):
        self._split: Optional[InputSplit] = None

    def initialize(self, split: InputSplit):
        self._split = split
        return self

    def __iter__(self):
        for loc in self._split.locations():
            for line in _read_text(loc).splitlines():
                yield [line]


class CSVRecordReader(RecordReader):
    """CSV rows as records (reference ``CSVRecordReader``): skip-N-lines,
    custom delimiter/quote. Cells stay strings; numeric coercion happens in
    the transform process / dataset bridge, as in the reference."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ",",
                 quote: str = '"'):
        self.skip = int(skip_num_lines)
        self.delimiter = delimiter
        self.quote = quote
        self._split: Optional[InputSplit] = None

    def initialize(self, split: InputSplit):
        self._split = split
        return self

    def __iter__(self):
        for loc in self._split.locations():
            text = _read_text(loc)
            reader = csv.reader(io.StringIO(text), delimiter=self.delimiter,
                                quotechar=self.quote)
            for i, row in enumerate(reader):
                if i < self.skip or not row:
                    continue
                yield list(row)


def read_numeric_csv(split_or_path, delimiter: str = ",",
                     skip_num_lines: int = 0):
    """Fast path for ALL-NUMERIC CSVs: parse straight to a float32 matrix
    through the native OpenMP parser (``native/src/dl4j_native.cpp``),
    bypassing per-cell Python string handling (the role of DataVec's native
    ETL). Accepts a path or an InputSplit; files are concatenated row-wise.
    Falls back to pure Python when the native library is unavailable."""
    import numpy as _np

    from deeplearning4j_tpu import native as _native

    locs = (split_or_path.locations() if hasattr(split_or_path, "locations")
            else [split_or_path])
    mats = []
    for loc in locs:
        text = _read_text(loc)
        mats.append(_native.parse_numeric_csv(text, delimiter=delimiter,
                                              skip_lines=skip_num_lines))
    return mats[0] if len(mats) == 1 else _np.concatenate(mats, axis=0)


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (reference ``CSVSequenceRecordReader``,
    usually fed by ``NumberedFileInputSplit``)."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip = int(skip_num_lines)
        self.delimiter = delimiter
        self._split: Optional[InputSplit] = None

    def initialize(self, split: InputSplit):
        self._split = split
        return self

    def __iter__(self):
        for loc in self._split.locations():
            text = _read_text(loc)
            reader = csv.reader(io.StringIO(text), delimiter=self.delimiter)
            seq = [list(row) for i, row in enumerate(reader)
                   if i >= self.skip and row]
            yield seq


class RegexLineRecordReader(RecordReader):
    """Line → capture groups as cells (reference ``RegexLineRecordReader``)."""

    def __init__(self, regex: str, skip_num_lines: int = 0):
        self.pattern = re.compile(regex)
        self.skip = int(skip_num_lines)
        self._split: Optional[InputSplit] = None

    def initialize(self, split: InputSplit):
        self._split = split
        return self

    def __iter__(self):
        for loc in self._split.locations():
            for i, line in enumerate(_read_text(loc).splitlines()):
                if i < self.skip:
                    continue
                m = self.pattern.match(line)
                if m is None:
                    raise ValueError(
                        f"line {i} does not match {self.pattern.pattern!r}: "
                        f"{line!r}")
                yield list(m.groups())


class JsonRecordReader(RecordReader):
    """JSON objects → records with a fixed field order (reference: Jackson
    ``JacksonRecordReader`` with a ``FieldSelection``). Accepts a file of
    either one JSON object, a JSON array, or JSON-lines."""

    def __init__(self, field_selection: Sequence[str]):
        self.fields = list(field_selection)
        self._split: Optional[InputSplit] = None

    def initialize(self, split: InputSplit):
        self._split = split
        return self

    def _objects(self, text: str):
        text = text.strip()
        if not text:
            return
        if text.startswith("["):
            yield from json.loads(text)
            return
        for line in text.splitlines():
            line = line.strip()
            if line:
                yield json.loads(line)

    def __iter__(self):
        for loc in self._split.locations():
            for obj in self._objects(_read_text(loc)):
                yield [obj.get(f) for f in self.fields]


class CollectionRecordReader(RecordReader):
    """Records from an in-memory collection (reference
    ``CollectionRecordReader``)."""

    def __init__(self, records: Sequence[Sequence]):
        self._records = [list(r) for r in records]

    def initialize(self, split: InputSplit = None):
        return self

    def __iter__(self):
        return iter([list(r) for r in self._records])


class CollectionSequenceRecordReader(SequenceRecordReader):
    """Sequences from an in-memory collection (reference
    ``CollectionSequenceRecordReader``)."""

    def __init__(self, sequences: Sequence[Sequence[Sequence]]):
        self._seqs = [[list(r) for r in s] for s in sequences]

    def initialize(self, split: InputSplit = None):
        return self

    def __iter__(self):
        return iter([[list(r) for r in s] for s in self._seqs])


class TransformProcessRecordReader(RecordReader):
    """Wraps a reader, applying a TransformProcess per record (reference
    ``TransformProcessRecordReader``). Records removed by filters are
    skipped."""

    def __init__(self, reader: RecordReader, transform_process):
        self.reader = reader
        self.tp = transform_process

    def initialize(self, split: InputSplit):
        self.reader.initialize(split)
        return self

    def labels(self):
        return self.reader.labels()

    def reset(self):
        self.reader.reset()

    def __iter__(self):
        for rec in self.reader:
            out = self.tp.execute_record(rec)
            if out is not None:
                yield out
