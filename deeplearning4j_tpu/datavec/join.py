"""Record joins.

Reference: ``org.datavec.api.transform.join.Join`` (Builder with
``JoinType {Inner, LeftOuter, RightOuter, FullOuter}``, join columns, and
left/right schemas) executed by ``LocalTransformExecutor#executeJoin``.

Output record layout matches the reference: the join columns once, then
the remaining left columns, then the remaining right columns. Rows
missing on one side (outer joins) fill that side's columns with ``None``
(the reference's NullWritable); duplicate keys produce the cartesian
product of the matching groups, like any relational join.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu import serde
from deeplearning4j_tpu.datavec.schema import Schema
from deeplearning4j_tpu.datavec.transform import value_of


@serde.register_enum
class JoinType(enum.Enum):
    """Reference ``Join.JoinType``."""

    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"


@serde.register
@dataclasses.dataclass
class Join:
    """Reference ``Join`` (built via :class:`JoinBuilder` /
    ``Join.Builder``)."""

    join_type: JoinType = JoinType.INNER
    left_schema: Optional[Schema] = None
    right_schema: Optional[Schema] = None
    join_columns: Tuple[str, ...] = ()
    # when the right side names its key columns differently
    right_join_columns: Optional[Tuple[str, ...]] = None

    class Builder:
        def __init__(self, join_type: JoinType = JoinType.INNER):
            self._type = join_type
            self._left = self._right = None
            self._cols: Tuple[str, ...] = ()
            self._rcols: Optional[Tuple[str, ...]] = None

        def set_join_columns(self, *names: str) -> "Join.Builder":
            self._cols = tuple(names)
            return self

        def set_join_columns_right(self, *names: str) -> "Join.Builder":
            self._rcols = tuple(names)
            return self

        def set_schemas(self, left: Schema, right: Schema) -> "Join.Builder":
            self._left, self._right = left, right
            return self

        def build(self) -> "Join":
            j = Join(join_type=self._type, left_schema=self._left,
                     right_schema=self._right, join_columns=self._cols,
                     right_join_columns=self._rcols)
            j.output_schema()  # validate eagerly, like the reference
            return j

    def _right_keys(self) -> Tuple[str, ...]:
        return self.right_join_columns or self.join_columns

    def output_schema(self) -> Schema:
        """Join columns once (left naming), then left remainder, then
        right remainder (reference ``Join#getOutputSchema``)."""
        if self.left_schema is None or self.right_schema is None:
            raise ValueError("Join needs both schemas (setSchemas)")
        if not self.join_columns:
            raise ValueError("Join needs at least one join column")
        if len(self._right_keys()) != len(self.join_columns):
            raise ValueError(
                f"join key arity mismatch: {len(self.join_columns)} left "
                f"columns vs {len(self._right_keys())} right (keys are "
                "compared positionally)")
        for n in self.join_columns:
            self.left_schema.index_of(n)   # raises on unknown
        for n in self._right_keys():
            self.right_schema.index_of(n)
        cols = [self.left_schema.columns[self.left_schema.index_of(n)]
                for n in self.join_columns]
        cols += [c for c in self.left_schema.columns
                 if c.name not in self.join_columns]
        right_drop = set(self._right_keys())
        taken = {c.name for c in cols}
        for c in self.right_schema.columns:
            if c.name in right_drop:
                continue
            if c.name in taken:
                raise ValueError(
                    f"column {c.name!r} exists on both sides; rename one "
                    "(reference Join requires unique non-key names)")
            cols.append(c)
        return Schema(columns=tuple(cols))

    # -- execution ----------------------------------------------------------
    def execute(self, left_records: Sequence[Sequence],
                right_records: Sequence[Sequence]) -> List[List]:
        """Hash join (reference ``LocalTransformExecutor#executeJoin``)."""
        self.output_schema()  # validate even for hand-built/deserialized Joins
        ls, rs = self.left_schema, self.right_schema
        lkeys, rkeys = self.join_columns, self._right_keys()
        # index lists precomputed once — index_of is a linear column scan
        l_key_idx = [ls.index_of(n) for n in lkeys]
        r_key_idx = [rs.index_of(n) for n in rkeys]
        l_rest = [i for i, c in enumerate(ls.columns)
                  if c.name not in lkeys]
        r_rest = [i for i, c in enumerate(rs.columns)
                  if c.name not in set(rkeys)]

        groups: dict = {}
        for rec in right_records:
            k = tuple(value_of(rec[i]) for i in r_key_idx)
            groups.setdefault(k, []).append(rec)

        out: List[List] = []
        matched_keys = set()
        for rec in left_records:
            k = tuple(value_of(rec[i]) for i in l_key_idx)
            key_vals = [rec[i] for i in l_key_idx]
            lvals = [rec[i] for i in l_rest]
            matches = groups.get(k)
            if matches:
                matched_keys.add(k)
                for r in matches:
                    out.append(key_vals + lvals + [r[i] for i in r_rest])
            elif self.join_type in (JoinType.LEFT_OUTER,
                                    JoinType.FULL_OUTER):
                out.append(key_vals + lvals + [None] * len(r_rest))
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            for k, recs in groups.items():
                if k in matched_keys:
                    continue
                for r in recs:
                    out.append([r[i] for i in r_key_idx]
                               + [None] * len(l_rest)
                               + [r[i] for i in r_rest])
        return out


def execute_join(join: Join, left_records: Sequence[Sequence],
                 right_records: Sequence[Sequence]) -> List[List]:
    """Functional alias mirroring ``LocalTransformExecutor.executeJoin``."""
    return join.execute(left_records, right_records)
