"""Image loading + augmentation.

Reference: ``datavec-data-image`` — ``NativeImageLoader`` (OpenCV decode →
NCHW INDArray), ``ImageRecordReader`` (label inferred from parent dir via
``ParentPathLabelGenerator``), and ``org.datavec.image.transform.*``
augmentations (crop/flip/rotate/warp/color, composed by
``PipelineImageTransform``). Decode here uses PIL+numpy on the host; the
augmented batch crosses to device once, via the dataset bridge/prefetcher.

Layout is HWC float32 (the framework's TPU-native channels-last convention
— the reference defaults to channels-first; pass ``channels_first=True`` to
the loader/reader for that layout). Pixel values stay in [0,255]; scaling
is the normalizer's job, as in the reference.
"""

from __future__ import annotations

import dataclasses
import random
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.split import InputSplit


def _per_channel(img: np.ndarray, fn) -> np.ndarray:
    """Apply a 2D→2D float op per channel of an HWC image."""
    return np.stack([fn(img[:, :, c]) for c in range(img.shape[2])], axis=-1)


class ImageLoader:
    """Decode + resize + to-HWC (reference ``NativeImageLoader#asMatrix``)."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 channels_first: bool = False):
        self.height = int(height)
        self.width = int(width)
        self.channels = int(channels)
        self.channels_first = channels_first

    def _finish(self, arr: np.ndarray) -> np.ndarray:
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.channels_first:
            arr = np.transpose(arr, (2, 0, 1))
        return np.ascontiguousarray(arr)

    def as_matrix(self, path) -> np.ndarray:
        """file → float32 [H,W,C] (or [C,H,W] if channels_first)."""
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("L" if self.channels == 1 else "RGB")
            im = im.resize((self.width, self.height), Image.BILINEAR)
            arr = np.asarray(im, dtype=np.float32)
        return self._finish(arr)

    def from_array(self, arr: np.ndarray) -> np.ndarray:
        """HWC / HW / CHW array → float32 resized, target layout."""
        from PIL import Image

        arr = np.asarray(arr)
        if arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3):
            arr = np.transpose(arr, (1, 2, 0))  # CHW -> HWC
        im = Image.fromarray(arr.astype(np.uint8).squeeze())
        im = im.convert("L" if self.channels == 1 else "RGB")
        im = im.resize((self.width, self.height), Image.BILINEAR)
        return self._finish(np.asarray(im, dtype=np.float32))


# --------------------------------------------------------------------------
# augmentation transforms (reference org.datavec.image.transform.*)
# --------------------------------------------------------------------------
class ImageTransform:
    """HWC float image → HWC float image; randomness drawn from ``rng`` when
    the transform is stochastic (reference ``ImageTransform#transform``)."""

    def apply(self, img: np.ndarray, rng: random.Random) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass
class FlipImageTransform(ImageTransform):
    """Reference ``FlipImageTransform``: mode 0=vertical (flip about the
    x-axis), 1=horizontal, -1=both; None = random choice per image."""
    mode: Optional[int] = 1

    def apply(self, img, rng):
        mode = self.mode if self.mode is not None else rng.choice([-1, 0, 1])
        if mode in (1, -1):
            img = img[:, ::-1, :]
        if mode in (0, -1):
            img = img[::-1, :, :]
        return np.ascontiguousarray(img)


@dataclasses.dataclass
class RandomCropTransform(ImageTransform):
    """Reference ``RandomCropTransform``: random crop to (height,width)."""
    height: int
    width: int

    def apply(self, img, rng):
        h, w = img.shape[:2]
        if h < self.height or w < self.width:
            raise ValueError(f"crop {self.height}x{self.width} > image {h}x{w}")
        top = rng.randint(0, h - self.height)
        left = rng.randint(0, w - self.width)
        return img[top:top + self.height, left:left + self.width, :]


@dataclasses.dataclass
class CropImageTransform(ImageTransform):
    """Reference ``CropImageTransform``: deterministic border crop."""
    crop_top: int = 0
    crop_left: int = 0
    crop_bottom: int = 0
    crop_right: int = 0

    def apply(self, img, rng):
        h, w = img.shape[:2]
        return img[self.crop_top:h - self.crop_bottom,
                   self.crop_left:w - self.crop_right, :]


@dataclasses.dataclass
class RotateImageTransform(ImageTransform):
    """Reference ``RotateImageTransform``: rotate by angle±delta degrees
    about the center, same output size."""
    angle: float = 0.0
    delta: float = 0.0

    def apply(self, img, rng):
        from PIL import Image

        ang = self.angle + (rng.uniform(-self.delta, self.delta)
                            if self.delta else 0.0)
        return _per_channel(img, lambda c: np.asarray(
            Image.fromarray(c).rotate(ang, resample=Image.BILINEAR),
            dtype=np.float32))


@dataclasses.dataclass
class ResizeImageTransform(ImageTransform):
    """Reference ``ResizeImageTransform``."""
    height: int
    width: int

    def apply(self, img, rng):
        from PIL import Image

        return _per_channel(img, lambda c: np.asarray(
            Image.fromarray(c).resize((self.width, self.height),
                                      Image.BILINEAR), dtype=np.float32))


@dataclasses.dataclass
class ScaleImageTransform(ImageTransform):
    """Reference ``ScaleImageTransform``: random scale by up to ±delta
    pixels in each dimension."""
    delta: float

    def apply(self, img, rng):
        from PIL import Image

        h, w = img.shape[:2]
        nh = max(1, int(round(h + rng.uniform(-self.delta, self.delta))))
        nw = max(1, int(round(w + rng.uniform(-self.delta, self.delta))))
        return _per_channel(img, lambda c: np.asarray(
            Image.fromarray(c).resize((nw, nh), Image.BILINEAR),
            dtype=np.float32))


@dataclasses.dataclass
class EqualizeHistTransform(ImageTransform):
    """Reference ``EqualizeHistTransform``: per-channel histogram
    equalization."""

    def apply(self, img, rng):
        def eq(c):
            flat = c.astype(np.uint8).ravel()
            hist = np.bincount(flat, minlength=256).astype(np.float64)
            cdf = hist.cumsum()
            nz = cdf[cdf > 0]
            if nz.size == 0:
                return c
            cdf_min = nz[0]
            denom = max(cdf[-1] - cdf_min, 1)
            lut = np.round((cdf - cdf_min) / denom * 255.0).clip(0, 255)
            return lut[flat].reshape(c.shape).astype(np.float32)

        return _per_channel(img, eq)


class PipelineImageTransform(ImageTransform):
    """Reference ``PipelineImageTransform``: sequence of (transform, prob)
    pairs, each applied with its probability."""

    def __init__(self, transforms: Sequence, shuffle: bool = False):
        # accepts ImageTransform or (ImageTransform, prob)
        self.steps: List[Tuple[ImageTransform, float]] = []
        for t in transforms:
            if isinstance(t, tuple):
                self.steps.append((t[0], float(t[1])))
            else:
                self.steps.append((t, 1.0))
        self.shuffle = shuffle

    def apply(self, img, rng):
        steps = list(self.steps)
        if self.shuffle:
            rng.shuffle(steps)
        for t, p in steps:
            if p >= 1.0 or rng.random() < p:
                img = t.apply(img, rng)
        return img


# --------------------------------------------------------------------------
# readers
# --------------------------------------------------------------------------
class ParentPathLabelGenerator:
    """Label = parent directory name (reference
    ``ParentPathLabelGenerator``)."""

    def label_for(self, path: str) -> str:
        return Path(path).parent.name


class ImageRecordReader(RecordReader):
    """Reference ``ImageRecordReader``: record = [image ndarray, label
    index]. Labels discovered from parent dirs (sorted, as the reference
    does) or omitted when no label generator is set. Augmentation runs on
    the HWC image; ``channels_first`` transposes at the end."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator: Optional[ParentPathLabelGenerator] = None,
                 image_transform: Optional[ImageTransform] = None,
                 channels_first: bool = False, seed: int = 12345):
        self.loader = ImageLoader(height, width, channels)
        self.label_gen = label_generator
        self.transform = image_transform
        self.channels_first = channels_first
        self._labels: Optional[List[str]] = None
        self._split: Optional[InputSplit] = None
        self._rng = random.Random(seed)
        self._seed = seed

    def initialize(self, split: InputSplit):
        self._split = split
        if self.label_gen is not None:
            found = {self.label_gen.label_for(p) for p in split.locations()}
            self._labels = sorted(found)
        return self

    def labels(self):
        return self._labels

    def reset(self):
        self._rng = random.Random(self._seed)

    def __iter__(self):
        for loc in self._split.locations():
            img = self.loader.as_matrix(loc)
            if self.transform is not None:
                img = self.transform.apply(img, self._rng)
            if self.channels_first:
                img = np.transpose(img, (2, 0, 1))
            if self.label_gen is not None:
                label = self._labels.index(self.label_gen.label_for(loc))
                yield [img, label]
            else:
                yield [img]
