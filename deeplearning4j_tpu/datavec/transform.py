"""TransformProcess — schema-driven record transformation pipeline.

Reference: ``org.datavec.api.transform.TransformProcess`` (+ ``.Builder``)
and the transform/filter implementations under
``org.datavec.api.transform.transform.*`` / ``...transform.filter.*``:
each step maps (schema, record) → (schema', record'), so the output schema
is statically derivable (``TransformProcess#getFinalSchema``) and the whole
process JSON round-trips. Implemented subset covers the operations the
reference's examples lean on: remove/keep columns, rename, numeric math,
categorical↔integer/one-hot, string ops, conditional replace, filters,
time extraction, and min-max/standardize normalization given fitted stats.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Any, List, Optional, Sequence

from deeplearning4j_tpu import serde
from deeplearning4j_tpu.datavec.schema import ColumnMetadata, ColumnType, Schema
from deeplearning4j_tpu.datavec.writables import numeric_of, value_of


@serde.register_enum
class MathOp(enum.Enum):
    Add = "Add"
    Subtract = "Subtract"
    Multiply = "Multiply"
    Divide = "Divide"
    Modulus = "Modulus"
    ReverseSubtract = "ReverseSubtract"
    ReverseDivide = "ReverseDivide"
    ScalarMin = "ScalarMin"
    ScalarMax = "ScalarMax"


@serde.register_enum
class MathFunction(enum.Enum):
    Abs = "Abs"
    Ceil = "Ceil"
    Floor = "Floor"
    Exp = "Exp"
    Log = "Log"
    Log2 = "Log2"
    Sign = "Sign"
    Sin = "Sin"
    Cos = "Cos"
    Tan = "Tan"
    Sqrt = "Sqrt"


@serde.register_enum
class ConditionOp(enum.Enum):
    LessThan = "LessThan"
    LessOrEqual = "LessOrEqual"
    GreaterThan = "GreaterThan"
    GreaterOrEqual = "GreaterOrEqual"
    Equal = "Equal"
    NotEqual = "NotEqual"
    InSet = "InSet"
    NotInSet = "NotInSet"


def _coerced_eq(a, b) -> bool:
    """Equality with numeric coercion: CSV cells are strings, so "30" must
    equal a numeric condition value 30 (the reference compares via typed
    Writables; coercion restores that behavior here)."""
    if a == b:
        return True
    try:
        return float(a) == float(b)
    except (TypeError, ValueError):
        return False


def _check_condition(op: ConditionOp, cell, value) -> bool:
    v = value_of(cell)
    if op in (ConditionOp.InSet, ConditionOp.NotInSet):
        hit = any(_coerced_eq(v, item) for item in value)
        return hit if op is ConditionOp.InSet else not hit
    if op in (ConditionOp.Equal, ConditionOp.NotEqual):
        eq = _coerced_eq(v, value)
        return eq if op is ConditionOp.Equal else not eq
    x, y = float(numeric_of(cell)), float(value)
    return {ConditionOp.LessThan: x < y,
            ConditionOp.LessOrEqual: x <= y,
            ConditionOp.GreaterThan: x > y,
            ConditionOp.GreaterOrEqual: x >= y}[op]


class Transform:
    """One step: record→record with a derivable output schema."""

    def output_schema(self, schema: Schema) -> Schema:
        raise NotImplementedError

    def map_record(self, schema: Schema, record: List) -> List:
        raise NotImplementedError


class Filter:
    """Record predicate; True = REMOVE the record (reference
    ``FilterInvalidValues`` / ``ConditionFilter`` semantics)."""

    def remove_record(self, schema: Schema, record: List) -> bool:
        raise NotImplementedError


# --------------------------------------------------------------------------
# column management
# --------------------------------------------------------------------------
@serde.register
@dataclasses.dataclass
class RemoveColumns(Transform):
    """Reference ``RemoveColumnsTransform``."""
    names: List[str]

    def output_schema(self, schema):
        drop = set(self.names)
        for n in drop:
            schema.index_of(n)  # raise on unknown, as the reference does
        return schema.with_columns([c for c in schema.columns
                                    if c.name not in drop])

    def map_record(self, schema, record):
        drop = {schema.index_of(n) for n in self.names}
        return [v for i, v in enumerate(record) if i not in drop]


@serde.register
@dataclasses.dataclass
class RemoveAllColumnsExcept(Transform):
    """Reference ``RemoveAllColumnsExceptForTransform``."""
    names: List[str]

    def output_schema(self, schema):
        keep = set(self.names)
        return schema.with_columns([c for c in schema.columns if c.name in keep])

    def map_record(self, schema, record):
        keep = {schema.index_of(n) for n in self.names}
        return [v for i, v in enumerate(record) if i in keep]


@serde.register
@dataclasses.dataclass
class RenameColumns(Transform):
    """Reference ``RenameColumnsTransform``."""
    old_names: List[str]
    new_names: List[str]

    def output_schema(self, schema):
        mapping = dict(zip(self.old_names, self.new_names))
        return schema.with_columns([
            dataclasses.replace(c, name=mapping.get(c.name, c.name))
            for c in schema.columns])

    def map_record(self, schema, record):
        return list(record)


@serde.register
@dataclasses.dataclass
class ReorderColumns(Transform):
    """Reference ``ReorderColumnsTransform``; unlisted columns follow in
    original order."""
    names: List[str]

    def _order(self, schema):
        head = [schema.index_of(n) for n in self.names]
        tail = [i for i in range(schema.num_columns()) if i not in set(head)]
        return head + tail

    def output_schema(self, schema):
        return schema.with_columns([schema.columns[i] for i in self._order(schema)])

    def map_record(self, schema, record):
        return [record[i] for i in self._order(schema)]


@serde.register
@dataclasses.dataclass
class DuplicateColumns(Transform):
    """Reference ``DuplicateColumnsTransform`` — copies appended with new
    names."""
    names: List[str]
    new_names: List[str]

    def output_schema(self, schema):
        extra = [dataclasses.replace(schema.column(o), name=n)
                 for o, n in zip(self.names, self.new_names)]
        return schema.with_columns(list(schema.columns) + extra)

    def map_record(self, schema, record):
        return list(record) + [record[schema.index_of(o)] for o in self.names]


# --------------------------------------------------------------------------
# numeric / math
# --------------------------------------------------------------------------
@serde.register
@dataclasses.dataclass
class MathOpTransform(Transform):
    """Reference ``DoubleMathOpTransform``/``IntegerMathOpTransform``."""
    name: str
    op: MathOp
    scalar: float

    def output_schema(self, schema):
        return schema

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        x = numeric_of(record[i])
        s = self.scalar
        y = {MathOp.Add: x + s, MathOp.Subtract: x - s,
             MathOp.Multiply: x * s, MathOp.Divide: x / s,
             MathOp.Modulus: x % s, MathOp.ReverseSubtract: s - x,
             MathOp.ReverseDivide: s / x, MathOp.ScalarMin: min(x, s),
             MathOp.ScalarMax: max(x, s)}[self.op]
        out = list(record)
        if schema.columns[i].column_type in (ColumnType.Integer, ColumnType.Long):
            y = int(y)
        out[i] = y
        return out


@serde.register
@dataclasses.dataclass
class MathFunctionTransform(Transform):
    """Reference ``DoubleMathFunctionTransform``."""
    name: str
    function: MathFunction

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i], column_type=ColumnType.Double,
                                      state_names=None)
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        x = numeric_of(record[i])
        f = {MathFunction.Abs: abs, MathFunction.Ceil: math.ceil,
             MathFunction.Floor: math.floor, MathFunction.Exp: math.exp,
             MathFunction.Log: math.log, MathFunction.Log2: math.log2,
             MathFunction.Sign: lambda v: float((v > 0) - (v < 0)),
             MathFunction.Sin: math.sin, MathFunction.Cos: math.cos,
             MathFunction.Tan: math.tan, MathFunction.Sqrt: math.sqrt}
        out = list(record)
        out[i] = float(f[self.function](x))
        return out


@serde.register
@dataclasses.dataclass
class MinMaxNormalize(Transform):
    """Reference normalize ``Normalize.MinMax`` (stats supplied, as produced
    by an AnalyzeLocal pass — see :func:`TransformProcess.fit_normalizers`)."""
    name: str
    min_value: float
    max_value: float
    new_min: float = 0.0
    new_max: float = 1.0

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i], column_type=ColumnType.Double,
                                      state_names=None)
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        x = numeric_of(record[i])
        rng = self.max_value - self.min_value
        frac = 0.0 if rng == 0 else (x - self.min_value) / rng
        out = list(record)
        out[i] = self.new_min + frac * (self.new_max - self.new_min)
        return out


@serde.register
@dataclasses.dataclass
class StandardizeNormalize(Transform):
    """Reference ``Normalize.Standardize`` (z-score with supplied stats)."""
    name: str
    mean: float
    std: float

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i], column_type=ColumnType.Double,
                                      state_names=None)
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        x = numeric_of(record[i])
        out = list(record)
        out[i] = (x - self.mean) / (self.std if self.std != 0 else 1.0)
        return out


# --------------------------------------------------------------------------
# categorical / string
# --------------------------------------------------------------------------
@serde.register
@dataclasses.dataclass
class CategoricalToInteger(Transform):
    """Reference ``CategoricalToIntegerTransform``."""
    name: str

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        if schema.columns[i].column_type is not ColumnType.Categorical:
            raise ValueError(f"{self.name} is not categorical")
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i], column_type=ColumnType.Integer,
                                      state_names=None)
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        states = schema.columns[i].state_names
        out = list(record)
        out[i] = states.index(str(value_of(record[i])))
        return out


@serde.register
@dataclasses.dataclass
class CategoricalToOneHot(Transform):
    """Reference ``CategoricalToOneHotTransform`` — expands to one
    0/1 Integer column per state, named ``col[state]``."""
    name: str

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        meta = schema.columns[i]
        if meta.column_type is not ColumnType.Categorical:
            raise ValueError(f"{self.name} is not categorical")
        new = [ColumnMetadata(f"{self.name}[{s}]", ColumnType.Integer)
               for s in meta.state_names]
        cols = list(schema.columns)
        cols[i:i + 1] = new
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        states = schema.columns[i].state_names
        idx = states.index(str(value_of(record[i])))
        onehot = [1 if j == idx else 0 for j in range(len(states))]
        out = list(record)
        out[i:i + 1] = onehot
        return out


@serde.register
@dataclasses.dataclass
class IntegerToCategorical(Transform):
    """Reference ``IntegerToCategoricalTransform``."""
    name: str
    state_names: List[str]

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i],
                                      column_type=ColumnType.Categorical,
                                      state_names=list(self.state_names))
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        out = list(record)
        out[i] = self.state_names[int(numeric_of(record[i]))]
        return out


@serde.register
@dataclasses.dataclass
class StringToCategorical(Transform):
    """Reference ``StringToCategoricalTransform``."""
    name: str
    state_names: List[str]

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i],
                                      column_type=ColumnType.Categorical,
                                      state_names=list(self.state_names))
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        return list(record)


@serde.register
@dataclasses.dataclass
class StringMapTransform(Transform):
    """Reference ``StringMapTransform`` — exact-match replacement map."""
    name: str
    mapping: dict

    def output_schema(self, schema):
        return schema

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        out = list(record)
        s = str(value_of(record[i]))
        out[i] = self.mapping.get(s, s)
        return out


@serde.register
@dataclasses.dataclass
class ReplaceEmptyWithValue(Transform):
    """Reference ``ReplaceEmptyStringTransform`` /
    ``ReplaceInvalidWithIntegerTransform`` family."""
    name: str
    value: Any

    def output_schema(self, schema):
        return schema

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        out = list(record)
        v = value_of(record[i])
        if v is None or (isinstance(v, str) and v.strip() == ""):
            out[i] = self.value
        return out


@serde.register
@dataclasses.dataclass
class ConditionalReplaceValue(Transform):
    """Reference ``ConditionalReplaceValueTransform``: replace cell when the
    condition on (possibly another) column holds."""
    name: str
    value: Any
    condition_column: str
    op: ConditionOp
    condition_value: Any

    def output_schema(self, schema):
        return schema

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        j = schema.index_of(self.condition_column)
        out = list(record)
        if _check_condition(self.op, record[j], self.condition_value):
            out[i] = self.value
        return out


@serde.register
@dataclasses.dataclass
class AppendStringColumn(Transform):
    """Reference ``AppendStringColumnTransform``."""
    name: str
    to_append: str

    def output_schema(self, schema):
        return schema

    def map_record(self, schema, record):
        i = schema.index_of(self.name)
        out = list(record)
        out[i] = str(value_of(record[i])) + self.to_append
        return out


@serde.register
@dataclasses.dataclass
class ConcatenateStringColumns(Transform):
    """Reference ``ConcatenateStringColumns`` — new column appended."""
    new_name: str
    delimiter: str
    names: List[str]

    def output_schema(self, schema):
        return schema.with_columns(
            list(schema.columns) + [ColumnMetadata(self.new_name,
                                                   ColumnType.String)])

    def map_record(self, schema, record):
        parts = [str(value_of(record[schema.index_of(n)])) for n in self.names]
        return list(record) + [self.delimiter.join(parts)]


# --------------------------------------------------------------------------
# time
# --------------------------------------------------------------------------
@serde.register
@dataclasses.dataclass
class StringToTime(Transform):
    """Reference ``StringToTimeTransform`` — parse to epoch millis with a
    strptime format."""
    name: str
    format: str

    def output_schema(self, schema):
        i = schema.index_of(self.name)
        cols = list(schema.columns)
        cols[i] = dataclasses.replace(cols[i], column_type=ColumnType.Time,
                                      state_names=None)
        return schema.with_columns(cols)

    def map_record(self, schema, record):
        import datetime as dt
        i = schema.index_of(self.name)
        t = dt.datetime.strptime(str(value_of(record[i])), self.format)
        t = t.replace(tzinfo=dt.timezone.utc)
        out = list(record)
        out[i] = int(t.timestamp() * 1000)
        return out


@serde.register
@dataclasses.dataclass
class DeriveColumnsFromTime(Transform):
    """Reference ``DeriveColumnsFromTimeTransform`` — derive
    hour/day/month/year integer columns from an epoch-millis Time column."""
    name: str
    fields: List[str]  # subset of hour, minute, day, month, year, dayofweek

    def output_schema(self, schema):
        extra = [ColumnMetadata(f"{self.name}_{f}", ColumnType.Integer)
                 for f in self.fields]
        return schema.with_columns(list(schema.columns) + extra)

    def map_record(self, schema, record):
        import datetime as dt
        i = schema.index_of(self.name)
        t = dt.datetime.fromtimestamp(numeric_of(record[i]) / 1000.0,
                                      tz=dt.timezone.utc)
        fmap = {"hour": t.hour, "minute": t.minute, "day": t.day,
                "month": t.month, "year": t.year,
                "dayofweek": t.weekday()}
        return list(record) + [fmap[f] for f in self.fields]


# --------------------------------------------------------------------------
# filters
# --------------------------------------------------------------------------
@serde.register
@dataclasses.dataclass
class ConditionFilter(Filter):
    """Reference ``ConditionFilter``: remove record when condition holds."""
    name: str
    op: ConditionOp
    value: Any

    def remove_record(self, schema, record):
        return _check_condition(self.op, record[schema.index_of(self.name)],
                                self.value)


@serde.register
@dataclasses.dataclass
class FilterInvalidValues(Filter):
    """Reference ``FilterInvalidValues``: drop records whose listed numeric
    columns fail to parse."""
    names: List[str]

    def remove_record(self, schema, record):
        for n in self.names:
            try:
                numeric_of(record[schema.index_of(n)])
            except (TypeError, ValueError):
                return True
        return False


@dataclasses.dataclass
class _FilterStep:
    filter: Filter


@dataclasses.dataclass
class _TransformStep:
    transform: Transform


serde.register(_FilterStep, name="FilterStep")
serde.register(_TransformStep, name="TransformStep")


# --------------------------------------------------------------------------
# the process
# --------------------------------------------------------------------------
@serde.register
@dataclasses.dataclass
class TransformProcess:
    """Ordered steps from an initial schema (reference ``TransformProcess``;
    JSON round-trip is a tested parity requirement there)."""

    initial_schema: Schema
    steps: List[Any] = dataclasses.field(default_factory=list)

    @staticmethod
    def builder(initial_schema: Schema) -> "TransformProcessBuilder":
        return TransformProcessBuilder(initial_schema)

    def _schema_chain(self) -> List[Schema]:
        """Per-step input schemas, derived once (the chain is static; deriving
        it per record would be O(records × steps) wasted work)."""
        chain = []
        s = self.initial_schema
        for st in self.steps:
            chain.append(s)
            if isinstance(st, _TransformStep):
                s = st.transform.output_schema(s)
        chain.append(s)
        return chain

    def final_schema(self) -> Schema:
        return self._schema_chain()[-1]

    def execute_record(self, record: List) -> Optional[List]:
        """record → transformed record, or None if filtered out."""
        chain = getattr(self, "_chain_cache", None)
        if chain is None:
            chain = self._chain_cache = self._schema_chain()
        rec = list(record)
        for st, s in zip(self.steps, chain):
            if isinstance(st, _FilterStep):
                if st.filter.remove_record(s, rec):
                    return None
            else:
                rec = st.transform.map_record(s, rec)
        return rec

    def execute(self, records: Sequence[List]) -> List[List]:
        """Local executor (reference ``LocalTransformExecutor#execute``)."""
        out = []
        for r in records:
            t = self.execute_record(r)
            if t is not None:
                out.append(t)
        return out

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        return serde.from_json(s)

    # --- stats fitting helper ----------------------------------------------
    @staticmethod
    def fit_normalizers(schema: Schema, records: Sequence[List],
                        names: Sequence[str], kind: str = "standardize"):
        """AnalyzeLocal-equivalent pass: compute per-column stats and return
        ready normalize transforms (reference: ``AnalyzeLocal.analyze`` +
        ``Normalize`` transform construction)."""
        import numpy as np
        cols = {n: [] for n in names}
        for r in records:
            for n in names:
                cols[n].append(numeric_of(r[schema.index_of(n)]))
        out = []
        for n in names:
            arr = np.asarray(cols[n], dtype=np.float64)
            if kind == "standardize":
                out.append(StandardizeNormalize(n, float(arr.mean()),
                                                float(arr.std())))
            elif kind == "minmax":
                out.append(MinMaxNormalize(n, float(arr.min()),
                                           float(arr.max())))
            else:
                raise ValueError(f"unknown normalizer kind {kind!r}")
        return out


class TransformProcessBuilder:
    """Reference ``TransformProcess.Builder`` fluent API."""

    def __init__(self, initial_schema: Schema):
        self._schema = initial_schema
        self._steps: List[Any] = []

    def transform(self, t: Transform):
        self._steps.append(_TransformStep(t))
        return self

    def filter(self, f: Filter):
        self._steps.append(_FilterStep(f))
        return self

    # convenience mirrors of the reference builder methods
    def remove_columns(self, *names: str):
        return self.transform(RemoveColumns(list(names)))

    def remove_all_columns_except(self, *names: str):
        return self.transform(RemoveAllColumnsExcept(list(names)))

    def rename_column(self, old: str, new: str):
        return self.transform(RenameColumns([old], [new]))

    def reorder_columns(self, *names: str):
        return self.transform(ReorderColumns(list(names)))

    def duplicate_column(self, name: str, new_name: str):
        return self.transform(DuplicateColumns([name], [new_name]))

    def math_op(self, name: str, op: MathOp, scalar: float):
        return self.transform(MathOpTransform(name, op, scalar))

    def math_function(self, name: str, fn: MathFunction):
        return self.transform(MathFunctionTransform(name, fn))

    def categorical_to_integer(self, *names: str):
        for n in names:
            self.transform(CategoricalToInteger(n))
        return self

    def categorical_to_one_hot(self, *names: str):
        for n in names:
            self.transform(CategoricalToOneHot(n))
        return self

    def integer_to_categorical(self, name: str, states: Sequence[str]):
        return self.transform(IntegerToCategorical(name, list(states)))

    def string_to_categorical(self, name: str, states: Sequence[str]):
        return self.transform(StringToCategorical(name, list(states)))

    def string_map(self, name: str, mapping: dict):
        return self.transform(StringMapTransform(name, dict(mapping)))

    def append_string(self, name: str, to_append: str):
        return self.transform(AppendStringColumn(name, to_append))

    def concat_strings(self, new_name: str, delimiter: str, names: Sequence[str]):
        return self.transform(ConcatenateStringColumns(new_name, delimiter,
                                                       list(names)))

    def string_to_time(self, name: str, fmt: str):
        return self.transform(StringToTime(name, fmt))

    def derive_from_time(self, name: str, fields: Sequence[str]):
        return self.transform(DeriveColumnsFromTime(name, list(fields)))

    def conditional_replace(self, name: str, value, condition_column: str,
                            op: ConditionOp, condition_value):
        return self.transform(ConditionalReplaceValue(
            name, value, condition_column, op, condition_value))

    def replace_empty(self, name: str, value):
        return self.transform(ReplaceEmptyWithValue(name, value))

    def filter_condition(self, name: str, op: ConditionOp, value):
        return self.filter(ConditionFilter(name, op, value))

    def filter_invalid(self, *names: str):
        return self.filter(FilterInvalidValues(list(names)))

    def normalize(self, t: Transform):
        return self.transform(t)

    def build(self) -> TransformProcess:
        tp = TransformProcess(self._schema, list(self._steps))
        tp.final_schema()  # validate the chain eagerly, as the reference does
        return tp
