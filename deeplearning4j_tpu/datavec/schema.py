"""Schema — typed column description of a record stream.

Reference: ``org.datavec.api.transform.schema.Schema`` + ``ColumnType``:
a TransformProcess starts from a schema and every transform step produces
a new schema, so column names/types are statically known after each step.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence

from deeplearning4j_tpu import serde


@serde.register_enum
class ColumnType(enum.Enum):
    Integer = "Integer"
    Long = "Long"
    Double = "Double"
    Float = "Float"
    Categorical = "Categorical"
    String = "String"
    Boolean = "Boolean"
    Time = "Time"
    NDArray = "NDArray"


@serde.register
@dataclasses.dataclass
class ColumnMetadata:
    name: str
    column_type: ColumnType
    state_names: Optional[List[str]] = None  # categorical values

    def is_numeric(self) -> bool:
        return self.column_type in (ColumnType.Integer, ColumnType.Long,
                                    ColumnType.Double, ColumnType.Float,
                                    ColumnType.Time, ColumnType.Boolean)


@serde.register
@dataclasses.dataclass
class Schema:
    """Ordered, named, typed columns (reference ``Schema`` + its Builder)."""

    columns: List[ColumnMetadata] = dataclasses.field(default_factory=list)

    # --- builder API (reference Schema.Builder#addColumn*) ------------------
    @staticmethod
    def builder() -> "SchemaBuilder":
        return SchemaBuilder()

    # --- queries ------------------------------------------------------------
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def num_columns(self) -> int:
        return len(self.columns)

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column {name!r}; have {self.names()}")

    def column(self, name: str) -> ColumnMetadata:
        return self.columns[self.index_of(name)]

    def has_column(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    # --- functional updates (each transform derives a new schema) -----------
    def with_columns(self, columns: Sequence[ColumnMetadata]) -> "Schema":
        return Schema(list(columns))

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "Schema":
        return serde.from_json(s)


class SchemaBuilder:
    def __init__(self):
        self._cols: List[ColumnMetadata] = []

    def add_column_integer(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.Integer))
        return self

    def add_column_long(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.Long))
        return self

    def add_column_double(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.Double))
        return self

    def add_column_float(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.Float))
        return self

    def add_column_string(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.String))
        return self

    def add_column_categorical(self, name: str, state_names: Sequence[str]):
        self._cols.append(ColumnMetadata(name, ColumnType.Categorical,
                                         list(state_names)))
        return self

    def add_column_boolean(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.Boolean))
        return self

    def add_column_time(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.Time))
        return self

    def add_column_ndarray(self, *names: str):
        for n in names:
            self._cols.append(ColumnMetadata(n, ColumnType.NDArray))
        return self

    def build(self) -> Schema:
        names = [c.name for c in self._cols]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")
        return Schema(list(self._cols))
