"""Audio records + features (reference ``datavec-data-audio``:
``WavFileRecordReader``, ``Wave``/spectrogram via the musicg lib, and the
MFCC pipeline the examples build on it).

TPU-native: decode with the stdlib ``wave`` module (zero-egress env, no
native codec), features are plain numpy — frames are produced host-side
exactly like the image pipeline, then batched into the jitted train step.
"""

from __future__ import annotations

import functools
import wave
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.split import InputSplit


def read_wav(path: str):
    """-> (samples float32 in [-1, 1] shaped [frames] (mono-mixed),
    sample_rate). Supports 8/16/32-bit PCM WAV."""
    with wave.open(path, "rb") as f:
        n = f.getnframes()
        raw = f.readframes(n)
        width = f.getsampwidth()
        channels = f.getnchannels()
        rate = f.getframerate()
    if width == 1:       # unsigned 8-bit
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if channels > 1:
        x = x.reshape(-1, channels).mean(axis=1)
    return x, rate


class WavFileRecordReader(RecordReader):
    """Reference class of the same name: record = [waveform ndarray,
    sample_rate] plus a trailing label index when
    ``label_from_parent_dir`` is set; one record per file."""

    def __init__(self, label_from_parent_dir: bool = False):
        self.label_from_parent_dir = label_from_parent_dir
        self._labels: Optional[List[str]] = None
        self._split: Optional[InputSplit] = None

    def initialize(self, split: InputSplit):
        from deeplearning4j_tpu.datavec.image import ParentPathLabelGenerator

        self._split = split
        if self.label_from_parent_dir:
            gen = self._label_gen = ParentPathLabelGenerator()
            self._labels = sorted({gen.label_for(p)
                                   for p in split.locations()})
        return self

    def labels(self):
        return self._labels

    def __iter__(self):
        for loc in self._split.locations():
            x, rate = read_wav(loc)
            rec = [x, rate]
            if self._labels is not None:
                rec.append(self._labels.index(self._label_gen.label_for(loc)))
            yield rec

    def reset(self):
        return None


def frame_signal(x: np.ndarray, frame_length: int, hop: int) -> np.ndarray:
    """[T] -> [n_frames, frame_length] with a trailing zero-padded frame."""
    if len(x) < frame_length:
        x = np.pad(x, (0, frame_length - len(x)))
    n = 1 + max(0, (len(x) - frame_length + hop - 1) // hop)
    total = (n - 1) * hop + frame_length
    x = np.pad(x, (0, max(0, total - len(x))))
    idx = np.arange(frame_length)[None, :] + hop * np.arange(n)[:, None]
    return x[idx]


def spectrogram(x: np.ndarray, frame_length: int = 256,
                hop: Optional[int] = None) -> np.ndarray:
    """Hann-windowed magnitude spectrogram [n_frames, frame_length//2+1]
    (reference ``Spectrogram`` from musicg)."""
    hop = hop or frame_length // 2
    frames = frame_signal(np.asarray(x, np.float32), frame_length, hop)
    window = np.hanning(frame_length).astype(np.float32)
    return np.abs(np.fft.rfft(frames * window, axis=-1)).astype(np.float32)


@functools.lru_cache(maxsize=16)  # identical per dataset: one file per call
def _mel_filterbank(n_mels: int, n_fft: int, rate: float) -> np.ndarray:
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    mels = np.linspace(0.0, hz_to_mel(rate / 2), n_mels + 2)
    freqs = mel_to_hz(mels)
    bins = np.floor((n_fft + 1) * freqs / rate).astype(int)
    n_bins = n_fft // 2 + 1
    fb = np.zeros((n_mels, n_bins), np.float32)
    for i in range(n_mels):
        lo, mid, hi = bins[i], bins[i + 1], bins[i + 2]
        if mid > lo:
            fb[i, lo:mid] = (np.arange(lo, mid) - lo) / (mid - lo)
        if hi > mid:
            fb[i, mid:hi] = (hi - np.arange(mid, hi)) / (hi - mid)
    return fb


def mfcc(x: np.ndarray, rate: float, n_mfcc: int = 13, n_mels: int = 26,
         frame_length: int = 256, hop: Optional[int] = None) -> np.ndarray:
    """[T] -> [n_frames, n_mfcc] mel-frequency cepstral coefficients
    (reference MFCC feature path; DCT-II, ortho-normalized)."""
    spec = spectrogram(x, frame_length, hop)           # [F, bins]
    power = spec ** 2
    fb = _mel_filterbank(n_mels, frame_length, float(rate))
    mel = np.log(power @ fb.T + 1e-10)                 # [F, n_mels]
    return (mel @ _dct_basis(n_mfcc, n_mels).T).astype(np.float32)


@functools.lru_cache(maxsize=16)
def _dct_basis(n_mfcc: int, n_mels: int) -> np.ndarray:
    """DCT-II (ortho) basis without scipy."""
    k = np.arange(n_mels)
    basis = np.cos(np.pi * np.outer(np.arange(n_mfcc), (2 * k + 1))
                   / (2.0 * n_mels))
    basis *= np.sqrt(2.0 / n_mels)
    basis[0] *= np.sqrt(0.5)
    return basis
