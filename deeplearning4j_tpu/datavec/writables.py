"""Writable value types.

Reference: ``org.datavec.api.writable.*`` — typed record cell values
(IntWritable, DoubleWritable, Text, NDArrayWritable, …) flowing between
record readers and transforms. Here they are thin wrappers over Python
scalars/ndarrays; readers may also emit raw Python values, and
``as_writable``/``value_of`` normalize at the boundaries.
"""

from __future__ import annotations

import numpy as np


class Writable:
    """Base record cell (reference ``org.datavec.api.writable.Writable``)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def to_double(self) -> float:
        return float(self.value)

    def to_int(self) -> int:
        return int(self.value)

    def to_string(self) -> str:
        return str(self.value)

    def __repr__(self):
        return f"{type(self).__name__}({self.value!r})"

    def __eq__(self, other):
        return type(self) is type(other) and _eq(self.value, other.value)

    def __hash__(self):
        return hash((type(self).__name__, _hashable(self.value)))


def _eq(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return np.array_equal(np.asarray(a), np.asarray(b))
    return a == b


def _hashable(v):
    if isinstance(v, np.ndarray):
        return v.tobytes()
    return v


class IntWritable(Writable):
    def __init__(self, value):
        super().__init__(int(value))


class LongWritable(Writable):
    def __init__(self, value):
        super().__init__(int(value))


class FloatWritable(Writable):
    def __init__(self, value):
        super().__init__(float(value))


class DoubleWritable(Writable):
    def __init__(self, value):
        super().__init__(float(value))


class BooleanWritable(Writable):
    def __init__(self, value):
        super().__init__(bool(value))

    def to_double(self):
        return 1.0 if self.value else 0.0


class Text(Writable):
    def __init__(self, value):
        super().__init__(str(value))

    def to_double(self):
        return float(self.value)


class NullWritable(Writable):
    def __init__(self):
        super().__init__(None)

    def to_double(self):
        raise ValueError("NullWritable has no numeric value")


class NDArrayWritable(Writable):
    """Whole-tensor cell (reference ``NDArrayWritable`` wrapping INDArray)."""

    def __init__(self, value):
        super().__init__(np.asarray(value))

    def to_double(self):
        if self.value.size != 1:
            raise ValueError("NDArrayWritable with size != 1 has no scalar value")
        return float(self.value.reshape(())[()])


def as_writable(v) -> Writable:
    """Wrap a raw Python/numpy value in the matching Writable."""
    if isinstance(v, Writable):
        return v
    if v is None:
        return NullWritable()
    if isinstance(v, bool):
        return BooleanWritable(v)
    if isinstance(v, (int, np.integer)):
        return IntWritable(v)
    if isinstance(v, (float, np.floating)):
        return DoubleWritable(v)
    if isinstance(v, str):
        return Text(v)
    if isinstance(v, np.ndarray):
        return NDArrayWritable(v)
    raise TypeError(f"no Writable for {type(v).__name__}")


def value_of(v):
    """Unwrap a Writable (or pass through a raw value)."""
    return v.value if isinstance(v, Writable) else v


def numeric_of(v) -> float:
    """Cell → float (used when assembling feature matrices)."""
    if isinstance(v, Writable):
        return v.to_double()
    if isinstance(v, str):
        return float(v)
    return float(v)
