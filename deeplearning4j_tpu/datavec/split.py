"""Input splits — where records come from.

Reference: ``org.datavec.api.split.*`` (FileSplit, CollectionInputSplit,
NumberedFileInputSplit, StringSplit): enumerate URIs/locations for record
readers, with optional extension filtering, recursion and shuffling.
"""

from __future__ import annotations

import random
import re
from pathlib import Path
from typing import List, Optional, Sequence


class InputSplit:
    """Enumerable source locations (reference ``InputSplit``)."""

    def locations(self) -> List[str]:
        raise NotImplementedError

    def length(self) -> int:
        return len(self.locations())


class FileSplit(InputSplit):
    """Files under a root dir (reference ``FileSplit``): recursive walk,
    optional allowed-extension filter, optional seeded shuffle."""

    def __init__(self, root, allowed_extensions: Optional[Sequence[str]] = None,
                 recursive: bool = True, seed: Optional[int] = None):
        self.root = Path(root)
        self.allowed = (None if allowed_extensions is None else
                        {e.lower().lstrip(".") for e in allowed_extensions})
        self.recursive = recursive
        self.seed = seed

    def locations(self) -> List[str]:
        if self.root.is_file():
            return [str(self.root)]
        pat = "**/*" if self.recursive else "*"
        files = [p for p in sorted(self.root.glob(pat)) if p.is_file()]
        if self.allowed is not None:
            files = [p for p in files
                     if p.suffix.lower().lstrip(".") in self.allowed]
        out = [str(p) for p in files]
        if self.seed is not None:
            random.Random(self.seed).shuffle(out)
        return out


class CollectionInputSplit(InputSplit):
    """A fixed list of locations (reference ``CollectionInputSplit``)."""

    def __init__(self, locations: Sequence[str]):
        self._locations = [str(u) for u in locations]

    def locations(self) -> List[str]:
        return list(self._locations)


class NumberedFileInputSplit(InputSplit):
    """Pattern like ``file_%d.csv`` over an index range (reference
    ``NumberedFileInputSplit``), used heavily for per-sequence CSV files."""

    def __init__(self, base_string: str, min_idx: int, max_idx: int):
        if not re.search(r"%(0\d+)?d", base_string):
            raise ValueError(f"pattern must contain %d: {base_string!r}")
        self.base_string = base_string
        self.min_idx = int(min_idx)
        self.max_idx = int(max_idx)

    def locations(self) -> List[str]:
        return [self.base_string % i
                for i in range(self.min_idx, self.max_idx + 1)]


class StringSplit(InputSplit):
    """A single in-memory string 'location' (reference ``StringSplit``)."""

    def __init__(self, data: str):
        self.data = data

    def locations(self) -> List[str]:
        return [self.data]
