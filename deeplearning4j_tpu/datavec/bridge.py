"""RecordReader ↔ DataSet bridge.

Reference: ``deeplearning4j-core/.../datasets/datavec/`` —
``RecordReaderDataSetIterator`` (records → feature matrix + one-hot labels)
and ``SequenceRecordReaderDataSetIterator`` (sequence records → [N,C,T]
tensors with per-timestep masks for variable-length sequences, ALIGN_END or
ALIGN_START padding alignment).
"""

from __future__ import annotations

import enum
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.datavec.records import RecordReader, SequenceRecordReader
from deeplearning4j_tpu.datavec.writables import numeric_of, value_of


def _one_hot(idx: int, n: int) -> np.ndarray:
    v = np.zeros(n, np.float32)
    v[int(idx)] = 1.0
    return v


class RecordReaderDataSetIterator(DataSetIterator):
    """Reference ``RecordReaderDataSetIterator``: batches records into
    (features, one-hot labels). ``label_index`` selects the label cell
    (or a [from,to] range for regression via ``regression=True``);
    NDArray-valued cells (image reader) are flattened into the feature
    tensor, preserving their shape when they are the only feature."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_possible_labels: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch = int(batch_size)
        self.label_index = label_index
        self.label_index_to = label_index_to
        self.num_labels = num_possible_labels
        self.regression = regression
        self._preprocessor = None

    def set_preprocessor(self, pre):
        """Reference ``#setPreProcessor`` (DataNormalization etc.)."""
        self._preprocessor = pre
        return self

    def batch_size(self):
        return self.batch

    def reset(self):
        self.reader.reset()

    def _split_record(self, rec: List):
        cells = list(rec)
        if self.label_index is None:
            return cells, None
        li = self.label_index
        lt = self.label_index_to if self.label_index_to is not None else li
        label_cells = cells[li:lt + 1]
        feat_cells = cells[:li] + cells[lt + 1:]
        return feat_cells, label_cells

    def _features_of(self, cells: List) -> np.ndarray:
        vals = [value_of(c) for c in cells]
        if len(vals) == 1 and isinstance(vals[0], np.ndarray):
            return vals[0].astype(np.float32)
        parts = []
        for v in vals:
            if isinstance(v, np.ndarray):
                parts.append(v.astype(np.float32).ravel())
            else:
                parts.append(np.asarray([numeric_of(v)], np.float32))
        return np.concatenate(parts)

    def _labels_of(self, cells: Optional[List]) -> Optional[np.ndarray]:
        if cells is None:
            return None
        if self.regression:
            return np.asarray([numeric_of(c) for c in cells], np.float32)
        if len(cells) != 1:
            raise ValueError("classification expects exactly one label cell")
        if self.num_labels is None:
            raise ValueError("num_possible_labels required for classification")
        return _one_hot(int(numeric_of(cells[0])), self.num_labels)

    def __iter__(self):
        feats, labs = [], []
        for rec in self.reader:
            f_cells, l_cells = self._split_record(rec)
            feats.append(self._features_of(f_cells))
            lab = self._labels_of(l_cells)
            if lab is not None:
                labs.append(lab)
            if len(feats) == self.batch:
                yield self._emit(feats, labs)
                feats, labs = [], []
        if feats:
            yield self._emit(feats, labs)

    def _emit(self, feats, labs):
        f = np.stack(feats)
        l = np.stack(labs) if labs else np.zeros((f.shape[0], 0), np.float32)
        ds = DataSet(f, l)
        if self._preprocessor is not None:
            self._preprocessor.transform(ds)
        return ds


@enum.unique
class AlignmentMode(enum.Enum):
    """Reference ``SequenceRecordReaderDataSetIterator.AlignmentMode``."""
    ALIGN_START = "ALIGN_START"
    ALIGN_END = "ALIGN_END"
    EQUAL_LENGTH = "EQUAL_LENGTH"


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Reference ``SequenceRecordReaderDataSetIterator`` (single-reader
    mode): each sequence supplies features and a label per timestep;
    variable lengths are padded to the batch max with 0s and a per-timestep
    mask, aligned start or end — the exact masking contract the RNN layers
    and losses consume (SURVEY.md §5.7)."""

    def __init__(self, reader: SequenceRecordReader, batch_size: int,
                 label_index: int, num_possible_labels: Optional[int] = None,
                 regression: bool = False,
                 alignment: AlignmentMode = AlignmentMode.ALIGN_START,
                 channels_first: bool = False):
        self.reader = reader
        self.batch = int(batch_size)
        self.label_index = label_index
        self.num_labels = num_possible_labels
        self.regression = regression
        self.alignment = alignment
        # False (default): framework-native [batch, time, features];
        # True: the reference's [batch, features, time] layout.
        self.channels_first = channels_first

    def batch_size(self):
        return self.batch

    def reset(self):
        self.reader.reset()

    def _seq_arrays(self, seq):
        """sequence → (features [T,F], labels [T,L])."""
        f_rows, l_rows = [], []
        for rec in seq:
            cells = list(rec)
            lab = cells.pop(self.label_index)
            f_rows.append([numeric_of(c) for c in cells])
            if self.regression:
                l_rows.append([numeric_of(lab)])
            else:
                l_rows.append(_one_hot(int(numeric_of(lab)), self.num_labels))
        return (np.asarray(f_rows, np.float32), np.asarray(l_rows, np.float32))

    def __iter__(self):
        bucket = []
        for seq in self.reader:
            bucket.append(self._seq_arrays(seq))
            if len(bucket) == self.batch:
                yield self._emit(bucket)
                bucket = []
        if bucket:
            yield self._emit(bucket)

    def _emit(self, bucket):
        max_t = max(f.shape[0] for f, _ in bucket)
        n = len(bucket)
        nf = bucket[0][0].shape[1]
        nl = bucket[0][1].shape[1]
        feats = np.zeros((n, max_t, nf), np.float32)
        labs = np.zeros((n, max_t, nl), np.float32)
        mask = np.zeros((n, max_t), np.float32)
        for i, (f, l) in enumerate(bucket):
            t = f.shape[0]
            if self.alignment is AlignmentMode.ALIGN_END:
                sl = slice(max_t - t, max_t)
            else:
                if self.alignment is AlignmentMode.EQUAL_LENGTH and t != max_t:
                    raise ValueError("EQUAL_LENGTH but sequence lengths differ")
                sl = slice(0, t)
            feats[i, sl, :] = f
            labs[i, sl, :] = l
            mask[i, sl] = 1.0
        if self.channels_first:
            feats = np.transpose(feats, (0, 2, 1))
            labs = np.transpose(labs, (0, 2, 1))
        return DataSet(feats, labs, features_mask=mask, labels_mask=mask)
