from deeplearning4j_tpu.eval.evaluation import (
    Evaluation,
    EvaluationBinary,
    RegressionEvaluation,
    ROC,
)
