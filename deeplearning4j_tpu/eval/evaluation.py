"""Evaluation metrics.

Reference: ``org.nd4j.evaluation.classification.Evaluation`` (accuracy /
precision / recall / F1 / confusion matrix / per-class stats),
``EvaluationBinary``, ``ROC`` (AUC/AUPRC), and
``org.nd4j.evaluation.regression.RegressionEvaluation`` (MSE/MAE/RMSE/R^2).

Accumulator objects: ``eval(labels, predictions, mask)`` may be called per
batch (device arrays come back to host once per batch — the confusion
accumulation itself is a tiny host-side op, matching the reference's design
where Evaluation runs on the JVM side).
"""

from __future__ import annotations

import numpy as np


class Evaluation:
    """Multi-class classification evaluation via confusion matrix."""

    def __init__(self, num_classes: int | None = None,
                 labels_names: list[str] | None = None):
        self.num_classes = num_classes
        self.labels_names = labels_names
        self.confusion: np.ndarray | None = None

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = np.zeros((self.num_classes, self.num_classes),
                                      np.int64)

    def eval(self, labels, predictions, mask=None):
        """labels/predictions: [batch, n_classes] probabilities/one-hot, or
        [batch, n_classes, ...time] — time dims flattened; int class vectors
        also accepted."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim >= 3:  # [batch, time, classes] -> [batch*time, classes]
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if labels.ndim == 2:
            true_idx = labels.argmax(-1)
        else:
            true_idx = labels.astype(np.int64)
        if predictions.ndim == 2:
            pred_idx = predictions.argmax(-1)
            n = predictions.shape[-1]
        else:
            pred_idx = predictions.astype(np.int64)
            n = int(max(true_idx.max(), pred_idx.max())) + 1
        self._ensure(n)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            true_idx, pred_idx = true_idx[m], pred_idx[m]
        np.add.at(self.confusion, (true_idx, pred_idx), 1)
        return self

    # --- aggregate metrics -------------------------------------------------
    def _counts(self):
        c = self.confusion
        tp = np.diag(c).astype(np.float64)
        fp = c.sum(0) - tp
        fn = c.sum(1) - tp
        return tp, fp, fn

    def accuracy(self) -> float:
        c = self.confusion
        total = c.sum()
        return float(np.diag(c).sum() / total) if total else 0.0

    def precision(self, cls: int | None = None) -> float:
        tp, fp, _ = self._counts()
        if cls is not None:
            d = tp[cls] + fp[cls]
            return float(tp[cls] / d) if d else 0.0
        # macro-average over classes that appear (reference default)
        d = tp + fp
        valid = (tp + self.confusion.sum(1)) > 0
        vals = np.where(d > 0, tp / np.maximum(d, 1), 0.0)
        return float(vals[valid].mean()) if valid.any() else 0.0

    def recall(self, cls: int | None = None) -> float:
        tp, _, fn = self._counts()
        if cls is not None:
            d = tp[cls] + fn[cls]
            return float(tp[cls] / d) if d else 0.0
        d = tp + fn
        valid = d > 0
        vals = np.where(valid, tp / np.maximum(d, 1), 0.0)
        return float(vals[valid].mean()) if valid.any() else 0.0

    def f1(self, cls: int | None = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, cls: int) -> float:
        tp, fp, fn = self._counts()
        tn = self.confusion.sum() - tp[cls] - fp[cls] - fn[cls]
        d = fp[cls] + tn
        return float(fp[cls] / d) if d else 0.0

    def stats(self) -> str:
        """Printable summary (reference: ``Evaluation#stats``)."""
        n = self.num_classes or 0
        names = self.labels_names or [str(i) for i in range(n)]
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {n}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
        ]
        header = "     " + " ".join(f"{nm:>5}" for nm in names)
        lines.append(header)
        for i in range(n):
            row = " ".join(f"{self.confusion[i, j]:>5}" for j in range(n))
            lines.append(f"{names[i]:>4} {row}")
        return "\n".join(lines)

    def merge(self, other: "Evaluation") -> "Evaluation":
        if other.confusion is not None:
            self._ensure(other.num_classes)
            self.confusion += other.confusion
        return self


class EvaluationBinary:
    """Per-output binary evaluation (reference ``EvaluationBinary``):
    each output column is an independent binary problem at threshold 0.5."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        preds = (np.asarray(predictions).reshape(labels.shape) >= self.threshold)
        labs = labels >= 0.5
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labs, preds = labs[m], preds[m]
        tp = (labs & preds).sum(0)
        fp = (~labs & preds).sum(0)
        fn = (labs & ~preds).sum(0)
        tn = (~labs & ~preds).sum(0)
        if self.tp is None:
            self.tp, self.fp, self.fn, self.tn = tp, fp, fn, tn
        else:
            self.tp += tp; self.fp += fp; self.fn += fn; self.tn += tn
        return self

    def accuracy(self, i: int) -> float:
        total = self.tp[i] + self.fp[i] + self.fn[i] + self.tn[i]
        return float((self.tp[i] + self.tn[i]) / total) if total else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0


class ROC:
    """Binary ROC/AUC with exact threshold sweep (reference ``ROC`` with
    thresholdSteps=0 = exact mode). Stores scores; AUC via rank statistic."""

    def __init__(self):
        self.scores: list[np.ndarray] = []
        self.labels: list[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1)
        preds = np.asarray(predictions).reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        self.labels.append(labels >= 0.5)
        self.scores.append(preds)
        return self

    def calculate_auc(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        pos, neg = int(y.sum()), int((~y).sum())
        if pos == 0 or neg == 0:
            return 0.0
        order = np.argsort(s, kind="mergesort")
        ranks = np.empty_like(order, dtype=np.float64)
        # average ranks for ties
        sorted_s = s[order]
        ranks[order] = np.arange(1, len(s) + 1)
        i = 0
        while i < len(s):
            j = i
            while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
                j += 1
            if j > i:
                ranks[order[i:j + 1]] = (i + j + 2) / 2.0
            i = j + 1
        return float((ranks[y].sum() - pos * (pos + 1) / 2.0) / (pos * neg))

    def calculate_auprc(self) -> float:
        y = np.concatenate(self.labels).astype(np.float64)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="mergesort")
        y = y[order]
        tp = np.cumsum(y)
        precision = tp / np.arange(1, len(y) + 1)
        total_pos = y.sum()
        if total_pos == 0:
            return 0.0
        return float(np.sum(precision * y) / total_pos)


class RegressionEvaluation:
    """Reference ``RegressionEvaluation``: per-column MSE/MAE/RMSE/R^2/
    correlation, accumulated over batches."""

    def __init__(self):
        self.n = 0
        self.sum_err2 = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64).reshape(labels.shape)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        if self.sum_err2 is None:
            cols = labels.shape[-1]
            self.sum_err2 = np.zeros(cols)
            self.sum_abs = np.zeros(cols)
            self.sum_label = np.zeros(cols)
            self.sum_label2 = np.zeros(cols)
            self.sum_pred = np.zeros(cols)
            self.sum_pred2 = np.zeros(cols)
            self.sum_lp = np.zeros(cols)
        err = preds - labels
        self.n += labels.shape[0]
        self.sum_err2 += (err ** 2).sum(0)
        self.sum_abs += np.abs(err).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label2 += (labels ** 2).sum(0)
        self.sum_pred += preds.sum(0)
        self.sum_pred2 += (preds ** 2).sum(0)
        self.sum_lp += (labels * preds).sum(0)
        return self

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self.sum_label2[col] - self.sum_label[col] ** 2 / self.n
        ss_res = self.sum_err2[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot else 0.0

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.n
        cov = self.sum_lp[col] - self.sum_label[col] * self.sum_pred[col] / n
        vl = self.sum_label2[col] - self.sum_label[col] ** 2 / n
        vp = self.sum_pred2[col] - self.sum_pred[col] ** 2 / n
        d = np.sqrt(vl * vp)
        return float(cov / d) if d else 0.0


class ROCMultiClass:
    """One-vs-all ROC per class (reference ``ROCMultiClass``): per-class
    AUC/AUPRC plus macro average."""

    def __init__(self, num_classes: int | None = None):
        self.num_classes = num_classes
        self._rocs: list[ROC] | None = None

    def _ensure(self, n: int):
        if self._rocs is None:
            self.num_classes = self.num_classes or n
            self._rocs = [ROC() for _ in range(self.num_classes)]

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        self._ensure(labels.shape[-1])
        for c in range(self.num_classes):
            self._rocs[c].eval(labels[:, c], preds[:, c], mask)
        return self

    def calculate_auc(self, class_idx: int) -> float:
        return self._rocs[class_idx].calculate_auc()

    def calculate_auprc(self, class_idx: int) -> float:
        return self._rocs[class_idx].calculate_auprc()

    def _defined(self):
        # a class with no positives or no negatives has undefined ROC;
        # skip it rather than dragging the macro average toward 0
        out = []
        for r in self._rocs:
            y = (np.concatenate(r.labels) if r.labels
                 else np.zeros(0, bool))
            if 0 < int(y.sum()) < y.size:
                out.append(r)
        return out

    def calculate_average_auc(self) -> float:
        rocs = self._defined()
        if not rocs:
            return 0.0
        return float(np.mean([r.calculate_auc() for r in rocs]))

    def calculate_average_auprc(self) -> float:
        rocs = self._defined()
        if not rocs:
            return 0.0
        return float(np.mean([r.calculate_auprc() for r in rocs]))


class EvaluationCalibration:
    """Reliability/calibration accumulator (reference
    ``EvaluationCalibration``): confidence-binned counts and accuracies
    (reliability diagram data), residual histogram, and expected
    calibration error."""

    def __init__(self, reliability_bins: int = 10,
                 histogram_bins: int = 50):
        self.bins = int(reliability_bins)
        self.hist_bins = int(histogram_bins)
        self.bin_counts = np.zeros(self.bins, np.int64)
        self.bin_correct = np.zeros(self.bins, np.int64)
        self.bin_conf_sum = np.zeros(self.bins, np.float64)
        self.residual_hist = np.zeros(self.hist_bins, np.int64)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        conf = preds.max(-1)
        correct = preds.argmax(-1) == labels.argmax(-1)
        idx = np.clip((conf * self.bins).astype(int), 0, self.bins - 1)
        np.add.at(self.bin_counts, idx, 1)
        np.add.at(self.bin_correct, idx, correct.astype(np.int64))
        np.add.at(self.bin_conf_sum, idx, conf)
        # residual = |label - prob| over all entries (reference residual plot)
        resid = np.abs(labels - preds).reshape(-1)
        h = np.clip((resid * self.hist_bins).astype(int), 0,
                    self.hist_bins - 1)
        np.add.at(self.residual_hist, h, 1)
        return self

    def reliability_accuracy(self) -> np.ndarray:
        """Per-bin observed accuracy (nan for empty bins)."""
        with np.errstate(invalid="ignore"):
            return self.bin_correct / np.where(self.bin_counts, self.bin_counts,
                                               np.nan)

    def reliability_confidence(self) -> np.ndarray:
        with np.errstate(invalid="ignore"):
            return self.bin_conf_sum / np.where(self.bin_counts,
                                                self.bin_counts, np.nan)

    def expected_calibration_error(self) -> float:
        total = self.bin_counts.sum()
        if total == 0:
            return 0.0
        acc = np.nan_to_num(self.reliability_accuracy())
        conf = np.nan_to_num(self.reliability_confidence())
        return float(np.sum(self.bin_counts * np.abs(acc - conf)) / total)


class ROCBinary(ROCMultiClass):
    """Per-output-column binary ROC (reference ``ROCBinary`` for multi-label
    sigmoid outputs) — same accumulation as ROCMultiClass, labels are
    independent {0,1} columns rather than one-hot rows."""
