"""Model zoo — ComputationGraph models.

Reference: ``org.deeplearning4j.zoo.model.{VGG16,VGG19,ResNet50,SqueezeNet,
Darknet19,UNet}`` — each ``init()`` builds a ComputationGraphConfiguration;
topologies follow the reference's graph builders (conv/bn orderings, residual
wiring via ``ElementWiseVertex(Add)``, fire-module concat via
``MergeVertex``). Layouts are NHWC (TPU-native) instead of the reference's
NCHW; shapes/channel counts match.
"""

from __future__ import annotations

from typing import Tuple

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.graph import (
    ComputationGraphConfiguration,
    ElementWiseOp,
    ElementWiseVertex,
    LayerVertex,
    MergeVertex,
)
from deeplearning4j_tpu.conf.layers import (ActivationLayer, DenseLayer,
    LossLayer, OutputLayer)
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    CnnLossLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    PoolingType,
    SubsamplingLayer,
    Upsampling2D,
)
from deeplearning4j_tpu.conf.losses import LossBinaryXENT, LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam, IUpdater, Nesterovs
from deeplearning4j_tpu.zoo.models import ZooModel


def _conv(n_out, k, s=(1, 1), act=Activation.RELU, mode=ConvolutionMode.SAME,
          bias=True):
    return ConvolutionLayer(n_out=n_out, kernel_size=k, stride=s,
                            activation=act, convolution_mode=mode,
                            has_bias=bias)


def _maxpool(k=(2, 2), s=(2, 2), mode=ConvolutionMode.TRUNCATE):
    return SubsamplingLayer(pooling_type=PoolingType.MAX, kernel_size=k,
                            stride=s, convolution_mode=mode)


class GraphZooModel(ZooModel):
    def init(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return ComputationGraph(self.conf()).init()


class VGG16(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.VGG16``: 13 conv3x3 SAME +
    5 maxpools + FC 4096/4096/classes."""

    BLOCKS: Tuple[Tuple[int, int], ...] = (
        (64, 2), (128, 2), (256, 3), (512, 3), (512, 3))

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Nesterovs(learning_rate=0.01, momentum=0.9)

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        prev = "input"
        for bi, (ch, reps) in enumerate(self.BLOCKS):
            for ri in range(reps):
                name = f"conv{bi + 1}_{ri + 1}"
                g.add_layer(name, _conv(ch, (3, 3)), prev)
                prev = name
            g.add_layer(f"pool{bi + 1}", _maxpool(), prev)
            prev = f"pool{bi + 1}"
        g.add_layer("fc1", DenseLayer(n_out=4096, activation=Activation.RELU),
                    prev)
        g.add_layer("fc2", DenseLayer(n_out=4096, activation=Activation.RELU),
                    "fc1")
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "fc2")
        g.set_outputs("output")
        return g.build()


class VGG19(VGG16):
    """Reference ``VGG19``: VGG16 with 4-deep conv blocks 3..5."""

    BLOCKS = ((64, 2), (128, 2), (256, 4), (512, 4), (512, 4))


class ResNet50(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.ResNet50``: conv7x7/2 + BN +
    maxpool3x3/2, 4 stages of bottleneck blocks [3,4,6,3] with channel
    triples (64,64,256)x, residual add via ``ElementWiseVertex(Add)``,
    global avg pool + softmax."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    stem_space_to_depth: bool = False
    """EXACT rewrite of the 7x7/s2 stem conv as space-to-depth(2) +
    zero-pad(1,2) + 4x4/s1 conv (the MLPerf TPU ResNet trick):
    out[i,j] = sum_{di,dj<7} x[2i+di-2, 2j+dj-2]*W regroups over 2x2
    input blocks into a stride-1 conv whose input has 4x the channels —
    3 -> 12 fills the 128-wide MXU 4x better, which matters most in the
    stem's dW backward (measured ~30 ms of the 113 ms batch-256 fwd+bwd,
    bench_resnet_profile.py). Same function class, weights map 1:1
    (tests pin the equivalence); default off keeps the reference's exact
    topology. Set via attribute after construction."""

    @staticmethod
    def stem_weights_to_s2d(w7):
        """Exact weight remap for ``stem_space_to_depth``: the reference
        stem's [7, 7, 3, C] kernel -> the rewrite's [4, 4, 12, C] kernel
        (w'[m, n, (a*2+b)*3 + ch] = w[2m+a, 2n+b, ch]; taps with
        2m+a >= 7 are zero). Transfer-learning/pretrained weights load
        through this."""
        import numpy as _np

        k7 = _np.asarray(w7)
        cin = k7.shape[2]
        out = _np.zeros((4, 4, 4 * cin, k7.shape[-1]), k7.dtype)
        for m in range(4):
            for a in range(2):
                if 2 * m + a >= 7:
                    continue
                for n in range(4):
                    for b in range(2):
                        if 2 * n + b >= 7:
                            continue
                        f = (a * 2 + b) * cin
                        out[m, n, f:f + cin] = k7[2 * m + a, 2 * n + b]
        return out

    fused_conv_bn: bool = False
    """Route every 1x1-conv + BN pair through ``FusedConvBN1x1`` (same
    math, BN statistics fused into the conv's output pass by a Pallas
    kernel — see ``ops/conv_fused.py``). ResNet-50 has 36 such pairs
    (bottleneck a/c convs + projections); the unfused schedule re-reads
    each conv output once for the statistics. Weights map 1:1 from the
    unfused graph via :meth:`fused_param_remap`; parity pinned by
    ``tests/test_zoo.py``. Default off keeps the reference's exact
    layer-pair topology. Set via attribute after construction."""

    def _conv_bn(self, g, name, n_out, k, s, inp, act=True,
                 mode=ConvolutionMode.SAME):
        if self.fused_conv_bn and tuple(k) == (1, 1):
            from deeplearning4j_tpu.conf.layers_cnn import FusedConvBN1x1

            g.add_layer(f"{name}_cb", FusedConvBN1x1(
                n_out=n_out, stride=s,
                activation=Activation.RELU if act else Activation.IDENTITY),
                inp)
            return f"{name}_cb"
        g.add_layer(f"{name}_conv",
                    _conv(n_out, k, s, Activation.IDENTITY, mode,
                          bias=False), inp)
        g.add_layer(f"{name}_bn", BatchNormalization(
            activation=Activation.RELU if act else Activation.IDENTITY),
            f"{name}_conv")
        return f"{name}_bn"

    @staticmethod
    def fused_param_remap(params, state):
        """Map an unfused ResNet-50's params/state onto the
        ``fused_conv_bn=True`` graph: every ``{n}_conv`` (W) + ``{n}_bn``
        (gamma/beta, running mean/var) pair collapses into ``{n}_cb``
        holding all five; non-1x1 layers pass through unchanged.
        Transfer-learning/pretrained weights load through this."""
        new_p, new_s = {}, {}
        for k, v in params.items():
            if k.endswith("_conv") and v.get("W") is not None \
                    and v["W"].shape[:2] == (1, 1) \
                    and f"{k[:-5]}_bn" in params and "b" not in v:
                n = k[:-5]
                new_p[f"{n}_cb"] = {"W": v["W"],
                                    "gamma": params[f"{n}_bn"]["gamma"],
                                    "beta": params[f"{n}_bn"]["beta"]}
                new_s[f"{n}_cb"] = dict(state.get(f"{n}_bn", {}))
            elif k.endswith("_bn") and f"{k[:-3]}_conv" in params \
                    and params[f"{k[:-3]}_conv"].get("W") is not None \
                    and params[f"{k[:-3]}_conv"]["W"].shape[:2] == (1, 1) \
                    and "b" not in params[f"{k[:-3]}_conv"]:
                continue  # folded into the _cb entry above
            else:
                new_p[k] = v
                if k in state:
                    new_s[k] = state[k]
        for k, v in state.items():
            if k not in new_s and k in new_p:
                new_s[k] = v
        return new_p, new_s

    def _bottleneck(self, g, name, inp, filters, stride, project):
        f1, f2, f3 = filters
        x = self._conv_bn(g, f"{name}_a", f1, (1, 1), stride, inp)
        x = self._conv_bn(g, f"{name}_b", f2, (3, 3), (1, 1), x)
        x = self._conv_bn(g, f"{name}_c", f3, (1, 1), (1, 1), x, act=False)
        if project:
            sc = self._conv_bn(g, f"{name}_sc", f3, (1, 1), stride, inp,
                               act=False)
        else:
            sc = inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op=ElementWiseOp.ADD),
                     x, sc)
        g.add_layer(f"{name}_relu", ActivationLayer(activation=Activation.RELU),
                    f"{name}_add")
        return f"{name}_relu"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        if self.stem_space_to_depth:
            from deeplearning4j_tpu.conf.layers_cnn import (
                SpaceToDepthLayer,
                ZeroPaddingLayer,
            )

            g.add_vertex("stem_s2d", LayerVertex(
                layer=SpaceToDepthLayer(block_size=2)), "input")
            g.add_vertex("stem_pad", LayerVertex(
                layer=ZeroPaddingLayer(padding=(1, 2, 1, 2))), "stem_s2d")
            x = self._conv_bn(g, "stem", 64, (4, 4), (1, 1), "stem_pad",
                              mode=ConvolutionMode.TRUNCATE)
        else:
            x = self._conv_bn(g, "stem", 64, (7, 7), (2, 2), "input")
        g.add_layer("stem_pool", _maxpool((3, 3), (2, 2),
                                          ConvolutionMode.SAME), x)
        x = "stem_pool"
        stages = ((64, 64, 256, 3), (128, 128, 512, 4),
                  (256, 256, 1024, 6), (512, 512, 2048, 3))
        for si, (f1, f2, f3, reps) in enumerate(stages):
            for ri in range(reps):
                stride = (1, 1) if (si == 0 or ri > 0) else (2, 2)
                x = self._bottleneck(g, f"res{si + 2}{chr(97 + ri)}", x,
                                     (f1, f2, f3), stride, project=(ri == 0))
        g.add_layer("avgpool",
                    GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "avgpool")
        g.set_outputs("output")
        return g.build()


class SqueezeNet(GraphZooModel):
    """Reference ``SqueezeNet`` (v1.1): conv3x3/2 + fire modules with
    squeeze(1x1) -> expand(1x1 || 3x3) -> MergeVertex concat, conv1x1 head +
    global avg pool."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    def _fire(self, g, name, inp, squeeze, expand):
        g.add_layer(f"{name}_sq", _conv(squeeze, (1, 1)), inp)
        g.add_layer(f"{name}_e1", _conv(expand, (1, 1)), f"{name}_sq")
        g.add_layer(f"{name}_e3", _conv(expand, (3, 3)), f"{name}_sq")
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_cat"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("conv1", _conv(64, (3, 3), (2, 2)), "input")
        g.add_layer("pool1", _maxpool((3, 3), (2, 2)), "conv1")
        x = self._fire(g, "fire2", "pool1", 16, 64)
        x = self._fire(g, "fire3", x, 16, 64)
        g.add_layer("pool3", _maxpool((3, 3), (2, 2)), x)
        x = self._fire(g, "fire4", "pool3", 32, 128)
        x = self._fire(g, "fire5", x, 32, 128)
        g.add_layer("pool5", _maxpool((3, 3), (2, 2)), x)
        x = self._fire(g, "fire6", "pool5", 48, 192)
        x = self._fire(g, "fire7", x, 48, 192)
        x = self._fire(g, "fire8", x, 64, 256)
        x = self._fire(g, "fire9", x, 64, 256)
        g.add_layer("conv10", _conv(self.num_classes, (1, 1)), x)
        g.add_layer("avgpool",
                    GlobalPoolingLayer(pooling_type=PoolingType.AVG), "conv10")
        # avgpool already yields num_classes features: a parameter-free
        # LossLayer head, matching the reference topology (no extra dense)
        g.add_layer("output", LossLayer(
            activation=Activation.SOFTMAX, loss_fn=LossMCXENT()), "avgpool")
        g.set_outputs("output")
        return g.build()


class Darknet19(GraphZooModel):
    """Reference ``Darknet19`` (YOLO9000 backbone): 19 convs (3x3/1x1
    alternation) + BN + LeakyReLU, 5 maxpools, conv1x1 head + global
    avg pool."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    def _conv_bn_leaky(self, g, i, n_out, k, inp):
        name = f"conv{i}"
        g.add_layer(name, _conv(n_out, k, (1, 1), Activation.IDENTITY,
                                bias=False), inp)
        g.add_layer(f"{name}_bn",
                    BatchNormalization(activation=Activation.LEAKYRELU), name)
        return f"{name}_bn"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        spec = [(32, 3), "M", (64, 3), "M", (128, 3), (64, 1), (128, 3), "M",
                (256, 3), (128, 1), (256, 3), "M",
                (512, 3), (256, 1), (512, 3), (256, 1), (512, 3), "M",
                (1024, 3), (512, 1), (1024, 3), (512, 1), (1024, 3)]
        x, ci, pi = "input", 0, 0
        for s in spec:
            if s == "M":
                pi += 1
                g.add_layer(f"pool{pi}", _maxpool(), x)
                x = f"pool{pi}"
            else:
                ci += 1
                n_out, k = s
                x = self._conv_bn_leaky(g, ci, n_out, (k, k), x)
        g.add_layer("head", _conv(self.num_classes, (1, 1),
                                  act=Activation.IDENTITY), x)
        g.add_layer("avgpool",
                    GlobalPoolingLayer(pooling_type=PoolingType.AVG), "head")
        # avgpool already yields num_classes features: a parameter-free
        # LossLayer head, matching the reference topology (no extra dense)
        g.add_layer("output", LossLayer(
            activation=Activation.SOFTMAX, loss_fn=LossMCXENT()), "avgpool")
        g.set_outputs("output")
        return g.build()


class UNet(GraphZooModel):
    """Reference ``UNet``: 4-down/4-up encoder-decoder, skip connections via
    ``MergeVertex``, nearest-neighbour ``Upsampling2D`` + conv on the way up,
    sigmoid ``CnnLossLayer`` head (binary segmentation)."""

    def __init__(self, height: int = 128, width: int = 128, channels: int = 1,
                 base: int = 64, seed: int = 123,
                 updater: IUpdater | None = None):
        self.height, self.width, self.channels = height, width, channels
        self.base = base
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-4)

    def _double_conv(self, g, name, n_out, inp):
        g.add_layer(f"{name}_1", _conv(n_out, (3, 3)), inp)
        g.add_layer(f"{name}_2", _conv(n_out, (3, 3)), f"{name}_1")
        return f"{name}_2"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        b = self.base
        skips = []
        x = "input"
        for i, ch in enumerate([b, b * 2, b * 4, b * 8]):
            x = self._double_conv(g, f"down{i + 1}", ch, x)
            skips.append(x)
            g.add_layer(f"dpool{i + 1}", _maxpool(), x)
            x = f"dpool{i + 1}"
        x = self._double_conv(g, "bottom", b * 16, x)
        for i, ch in enumerate([b * 8, b * 4, b * 2, b]):
            g.add_layer(f"up{i + 1}_us", Upsampling2D(size=(2, 2)), x)
            g.add_layer(f"up{i + 1}_conv", _conv(ch, (2, 2)), f"up{i + 1}_us")
            g.add_vertex(f"up{i + 1}_cat", MergeVertex(),
                         skips[3 - i], f"up{i + 1}_conv")
            x = self._double_conv(g, f"up{i + 1}", ch, f"up{i + 1}_cat")
        g.add_layer("head", _conv(1, (1, 1), act=Activation.IDENTITY), x)
        g.add_layer("output", CnnLossLayer(activation=Activation.SIGMOID,
                                           loss_fn=LossBinaryXENT()), "head")
        g.set_outputs("output")
        return g.build()


class Xception(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.Xception``: entry flow
    (conv32/2, conv64, separable-conv residual blocks 128/256/728), middle
    flow (8 x three separable-conv-728 residual blocks), exit flow
    (728->1024 residual, sepconv 1536, 2048, global average pool)."""

    def __init__(self, num_classes: int = 1000, height: int = 299,
                 width: int = 299, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None,
                 middle_flow_repeats: int = 8):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Nesterovs(learning_rate=1e-2, momentum=0.9)
        self.middle_flow_repeats = middle_flow_repeats

    def conf(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.conf.layers_cnn import SeparableConvolution2D

        def sep(n):
            return SeparableConvolution2D(
                n_out=n, kernel_size=(3, 3), stride=(1, 1),
                activation=Activation.IDENTITY,
                convolution_mode=ConvolutionMode.SAME)

        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("c1", _conv(32, (3, 3), (2, 2),
                                mode=ConvolutionMode.TRUNCATE,
                                act=Activation.IDENTITY), "input")
        g.add_layer("c1bn", BatchNormalization(activation=Activation.RELU),
                    "c1")
        g.add_layer("c2", _conv(64, (3, 3), act=Activation.IDENTITY), "c1bn")
        g.add_layer("c2bn", BatchNormalization(activation=Activation.RELU),
                    "c2")
        prev = "c2bn"
        # entry-flow residual blocks
        for i, ch in enumerate((128, 256, 728)):
            rname = f"e{i}_res"
            g.add_layer(rname, _conv(ch, (1, 1), (2, 2),
                                     act=Activation.IDENTITY,
                                     mode=ConvolutionMode.SAME), prev)
            g.add_layer(f"e{i}_s1", sep(ch), prev)
            g.add_layer(f"e{i}_b1",
                        BatchNormalization(activation=Activation.RELU),
                        f"e{i}_s1")
            g.add_layer(f"e{i}_s2", sep(ch), f"e{i}_b1")
            g.add_layer(f"e{i}_b2", BatchNormalization(), f"e{i}_s2")
            g.add_layer(f"e{i}_pool", _maxpool((3, 3), (2, 2),
                                               ConvolutionMode.SAME),
                        f"e{i}_b2")
            g.add_vertex(f"e{i}_add",
                         ElementWiseVertex(op=ElementWiseOp.ADD),
                         f"e{i}_pool", rname)
            prev = f"e{i}_add"
        # middle flow
        for r in range(self.middle_flow_repeats):
            inp = prev
            last = inp
            for j in range(3):
                g.add_layer(f"m{r}_a{j}",
                            ActivationLayer(activation=Activation.RELU),
                            last)
                g.add_layer(f"m{r}_s{j}", sep(728), f"m{r}_a{j}")
                g.add_layer(f"m{r}_b{j}", BatchNormalization(),
                            f"m{r}_s{j}")
                last = f"m{r}_b{j}"
            g.add_vertex(f"m{r}_add",
                         ElementWiseVertex(op=ElementWiseOp.ADD),
                         last, inp)
            prev = f"m{r}_add"
        # exit flow
        g.add_layer("x_res", _conv(1024, (1, 1), (2, 2),
                                   act=Activation.IDENTITY,
                                   mode=ConvolutionMode.SAME), prev)
        g.add_layer("x_s1", sep(728), prev)
        g.add_layer("x_b1", BatchNormalization(activation=Activation.RELU),
                    "x_s1")
        g.add_layer("x_s2", sep(1024), "x_b1")
        g.add_layer("x_b2", BatchNormalization(), "x_s2")
        g.add_layer("x_pool", _maxpool((3, 3), (2, 2), ConvolutionMode.SAME),
                    "x_b2")
        g.add_vertex("x_add", ElementWiseVertex(op=ElementWiseOp.ADD),
                     "x_pool", "x_res")
        g.add_layer("x_s3", sep(1536), "x_add")
        g.add_layer("x_b3", BatchNormalization(activation=Activation.RELU),
                    "x_s3")
        g.add_layer("x_s4", sep(2048), "x_b3")
        g.add_layer("x_b4", BatchNormalization(activation=Activation.RELU),
                    "x_s4")
        g.add_layer("gap", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                    "x_b4")
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "gap")
        g.set_outputs("output")
        return g.build()


class InceptionResNetV1(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.InceptionResNetV1`` (the
    FaceNet variant): stem, 5 x Inception-ResNet-A (scale 0.17), reduction-A,
    10 x Inception-ResNet-B (scale 0.10), reduction-B, 5 x Inception-ResNet-C
    (scale 0.20), average pool, embedding + softmax head. Residual scaling
    uses ``ScaleVertex`` + ``ElementWiseVertex(Add)`` as in the reference."""

    def __init__(self, num_classes: int = 1001, height: int = 160,
                 width: int = 160, channels: int = 3,
                 embedding_size: int = 128, seed: int = 123,
                 updater: IUpdater | None = None,
                 blocks_a: int = 5, blocks_b: int = 10, blocks_c: int = 5):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.embedding_size = embedding_size
        self.seed = seed
        self.updater = updater or Adam(learning_rate=0.1)
        self.blocks_a, self.blocks_b, self.blocks_c = blocks_a, blocks_b, blocks_c

    def conf(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.conf.graph import ScaleVertex

        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def cbr(name, n, k, s, inp, mode=ConvolutionMode.SAME):
            g.add_layer(name, _conv(n, k, s, act=Activation.IDENTITY,
                                    mode=mode), inp)
            g.add_layer(name + "_bn",
                        BatchNormalization(activation=Activation.RELU), name)
            return name + "_bn"

        # stem
        p = cbr("s1", 32, (3, 3), (2, 2), "input",
                ConvolutionMode.TRUNCATE)
        p = cbr("s2", 32, (3, 3), (1, 1), p)
        p = cbr("s3", 64, (3, 3), (1, 1), p)
        g.add_layer("s4", _maxpool((3, 3), (2, 2)), p)
        p = cbr("s5", 80, (1, 1), (1, 1), "s4")
        p = cbr("s6", 192, (3, 3), (1, 1), p)
        p = cbr("s7", 256, (3, 3), (2, 2), p, ConvolutionMode.SAME)

        def block_a(i, inp):
            b1 = cbr(f"a{i}_b1", 32, (1, 1), (1, 1), inp)
            b2 = cbr(f"a{i}_b2b", 32, (3, 3), (1, 1),
                     cbr(f"a{i}_b2a", 32, (1, 1), (1, 1), inp))
            b3 = cbr(f"a{i}_b3c", 32, (3, 3), (1, 1),
                     cbr(f"a{i}_b3b", 32, (3, 3), (1, 1),
                         cbr(f"a{i}_b3a", 32, (1, 1), (1, 1), inp)))
            g.add_vertex(f"a{i}_cat", MergeVertex(), b1, b2, b3)
            g.add_layer(f"a{i}_up", _conv(256, (1, 1),
                                          act=Activation.IDENTITY),
                        f"a{i}_cat")
            g.add_vertex(f"a{i}_scale", ScaleVertex(scale_factor=0.17),
                         f"a{i}_up")
            g.add_vertex(f"a{i}_add",
                         ElementWiseVertex(op=ElementWiseOp.ADD),
                         inp, f"a{i}_scale")
            g.add_layer(f"a{i}_relu",
                        ActivationLayer(activation=Activation.RELU),
                        f"a{i}_add")
            return f"a{i}_relu"

        for i in range(self.blocks_a):
            p = block_a(i, p)

        # reduction-A -> 896 channels
        g.add_layer("ra_pool", _maxpool((3, 3), (2, 2),
                                        ConvolutionMode.SAME), p)
        ra1 = cbr("ra_c1", 384, (3, 3), (2, 2), p, ConvolutionMode.SAME)
        ra2 = cbr("ra_c2c", 256, (3, 3), (2, 2),
                  cbr("ra_c2b", 192, (3, 3), (1, 1),
                      cbr("ra_c2a", 192, (1, 1), (1, 1), p)),
                  ConvolutionMode.SAME)
        g.add_vertex("ra_cat", MergeVertex(), "ra_pool", ra1, ra2)
        p = "ra_cat"  # 256+384+256 = 896

        def block_b(i, inp):
            b1 = cbr(f"b{i}_b1", 128, (1, 1), (1, 1), inp)
            b2 = cbr(f"b{i}_b2c", 128, (7, 1), (1, 1),
                     cbr(f"b{i}_b2b", 128, (1, 7), (1, 1),
                         cbr(f"b{i}_b2a", 128, (1, 1), (1, 1), inp)))
            g.add_vertex(f"b{i}_cat", MergeVertex(), b1, b2)
            g.add_layer(f"b{i}_up", _conv(896, (1, 1),
                                          act=Activation.IDENTITY),
                        f"b{i}_cat")
            g.add_vertex(f"b{i}_scale", ScaleVertex(scale_factor=0.10),
                         f"b{i}_up")
            g.add_vertex(f"b{i}_add",
                         ElementWiseVertex(op=ElementWiseOp.ADD),
                         inp, f"b{i}_scale")
            g.add_layer(f"b{i}_relu",
                        ActivationLayer(activation=Activation.RELU),
                        f"b{i}_add")
            return f"b{i}_relu"

        for i in range(self.blocks_b):
            p = block_b(i, p)

        # reduction-B -> 1792 channels
        g.add_layer("rb_pool", _maxpool((3, 3), (2, 2),
                                        ConvolutionMode.SAME), p)
        rb1 = cbr("rb_c1b", 384, (3, 3), (2, 2),
                  cbr("rb_c1a", 256, (1, 1), (1, 1), p),
                  ConvolutionMode.SAME)
        rb2 = cbr("rb_c2b", 256, (3, 3), (2, 2),
                  cbr("rb_c2a", 256, (1, 1), (1, 1), p),
                  ConvolutionMode.SAME)
        rb3 = cbr("rb_c3c", 256, (3, 3), (2, 2),
                  cbr("rb_c3b", 256, (3, 3), (1, 1),
                      cbr("rb_c3a", 256, (1, 1), (1, 1), p)),
                  ConvolutionMode.SAME)
        g.add_vertex("rb_cat", MergeVertex(), "rb_pool", rb1, rb2, rb3)
        p = "rb_cat"  # 896+384+256+256 = 1792

        def block_c(i, inp):
            b1 = cbr(f"c{i}_b1", 192, (1, 1), (1, 1), inp)
            b2 = cbr(f"c{i}_b2c", 192, (3, 1), (1, 1),
                     cbr(f"c{i}_b2b", 192, (1, 3), (1, 1),
                         cbr(f"c{i}_b2a", 192, (1, 1), (1, 1), inp)))
            g.add_vertex(f"c{i}_cat", MergeVertex(), b1, b2)
            g.add_layer(f"c{i}_up", _conv(1792, (1, 1),
                                          act=Activation.IDENTITY),
                        f"c{i}_cat")
            g.add_vertex(f"c{i}_scale", ScaleVertex(scale_factor=0.20),
                         f"c{i}_up")
            g.add_vertex(f"c{i}_add",
                         ElementWiseVertex(op=ElementWiseOp.ADD),
                         inp, f"c{i}_scale")
            g.add_layer(f"c{i}_relu",
                        ActivationLayer(activation=Activation.RELU),
                        f"c{i}_add")
            return f"c{i}_relu"

        for i in range(self.blocks_c):
            p = block_c(i, p)

        g.add_layer("gap", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                    p)
        g.add_layer("embedding", DenseLayer(
            n_out=self.embedding_size, activation=Activation.IDENTITY), "gap")
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "embedding")
        g.set_outputs("output")
        return g.build()


class TinyYOLO(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.TinyYOLO``: Darknet-tiny
    backbone (conv3x3 16..1024 with leaky-relu BN and maxpools) + 1x1
    detection conv + ``Yolo2OutputLayer``; input 416x416 -> 13x13 grid,
    5 anchor priors."""

    PRIORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11),
              (16.62, 10.52))

    def __init__(self, num_classes: int = 20, height: int = 416,
                 width: int = 416, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None,
                 boxes: Tuple[Tuple[float, float], ...] | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)
        self.boxes = boxes or self.PRIORS

    def conf(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.conf.layers_objdetect import Yolo2OutputLayer

        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def cbl(name, n, inp):  # conv + BN + leaky relu
            g.add_layer(name, _conv(n, (3, 3), act=Activation.IDENTITY,
                                    bias=False), inp)
            g.add_layer(name + "_bn", BatchNormalization(
                activation=Activation.LEAKYRELU), name)
            return name + "_bn"

        p = cbl("c1", 16, "input")
        for i, n in enumerate((32, 64, 128, 256, 512)):
            g.add_layer(f"p{i + 1}", _maxpool((2, 2), (2, 2)), p)
            p = cbl(f"c{i + 2}", n, f"p{i + 1}")
        # final pool is stride-1 SAME in tiny-yolo (keeps 13x13)
        g.add_layer("p6", _maxpool((2, 2), (1, 1), ConvolutionMode.SAME), p)
        p = cbl("c7", 1024, "p6")
        p = cbl("c8", 1024, p)
        nb = len(self.boxes)
        g.add_layer("detect", _conv(nb * (5 + self.num_classes), (1, 1),
                                    act=Activation.IDENTITY), p)
        g.add_layer("yolo", Yolo2OutputLayer(boxes=tuple(self.boxes)),
                    "detect")
        g.set_outputs("yolo")
        return g.build()


class YOLO2(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.YOLO2``: Darknet-19 backbone
    with the passthrough route — the 26x26x512 feature map goes through a
    1x1x64 conv and ``SpaceToDepth(2)`` then concats with the 13x13x1024
    head before the detection conv (reference wiring via the same
    vertices)."""

    PRIORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
              (7.88282, 3.52778), (9.77052, 9.16828))

    def __init__(self, num_classes: int = 80, height: int = 416,
                 width: int = 416, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None,
                 boxes: Tuple[Tuple[float, float], ...] | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)
        self.boxes = boxes or self.PRIORS

    def conf(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.conf.layers_cnn import SpaceToDepthLayer
        from deeplearning4j_tpu.conf.layers_objdetect import Yolo2OutputLayer

        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def cbl(name, n, k, inp):
            g.add_layer(name, _conv(n, k, act=Activation.IDENTITY,
                                    bias=False), inp)
            g.add_layer(name + "_bn", BatchNormalization(
                activation=Activation.LEAKYRELU), name)
            return name + "_bn"

        # darknet-19 trunk
        p = cbl("c1", 32, (3, 3), "input")
        g.add_layer("p1", _maxpool((2, 2), (2, 2)), p)
        p = cbl("c2", 64, (3, 3), "p1")
        g.add_layer("p2", _maxpool((2, 2), (2, 2)), p)
        p = cbl("c3", 128, (3, 3), "p2")
        p = cbl("c4", 64, (1, 1), p)
        p = cbl("c5", 128, (3, 3), p)
        g.add_layer("p3", _maxpool((2, 2), (2, 2)), p)
        p = cbl("c6", 256, (3, 3), "p3")
        p = cbl("c7", 128, (1, 1), p)
        p = cbl("c8", 256, (3, 3), p)
        g.add_layer("p4", _maxpool((2, 2), (2, 2)), p)
        p = cbl("c9", 512, (3, 3), "p4")
        p = cbl("c10", 256, (1, 1), p)
        p = cbl("c11", 512, (3, 3), p)
        p = cbl("c12", 256, (1, 1), p)
        route = cbl("c13", 512, (3, 3), p)  # 26x26x512 passthrough source
        g.add_layer("p5", _maxpool((2, 2), (2, 2)), route)
        p = cbl("c14", 1024, (3, 3), "p5")
        p = cbl("c15", 512, (1, 1), p)
        p = cbl("c16", 1024, (3, 3), p)
        p = cbl("c17", 512, (1, 1), p)
        p = cbl("c18", 1024, (3, 3), p)
        p = cbl("c19", 1024, (3, 3), p)
        p = cbl("c20", 1024, (3, 3), p)
        # passthrough: 26x26x512 -> 1x1x64 -> space-to-depth -> 13x13x256
        r = cbl("route_conv", 64, (1, 1), route)
        g.add_layer("route_s2d", SpaceToDepthLayer(block_size=2), r)
        g.add_vertex("concat", MergeVertex(), "route_s2d", p)
        p = cbl("c21", 1024, (3, 3), "concat")
        nb = len(self.boxes)
        g.add_layer("detect", _conv(nb * (5 + self.num_classes), (1, 1),
                                    act=Activation.IDENTITY), p)
        g.add_layer("yolo", Yolo2OutputLayer(boxes=tuple(self.boxes)),
                    "detect")
        g.set_outputs("yolo")
        return g.build()


class NASNet(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.NASNet`` (NASNet-A mobile
    schema): stem conv, alternating stacks of NORMAL cells separated by
    REDUCTION cells, each cell the NASNet-A 5-block DAG over (h, h_prev)
    with separable convs / average pools / identities, 1x1 squeeze
    adjustments on both inputs, block outputs concatenated."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None,
                 penultimate_filters: int = 1056, num_cells: int = 4):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)
        # NASNet-A (N @ P): filters per normal cell = P / 24 * 4
        self.filters = max(penultimate_filters // 24, 8)
        self.num_cells = num_cells

    def conf(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.conf.layers_cnn import SeparableConvolution2D

        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def sep(name, n, k, s, inp):
            g.add_layer(name + "_r",
                        ActivationLayer(activation=Activation.RELU), inp)
            g.add_layer(name, SeparableConvolution2D(
                n_out=n, kernel_size=k, stride=s,
                activation=Activation.IDENTITY,
                convolution_mode=ConvolutionMode.SAME), name + "_r")
            g.add_layer(name + "_bn", BatchNormalization(), name)
            return name + "_bn"

        def squeeze(name, n, s, inp):
            g.add_layer(name, _conv(n, (1, 1), s, act=Activation.IDENTITY,
                                    mode=ConvolutionMode.SAME), inp)
            g.add_layer(name + "_bn", BatchNormalization(), name)
            return name + "_bn"

        def avg3(name, s, inp):
            g.add_layer(name, SubsamplingLayer(
                pooling_type=PoolingType.AVG, kernel_size=(3, 3), stride=s,
                convolution_mode=ConvolutionMode.SAME), inp)
            return name

        def add(name, a, b):
            g.add_vertex(name, ElementWiseVertex(op=ElementWiseOp.ADD), a, b)
            return name

        def normal_cell(cid, h, h_prev, f, prev_stride=(1, 1)):
            # adjust both inputs to f channels (reference squeeze/adjust);
            # right after a reduction cell h_prev still has the pre-reduction
            # spatial size, so its adjust runs at stride 2 (the reference's
            # factorized-reduction adjust block)
            h = squeeze(f"{cid}_adj", f, (1, 1), h)
            hp = squeeze(f"{cid}_adjp", f, prev_stride, h_prev)
            b1 = add(f"{cid}_b1", sep(f"{cid}_b1s", f, (3, 3), (1, 1), h), h)
            b2 = add(f"{cid}_b2",
                     sep(f"{cid}_b2a", f, (3, 3), (1, 1), hp),
                     sep(f"{cid}_b2b", f, (5, 5), (1, 1), h))
            b3 = add(f"{cid}_b3", avg3(f"{cid}_b3p", (1, 1), h), hp)
            b4 = add(f"{cid}_b4", avg3(f"{cid}_b4a", (1, 1), hp),
                     avg3(f"{cid}_b4b", (1, 1), hp))
            b5 = add(f"{cid}_b5",
                     sep(f"{cid}_b5a", f, (5, 5), (1, 1), hp),
                     sep(f"{cid}_b5b", f, (3, 3), (1, 1), hp))
            g.add_vertex(f"{cid}_out", MergeVertex(), b1, b2, b3, b4, b5)
            return f"{cid}_out"

        def reduction_cell(cid, h, h_prev, f):
            h = squeeze(f"{cid}_adj", f, (1, 1), h)
            hp = squeeze(f"{cid}_adjp", f, (1, 1), h_prev)
            b1 = add(f"{cid}_b1",
                     sep(f"{cid}_b1a", f, (5, 5), (2, 2), hp),
                     sep(f"{cid}_b1b", f, (7, 7), (2, 2), h))
            g.add_layer(f"{cid}_b2m", _maxpool((3, 3), (2, 2),
                                               ConvolutionMode.SAME), h)
            b2 = add(f"{cid}_b2", f"{cid}_b2m",
                     sep(f"{cid}_b2s", f, (7, 7), (2, 2), hp))
            b3 = add(f"{cid}_b3", avg3(f"{cid}_b3a", (2, 2), h),
                     sep(f"{cid}_b3s", f, (5, 5), (2, 2), hp))
            b4 = add(f"{cid}_b4", avg3(f"{cid}_b4a", (1, 1), b1),
                     f"{cid}_b2m")
            b5 = add(f"{cid}_b5", sep(f"{cid}_b5s", f, (3, 3), (1, 1), b1),
                     avg3(f"{cid}_b5a", (2, 2), h))
            g.add_vertex(f"{cid}_out", MergeVertex(), b2, b3, b4, b5)
            return f"{cid}_out"

        f = self.filters
        g.add_layer("stem", _conv(f, (3, 3), (2, 2),
                                  act=Activation.IDENTITY,
                                  mode=ConvolutionMode.SAME), "input")
        g.add_layer("stem_bn", BatchNormalization(), "stem")
        h_prev, h = "stem_bn", "stem_bn"
        cid = 0
        for stack in range(3):
            for ci in range(self.num_cells):
                stride_prev = (2, 2) if stack > 0 and ci == 0 else (1, 1)
                out = normal_cell(f"n{cid}", h, h_prev, f,
                                  prev_stride=stride_prev)
                h_prev, h = h, out
                cid += 1
            if stack < 2:
                f *= 2
                out = reduction_cell(f"r{stack}", h, h_prev, f)
                h_prev, h = h, out
        g.add_layer("final_relu", ActivationLayer(
            activation=Activation.RELU), h)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                    "final_relu")
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "gap")
        g.set_outputs("output")
        return g.build()


class TransformerEncoder(GraphZooModel):
    """Transformer encoder classifier (no direct reference zoo model — the
    reference reaches Transformers only through SameDiff
    ``multiHeadDotProductAttention`` / TF import, SURVEY.md §5.7; this makes
    the same architecture a first-class graph config). Learned positional
    embeddings, then pre-LN blocks: x + MHA(LN(x)), x + FFN(LN(x)). The
    attention core goes through ``ops.dot_product_attention`` (``auto``
    dispatches by measured crossover — bench_attention.py — to full
    materialization, XLA blockwise, or the Pallas flash kernel;
    ``attention_impl='flash'`` forces the strictly-O(T)-VMEM kernel)."""

    def __init__(self, num_classes: int = 2, vocab_size: int = 0,
                 embed_dim: int = 64, n_heads: int = 4, n_layers: int = 2,
                 ffn_dim: int = 0, max_len: int = 128, seed: int = 123,
                 updater: IUpdater | None = None,
                 attention_impl: str = "auto", causal: bool = False,
                 moe_experts: int = 0, moe_top_k: int = 2,
                 moe_capacity_factor: float = 1.25,
                 lm_head: bool = False, use_kernels: bool = False):
        """``vocab_size``>0: token-id inputs through an embedding;
        0: continuous ``[batch, time, embed_dim]`` inputs.

        ``moe_experts`` > 0 replaces every block's dense FFN with a
        GShard-style ``MoELayer`` (round-4 productization): the same
        config then trains data+expert-parallel under
        ``ParallelWrapper(expert_parallel=True)`` with no hand-written
        shard_map.

        ``lm_head=True`` makes this a causal language model instead of a
        classifier: the pooling layer is dropped and the output head is a
        time-distributed ``[batch, time, vocab_size]`` softmax over the
        vocabulary (requires ``vocab_size > 0`` and ``causal=True``).
        This is the configuration :meth:`decoder` serves with a KV cache
        (``nn.decoding.TransformerDecoder`` /
        ``parallel.generation.GenerationEngine``).

        ``use_kernels=True`` opts the conf into registry kernel routing
        (tuned flash-attention prefill / paged decode attention plus the
        matmul-class fusions); untuned envelopes stay stock XLA."""
        self.num_classes = num_classes
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.n_heads = n_heads
        self.n_layers = n_layers
        self.ffn_dim = ffn_dim or 4 * embed_dim
        self.max_len = max_len
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)
        self.attention_impl = attention_impl
        self.causal = causal
        self.moe_experts = moe_experts
        self.moe_top_k = moe_top_k
        self.moe_capacity_factor = moe_capacity_factor
        self.lm_head = lm_head
        self.use_kernels = use_kernels
        if lm_head and not (vocab_size and causal):
            raise ValueError("lm_head=True requires vocab_size > 0 and "
                             "causal=True (a language model decodes token "
                             "ids left to right)")

    def conf(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.conf.layers import EmbeddingSequenceLayer
        from deeplearning4j_tpu.conf.layers_attention import (
            SelfAttentionLayer,
        )
        from deeplearning4j_tpu.conf.layers_extra import (
            LayerNormalization,
            PositionEmbeddingLayer,
        )

        e = self.embed_dim
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .use_kernels(self.use_kernels)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.recurrent(
                 e if not self.vocab_size else 1, timesteps=self.max_len)))
        prev = "input"
        if self.vocab_size:
            g.add_layer("embed", EmbeddingSequenceLayer(
                n_in=self.vocab_size, n_out=e), prev)
            prev = "embed"
        g.add_layer("pos", PositionEmbeddingLayer(max_len=self.max_len),
                    prev)
        prev = "pos"
        for i in range(self.n_layers):
            g.add_layer(f"b{i}_ln1", LayerNormalization(), prev)
            g.add_layer(f"b{i}_attn", SelfAttentionLayer(
                n_out=e, n_heads=self.n_heads, causal=self.causal,
                attention_impl=self.attention_impl), f"b{i}_ln1")
            g.add_vertex(f"b{i}_res1",
                         ElementWiseVertex(op=ElementWiseOp.ADD),
                         prev, f"b{i}_attn")
            g.add_layer(f"b{i}_ln2", LayerNormalization(), f"b{i}_res1")
            if self.moe_experts:
                from deeplearning4j_tpu.conf.layers_moe import MoELayer

                g.add_layer(f"b{i}_moe", MoELayer(
                    n_experts=self.moe_experts, d_hidden=self.ffn_dim,
                    top_k=self.moe_top_k,
                    capacity_factor=self.moe_capacity_factor,
                    residual=False), f"b{i}_ln2")
                ff_out = f"b{i}_moe"
            else:
                g.add_layer(f"b{i}_ff1", DenseLayer(
                    n_out=self.ffn_dim, activation=Activation.GELU),
                    f"b{i}_ln2")
                g.add_layer(f"b{i}_ff2", DenseLayer(
                    n_out=e, activation=Activation.IDENTITY), f"b{i}_ff1")
                ff_out = f"b{i}_ff2"
            g.add_vertex(f"b{i}_res2",
                         ElementWiseVertex(op=ElementWiseOp.ADD),
                         f"b{i}_res1", ff_out)
            prev = f"b{i}_res2"
        g.add_layer("final_ln", LayerNormalization(), prev)
        if self.lm_head:
            # language-model head: time-distributed vocab logits — no
            # pooling, every position predicts its next token
            g.add_layer("output", OutputLayer(
                n_out=self.vocab_size, activation=Activation.SOFTMAX,
                loss_fn=LossMCXENT()), "final_ln")
        else:
            g.add_layer("pool", GlobalPoolingLayer(
                pooling_type=PoolingType.AVG), "final_ln")
            g.add_layer("output", OutputLayer(
                n_out=self.num_classes, activation=Activation.SOFTMAX,
                loss_fn=LossMCXENT()), "pool")
        g.set_outputs("output")
        return g.build()

    def decoder(self, net=None, **kw):
        """KV-cached generation front for this configuration: a
        ``nn.decoding.TransformerDecoder`` with ``prefill`` (one-launch
        prompt ingestion) and ``decode_step`` (fused multi-token
        autoregressive decode) executables, AOT-cached per KV
        length-bucket. ``net``: an already-initialized/trained
        ComputationGraph of this conf (default: a fresh ``init()``).
        Remaining kwargs go to ``TransformerDecoder`` (``max_batch``,
        ``fused_steps``, bucket knobs)."""
        if not self.lm_head:
            raise ValueError(
                "decoder() requires lm_head=True (the classifier head "
                "pools over time and cannot emit next-token logits)")
        from deeplearning4j_tpu.nn.decoding import TransformerDecoder

        return TransformerDecoder(net if net is not None else self.init(),
                                  max_len=self.max_len, **kw)
