"""Model zoo — ComputationGraph models.

Reference: ``org.deeplearning4j.zoo.model.{VGG16,VGG19,ResNet50,SqueezeNet,
Darknet19,UNet}`` — each ``init()`` builds a ComputationGraphConfiguration;
topologies follow the reference's graph builders (conv/bn orderings, residual
wiring via ``ElementWiseVertex(Add)``, fire-module concat via
``MergeVertex``). Layouts are NHWC (TPU-native) instead of the reference's
NCHW; shapes/channel counts match.
"""

from __future__ import annotations

from typing import Tuple

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.graph import (
    ComputationGraphConfiguration,
    ElementWiseOp,
    ElementWiseVertex,
    MergeVertex,
)
from deeplearning4j_tpu.conf.layers import (ActivationLayer, DenseLayer,
    LossLayer, OutputLayer)
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    CnnLossLayer,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    PoolingType,
    SubsamplingLayer,
    Upsampling2D,
)
from deeplearning4j_tpu.conf.losses import LossBinaryXENT, LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam, IUpdater, Nesterovs
from deeplearning4j_tpu.zoo.models import ZooModel


def _conv(n_out, k, s=(1, 1), act=Activation.RELU, mode=ConvolutionMode.SAME,
          bias=True):
    return ConvolutionLayer(n_out=n_out, kernel_size=k, stride=s,
                            activation=act, convolution_mode=mode,
                            has_bias=bias)


def _maxpool(k=(2, 2), s=(2, 2), mode=ConvolutionMode.TRUNCATE):
    return SubsamplingLayer(pooling_type=PoolingType.MAX, kernel_size=k,
                            stride=s, convolution_mode=mode)


class GraphZooModel(ZooModel):
    def init(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return ComputationGraph(self.conf()).init()


class VGG16(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.VGG16``: 13 conv3x3 SAME +
    5 maxpools + FC 4096/4096/classes."""

    BLOCKS: Tuple[Tuple[int, int], ...] = (
        (64, 2), (128, 2), (256, 3), (512, 3), (512, 3))

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Nesterovs(learning_rate=0.01, momentum=0.9)

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.XAVIER)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        prev = "input"
        for bi, (ch, reps) in enumerate(self.BLOCKS):
            for ri in range(reps):
                name = f"conv{bi + 1}_{ri + 1}"
                g.add_layer(name, _conv(ch, (3, 3)), prev)
                prev = name
            g.add_layer(f"pool{bi + 1}", _maxpool(), prev)
            prev = f"pool{bi + 1}"
        g.add_layer("fc1", DenseLayer(n_out=4096, activation=Activation.RELU),
                    prev)
        g.add_layer("fc2", DenseLayer(n_out=4096, activation=Activation.RELU),
                    "fc1")
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "fc2")
        g.set_outputs("output")
        return g.build()


class VGG19(VGG16):
    """Reference ``VGG19``: VGG16 with 4-deep conv blocks 3..5."""

    BLOCKS = ((64, 2), (128, 2), (256, 4), (512, 4), (512, 4))


class ResNet50(GraphZooModel):
    """Reference ``org.deeplearning4j.zoo.model.ResNet50``: conv7x7/2 + BN +
    maxpool3x3/2, 4 stages of bottleneck blocks [3,4,6,3] with channel
    triples (64,64,256)x, residual add via ``ElementWiseVertex(Add)``,
    global avg pool + softmax."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    def _conv_bn(self, g, name, n_out, k, s, inp, act=True):
        g.add_layer(f"{name}_conv",
                    _conv(n_out, k, s, Activation.IDENTITY, bias=False), inp)
        g.add_layer(f"{name}_bn", BatchNormalization(
            activation=Activation.RELU if act else Activation.IDENTITY),
            f"{name}_conv")
        return f"{name}_bn"

    def _bottleneck(self, g, name, inp, filters, stride, project):
        f1, f2, f3 = filters
        x = self._conv_bn(g, f"{name}_a", f1, (1, 1), stride, inp)
        x = self._conv_bn(g, f"{name}_b", f2, (3, 3), (1, 1), x)
        x = self._conv_bn(g, f"{name}_c", f3, (1, 1), (1, 1), x, act=False)
        if project:
            sc = self._conv_bn(g, f"{name}_sc", f3, (1, 1), stride, inp,
                               act=False)
        else:
            sc = inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op=ElementWiseOp.ADD),
                     x, sc)
        g.add_layer(f"{name}_relu", ActivationLayer(activation=Activation.RELU),
                    f"{name}_add")
        return f"{name}_relu"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        x = self._conv_bn(g, "stem", 64, (7, 7), (2, 2), "input")
        g.add_layer("stem_pool", _maxpool((3, 3), (2, 2),
                                          ConvolutionMode.SAME), x)
        x = "stem_pool"
        stages = ((64, 64, 256, 3), (128, 128, 512, 4),
                  (256, 256, 1024, 6), (512, 512, 2048, 3))
        for si, (f1, f2, f3, reps) in enumerate(stages):
            for ri in range(reps):
                stride = (1, 1) if (si == 0 or ri > 0) else (2, 2)
                x = self._bottleneck(g, f"res{si + 2}{chr(97 + ri)}", x,
                                     (f1, f2, f3), stride, project=(ri == 0))
        g.add_layer("avgpool",
                    GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "avgpool")
        g.set_outputs("output")
        return g.build()


class SqueezeNet(GraphZooModel):
    """Reference ``SqueezeNet`` (v1.1): conv3x3/2 + fire modules with
    squeeze(1x1) -> expand(1x1 || 3x3) -> MergeVertex concat, conv1x1 head +
    global avg pool."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    def _fire(self, g, name, inp, squeeze, expand):
        g.add_layer(f"{name}_sq", _conv(squeeze, (1, 1)), inp)
        g.add_layer(f"{name}_e1", _conv(expand, (1, 1)), f"{name}_sq")
        g.add_layer(f"{name}_e3", _conv(expand, (3, 3)), f"{name}_sq")
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_cat"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("conv1", _conv(64, (3, 3), (2, 2)), "input")
        g.add_layer("pool1", _maxpool((3, 3), (2, 2)), "conv1")
        x = self._fire(g, "fire2", "pool1", 16, 64)
        x = self._fire(g, "fire3", x, 16, 64)
        g.add_layer("pool3", _maxpool((3, 3), (2, 2)), x)
        x = self._fire(g, "fire4", "pool3", 32, 128)
        x = self._fire(g, "fire5", x, 32, 128)
        g.add_layer("pool5", _maxpool((3, 3), (2, 2)), x)
        x = self._fire(g, "fire6", "pool5", 48, 192)
        x = self._fire(g, "fire7", x, 48, 192)
        x = self._fire(g, "fire8", x, 64, 256)
        x = self._fire(g, "fire9", x, 64, 256)
        g.add_layer("conv10", _conv(self.num_classes, (1, 1)), x)
        g.add_layer("avgpool",
                    GlobalPoolingLayer(pooling_type=PoolingType.AVG), "conv10")
        # avgpool already yields num_classes features: a parameter-free
        # LossLayer head, matching the reference topology (no extra dense)
        g.add_layer("output", LossLayer(
            activation=Activation.SOFTMAX, loss_fn=LossMCXENT()), "avgpool")
        g.set_outputs("output")
        return g.build()


class Darknet19(GraphZooModel):
    """Reference ``Darknet19`` (YOLO9000 backbone): 19 convs (3x3/1x1
    alternation) + BN + LeakyReLU, 5 maxpools, conv1x1 head + global
    avg pool."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    def _conv_bn_leaky(self, g, i, n_out, k, inp):
        name = f"conv{i}"
        g.add_layer(name, _conv(n_out, k, (1, 1), Activation.IDENTITY,
                                bias=False), inp)
        g.add_layer(f"{name}_bn",
                    BatchNormalization(activation=Activation.LEAKYRELU), name)
        return f"{name}_bn"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        spec = [(32, 3), "M", (64, 3), "M", (128, 3), (64, 1), (128, 3), "M",
                (256, 3), (128, 1), (256, 3), "M",
                (512, 3), (256, 1), (512, 3), (256, 1), (512, 3), "M",
                (1024, 3), (512, 1), (1024, 3), (512, 1), (1024, 3)]
        x, ci, pi = "input", 0, 0
        for s in spec:
            if s == "M":
                pi += 1
                g.add_layer(f"pool{pi}", _maxpool(), x)
                x = f"pool{pi}"
            else:
                ci += 1
                n_out, k = s
                x = self._conv_bn_leaky(g, ci, n_out, (k, k), x)
        g.add_layer("head", _conv(self.num_classes, (1, 1),
                                  act=Activation.IDENTITY), x)
        g.add_layer("avgpool",
                    GlobalPoolingLayer(pooling_type=PoolingType.AVG), "head")
        # avgpool already yields num_classes features: a parameter-free
        # LossLayer head, matching the reference topology (no extra dense)
        g.add_layer("output", LossLayer(
            activation=Activation.SOFTMAX, loss_fn=LossMCXENT()), "avgpool")
        g.set_outputs("output")
        return g.build()


class UNet(GraphZooModel):
    """Reference ``UNet``: 4-down/4-up encoder-decoder, skip connections via
    ``MergeVertex``, nearest-neighbour ``Upsampling2D`` + conv on the way up,
    sigmoid ``CnnLossLayer`` head (binary segmentation)."""

    def __init__(self, height: int = 128, width: int = 128, channels: int = 1,
                 base: int = 64, seed: int = 123,
                 updater: IUpdater | None = None):
        self.height, self.width, self.channels = height, width, channels
        self.base = base
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-4)

    def _double_conv(self, g, name, n_out, inp):
        g.add_layer(f"{name}_1", _conv(n_out, (3, 3)), inp)
        g.add_layer(f"{name}_2", _conv(n_out, (3, 3)), f"{name}_1")
        return f"{name}_2"

    def conf(self) -> ComputationGraphConfiguration:
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(self.updater)
             .weight_init(WeightInit.RELU)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        b = self.base
        skips = []
        x = "input"
        for i, ch in enumerate([b, b * 2, b * 4, b * 8]):
            x = self._double_conv(g, f"down{i + 1}", ch, x)
            skips.append(x)
            g.add_layer(f"dpool{i + 1}", _maxpool(), x)
            x = f"dpool{i + 1}"
        x = self._double_conv(g, "bottom", b * 16, x)
        for i, ch in enumerate([b * 8, b * 4, b * 2, b]):
            g.add_layer(f"up{i + 1}_us", Upsampling2D(size=(2, 2)), x)
            g.add_layer(f"up{i + 1}_conv", _conv(ch, (2, 2)), f"up{i + 1}_us")
            g.add_vertex(f"up{i + 1}_cat", MergeVertex(),
                         skips[3 - i], f"up{i + 1}_conv")
            x = self._double_conv(g, f"up{i + 1}", ch, f"up{i + 1}_cat")
        g.add_layer("head", _conv(1, (1, 1), act=Activation.IDENTITY), x)
        g.add_layer("output", CnnLossLayer(activation=Activation.SIGMOID,
                                           loss_fn=LossBinaryXENT()), "head")
        g.set_outputs("output")
        return g.build()
