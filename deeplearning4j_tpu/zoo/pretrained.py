"""Pretrained-weights machinery for the model zoo.

Reference: ``org.deeplearning4j.zoo.ZooModel#initPretrained(PretrainedType)``
+ ``DL4JResources``: per-model weight artifacts are fetched by URL into a
local cache directory (``~/.deeplearning4j/models/<model>/``), verified
against a hard-coded checksum, and loaded into the zoo topology.

TPU-native shape of the same workflow:

- The weight-artifact format IS the ModelSerializer zip
  (:mod:`deeplearning4j_tpu.util.serializer`) — config JSON + flat
  coefficients + runtime state — so a pretrained artifact is exactly a
  saved model and round-trips through the same code path.
- Cache layout: ``$DL4J_TPU_HOME/models/<model_name>_<type>.zip`` with a
  ``.sha256`` sidecar (``DL4J_TPU_HOME`` defaults to
  ``~/.deeplearning4j_tpu``; reference: ``DL4JResources.getBaseDirectory``).
- Checksums: every load re-hashes the artifact and compares against the
  sidecar written at publish time (corruption detection, the reference's
  checksum role); a model class may additionally pin a hard-coded hash in
  ``PRETRAINED_CHECKSUMS`` exactly like the reference pins its
  ``pretrainedChecksum(type)`` longs.
- Zero-egress environments: ``fetch=True`` attempts the model's
  ``PRETRAINED_URLS`` entry over HTTP exactly like the reference; when
  the artifact is already cached (the supported path here) no network is
  touched. ``save_pretrained`` is the publish half the reference keeps
  server-side: it writes the artifact + sidecar into the cache so local
  fixtures, converted checkpoints, or institutionally-mirrored weights
  slot into ``init_pretrained`` unchanged.
- Partial load (``restore_partial``): copy every parameter whose
  layer/key + shape matches from artifact to target network — the
  transfer-learning entry point when the head differs (reference users
  do this via ``TransferLearning`` after ``initPretrained``).
"""

from __future__ import annotations

import enum
import hashlib
import os
import urllib.request
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util import serializer


class PretrainedType(enum.Enum):
    """Reference ``org.deeplearning4j.zoo.PretrainedType``."""

    IMAGENET = "imagenet"
    IMAGENETLARGE = "imagenetlarge"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"
    SEGMENT = "segment"


def base_directory() -> Path:
    """Reference ``DL4JResources#getBaseDirectory`` (env-overridable)."""
    root = os.environ.get("DL4J_TPU_HOME",
                          os.path.join(os.path.expanduser("~"),
                                       ".deeplearning4j_tpu"))
    return Path(root)


def model_cache_dir() -> Path:
    return base_directory() / "models"


def artifact_path(model_name: str, ptype: PretrainedType) -> Path:
    return model_cache_dir() / f"{model_name}_{ptype.value}.zip"


def sha256_of(path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_pretrained(net, model_name: str, ptype: PretrainedType,
                    save_updater: bool = False) -> Path:
    """Publish a network's weights as a cached pretrained artifact
    (the server-side half of the reference's pretrained pipeline, made
    local): writes ``<cache>/<model_name>_<type>.zip`` + ``.sha256``."""
    path = artifact_path(model_name, ptype)
    path.parent.mkdir(parents=True, exist_ok=True)
    serializer.write_model(net, path, save_updater=save_updater)
    digest = sha256_of(path)
    path.with_suffix(".zip.sha256").write_text(digest + "\n")
    return path


def _verify(path: Path, expected: str | None, model_name: str,
            actual: str | None = None) -> None:
    sidecar = path.with_suffix(".zip.sha256")
    if expected is None and not sidecar.exists():
        return  # nothing to check against — skip the full-file hash
    actual = actual or sha256_of(path)
    if sidecar.exists():
        recorded = sidecar.read_text().strip()
        if actual != recorded:
            raise IOError(
                f"checksum mismatch for {path}: artifact hashes to "
                f"{actual[:16]}… but its sidecar records {recorded[:16]}… "
                "(corrupted download/copy — delete the artifact and "
                "re-fetch; reference: ZooModel#initPretrained checksum "
                "failure)")
    if expected is not None and actual != expected:
        raise IOError(
            f"checksum mismatch for {model_name}: artifact hashes to "
            f"{actual[:16]}… but the model pins {expected[:16]}…")


def load_pretrained(model, ptype: PretrainedType = PretrainedType.IMAGENET,
                    fetch: bool = True, load_updater: bool = False):
    """Core of ``ZooModel#initPretrained``: resolve the cached artifact
    (fetching it by URL if missing and the model publishes one), verify
    checksums, and restore the network."""
    name = getattr(model, "model_name", None) or type(model).__name__
    if not model.pretrained_available(ptype):
        raise ValueError(
            f"{name} has no pretrained weights for {ptype.name} "
            "(reference: initPretrained throws UnsupportedOperationException)"
        )
    path = artifact_path(name, ptype)
    if not path.exists():
        url = model.pretrained_url(ptype)
        if not (fetch and url):
            raise FileNotFoundError(
                f"no cached artifact at {path} and no fetchable URL; "
                "publish weights locally with zoo.pretrained.save_pretrained"
                "(net, model_name, type) or place the artifact in the cache")
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".zip.part")
        try:
            urllib.request.urlretrieve(url, tmp)  # noqa: S310 — model URL
        except Exception as e:
            tmp.unlink(missing_ok=True)
            raise IOError(
                f"could not fetch {name} {ptype.name} weights from {url}: "
                f"{e} (zero-egress environment? pre-populate the cache via "
                "save_pretrained or copy the artifact to "
                f"{path})") from e
        tmp.rename(path)
        # record the downloaded artifact's hash so every later load can
        # detect cache corruption even without a class-pinned checksum
        digest = sha256_of(path)
        path.with_suffix(".zip.sha256").write_text(digest + "\n")
        if model.pretrained_checksum(ptype) is None:
            # trust-on-first-use (round-2 advisor): with no class-pinned
            # checksum the sidecar is derived from the just-downloaded
            # bytes, so it detects LATER corruption but cannot detect a
            # tampered/truncated fetch — make that visible to the caller
            import warnings

            warnings.warn(
                f"{name} {ptype.name}: no pinned checksum for this "
                f"artifact — the download from {url} is trusted on first "
                "use (the .sha256 sidecar only guards against cache "
                "corruption, not a bad fetch). Pin pretrained_checksum() "
                "to remove this trust assumption.", stacklevel=2)
        _verify(path, model.pretrained_checksum(ptype), name, actual=digest)
    else:
        _verify(path, model.pretrained_checksum(ptype), name)
    return serializer.restore_model(path, load_updater=load_updater)


def restore_partial(path, net) -> tuple[list, list]:
    """Copy every parameter (and runtime-state entry) whose layer key,
    param key, and shape match from the artifact into ``net`` (already
    initialized). Returns (loaded, skipped) key lists. This is the
    weight-surgery primitive behind transfer learning with a replaced
    head: load the backbone, leave mismatched layers at init."""
    donor = serializer.restore_model(path, load_updater=False)
    loaded, skipped = [], []
    for lk, lp in donor.params.items():
        for pk, pv in lp.items():
            tgt = net.params.get(lk, {})
            if pk in tgt and tuple(tgt[pk].shape) != tuple(pv.shape):
                # space-to-depth stem rewrite (ResNet50.stem_space_to_depth,
                # exact): a reference [7,7,C,O] stem kernel loads into the
                # rewrite's [4,4,4C,O] slot through the documented remap —
                # without this, pretrained backbones would silently keep a
                # RANDOM stem (round-3 review finding)
                dv = np.asarray(pv)
                if (pk == "W" and dv.ndim == 4 and dv.shape[:2] == (7, 7)
                        and tuple(tgt[pk].shape)
                        == (4, 4, 4 * dv.shape[2], dv.shape[3])):
                    from deeplearning4j_tpu.zoo.graphs import ResNet50

                    net.params[lk][pk] = jnp.asarray(
                        ResNet50.stem_weights_to_s2d(dv))
                    loaded.append(f"{lk}/{pk}")
                    continue
            if pk in tgt and tuple(tgt[pk].shape) == tuple(pv.shape):
                net.params[lk][pk] = jnp.asarray(pv)
                loaded.append(f"{lk}/{pk}")
            else:
                skipped.append(f"{lk}/{pk}")
    for lk, ls in donor.state.items():
        for sk, sv in ls.items():
            tgt = net.state.get(lk, {})
            if sk in tgt and tuple(tgt[sk].shape) == tuple(sv.shape):
                net.state[lk][sk] = jnp.asarray(sv)
                loaded.append(f"state:{lk}/{sk}")
            else:
                skipped.append(f"state:{lk}/{sk}")
    return loaded, skipped


class PretrainedMixin:
    """Mixed into ``ZooModel``: the ``initPretrained`` API surface.

    Subclasses declare availability by populating ``PRETRAINED_URLS``
    (type -> URL, may be empty-string for cache-only models) and
    optionally ``PRETRAINED_CHECKSUMS`` (type -> sha256 hex, the
    reference's ``pretrainedChecksum``)."""

    #: type -> URL; presence of the key marks the weights as available
    PRETRAINED_URLS: dict = {}
    #: type -> sha256 hex digest pinned at publish time (optional)
    PRETRAINED_CHECKSUMS: dict = {}

    @property
    def model_name(self) -> str:
        return type(self).__name__

    def pretrained_available(self, ptype: PretrainedType) -> bool:
        """Reference ``ZooModel#pretrainedAvailable``. True also when a
        cache-only artifact exists locally (published via
        ``save_pretrained``)."""
        return (ptype in self.PRETRAINED_URLS
                or artifact_path(self.model_name, ptype).exists())

    def pretrained_url(self, ptype: PretrainedType):
        """Reference ``ZooModel#pretrainedUrl(type)``."""
        return self.PRETRAINED_URLS.get(ptype) or None

    def pretrained_checksum(self, ptype: PretrainedType):
        """Reference ``ZooModel#pretrainedChecksum(type)``."""
        return self.PRETRAINED_CHECKSUMS.get(ptype)

    def init_pretrained(self, ptype: PretrainedType = PretrainedType.IMAGENET,
                        load_updater: bool = False):
        """Reference ``ZooModel#initPretrained(type)`` — returns the
        network with pretrained weights loaded (checksum-verified)."""
        return load_pretrained(self, ptype, load_updater=load_updater)
