from deeplearning4j_tpu.zoo.graphs import (
    VGG16,
    VGG19,
    Darknet19,
    ResNet50,
    SqueezeNet,
    UNet,
)
from deeplearning4j_tpu.zoo.models import LeNet, SimpleCNN, ZooModel
from deeplearning4j_tpu.zoo import rules as rules  # noqa: F401  (partition-rule tables)
from deeplearning4j_tpu.zoo.pretrained import (
    PretrainedType,
    load_pretrained,
    restore_partial,
    save_pretrained,
)
