from deeplearning4j_tpu.zoo.graphs import (
    VGG16,
    VGG19,
    Darknet19,
    ResNet50,
    SqueezeNet,
    UNet,
)
from deeplearning4j_tpu.zoo.models import LeNet, SimpleCNN, ZooModel
