from deeplearning4j_tpu.zoo.models import LeNet, SimpleCNN
