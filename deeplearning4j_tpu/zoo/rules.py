"""Partition-rule tables for the built-in zoo nets.

One table per architecture family, written against the zoo configs'
actual vertex/param paths (``"res2a_branch2a/W"``, ``"b0_attn/Wq"``,
``"4/W"`` for sequential nets): dense/conv KERNELS are tensor-parallel
split over the ``model`` axis, biases and normalization parameters
replicate (their payload is negligible and replicating them keeps the
activation layouts simple). Every table ends with a replicate-by-default
catch-all, and :func:`plan_for` builds the table into a
``ShardingPlan`` with ``demote_indivisible=True`` — classifier heads
follow ``num_classes``, which a generic table cannot promise divides
the ``model`` axis.

Usage::

    from deeplearning4j_tpu.zoo import rules as zoo_rules

    net = ResNet50(num_classes=1000).init()
    plan = zoo_rules.plan_for(zoo_rules.resnet_rules(), data=4, model=2)
    pw = ParallelWrapper(net, workers=4, mesh=plan.mesh,
                         partition_rules=plan)
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS
from deeplearning4j_tpu.sharding import ShardingPlan

# split a rank-2 kernel's OUTPUT features / a rank-4 conv kernel's
# OUTPUT channels over the tensor-parallel axis
DENSE_OUT = P(None, MODEL_AXIS)
DENSE_IN = P(MODEL_AXIS, None)
CONV_OUT = P(None, None, None, MODEL_AXIS)
REPLICATED = P()


def resnet_rules():
    """ResNet/VGG-family ComputationGraphs (ResNet50, VGG16/19,
    SqueezeNet, Darknet19, …): conv kernels split on output channels,
    the dense head(s) on output features; biases / BN (gamma, beta,
    mean, var) replicated via the catch-all."""
    return [
        (r"(output|fc\d*)/W$", DENSE_OUT),
        (r"/W$", CONV_OUT),          # every remaining kernel is a conv
        (r".*", REPLICATED),
    ]


def transformer_rules():
    """``zoo.graphs.TransformerEncoder``: Megatron-style block split —
    QKV projections column-parallel, the attention output projection
    and second FFN matmul row-parallel (their input dim carries the
    split head/hidden features), embedding and classifier head
    column-parallel; LayerNorm and biases replicated."""
    return [
        (r"_attn/W[qkv]$", DENSE_OUT),
        (r"_attn/Wo$", DENSE_IN),
        (r"_ff1/W$", DENSE_OUT),
        (r"_ff2/W$", DENSE_IN),
        (r"(embed|output)/W$", DENSE_OUT),
        (r".*", REPLICATED),
    ]


def lenet_rules():
    """``zoo.models.LeNet`` (sequential — param paths are layer
    indices): conv kernels (layers 0/2) on output channels, dense +
    softmax head (layers 5/6) on output features."""
    return [
        (r"^[02]/W$", CONV_OUT),
        (r"^[56]/W$", DENSE_OUT),
        (r".*", REPLICATED),
    ]


def mlp_rules():
    """Any all-dense sequential net: every kernel column-parallel."""
    return [
        (r"/W$", DENSE_OUT),
        (r".*", REPLICATED),
    ]


def plan_for(rules, mesh=None, data: int = -1, model: int = 1
             ) -> ShardingPlan:
    """Rule table -> ``ShardingPlan`` on a DP×TP mesh, demoting
    indivisible dims (generic tables meet nets whose widths the model
    axis does not divide — a demoted tensor replicates and shows up
    flagged in ``plan.explain()``)."""
    return ShardingPlan(rules, mesh=mesh, data=data, model=model,
                        demote_indivisible=True)
