"""Model zoo — sequential models.

Reference: ``org.deeplearning4j.zoo.model.*`` (``ZooModel`` SPI: ``init()``
builds a config; ``initPretrained(type)`` loads checksum-verified cached
weights — see :mod:`deeplearning4j_tpu.zoo.pretrained`).
ComputationGraph-based zoo models (ResNet50, VGG16, …) are in
:mod:`deeplearning4j_tpu.zoo.graphs`.
"""

from __future__ import annotations

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.zoo.pretrained import PretrainedMixin
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    PoolingType,
    SubsamplingLayer,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.conf.updaters import Adam, IUpdater, Nesterovs


class ZooModel(PretrainedMixin):
    """SPI base (reference ``org.deeplearning4j.zoo.ZooModel``): ``conf()``
    builds the configuration, ``init()`` the network, and the mixin
    provides ``init_pretrained`` / ``pretrained_available`` /
    ``pretrained_url`` / ``pretrained_checksum``."""

    def init(self):
        """Build the (un-initialized) network object."""
        raise NotImplementedError

    def conf(self):
        raise NotImplementedError


class LeNet(ZooModel):
    """Reference ``org.deeplearning4j.zoo.model.LeNet`` topology:
    conv5x5(20) -> maxpool2 -> conv5x5(50) -> maxpool2 -> dense(500, relu)
    -> softmax output. Input 28x28xC (MNIST default)."""

    def __init__(self, num_classes: int = 10, height: int = 28,
                 width: int = 28, channels: int = 1, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    def conf(self) -> MultiLayerConfiguration:
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater)
                .weight_init(WeightInit.XAVIER)
                .list()
                .layer(ConvolutionLayer(
                    n_out=20, kernel_size=(5, 5), stride=(1, 1),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.IDENTITY))
                .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                        kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(
                    n_out=50, kernel_size=(5, 5), stride=(1, 1),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.IDENTITY))
                .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                        kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation=Activation.RELU))
                .layer(OutputLayer(n_out=self.num_classes,
                                   activation=Activation.SOFTMAX,
                                   loss_fn=LossMCXENT()))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork(self.conf()).init()


class SimpleCNN(ZooModel):
    """Reference ``org.deeplearning4j.zoo.model.SimpleCNN``: small
    conv/bn stack for 48x48x3-style inputs."""

    def __init__(self, num_classes: int = 10, height: int = 48,
                 width: int = 48, channels: int = 3, seed: int = 123):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed

    def conf(self) -> MultiLayerConfiguration:
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(learning_rate=0.01, momentum=0.9))
             .weight_init(WeightInit.RELU)
             .list())
        for n_out, pool in [(16, False), (32, True), (64, True)]:
            b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                     convolution_mode=ConvolutionMode.SAME,
                                     activation=Activation.IDENTITY))
            b.layer(BatchNormalization(activation=Activation.RELU))
            if pool:
                b.layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                         kernel_size=(2, 2), stride=(2, 2)))
        b.layer(DenseLayer(n_out=128, activation=Activation.RELU))
        b.layer(OutputLayer(n_out=self.num_classes,
                            activation=Activation.SOFTMAX,
                            loss_fn=LossMCXENT()))
        b.set_input_type(InputType.convolutional(self.height, self.width,
                                                 self.channels))
        return b.build()

    def init(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork(self.conf()).init()


class AlexNet(ZooModel):
    """Reference ``org.deeplearning4j.zoo.model.AlexNet``: conv11x11/4(96)
    -> LRN -> maxpool3/2 -> conv5x5(256) -> LRN -> maxpool -> conv3x3(384)
    x2 -> conv3x3(256) -> maxpool -> FC 4096 x2 (dropout 0.5) -> softmax."""

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, seed: int = 123,
                 updater: IUpdater | None = None):
        self.num_classes = num_classes
        self.height, self.width, self.channels = height, width, channels
        self.seed = seed
        self.updater = updater or Nesterovs(learning_rate=1e-2, momentum=0.9)

    def conf(self) -> MultiLayerConfiguration:
        from deeplearning4j_tpu.conf.layers_cnn import (
            LocalResponseNormalization,
        )

        conv = lambda n, k, s=(1, 1): ConvolutionLayer(  # noqa: E731
            n_out=n, kernel_size=k, stride=s, activation=Activation.RELU,
            convolution_mode=ConvolutionMode.SAME)
        pool = lambda: SubsamplingLayer(  # noqa: E731
            pooling_type=PoolingType.MAX, kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.TRUNCATE)
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater)
                .weight_init(WeightInit.NORMAL)
                .list()
                .layer(ConvolutionLayer(
                    n_out=96, kernel_size=(11, 11), stride=(4, 4),
                    activation=Activation.RELU,
                    convolution_mode=ConvolutionMode.TRUNCATE))
                .layer(LocalResponseNormalization())
                .layer(pool())
                .layer(conv(256, (5, 5)))
                .layer(LocalResponseNormalization())
                .layer(pool())
                .layer(conv(384, (3, 3)))
                .layer(conv(384, (3, 3)))
                .layer(conv(256, (3, 3)))
                .layer(pool())
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes,
                                   activation=Activation.SOFTMAX,
                                   loss_fn=LossMCXENT()))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork(self.conf()).init()


class TextGenerationLSTM(ZooModel):
    """Reference ``org.deeplearning4j.zoo.model.TextGenerationLSTM``:
    LSTM(256) x2 + RnnOutputLayer(MCXENT) over a character vocabulary,
    trained on one-hot sequences (tBPTT-friendly)."""

    def __init__(self, total_unique_characters: int = 47,
                 max_length: int = 40, layer_size: int = 256,
                 seed: int = 123, updater: IUpdater | None = None):
        self.vocab = total_unique_characters
        self.max_length = max_length
        self.layer_size = layer_size
        self.seed = seed
        self.updater = updater or Adam(learning_rate=1e-3)

    def conf(self) -> MultiLayerConfiguration:
        from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer

        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater)
                .weight_init(WeightInit.XAVIER)
                .list()
                .layer(LSTM(n_out=self.layer_size,
                            activation=Activation.TANH))
                .layer(LSTM(n_out=self.layer_size,
                            activation=Activation.TANH))
                .layer(RnnOutputLayer(n_out=self.vocab,
                                      activation=Activation.SOFTMAX,
                                      loss_fn=LossMCXENT()))
                .set_input_type(InputType.recurrent(
                    self.vocab, timesteps=self.max_length))
                .build())

    def init(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork(self.conf()).init()
