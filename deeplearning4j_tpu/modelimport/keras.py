"""Keras HDF5 model import.

Reference: ``deeplearning4j-modelimport`` —
``KerasModelImport#importKerasSequentialModelAndWeights`` (per-layer
``KerasLayer`` subclasses map configuration + weights into the DL4J config
DSL). Here the mapping targets the TPU config DSL; weight layouts line up
naturally (Keras kernels are [in, out] / HWIO, exactly this framework's
layouts — the reference has to transpose into its NCHW/ [out, in] forms).

Supports the Keras 2.x HDF5 format (``model_config`` JSON attribute +
``model_weights`` groups): Sequential models with InputLayer, Dense, Conv2D,
MaxPooling2D, AveragePooling2D, Flatten, Dropout, Activation,
BatchNormalization, LSTM, Embedding, GlobalAveragePooling2D. LSTM gates are
re-packed from Keras' IFCO order into this framework's IFOG.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.conf import InputType
from deeplearning4j_tpu.conf.activations import Activation as Act
from deeplearning4j_tpu.conf.layers import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingSequenceLayer,
    OutputLayer,
)
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    GlobalPoolingLayer,
    PoolingType,
    SubsamplingLayer,
)
from deeplearning4j_tpu.conf.layers_rnn import LSTM
from deeplearning4j_tpu.conf.losses import LossMCXENT, LossMSE
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration

_ACTIVATIONS = {
    "linear": Act.IDENTITY, "relu": Act.RELU, "softmax": Act.SOFTMAX,
    "tanh": Act.TANH, "sigmoid": Act.SIGMOID, "elu": Act.ELU,
    "selu": Act.SELU, "softplus": Act.SOFTPLUS, "softsign": Act.SOFTSIGN,
    "swish": Act.SWISH, "gelu": Act.GELU, "hard_sigmoid": Act.HARDSIGMOID,
}


class InvalidKerasConfigurationException(ValueError):
    """Reference exception of the same name."""


def _act(name: Optional[str]) -> Act:
    if not name:
        return Act.IDENTITY
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise InvalidKerasConfigurationException(
            f"unsupported Keras activation '{name}' "
            f"(supported: {sorted(_ACTIVATIONS)})")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _mode(padding: str) -> ConvolutionMode:
    return (ConvolutionMode.SAME if padding == "same"
            else ConvolutionMode.TRUNCATE)


class KerasModelImport:
    """Static import API (reference class of the same name)."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str, enforce_training_config: bool = False):
        """-> initialized MultiLayerNetwork with the Keras weights."""
        import h5py

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with h5py.File(path, "r") as f:
            raw = f.attrs.get("model_config")
            if raw is None:
                raise InvalidKerasConfigurationException(
                    "no model_config attribute — not a Keras HDF5 file "
                    "saved with model.save()")
            if isinstance(raw, bytes):
                raw = raw.decode()
            model_cfg = json.loads(raw)
            if model_cfg.get("class_name") != "Sequential":
                raise InvalidKerasConfigurationException(
                    "only Sequential models supported here; use "
                    "import_keras_model_and_weights for functional models "
                    "(not yet implemented)")
            layer_cfgs = model_cfg["config"]["layers"]
            conf, names = _build_conf(layer_cfgs)
            net = MultiLayerNetwork(conf)
            net.init()
            _load_weights(f, net, names)
        return net


def _input_type(first_cfg: dict):
    shape = (first_cfg.get("config", {}).get("batch_input_shape")
             or first_cfg.get("config", {}).get("batch_shape"))
    if shape is None:
        raise InvalidKerasConfigurationException(
            "first layer must carry batch_input_shape")
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(int(dims[0]))
    if len(dims) == 2:
        return InputType.recurrent(int(dims[1]), timesteps=int(dims[0] or -1))
    if len(dims) == 3:  # Keras default channels_last == our NHWC
        return InputType.convolutional(int(dims[0]), int(dims[1]),
                                       int(dims[2]))
    raise InvalidKerasConfigurationException(
        f"unsupported input rank {len(dims) + 1}")


def _build_conf(layer_cfgs: List[dict]):
    """-> (MultiLayerConfiguration, [keras_name in parameterized order])"""
    input_type = None
    mapped: List[Tuple[str, object]] = []  # (keras_name, layer_conf)
    pending_cfgs = list(layer_cfgs)

    for i, lc in enumerate(pending_cfgs):
        cls = lc["class_name"]
        cfg = lc.get("config", {})
        name = cfg.get("name", f"layer_{i}")
        if input_type is None and cls != "InputLayer":
            input_type = _input_type(lc)
        if cls == "InputLayer":
            input_type = _input_type(lc)
            continue
        if cls == "Dense":
            is_last = all(c["class_name"] in ("Activation", "Dropout")
                          for c in pending_cfgs[i + 1:])
            act = _act(cfg.get("activation"))
            if is_last and act is Act.SOFTMAX:
                layer = OutputLayer(n_out=int(cfg["units"]), activation=act,
                                    loss_fn=LossMCXENT(), name=name)
            elif is_last:
                layer = OutputLayer(n_out=int(cfg["units"]), activation=act,
                                    loss_fn=LossMSE(), name=name)
            else:
                layer = DenseLayer(n_out=int(cfg["units"]), activation=act,
                                   name=name)
        elif cls == "Conv2D":
            layer = ConvolutionLayer(
                n_out=int(cfg["filters"]),
                kernel_size=_pair(cfg.get("kernel_size", 3)),
                stride=_pair(cfg.get("strides", 1)),
                convolution_mode=_mode(cfg.get("padding", "valid")),
                activation=_act(cfg.get("activation")),
                has_bias=bool(cfg.get("use_bias", True)), name=name)
        elif cls in ("MaxPooling2D", "AveragePooling2D"):
            layer = SubsamplingLayer(
                pooling_type=(PoolingType.MAX if cls == "MaxPooling2D"
                              else PoolingType.AVG),
                kernel_size=_pair(cfg.get("pool_size", 2)),
                stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
                convolution_mode=_mode(cfg.get("padding", "valid")),
                name=name)
        elif cls == "Flatten":
            # shape inference inserts CnnToFeedForwardPreProcessor; nothing
            # to add explicitly
            continue
        elif cls == "Dropout":
            layer = DropoutLayer(dropout=1.0 - float(cfg.get("rate", 0.0)),
                                 name=name)
        elif cls == "Activation":
            layer = ActivationLayer(activation=_act(cfg.get("activation")),
                                    name=name)
        elif cls == "BatchNormalization":
            layer = BatchNormalization(
                eps=float(cfg.get("epsilon", 1e-3)),
                decay=float(cfg.get("momentum", 0.99)), name=name)
        elif cls == "LSTM":
            if not cfg.get("return_sequences", False):
                raise InvalidKerasConfigurationException(
                    "LSTM with return_sequences=False: wrap with "
                    "LastTimeStep manually (not auto-mapped)")
            layer = LSTM(n_out=int(cfg["units"]),
                         activation=_act(cfg.get("activation", "tanh")),
                         gate_activation=_act(
                             cfg.get("recurrent_activation", "sigmoid")),
                         name=name)
        elif cls == "Embedding":
            layer = EmbeddingSequenceLayer(
                n_out=int(cfg["output_dim"]),
                n_in=int(cfg["input_dim"]), name=name)
        elif cls == "GlobalAveragePooling2D":
            layer = GlobalPoolingLayer(pooling_type=PoolingType.AVG,
                                       name=name)
        else:
            raise InvalidKerasConfigurationException(
                f"unsupported Keras layer class '{cls}'")
        mapped.append((name, layer))

    # fold a trailing Activation into the preceding OutputLayer (the common
    # Keras idiom Dense(units) + Activation('softmax')) — the last layer
    # must be the scoring layer
    while (len(mapped) >= 2 and isinstance(mapped[-1][1], ActivationLayer)
           and isinstance(mapped[-2][1], OutputLayer)
           and mapped[-2][1].activation is Act.IDENTITY):
        act = mapped[-1][1].activation
        out = mapped[-2][1]
        out.activation = act
        if act is Act.SOFTMAX:
            out.loss_fn = LossMCXENT()
        mapped = mapped[:-1]

    if input_type is None:
        raise InvalidKerasConfigurationException("no input shape found")
    b = NeuralNetConfiguration.builder().seed(12345).list()
    for _, layer in mapped:
        b.layer(layer)
    b.set_input_type(input_type)
    conf = b.build()
    return conf, [n for n, _ in mapped]


def _weight_group(f, keras_name: str):
    mw = f["model_weights"]
    if keras_name not in mw:
        return None
    g = mw[keras_name]
    # Keras nests again by layer name (e.g. model_weights/dense/dense/...)
    datasets: Dict[str, np.ndarray] = {}

    def visit(name, obj):
        import h5py

        if isinstance(obj, h5py.Dataset):
            datasets[name.split("/")[-1].split(":")[0]] = np.asarray(obj)

    g.visititems(visit)
    return datasets


def _load_weights(f, net, keras_names: List[str]):
    import jax.numpy as jnp

    # map keras layer names onto OUR parameterized layers in order
    param_layers = [(i, l) for i, l in enumerate(net.conf.layers)
                    if l.param_order()]
    pi = 0
    for name in keras_names:
        ws = _weight_group(f, name)
        if not ws:
            continue
        if pi >= len(param_layers):
            break
        idx, layer = param_layers[pi]
        tgt = net.params[str(idx)]
        cls = type(layer).__name__
        if "kernel" in ws and cls in ("DenseLayer", "OutputLayer",
                                      "ConvolutionLayer"):
            _check_and_set(tgt, "W", ws["kernel"])
            if "bias" in ws and "b" in tgt:
                _check_and_set(tgt, "b", ws["bias"])
        elif cls == "LSTM":
            u = layer.n_out
            _check_and_set(tgt, "W", _ifco_to_ifog(ws["kernel"], u))
            _check_and_set(tgt, "RW",
                           _ifco_to_ifog(ws["recurrent_kernel"], u))
            if "bias" in ws:
                _check_and_set(tgt, "b", _ifco_to_ifog(ws["bias"], u))
        elif cls == "BatchNormalization":
            n = tgt["gamma"].shape[0]
            # Keras BN with scale=False / center=False omits gamma/beta
            _check_and_set(tgt, "gamma",
                           ws.get("gamma", np.ones(n, np.float32)))
            _check_and_set(tgt, "beta",
                           ws.get("beta", np.zeros(n, np.float32)))
            st = net.state.get(str(idx), {})
            if "mean" in st:
                st["mean"] = jnp.asarray(ws["moving_mean"])
                st["var"] = jnp.asarray(ws["moving_variance"])
        elif cls == "EmbeddingSequenceLayer":
            key = "embeddings" if "embeddings" in ws else "kernel"
            _check_and_set(tgt, "W", ws[key])
        else:
            raise InvalidKerasConfigurationException(
                f"no weight mapping for layer {cls} <- keras '{name}'")
        pi += 1


def _check_and_set(tgt: dict, key: str, value: np.ndarray):
    import jax.numpy as jnp

    if key not in tgt:
        raise InvalidKerasConfigurationException(f"missing param {key}")
    if tuple(tgt[key].shape) != tuple(value.shape):
        raise InvalidKerasConfigurationException(
            f"shape mismatch for {key}: model {tuple(tgt[key].shape)} vs "
            f"h5 {tuple(value.shape)}")
    tgt[key] = jnp.asarray(value)


def _ifco_to_ifog(w: np.ndarray, units: int) -> np.ndarray:
    """Keras packs LSTM gates [i, f, c, o]; this framework packs
    [i, f, o, g(=c)] (layers_rnn.py gate order)."""
    i, f_, c, o = np.split(w, 4, axis=-1)
    return np.concatenate([i, f_, o, c], axis=-1)
