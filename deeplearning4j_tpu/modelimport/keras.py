"""Keras HDF5 model import.

Reference: ``deeplearning4j-modelimport`` —
``KerasModelImport#importKerasSequentialModelAndWeights`` (per-layer
``KerasLayer`` subclasses map configuration + weights into the DL4J config
DSL). Here the mapping targets the TPU config DSL; weight layouts line up
naturally (Keras kernels are [in, out] / HWIO, exactly this framework's
layouts — the reference has to transpose into its NCHW/ [out, in] forms).

Supports the Keras 2.x HDF5 format (``model_config`` JSON attribute +
``model_weights`` groups): Sequential and functional models with
InputLayer, Dense, Conv1D/2D/3D, Separable/DepthwiseConv2D, pooling and
global pooling, Flatten, Dropout, Activation, BatchNormalization,
ZeroPadding2D/Cropping2D/UpSampling2D, RepeatVector, Embedding,
SimpleRNN/LSTM/GRU (incl. ``go_backwards`` and GRU ``reset_after``), and
the Bidirectional wrapper (forward_*/backward_* weight groups ->
f/b-prefixed params). LSTM gates are re-packed from Keras' IFCO order
into this framework's IFOG; GRU's Z|R|H packing is shared.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.conf import InputType
from deeplearning4j_tpu.conf.activations import Activation as Act
from deeplearning4j_tpu.conf.layers import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingSequenceLayer,
    OutputLayer,
)
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    Cropping2D,
    GlobalPoolingLayer,
    PoolingType,
    SeparableConvolution2D,
    SubsamplingLayer,
    Upsampling2D,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.conf.layers_cnn import Convolution1DLayer
from deeplearning4j_tpu.conf.layers_extra import (
    Convolution3D,
    DepthwiseConvolution2D,
    Permute,
    RepeatVector,
)
from deeplearning4j_tpu.conf.layers_rnn import SimpleRnn
from deeplearning4j_tpu.conf.graph import (
    ElementWiseOp,
    ElementWiseVertex,
    MergeVertex,
)
from deeplearning4j_tpu.conf.layers_rnn import (
    GRU,
    LSTM,
    Bidirectional,
    BidirectionalMode,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT, LossMSE
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration

_ACTIVATIONS = {
    "linear": Act.IDENTITY, "relu": Act.RELU, "softmax": Act.SOFTMAX,
    "tanh": Act.TANH, "sigmoid": Act.SIGMOID, "elu": Act.ELU,
    "selu": Act.SELU, "softplus": Act.SOFTPLUS, "softsign": Act.SOFTSIGN,
    "swish": Act.SWISH, "gelu": Act.GELU, "hard_sigmoid": Act.HARDSIGMOID,
}


class InvalidKerasConfigurationException(ValueError):
    """Reference exception of the same name."""


def _act(name: Optional[str]) -> Act:
    if not name:
        return Act.IDENTITY
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise InvalidKerasConfigurationException(
            f"unsupported Keras activation '{name}' "
            f"(supported: {sorted(_ACTIVATIONS)})")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _mode(padding: str) -> ConvolutionMode:
    return (ConvolutionMode.SAME if padding == "same"
            else ConvolutionMode.TRUNCATE)


class KerasModelImport:
    """Static import API (reference class of the same name)."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str, enforce_training_config: bool = False):
        """-> initialized MultiLayerNetwork with the Keras weights."""
        import h5py

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with h5py.File(path, "r") as f:
            model_cfg = _read_model_config(f)
            if model_cfg.get("class_name") != "Sequential":
                raise InvalidKerasConfigurationException(
                    "only Sequential models supported here; use "
                    "import_keras_model_and_weights for functional models")
            layer_cfgs = model_cfg["config"]["layers"]
            conf, names = _build_conf(layer_cfgs)
            net = MultiLayerNetwork(conf)
            net.init()
            _load_weights(f, net, names)
        return net

    @staticmethod
    def import_keras_model_and_weights(
            path: str, enforce_training_config: bool = False):
        """Functional-model import -> initialized ComputationGraph
        (reference ``importKerasModelAndWeights``). Sequential files are
        dispatched to the sequential path."""
        import h5py

        from deeplearning4j_tpu.nn.graph import ComputationGraph

        with h5py.File(path, "r") as f:
            model_cfg = _read_model_config(f)
            if model_cfg.get("class_name") == "Sequential":
                pass  # fall through to the sequential path below
            elif model_cfg.get("class_name") not in ("Model", "Functional"):
                raise InvalidKerasConfigurationException(
                    f"unsupported model class "
                    f"'{model_cfg.get('class_name')}'")
            else:
                conf, names = _build_graph_conf(model_cfg["config"])
                net = ComputationGraph(conf)
                net.init()
                _load_graph_weights(f, net, names)
                return net
        return KerasModelImport.import_keras_sequential_model_and_weights(
            path, enforce_training_config)


def _read_model_config(f) -> dict:
    raw = f.attrs.get("model_config")
    if raw is None:
        raise InvalidKerasConfigurationException(
            "no model_config attribute — not a Keras HDF5 file "
            "saved with model.save()")
    if isinstance(raw, bytes):
        raw = raw.decode()
    return json.loads(raw)


def _input_type(first_cfg: dict):
    shape = (first_cfg.get("config", {}).get("batch_input_shape")
             or first_cfg.get("config", {}).get("batch_shape"))
    if shape is None:
        raise InvalidKerasConfigurationException(
            "first layer must carry batch_input_shape")
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(int(dims[0]))
    if len(dims) == 2:
        return InputType.recurrent(int(dims[1]), timesteps=int(dims[0] or -1))
    if len(dims) == 3:  # Keras default channels_last == our NHWC
        return InputType.convolutional(int(dims[0]), int(dims[1]),
                                       int(dims[2]))
    if len(dims) == 4:  # Conv3D: channels_last NDHWC
        return InputType.convolutional_3d(int(dims[0]), int(dims[1]),
                                          int(dims[2]), int(dims[3]))
    raise InvalidKerasConfigurationException(
        f"unsupported input rank {len(dims) + 1}")


def _map_layer(cls: str, cfg: dict, name: str, is_output: bool = False):
    """One Keras layer config -> one layer conf (or None for structural
    layers that vanish here: InputLayer/Flatten). Shared by the Sequential
    and functional paths."""
    if cls == "Dense":
        act = _act(cfg.get("activation"))
        if is_output and act is Act.SOFTMAX:
            return OutputLayer(n_out=int(cfg["units"]), activation=act,
                               loss_fn=LossMCXENT(), name=name)
        if is_output:
            return OutputLayer(n_out=int(cfg["units"]), activation=act,
                               loss_fn=LossMSE(), name=name)
        return DenseLayer(n_out=int(cfg["units"]), activation=act, name=name)
    if cls == "Conv2D":
        return ConvolutionLayer(
            n_out=int(cfg["filters"]),
            kernel_size=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", 1)),
            convolution_mode=_mode(cfg.get("padding", "valid")),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)), name=name)
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        return SubsamplingLayer(
            pooling_type=(PoolingType.MAX if cls == "MaxPooling2D"
                          else PoolingType.AVG),
            kernel_size=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            convolution_mode=_mode(cfg.get("padding", "valid")), name=name)
    if cls == "Dropout":
        return DropoutLayer(dropout=1.0 - float(cfg.get("rate", 0.0)),
                            name=name)
    if cls == "Activation":
        return ActivationLayer(activation=_act(cfg.get("activation")),
                               name=name)
    if cls == "BatchNormalization":
        return BatchNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                                  decay=float(cfg.get("momentum", 0.99)),
                                  name=name)
    if cls == "LSTM":
        if not cfg.get("return_sequences", False):
            raise InvalidKerasConfigurationException(
                "LSTM with return_sequences=False: wrap with "
                "LastTimeStep manually (not auto-mapped)")
        return LSTM(n_out=int(cfg["units"]),
                    activation=_act(cfg.get("activation", "tanh")),
                    gate_activation=_act(
                        cfg.get("recurrent_activation", "sigmoid")),
                    go_backwards=bool(cfg.get("go_backwards", False)),
                    name=name)
    if cls == "GRU":
        if not cfg.get("return_sequences", False):
            raise InvalidKerasConfigurationException(
                "GRU with return_sequences=False: wrap with "
                "LastTimeStep manually (not auto-mapped)")
        # reset_after absent = Keras <= 2.1 files, whose GRU math is
        # reset-BEFORE — default False (Keras >= 2.2 always writes the key)
        return GRU(n_out=int(cfg["units"]),
                   activation=_act(cfg.get("activation", "tanh")),
                   gate_activation=_act(
                       cfg.get("recurrent_activation", "sigmoid")),
                   reset_after=bool(cfg.get("reset_after", False)),
                   go_backwards=bool(cfg.get("go_backwards", False)),
                   name=name)
    if cls == "Bidirectional":
        inner_cfg = cfg.get("layer", {})
        # go_backwards=True inner layers import as-is (round 3): the
        # Bidirectional runtime applies Keras' exact composition (forward
        # copy processes reversed, backward copy is the flipped clone)
        inner = _map_layer(inner_cfg.get("class_name"),
                           dict(inner_cfg.get("config", {})),
                           name + "_inner")
        merge = {"concat": BidirectionalMode.CONCAT,
                 "sum": BidirectionalMode.ADD,
                 "ave": BidirectionalMode.AVERAGE,
                 "mul": BidirectionalMode.MUL}.get(
            cfg.get("merge_mode", "concat"))
        if merge is None:
            raise InvalidKerasConfigurationException(
                f"{name}: unsupported Bidirectional merge_mode "
                f"{cfg.get('merge_mode')!r}")
        return Bidirectional(layer=inner, mode=merge, name=name)
    if cls == "Embedding":
        return EmbeddingSequenceLayer(n_out=int(cfg["output_dim"]),
                                      n_in=int(cfg["input_dim"]), name=name)
    if cls == "GlobalAveragePooling2D":
        return GlobalPoolingLayer(pooling_type=PoolingType.AVG, name=name)
    if cls == "GlobalMaxPooling2D":
        return GlobalPoolingLayer(pooling_type=PoolingType.MAX, name=name)
    if cls == "SeparableConv2D":
        return SeparableConvolution2D(
            n_out=int(cfg["filters"]),
            kernel_size=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", 1)),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=_mode(cfg.get("padding", "valid")),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)), name=name)
    if cls == "DepthwiseConv2D":
        if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
            raise InvalidKerasConfigurationException(
                f"{name}: dilated DepthwiseConv2D not supported")
        return DepthwiseConvolution2D(
            kernel_size=_pair(cfg.get("kernel_size", 3)),
            stride=_pair(cfg.get("strides", 1)),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=_mode(cfg.get("padding", "valid")),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)), name=name)
    if cls == "UpSampling2D":
        if cfg.get("interpolation", "nearest") != "nearest":
            raise InvalidKerasConfigurationException(
                f"{name}: only nearest-neighbour UpSampling2D supported")
        return Upsampling2D(size=_pair(cfg.get("size", 2)), name=name)
    if cls == "ZeroPadding2D":
        (t, b), (l, r) = _pad_pairs(cfg.get("padding", 1))
        return ZeroPaddingLayer(padding=(t, b, l, r), name=name)
    if cls == "Cropping2D":
        (t, b), (l, r) = _pad_pairs(cfg.get("cropping", 0))
        return Cropping2D(cropping=(t, b, l, r), name=name)
    if cls == "SimpleRNN":
        if not cfg.get("return_sequences", False):
            raise InvalidKerasConfigurationException(
                "SimpleRNN with return_sequences=False: wrap with "
                "LastTimeStep manually (not auto-mapped)")
        return SimpleRnn(n_out=int(cfg["units"]),
                         activation=_act(cfg.get("activation", "tanh")),
                         go_backwards=bool(cfg.get("go_backwards", False)),
                         name=name)
    if cls == "Conv1D":
        one = lambda v: int(v[0] if isinstance(v, (list, tuple)) else v)  # noqa: E731
        if one(cfg.get("dilation_rate", 1)) != 1:
            raise InvalidKerasConfigurationException(
                f"{name}: dilated Conv1D not supported")
        if cfg.get("padding") == "causal":
            raise InvalidKerasConfigurationException(
                f"{name}: causal Conv1D padding not supported (pad the "
                "input explicitly)")
        return Convolution1DLayer(
            n_out=int(cfg["filters"]), kernel=one(cfg.get("kernel_size", 3)),
            stride1d=one(cfg.get("strides", 1)),
            convolution_mode=_mode(cfg.get("padding", "valid")),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)), name=name)
    if cls == "Conv3D":
        triple = (lambda v: tuple(int(x) for x in v)
                  if isinstance(v, (list, tuple)) else (int(v),) * 3)
        if triple(cfg.get("dilation_rate", 1)) != (1, 1, 1):
            raise InvalidKerasConfigurationException(
                f"{name}: dilated Conv3D not supported")
        return Convolution3D(
            n_out=int(cfg["filters"]),
            kernel_size=triple(cfg.get("kernel_size", 2)),
            stride=triple(cfg.get("strides", 1)),
            convolution_mode=_mode(cfg.get("padding", "valid")),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)), name=name)
    if cls == "RepeatVector":
        return RepeatVector(repetition_factor=int(cfg["n"]), name=name)
    if cls == "Permute":
        return Permute(dims=tuple(int(d) for d in cfg["dims"]), name=name)
    raise InvalidKerasConfigurationException(
        f"unsupported Keras layer class '{cls}'")


def _pad_pairs(v):
    """Keras 2D padding/cropping spec: int | (sym_h, sym_w) |
    ((t, b), (l, r)) -> ((t, b), (l, r))."""
    if isinstance(v, int):
        return (v, v), (v, v)
    a, b = v
    if isinstance(a, int):
        return (a, a), (b, b)
    return (int(a[0]), int(a[1])), (int(b[0]), int(b[1]))


def _inbound_names(layer_cfg: dict) -> List[str]:
    """Parse ``inbound_nodes`` (Keras 2.x nested-list format, plus the
    Keras 3 dict form) -> list of producer layer names."""
    nodes = layer_cfg.get("inbound_nodes") or []
    if not nodes:
        return []
    if len(nodes) > 1:
        raise InvalidKerasConfigurationException(
            f"layer '{layer_cfg.get('config', {}).get('name')}' is called "
            f"{len(nodes)} times (shared layer) — weight sharing across "
            "calls is not supported by this importer")
    node = nodes[0]
    names: List[str] = []
    if isinstance(node, dict):
        for a in node.get("args", []):
            items = a if isinstance(a, list) else [a]
            for item in items:
                hist = (item.get("config", {}).get("keras_history")
                        if isinstance(item, dict) else None)
                if hist:
                    names.append(hist[0])
        return names
    for item in node:
        names.append(item[0])
    return names


_MERGE_CLASSES = {
    "Add": ElementWiseOp.ADD, "Subtract": ElementWiseOp.SUBTRACT,
    "Multiply": ElementWiseOp.PRODUCT, "Average": ElementWiseOp.AVERAGE,
    "Maximum": ElementWiseOp.MAX,
}


def _build_graph_conf(config: dict):
    """Functional-model config -> (ComputationGraphConfiguration,
    [keras name in order] for weight loading). DAG wiring comes from
    ``inbound_nodes``; Flatten vanishes (the builder auto-inserts
    CnnToFeedForward preprocessors from input types)."""
    layer_cfgs = config["layers"]
    out_names = {o[0] if isinstance(o, list) else o
                 for o in config.get("output_layers", [])}

    # fold a terminal Activation into its preceding linear Dense (the
    # Dense(units) + Activation('softmax') idiom) so the scoring vertex is
    # an OutputLayer — mirrors the Sequential path's fold
    cfg_by_name: Dict[str, dict] = {}
    for i, lc in enumerate(layer_cfgs):
        n = lc.get("config", {}).get("name") or lc.get("name") or f"layer_{i}"
        cfg_by_name[n] = lc
    folded: Dict[str, str] = {}      # activation name -> dense name
    for out in list(out_names):
        lc = cfg_by_name.get(out)
        if lc is None or lc["class_name"] != "Activation":
            continue
        ins = _inbound_names(lc)
        prev = cfg_by_name.get(ins[0]) if len(ins) == 1 else None
        if (prev is not None and prev["class_name"] == "Dense"
                and prev["config"].get("activation") in (None, "linear")
                # the Dense must feed ONLY this Activation — folding would
                # change what any other consumer branch sees
                and sum(ins[0] in _inbound_names(c) for c in layer_cfgs) == 1
                and ins[0] not in out_names):
            prev["config"]["activation"] = lc["config"].get("activation")
            folded[out] = ins[0]
            out_names.discard(out)
            out_names.add(ins[0])

    b = (NeuralNetConfiguration.builder().seed(12345).graph_builder())
    alias: Dict[str, str] = {}   # structural layers forward their input
    param_names: List[str] = []
    input_type_of: Dict[str, object] = {}

    for i, lc in enumerate(layer_cfgs):
        cls = lc["class_name"]
        cfg = lc.get("config", {})
        name = cfg.get("name") or lc.get("name") or f"layer_{i}"
        inputs = [alias.get(n, n) for n in _inbound_names(lc)]
        if name in folded:
            alias[name] = inputs[0]
            continue
        if cls == "InputLayer":
            input_type_of[name] = _input_type(lc)
            continue
        if not inputs:
            raise InvalidKerasConfigurationException(
                f"layer '{name}' has no inbound nodes — not a functional "
                "model config")
        if cls == "Flatten":
            alias[name] = inputs[0]
            continue
        if cls == "Concatenate":
            if cfg.get("axis", -1) != -1:
                raise InvalidKerasConfigurationException(
                    "Concatenate: only axis=-1 (feature/channel) supported")
            b.add_vertex(name, MergeVertex(), *inputs)
            continue
        if cls in _MERGE_CLASSES:
            b.add_vertex(name, ElementWiseVertex(op=_MERGE_CLASSES[cls]),
                         *inputs)
            continue
        layer = _map_layer(cls, cfg, name, is_output=name in out_names)
        b.add_layer(name, layer, *inputs)
        param_names.append(name)

    # network input ORDER comes from config['input_layers'] (the order the
    # user passed to keras.Model(inputs=...)), not layer-list order
    in_order = [o[0] if isinstance(o, list) else o
                for o in config.get("input_layers", [])]
    if not in_order:
        in_order = list(input_type_of)
    unknown = [n for n in in_order if n not in input_type_of]
    if unknown:
        raise InvalidKerasConfigurationException(
            f"input_layers name {unknown} not found among InputLayer "
            "definitions")
    b.add_inputs(*in_order)
    b.set_input_types(*(input_type_of[n] for n in in_order))

    outputs = [alias.get(n, n) for n in
               (o[0] if isinstance(o, list) else o
                for o in config.get("output_layers", []))]
    if not outputs:
        raise InvalidKerasConfigurationException("no output_layers in config")
    b.set_outputs(*outputs)
    return b.build(), param_names


def _copy_layer_weights(tgt: dict, layer, ws: Dict[str, np.ndarray],
                        state: dict, keras_name: str):
    """Copy one Keras weight group into one layer's param dict (shared by
    the Sequential and functional loaders). ``state`` is the layer's
    mutable state dict (BN moving stats) — may be empty. ``ws`` keys are
    h5 paths; flattened to leaf names here (wrappers consume the paths)."""
    import jax.numpy as jnp

    cls = type(layer).__name__
    if cls == "Bidirectional":
        # keras nests forward_<name>/... and backward_<name>/... weight
        # groups; our param dict prefixes the inner keys with f/b
        def _is_backward(path: str) -> bool:
            return any(p.startswith("backward") for p in path.split("/"))

        fws = _leaves({k: v for k, v in ws.items() if not _is_backward(k)})
        bws = _leaves({k: v for k, v in ws.items() if _is_backward(k)})
        sub_f = {k[1:]: v for k, v in tgt.items() if k.startswith("f")}
        sub_b = {k[1:]: v for k, v in tgt.items() if k.startswith("b")}
        _copy_layer_weights(sub_f, layer.layer, fws, {},
                            keras_name + "/forward")
        _copy_layer_weights(sub_b, layer.layer, bws, {},
                            keras_name + "/backward")
        tgt.update({f"f{k}": v for k, v in sub_f.items()})
        tgt.update({f"b{k}": v for k, v in sub_b.items()})
        return
    ws = _leaves(ws)
    if "kernel" in ws and cls in ("DenseLayer", "OutputLayer",
                                  "ConvolutionLayer", "Convolution1DLayer",
                                  "Convolution3D"):
        _check_and_set(tgt, "W", ws["kernel"])
        if "bias" in ws and "b" in tgt:
            _check_and_set(tgt, "b", ws["bias"])
    elif cls == "LSTM":
        u = layer.n_out
        _check_and_set(tgt, "W", _ifco_to_ifog(ws["kernel"], u))
        _check_and_set(tgt, "RW", _ifco_to_ifog(ws["recurrent_kernel"], u))
        if "bias" in ws:
            _check_and_set(tgt, "b", _ifco_to_ifog(ws["bias"], u))
    elif cls == "BatchNormalization":
        n = tgt["gamma"].shape[0]
        # Keras BN with scale=False / center=False omits gamma/beta
        _check_and_set(tgt, "gamma", ws.get("gamma", np.ones(n, np.float32)))
        _check_and_set(tgt, "beta", ws.get("beta", np.zeros(n, np.float32)))
        if "mean" in state:
            state["mean"] = jnp.asarray(ws["moving_mean"])
            state["var"] = jnp.asarray(ws["moving_variance"])
    elif cls == "EmbeddingSequenceLayer":
        key = "embeddings" if "embeddings" in ws else "kernel"
        _check_and_set(tgt, "W", ws[key])
    elif cls == "SeparableConvolution2D":
        # Keras depthwise kernel [kh,kw,c,mult] -> grouped HWIO
        # [kh,kw,1,c*mult]; pointwise matches directly
        dk = ws["depthwise_kernel"]
        kh, kw, c, m = dk.shape
        _check_and_set(tgt, "dW", dk.reshape(kh, kw, 1, c * m))
        _check_and_set(tgt, "pW", ws["pointwise_kernel"])
        if "bias" in ws and "b" in tgt:
            _check_and_set(tgt, "b", ws["bias"])
    elif cls == "DepthwiseConvolution2D":
        dk = ws["depthwise_kernel"]
        kh, kw, c, m = dk.shape
        _check_and_set(tgt, "W", dk.reshape(kh, kw, 1, c * m))
        if "bias" in ws and "b" in tgt:
            _check_and_set(tgt, "b", ws["bias"])
    elif cls == "SimpleRnn":
        _check_and_set(tgt, "W", ws["kernel"])
        _check_and_set(tgt, "RW", ws["recurrent_kernel"])
        if "bias" in ws and "b" in tgt:
            _check_and_set(tgt, "b", ws["bias"])
    elif cls == "GRU":
        # keras packs z|r|h — identical to this framework's GRU layout
        _check_and_set(tgt, "W", ws["kernel"])
        _check_and_set(tgt, "RW", ws["recurrent_kernel"])
        if "bias" in ws:
            bias = ws["bias"]
            if bias.ndim == 2:  # reset_after: [2, 3u] = input/recurrent
                _check_and_set(tgt, "b", bias[0])
                _check_and_set(tgt, "rb", bias[1])
            else:
                _check_and_set(tgt, "b", bias)
    else:
        raise InvalidKerasConfigurationException(
            f"no weight mapping for layer {cls} <- keras '{keras_name}'")


def _load_graph_weights(f, net, keras_names: List[str]):
    """Copy Keras weight groups into ComputationGraph params (keyed by
    vertex name — identical to the Keras layer name here)."""
    for name in keras_names:
        ws = _weight_group(f, name)
        if not ws:
            continue
        if name not in (net.params or {}):
            raise InvalidKerasConfigurationException(
                f"h5 has weights for '{name}' but the graph has no "
                "parameterized vertex of that name")
        _copy_layer_weights(net.params[name], net._vmap[name].vertex.layer,
                            ws, net.state.get(name, {}), name)


def _build_conf(layer_cfgs: List[dict]):
    """-> (MultiLayerConfiguration, [keras_name in parameterized order])"""
    input_type = None
    mapped: List[Tuple[str, object]] = []  # (keras_name, layer_conf)
    pending_cfgs = list(layer_cfgs)

    for i, lc in enumerate(pending_cfgs):
        cls = lc["class_name"]
        cfg = lc.get("config", {})
        name = cfg.get("name", f"layer_{i}")
        if input_type is None and cls != "InputLayer":
            input_type = _input_type(lc)
        if cls == "InputLayer":
            input_type = _input_type(lc)
            continue
        if cls == "Flatten":
            # shape inference inserts CnnToFeedForwardPreProcessor; nothing
            # to add explicitly
            continue
        is_last = (cls == "Dense"
                   and all(c["class_name"] in ("Activation", "Dropout")
                           for c in pending_cfgs[i + 1:]))
        layer = _map_layer(cls, cfg, name, is_output=is_last)
        mapped.append((name, layer))

    # fold a trailing Activation into the preceding OutputLayer (the common
    # Keras idiom Dense(units) + Activation('softmax')) — the last layer
    # must be the scoring layer
    while (len(mapped) >= 2 and isinstance(mapped[-1][1], ActivationLayer)
           and isinstance(mapped[-2][1], OutputLayer)
           and mapped[-2][1].activation is Act.IDENTITY):
        act = mapped[-1][1].activation
        out = mapped[-2][1]
        out.activation = act
        if act is Act.SOFTMAX:
            out.loss_fn = LossMCXENT()
        mapped = mapped[:-1]

    if input_type is None:
        raise InvalidKerasConfigurationException("no input shape found")
    b = NeuralNetConfiguration.builder().seed(12345).list()
    for _, layer in mapped:
        b.layer(layer)
    b.set_input_type(input_type)
    conf = b.build()
    return conf, [n for n, _ in mapped]


def _weight_group(f, keras_name: str):
    """-> {relative_path: array} for the layer's weight group (paths keep
    the nesting so wrappers like Bidirectional can tell forward_*/
    backward_* apart; use :func:`_leaves` for leaf-name access)."""
    mw = f["model_weights"]
    if keras_name not in mw:
        return None
    g = mw[keras_name]
    datasets: Dict[str, np.ndarray] = {}

    def visit(name, obj):
        import h5py

        if isinstance(obj, h5py.Dataset):
            datasets[name] = np.asarray(obj)

    g.visititems(visit)
    return datasets


def _leaves(ws: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """{path: arr} -> {leaf_name_without_:0: arr} (Keras nests again by
    layer name, e.g. model_weights/dense/dense/kernel:0)."""
    return {k.split("/")[-1].split(":")[0]: v for k, v in ws.items()}


def _load_weights(f, net, keras_names: List[str]):
    # map keras layer names onto OUR parameterized layers in order
    param_layers = [(i, l) for i, l in enumerate(net.conf.layers)
                    if l.param_order()]
    pi = 0
    for name in keras_names:
        ws = _weight_group(f, name)
        if not ws:
            continue
        if pi >= len(param_layers):
            break
        idx, layer = param_layers[pi]
        _copy_layer_weights(net.params[str(idx)], layer, ws,
                            net.state.get(str(idx), {}), name)
        pi += 1


def _check_and_set(tgt: dict, key: str, value: np.ndarray):
    import jax.numpy as jnp

    if key not in tgt:
        raise InvalidKerasConfigurationException(f"missing param {key}")
    if tuple(tgt[key].shape) != tuple(value.shape):
        raise InvalidKerasConfigurationException(
            f"shape mismatch for {key}: model {tuple(tgt[key].shape)} vs "
            f"h5 {tuple(value.shape)}")
    tgt[key] = jnp.asarray(value)


def _ifco_to_ifog(w: np.ndarray, units: int) -> np.ndarray:
    """Keras packs LSTM gates [i, f, c, o]; this framework packs
    [i, f, o, g(=c)] (layers_rnn.py gate order)."""
    i, f_, c, o = np.split(w, 4, axis=-1)
    return np.concatenate([i, f_, o, c], axis=-1)
