"""Gradient post-processing + updater application — the solver step.

Reference: ``org.deeplearning4j.optimize.solvers.BaseOptimizer`` (gradient
normalization/clipping per layer conf) + ``org.deeplearning4j.nn.updater``
(``MultiLayerUpdater``/``UpdaterBlock`` grouping layers over the flat params
view, applying regularization then the layer's updater).

All pure functions composed inside the jitted train step — where the
reference's ``StochasticGradientDescent#optimize`` crosses JNI per update op,
this entire pipeline is one fused XLA program.
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.conf.layers import GradientNormalization


def normalize_layer_gradients(layer_conf, grads: dict) -> dict:
    """Apply the layer's GradientNormalization (reference
    ``BaseOptimizer#postProcessGradient``)."""
    # duck-typed (not isinstance BaseLayer): wrapper layers delegate these
    # attrs to their wrapped layer
    gn = getattr(layer_conf, "gradient_normalization", None)
    thr = getattr(layer_conf, "gradient_normalization_threshold", 1.0)
    if not grads or gn is None or gn is GradientNormalization.NONE:
        return grads
    if gn is GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return {k: g / (jnp.linalg.norm(g) + 1e-12) for k, g in grads.items()}
    if gn is GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + 1e-24)
        return {k: g / norm for k, g in grads.items()}
    if gn is GradientNormalization.CLIP_ELEMENTWISE_ABSOLUTE_VALUE:
        return {k: jnp.clip(g, -thr, thr) for k, g in grads.items()}
    if gn is GradientNormalization.CLIP_L2_PER_LAYER:
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + 1e-24)
        scale = jnp.minimum(1.0, thr / norm)
        return {k: g * scale for k, g in grads.items()}
    if gn is GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
        out = {}
        for k, g in grads.items():
            norm = jnp.linalg.norm(g) + 1e-12
            out[k] = g * jnp.minimum(1.0, thr / norm)
        return out
    raise ValueError(f"unhandled GradientNormalization {gn}")


def apply_updater_to_layer(layer_conf, updater, params: dict, grads: dict,
                           opt_state: dict, lr, t, epoch=0.0):
    """Regularization (before/after updater) + updater transform for ONE
    layer. Returns (new_params, new_opt_state).

    Reference flow (``UpdaterBlock#update``): L1/L2 added to gradient ->
    ``GradientUpdater#applyUpdater`` -> WeightDecay added to update ->
    ``params -= update``.
    """
    reg_w = tuple(getattr(layer_conf, "regularization", ()) or ())
    reg_b = tuple(getattr(layer_conf, "regularization_bias", ()) or ())
    reg_keys = set(layer_conf.regularized_param_keys())
    new_params, new_opt = {}, {}
    for k, p in params.items():
        g = grads[k]
        regs = reg_w if k in reg_keys else reg_b
        for r in regs:
            g = r.apply_before_updater(g, p, lr)
        upd, new_opt[k] = updater.update_leaf(g, opt_state[k], lr, t,
                                              epoch=epoch, param=p)
        for r in regs:
            upd = r.apply_after_updater(upd, p, lr)
        new_params[k] = p - upd
    return new_params, new_opt


def regularization_score(layers, params: dict):
    """Total regularization penalty added to the reported score (reference:
    ``BaseLayer#calcRegularizationScore``)."""
    total = 0.0
    for idx_str, layer_params in params.items():
        conf = layers[int(idx_str)]
        reg_keys = set(conf.regularized_param_keys())
        for k, p in layer_params.items():
            regs = (getattr(conf, "regularization", ()) if k in reg_keys
                    else getattr(conf, "regularization_bias", ()))
            for r in regs or ():
                total = total + r.score_term(p)
    return total
