"""AOT step-executable cache.

``jax.jit`` keeps a per-jit-object trace cache, so every model instance
that builds a fresh jitted step pays a full retrace+recompile even when an
identical network was compiled seconds ago — and a silent retrace (shape
drift, a rebuilt wrapper, a cloned model) is invisible until the step-time
spike shows up in a profile. This module makes compilation explicit and
shared:

- executables are keyed by ``(graph signature, step kind, input avals +
  shardings, donation set)`` and compiled ONCE per key via
  ``jit(...).lower(*args).compile()``;
- the key is process-global, so a cloned/re-instantiated model with the
  same configuration reuses the already-compiled executable instead of
  retracing;
- every dispatch records a hit or a miss, and misses record their compile
  seconds — surfaced through ``optimize.listeners.AotCacheStatsListener``
  and the ``ui.stats`` System tab, so "zero recompiles across repeated
  fit() calls" is an observable invariant instead of a hope.

The reference has no equivalent (each fit walks the op graph from Java
every iteration); this is the TPU-native hot-path contract: the ONLY
per-step host work is a cache lookup + one dispatch.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from typing import Callable, Optional

import numpy as np


class AotCacheStats:
    """Process-global counters (thread-safe; the async fit loops dispatch
    from one thread but listeners may read from another)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with getattr(self, "_lock", threading.Lock()):
            self.hits = 0
            self.misses = 0
            self.compile_seconds = 0.0
            self.entries = 0
            self.fallbacks = 0
            self.overflows = 0
            self.last_miss_key = None

    def record_hit(self):
        with self._lock:
            self.hits += 1

    def record_miss(self, key, seconds: float):
        with self._lock:
            self.misses += 1
            self.compile_seconds += float(seconds)
            self.entries += 1
            self.last_miss_key = key

    def record_fallback(self):
        with self._lock:
            self.fallbacks += 1

    def record_overflow(self):
        with self._lock:
            self.overflows += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "entries": self.entries,
                "compile_seconds": round(self.compile_seconds, 3),
                "fallbacks": self.fallbacks,
                "overflows": self.overflows,
            }


STATS = AotCacheStats()

# key -> compiled executable. Bounded: evicting a compiled XLA program to
# recompile it later is strictly worse than holding it, and a process that
# compiles >256 distinct step signatures has a retrace bug this cache
# exists to SURFACE (the stats keep counting either way).
_MAX_ENTRIES = 256
_EXECUTABLES: dict = {}
_LOCK = threading.Lock()


def stats() -> dict:
    """Current cache counters (the System-tab record)."""
    return STATS.snapshot()


def clear():
    """Drop every cached executable (tests; a long-lived server swapping
    model families can call this to release device programs). Identity
    pins are released with the entries they guarded."""
    with _LOCK:
        _EXECUTABLES.clear()
        _ID_PINNED.clear()
    STATS.reset()


_NAMED_SHARDING = None  # lazy: keep this module importable without jax


def _leaf_sig(x):
    # jax Arrays cache their aval — ~0.1us vs ~6us for .shape/.dtype
    # property chains; this function runs per leaf per step
    a = getattr(x, "aval", None)
    if a is not None:
        global _NAMED_SHARDING
        if _NAMED_SHARDING is None:
            from jax.sharding import NamedSharding

            _NAMED_SHARDING = NamedSharding
        # sharding-aware signature (sharding subsystem): a leaf committed
        # to a mesh with a NON-TRIVIAL PartitionSpec keys its spec, so a
        # ZeRO-scattered opt tree, a TP-split param and their replicated
        # twins can never alias one executable (identical avals,
        # different layouts). Replicated/single-device leaves — the
        # single-model hot path — stay (shape, dtype) at one isinstance
        # check of extra cost.
        sh = getattr(x, "sharding", None)
        if type(sh) is _NAMED_SHARDING:
            spec = sh.spec
            if any(e is not None for e in spec):
                return (a.shape, a.dtype, str(spec))
        return (a.shape, a.dtype)
    if isinstance(x, np.ndarray) or hasattr(x, "dtype"):
        return (np.shape(x), np.asarray(x).dtype if not hasattr(x, "dtype")
                else x.dtype)
    # python scalars are weak-typed under jit; keyed by type
    return type(x).__name__


def signature_of(args):
    """Hashable abstract signature of a call's arguments: per-leaf
    (shape, dtype) + the argument treedef (which encodes structure,
    including None-vs-array optional args). Built from cached avals —
    this runs on the per-step dispatch path, so it must stay ~0.1us per
    leaf. Mesh-committed leaves with a non-trivial ``PartitionSpec``
    additionally key the spec (see ``_leaf_sig``) — sharded wrapper
    steps (ZeRO, partition-rule plans) cache through here, and two
    placements of the same avals must compile separately. Exotic
    layout mismatches outside the signature still fall back to the
    plain jit (see AotStep.__call__)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (tuple(map(_leaf_sig, leaves)), treedef)


# objects keyed by identity are PINNED here so their id() can never be
# recycled by the allocator and collide with a later object's key while
# the (immortal) executable cache still holds entries under it
_ID_PINNED: list = []


def pin_id(obj) -> int:
    """-> id(obj), with obj kept alive for the life of the cache."""
    with _LOCK:  # clear() mutates the pin list under the same lock
        _ID_PINNED.append(obj)
    return id(obj)


def graph_signature(obj, fallback=None) -> str:
    """Stable content key for a model configuration: the sha1 of its repr
    when that repr is deterministic, else an identity key (two instances
    then never share — the safe direction; the keyed object is pinned so
    CPython address reuse cannot alias it). conf objects are nested
    dataclasses whose reprs embed every hyperparameter; reprs containing
    raw object addresses simply fail to match across instances."""
    try:
        r = repr(obj)
    except Exception:
        r = None
    # "..." = numpy's large-array elision: the repr no longer uniquely
    # identifies the config, so fall back to identity (never shares)
    if r and "..." not in r:
        return hashlib.sha1(r.encode()).hexdigest()
    return f"id:{pin_id(obj if fallback is None else fallback)}"


class WarmupBudgetExceeded(RuntimeError):
    """A compile requested under an exhausted :class:`WarmupBudget`
    scope was refused. Raised BEFORE the compile starts, so the budget
    bounds work, not just accounting."""


class WarmupBudget:
    """Per-tenant cap on warmup compilation (multi-tenant serving: one
    model's warmup storm — a huge bucket ladder, a conf churning graph
    keys — must not monopolize the host's compile bandwidth while its
    co-tenants wait to come up).

    Activate with :func:`warmup_budget`; while the scope is active on
    the current thread, every FRESH compile through the cache (warm()
    or a dispatch miss) is charged to the budget, and a compile that
    would start with the budget exhausted raises
    :class:`WarmupBudgetExceeded` instead. Cache hits are free — a
    tenant whose buckets are already compiled (same conf as a live
    version) warms at zero cost. Thread-local: live traffic on other
    threads never sees another tenant's budget.
    """

    def __init__(self, name: str, max_compiles: Optional[int] = None,
                 max_compile_seconds: Optional[float] = None):
        self.name = name
        self.max_compiles = max_compiles
        self.max_compile_seconds = max_compile_seconds
        self.compiles = 0
        self.compile_seconds = 0.0
        self._lock = threading.Lock()

    def allow(self) -> bool:
        """Whether another compile may start under this budget."""
        with self._lock:
            if self.max_compiles is not None \
                    and self.compiles >= self.max_compiles:
                return False
            if self.max_compile_seconds is not None \
                    and self.compile_seconds >= self.max_compile_seconds:
                return False
            return True

    def charge(self, seconds: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compile_seconds += float(seconds)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 3),
                "max_compiles": self.max_compiles,
                "max_compile_seconds": self.max_compile_seconds,
            }


_BUDGET_SCOPE = threading.local()


def active_budget() -> Optional[WarmupBudget]:
    """The :class:`WarmupBudget` active on this thread (or None)."""
    return getattr(_BUDGET_SCOPE, "active", None)


@contextlib.contextmanager
def warmup_budget(budget: WarmupBudget):
    """Scope ``budget`` over this thread's compiles (nesting restores
    the outer scope on exit)."""
    prev = active_budget()
    _BUDGET_SCOPE.active = budget
    try:
        yield budget
    finally:
        _BUDGET_SCOPE.active = prev


# the compile-time program linter (analysis.program.on_compile), bound
# lazily on the first miss so importing this module never imports the
# analysis package; DL4J_TPU_PROGRAM_LINT=0 leaves it unbound
_LINT_HOOK = None
_LINT_INIT = False


def _program_lint(key, traced, exe) -> None:
    """Run the program linter over one fresh compile (caller holds
    ``_LOCK``). Lint failures never break a compile — except in strict
    mode, where ProgramLintError is the point."""
    global _LINT_HOOK, _LINT_INIT
    if not _LINT_INIT:
        _LINT_INIT = True
        import os

        if os.environ.get("DL4J_TPU_PROGRAM_LINT", "1") != "0":
            try:
                from deeplearning4j_tpu.analysis import program

                _LINT_HOOK = program.on_compile
            except Exception:
                _LINT_HOOK = None
    if _LINT_HOOK is None:
        return
    try:
        siblings = [k for k in _EXECUTABLES
                    if k[:2] == key[:2] and k != key]
        _LINT_HOOK(key, traced, exe, siblings)
    except Exception as e:
        if type(e).__name__ == "ProgramLintError":
            raise  # strict mode: surface the findings to the caller
        # any other lint crash must never take down a working compile


class AotStep:
    """A jitted step behind the executable cache.

    Call it exactly like the wrapped jit. The first call for a given
    input signature lowers + compiles (a recorded miss); every later call
    with the same signature — from this model instance or any other that
    shares the graph key — dispatches the cached executable (a hit).
    ``donate_argnums`` must be baked into ``jit_fn``; it is part of the
    key via ``fn_key`` so differently-donating wrappers never collide.
    """

    def __init__(self, jit_fn: Callable, graph_key: str, fn_key: str):
        self._jit = jit_fn
        self._key = (graph_key, fn_key)

    def _compile_locked(self, key, args):
        """Shared miss path (caller holds ``_LOCK``): returns
        ``(executable_or_None, newly_compiled)``. ``None`` means the
        cache is at ``_MAX_ENTRIES`` (a recorded overflow) — the caller
        falls back to the plain jit, whose own trace cache amortizes the
        signature; re-AOT-compiling per call would turn an evicted key
        into a compile-per-step pathology."""
        exe = _EXECUTABLES.get(key)
        if exe is not None:
            return exe, False
        if len(_EXECUTABLES) >= _MAX_ENTRIES:
            STATS.record_overflow()
            return None, False
        budget = active_budget()
        if budget is not None and not budget.allow():
            # refused BEFORE compiling: the budget bounds the work. Only
            # the budget-holder's own thread (a tenant warming up under
            # warmup_budget()) can land here — live traffic on other
            # threads compiles unbudgeted as always.
            raise WarmupBudgetExceeded(
                f"warmup budget {budget.name!r} exhausted "
                f"({budget.compiles} compiles, "
                f"{budget.compile_seconds:.2f}s) — refusing to compile "
                f"{key[1]}")
        t0 = time.perf_counter()
        # trace and lower as separate stages when this jax supports it:
        # .lower() runs the same trace internally, but splitting keeps
        # the jaxpr available for the program linter at zero extra cost
        traced = None
        trace = getattr(self._jit, "trace", None)
        if trace is not None:
            try:
                traced = trace(*args)
            except Exception:
                traced = None
        lowered = (traced.lower() if traced is not None
                   else self._jit.lower(*args))
        exe = lowered.compile()
        seconds = time.perf_counter() - t0
        STATS.record_miss(key, seconds)
        if budget is not None:
            budget.charge(seconds)
        _EXECUTABLES[key] = exe
        _program_lint(key, traced, exe)
        return exe, True

    def __call__(self, *args):
        key = self._key + (signature_of(args),)
        exe = _EXECUTABLES.get(key)
        if exe is None:
            with _LOCK:
                exe, _ = self._compile_locked(key, args)
            if exe is None:
                return self._jit(*args)
            return exe(*args)
        try:
            out = exe(*args)
        except (TypeError, ValueError):
            # an input property outside the signature (committed mesh
            # sharding, exotic layout) diverged from the lowering — the
            # plain jit handles it (and compiles its own specialization).
            # Counted separately so the stats don't report a silent
            # retrace as a hit.
            STATS.record_fallback()
            return self._jit(*args)
        STATS.record_hit()
        return out

    def warm(self, *args) -> bool:
        """Compile-and-cache this signature WITHOUT dispatching — bucket
        warmup for serving engines (``parallel.batcher``): pre-compiling
        every padding bucket at server start costs compile time only, no
        device execution. Returns True when a new executable was compiled
        (a recorded miss), False when it was already cached (or the cache
        is full, a recorded overflow)."""
        key = self._key + (signature_of(args),)
        with _LOCK:
            _, compiled = self._compile_locked(key, args)
        return compiled

    # escape hatches for probes that want the raw jit (bench scripts call
    # .lower() for memory analysis)
    def lower(self, *args):
        return self._jit.lower(*args)

    @property
    def jit_fn(self):
        return self._jit


def wrap(jit_fn: Callable, graph_key: str, fn_key: str,
         enabled: Optional[bool] = None) -> Callable:
    """Wrap a jitted step in the AOT cache. ``enabled=False`` returns the
    jit untouched (env kill-switch honored when ``enabled`` is None)."""
    import os

    if enabled is None:
        enabled = os.environ.get("DL4J_TPU_AOT_CACHE", "1") != "0"
    return AotStep(jit_fn, graph_key, fn_key) if enabled else jit_fn
