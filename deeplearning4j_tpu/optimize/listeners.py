"""Training listeners.

Reference: ``org.deeplearning4j.optimize.api.TrainingListener`` SPI and impls
in ``org.deeplearning4j.optimize.listeners`` (`ScoreIterationListener`,
`PerformanceListener`, `EvaluativeListener`, `TimeIterationListener`,
`CollectScoresListener`, `CheckpointListener`).

Per SURVEY.md §5.1/§5.5 the listener SPI survives the rebuild; it is fed
step-level numbers (per-op timing is meaningless under XLA fusion).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, List, Optional


class TrainingListener:
    """SPI (reference ``TrainingListener``)."""

    def iteration_done(self, model, iteration: int, epoch: int,
                       score: float) -> None:
        pass

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Print score every N iterations (reference ``ScoreIterationListener``)."""

    def __init__(self, print_iterations: int = 10, stream=None):
        self.print_iterations = max(1, print_iterations)
        self.stream = stream or sys.stdout

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.print_iterations == 0:
            print(f"Score at iteration {iteration} is {score}", file=self.stream)


class PerformanceListener(TrainingListener):
    """Batches/sec + examples/sec (reference ``PerformanceListener``).

    The timing window RESETS at every epoch start: a listener kept across
    ``fit()`` calls would otherwise carry the previous fit's last
    timestamp into the new run, and the first report after a refit would
    average the idle wall-clock between fits into its rate (arbitrarily
    low examples/sec after a pause). ``on_epoch_start`` fires at the top
    of every fit epoch, so each run re-primes cleanly."""

    def __init__(self, frequency: int = 10, report_batch: bool = True,
                 stream=None):
        self.frequency = max(1, frequency)
        self.report_batch = report_batch
        self.stream = stream or sys.stdout
        self._last_time = None
        self._last_iter = None
        self.last_batches_per_sec: Optional[float] = None
        self.last_examples_per_sec: Optional[float] = None

    def on_epoch_start(self, model, epoch):
        # a fresh fit (or epoch) must not rate against the previous one's
        # final timestamp — re-prime on the first iteration instead
        self._last_time = None
        self._last_iter = None

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        if self._last_time is not None and iteration % self.frequency == 0:
            iters = iteration - self._last_iter
            dt = now - self._last_time
            if dt > 0 and iters > 0:
                bps = iters / dt
                self.last_batches_per_sec = bps
                batch = getattr(model, "last_batch_size", None)
                msg = f"batches/sec: {bps:.2f}"
                if batch and self.report_batch:
                    self.last_examples_per_sec = bps * batch
                    msg += f", examples/sec: {self.last_examples_per_sec:.2f}"
                print(msg, file=self.stream)
            self._last_time = now
            self._last_iter = iteration
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration


class CollectScoresListener(TrainingListener):
    """Accumulate (iteration, score) pairs (reference
    ``CollectScoresListener``)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.iterations: List[int] = []
        self.scores: List[float] = []

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency == 0:
            self.iterations.append(iteration)
            self.scores.append(float(score))


class TimeIterationListener(TrainingListener):
    """ETA printout (reference ``TimeIterationListener``)."""

    def __init__(self, total_iterations: int, frequency: int = 50, stream=None):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self.stream = stream or sys.stdout
        self._start = None

    def iteration_done(self, model, iteration, epoch, score):
        if self._start is None:
            self._start = time.perf_counter()
            return
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = elapsed / iteration
            remaining = (self.total - iteration) * rate
            print(f"Remaining time estimate: {remaining:.1f}s "
                  f"(iteration {iteration}/{self.total})", file=self.stream)


class AotCacheStatsListener(TrainingListener):
    """Report the AOT step-executable cache (optimize.aot_cache) every N
    iterations: hits / misses / cached entries / cumulative compile
    seconds — the observable form of "zero recompiles across repeated
    fit() calls". A miss after warmup means a silent retrace (shape
    drift, a rebuilt step) that would otherwise only show up as an
    unexplained step-time spike. ``history`` keeps the per-collection
    snapshots for programmatic checks (tests, dashboards)."""

    def __init__(self, frequency: int = 10, stream=None,
                 print_stats: bool = True):
        self.frequency = max(1, int(frequency))
        self.stream = stream or sys.stdout
        self.print_stats = bool(print_stats)
        self.history: List[dict] = []
        self._last = None

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency:
            return
        from deeplearning4j_tpu.optimize import aot_cache

        snap = aot_cache.stats()
        snap["iteration"] = int(iteration)
        self.history.append(snap)
        if self.print_stats:
            delta_miss = (snap["misses"] - self._last["misses"]
                          if self._last else snap["misses"])
            msg = (f"[aot-cache] iter {iteration}: {snap['hits']} hits, "
                   f"{snap['misses']} misses ({snap['entries']} "
                   f"executables, {snap['compile_seconds']:.2f}s compile)")
            if self._last and delta_miss:
                msg += f" — {delta_miss} NEW compile(s) since last report"
            if snap.get("fallbacks"):
                msg += (f" — {snap['fallbacks']} sharding/layout "
                        "fallback(s) to plain jit")
            print(msg, file=self.stream)
        self._last = snap


class HealthListener(TrainingListener):
    """Surface the training-health monitor (``telemetry.health``) through
    the listener SPI: every N iterations flush the monitor's lazily
    queued guard vectors and report counts + the latest gradient norm /
    update:param ratio. A report line prints only when something changed
    (``print_all=True`` prints every collection). ``history`` keeps the
    per-collection reports for programmatic checks."""

    def __init__(self, frequency: int = 10, stream=None,
                 print_all: bool = False):
        self.frequency = max(1, int(frequency))
        self.stream = stream or sys.stdout
        self.print_all = bool(print_all)
        self.history: List[dict] = []
        self._last_nonfinite = 0

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency:
            return
        from deeplearning4j_tpu.telemetry import health

        rep = health.report()
        rep["iteration"] = int(iteration)
        self.history.append(rep)
        new_bad = rep["nonfinite_steps"] - self._last_nonfinite
        self._last_nonfinite = rep["nonfinite_steps"]
        if new_bad or self.print_all:
            last = rep.get("last") or {}
            msg = (f"[health] iter {iteration}: status={rep['status']}, "
                   f"{rep['nonfinite_steps']} non-finite step(s)")
            if rep["skipped_steps"]:
                msg += f", {rep['skipped_steps']} skipped"
            if rep["rollbacks"]:
                msg += f", {rep['rollbacks']} rollback(s)"
            if last:
                msg += (f", grad_norm={last['grad_norm']:.4g}, "
                        f"update:param={last['update_param_ratio']:.3g}")
            print(msg, file=self.stream)


class EvaluativeListener(TrainingListener):
    """Periodic evaluation during fit (reference ``EvaluativeListener``)."""

    def __init__(self, iterator, frequency: int = 1,
                 unit: str = "epoch",
                 evaluation_factory: Optional[Callable] = None,
                 stream=None):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.unit = unit
        self.evaluation_factory = evaluation_factory
        self.stream = stream or sys.stdout
        self.last_evaluation = None

    def _run(self, model):
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        factory = self.evaluation_factory or Evaluation
        self.last_evaluation = model.evaluate(self.iterator,
                                              evaluation=factory())
        acc = getattr(self.last_evaluation, "accuracy", None)
        if callable(acc):
            print(f"[EvaluativeListener] accuracy: {acc():.4f}",
                  file=self.stream)

    def iteration_done(self, model, iteration, epoch, score):
        if self.unit == "iteration" and iteration % self.frequency == 0:
            self._run(model)

    def on_epoch_end(self, model, epoch):
        if self.unit == "epoch" and (epoch + 1) % self.frequency == 0:
            self._run(model)
