"""Checkpointing.

Reference: ``org.deeplearning4j.optimize.listeners.CheckpointListener`` —
periodic model zips (every N iterations / epochs / minutes) with retention
(keep-last-N / keep-every-N), plus static load helpers; checkpoint format is
``ModelSerializer``'s zip (config + params + updater state), so resume is
exact (SURVEY.md §5.4).
"""

from __future__ import annotations

import csv
import os
import time
from typing import List, Optional

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.util import serializer


def snapshot_training_state(model) -> dict:
    """Host copy of everything an exact in-process resume needs:
    params + layer state + updater state + counters. The copies are
    numpy (``np.asarray`` syncs on the device values), so a later donated
    step can never invalidate the snapshot — this is what the health
    layer's ROLLBACK policy restores from.

    Sharding-aware: while a parallel wrapper owns the live training
    trees (ZeRO-scattered opt state, TP-sharded params), they are
    gathered back onto the model first through the ``_live_trainer``
    hook — the snapshot is always full host arrays, restorable onto any
    mesh (wrapper-level rollback uses the wrapper's own device-copy
    hooks instead; this path serves model-level callers)."""
    live = getattr(model, "_live_trainer", None)
    trainer = live() if live is not None else None
    if trainer is not None:
        trainer.sync_model()

    # host_gather: bitwise np.asarray for fully-addressable leaves, and
    # the compiled cross-host replicate for pod-spanning trees — the
    # snapshot is full host arrays at any process count
    from deeplearning4j_tpu.parallel import mesh as mesh_mod

    host = mesh_mod.host_gather
    return {
        "params": host(model.params),
        "state": host(model.state),
        "opt_state": host(model.opt_state),
        "iteration": int(model.iteration),
        "epoch": int(model.epoch),
    }


def restore_training_state(model, snap: dict) -> None:
    """Inverse of :func:`snapshot_training_state`: re-stage the snapshot
    onto the model (fresh device copies — the snapshot stays valid for
    repeated rollbacks). Counters rewind too, so LR schedules and RNG
    folds replay exactly."""
    import jax
    import jax.numpy as jnp

    dev = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda x: jnp.asarray(x), t)
    model.params = dev(snap["params"])
    model.state = dev(snap["state"])
    model.opt_state = dev(snap["opt_state"])
    model.iteration = int(snap["iteration"])
    model.epoch = int(snap["epoch"])
    # invalidate the lazy score (it reflects the rolled-back step)
    if hasattr(model, "_score_dev"):
        model._score_dev = None
        model._score_cache = None


class Checkpoint:
    """One row of checkpoint.csv metadata (reference ``Checkpoint``).
    ``digest`` (sha256 of the zip, recorded at save time) is empty for
    rows written before the integrity column existed — those load
    unverified, exactly as they always did."""

    def __init__(self, number: int, timestamp: float, iteration: int,
                 epoch: int, filename: str, digest: str = ""):
        self.number = int(number)
        self.timestamp = float(timestamp)
        self.iteration = int(iteration)
        self.epoch = int(epoch)
        self.filename = filename
        self.digest = digest


class CheckpointListener(TrainingListener):
    """Save-every-N listener with retention (reference
    ``CheckpointListener.Builder``)::

        CheckpointListener(dir, save_every_n_epochs=1, keep_last=3)
        CheckpointListener(dir, save_every_n_iterations=500, keep_mod=5)

    ``keep_last``: only the newest N zips survive; ``keep_mod``: every
    ``keep_mod``-th checkpoint is additionally kept forever (reference
    ``keepLastAndEvery``). Default keeps everything.
    """

    def __init__(self, directory: str,
                 save_every_n_epochs: Optional[int] = None,
                 save_every_n_iterations: Optional[int] = None,
                 save_every_n_seconds: Optional[float] = None,
                 keep_last: Optional[int] = None,
                 keep_mod: Optional[int] = None,
                 delete_existing: bool = False):
        if not any((save_every_n_epochs, save_every_n_iterations,
                    save_every_n_seconds)):
            raise ValueError("configure at least one save frequency")
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._csv = os.path.join(self.directory, "checkpoint.csv")
        if delete_existing:
            for c in self.list_checkpoints():
                p = os.path.join(self.directory, c.filename)
                if os.path.exists(p):
                    os.remove(p)
            if os.path.exists(self._csv):
                os.remove(self._csv)
        self.every_epochs = save_every_n_epochs
        self.every_iters = save_every_n_iterations
        self.every_seconds = save_every_n_seconds
        self.keep_last = keep_last
        self.keep_mod = keep_mod
        self._last_save_time = time.monotonic()
        rows = self._read_rows()
        self._count = (max(c.number for c in rows) + 1) if rows else 0

    # --- listener hooks -----------------------------------------------------
    def iteration_done(self, model, iteration, epoch, score):
        if self.every_iters and (iteration + 1) % self.every_iters == 0:
            self._save(model, iteration, epoch)
        elif (self.every_seconds
              and time.monotonic() - self._last_save_time
              >= self.every_seconds):
            self._save(model, iteration, epoch)

    def on_epoch_end(self, model, epoch):
        if self.every_epochs and (epoch + 1) % self.every_epochs == 0:
            self._save(model, getattr(model, "iteration", -1), epoch)

    # --- mechanics ----------------------------------------------------------
    def _save(self, model, iteration, epoch):
        from deeplearning4j_tpu.resilience.retry import CHECKPOINT_RETRY

        num = self._count
        self._count += 1
        fname = f"checkpoint_{num}_iter_{iteration}_epoch_{epoch}.zip"
        path = os.path.join(self.directory, fname)
        # retried: a transient ENOSPC/EINTR mid-save costs a backoff, not
        # the checkpoint (write_model cleans its temp file per attempt)
        CHECKPOINT_RETRY.call(serializer.write_model, model, path,
                              op="checkpoint.write")
        # digest recorded AFTER the atomic publish: checkpoint.csv only
        # ever references fully-written zips, with the content hash load
        # verifies against
        new_row = Checkpoint(num, time.time(), iteration, epoch, fname,
                             serializer.file_digest(path))
        rows = self._read_rows() + [new_row]
        # atomic rewrite: a crash mid-write must never truncate the
        # numbering authority (same temp+replace scheme as write_model)
        tmp = f"{self._csv}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", newline="") as f:
                w = csv.writer(f)
                for c in rows:
                    w.writerow([c.number, c.timestamp, c.iteration,
                                c.epoch, c.filename, c.digest])
            os.replace(tmp, self._csv)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        self._last_save_time = time.monotonic()
        self._apply_retention(rows)

    def _apply_retention(self, rows: List[Checkpoint]):
        if self.keep_last is None:
            return
        rows = [c for c in rows if os.path.exists(
            os.path.join(self.directory, c.filename))]
        keep = {c.number for c in rows[-self.keep_last:]}
        if self.keep_mod:
            keep |= {c.number for c in rows if c.number % self.keep_mod == 0}
        for c in rows:
            if c.number not in keep:
                p = os.path.join(self.directory, c.filename)
                if os.path.exists(p):
                    os.remove(p)

    # --- static API (reference's static helpers) ----------------------------
    def _read_rows(self) -> List[Checkpoint]:
        """All rows ever written (including retention-deleted) — the
        numbering authority."""
        if not os.path.exists(self._csv):
            return []
        out = []
        with open(self._csv, newline="") as f:
            for row in csv.reader(f):
                if row:
                    out.append(Checkpoint(*row))
        return out

    def list_checkpoints(self) -> List[Checkpoint]:
        # only checkpoints whose zip still exists (retention-aware)
        return [c for c in self._read_rows() if os.path.exists(
            os.path.join(self.directory, c.filename))]

    def last_checkpoint(self) -> Optional[Checkpoint]:
        cps = self.list_checkpoints()
        return cps[-1] if cps else None

    def verify(self, cp: Checkpoint) -> bool:
        """Whether ``cp``'s zip matches the content digest recorded at
        save time (rows from before the digest column pass unverified)."""
        path = os.path.join(self.directory, cp.filename)
        if not os.path.exists(path):
            return False
        if not cp.digest:
            return True
        return serializer.file_digest(path) == cp.digest

    def _restore_chain(self, number, restore_fn):
        """Digest-verified restore with last-good fallback
        (``serializer.restore_newest_verified``). An explicit ``number``
        disables the fallback (the caller asked for exactly that state;
        silently handing back a different one would be wrong)."""
        cps = self.list_checkpoints()
        if not cps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if number is not None:
            cp = next(c for c in cps if c.number == number)
            if not self.verify(cp):
                raise OSError(
                    f"checkpoint {cp.filename} failed digest verification")
            return restore_fn(os.path.join(self.directory, cp.filename))
        restored, _, last_err = serializer.restore_newest_verified(
            [(os.path.join(self.directory, c.filename), c.digest)
             for c in cps], restore_fn)
        if restored is None:
            raise FileNotFoundError(
                f"no loadable checkpoint in {self.directory} "
                f"({len(cps)} present, all corrupt/truncated)") \
                from last_err
        return restored

    def load_checkpoint(self, number: Optional[int] = None):
        """Restore a MultiLayerNetwork from checkpoint ``number`` (default:
        newest that passes digest verification and loads)."""
        return self._restore_chain(
            number, serializer.restore_multi_layer_network)

    def load_checkpoint_graph(self, number: Optional[int] = None):
        return self._restore_chain(
            number, serializer.restore_computation_graph)


class AsyncCheckpointListener(TrainingListener):
    """Orbax-backed ASYNC checkpointing (SURVEY.md §5.4's optional
    strengthening): saves (params, state, opt_state) pytrees in a background
    thread so the training loop never blocks on serialization; the model
    config JSON sits alongside for reconstruction. Retention via Orbax's
    ``max_to_keep``."""

    def __init__(self, directory: str, save_every_n_iterations: int = 100,
                 max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.every = int(save_every_n_iterations)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=int(max_to_keep), enable_async_checkpointing=True))
        self._conf_written = False

    def iteration_done(self, model, iteration, epoch, score):
        import orbax.checkpoint as ocp

        if (iteration + 1) % self.every:
            return
        if not self._conf_written:
            from deeplearning4j_tpu import serde

            with open(os.path.join(self.directory, "configuration.json"),
                      "w") as f:
                f.write(serde.to_json(model.conf))
            self._conf_written = True
        items = {"params": ocp.args.StandardSave(model.params),
                 "opt_state": ocp.args.StandardSave(model.opt_state),
                 # exact-resume counters: at listener time model.iteration
                 # is uniformly the NEXT iteration to run (both the
                 # fit_batch and tBPTT paths), so restore uses it verbatim
                 "meta": ocp.args.JsonSave({
                     "iteration": int(model.iteration),
                     "epoch": int(model.epoch)})}
        if model.state:  # orbax rejects empty pytrees
            items["state"] = ocp.args.StandardSave(model.state)
        self._mgr.save(iteration, args=ocp.args.Composite(**items))

    def wait(self):
        """Block until pending async saves complete (call before exit)."""
        self._mgr.wait_until_finished()
        return self

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore_latest(self):
        """-> reconstructed network at the newest step (exact resume,
        updater state included)."""
        import orbax.checkpoint as ocp

        from deeplearning4j_tpu import serde

        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no orbax checkpoints in "
                                    f"{self.directory}")
        with open(os.path.join(self.directory, "configuration.json")) as f:
            conf = serde.from_json(f.read())
        if type(conf).__name__ == "ComputationGraphConfiguration":
            from deeplearning4j_tpu.nn.graph import ComputationGraph

            net = ComputationGraph(conf)
        else:
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            net = MultiLayerNetwork(conf)
        net.init()
        items = {"params": ocp.args.StandardRestore(net.params),
                 "opt_state": ocp.args.StandardRestore(net.opt_state),
                 "meta": ocp.args.JsonRestore()}
        if net.state:
            items["state"] = ocp.args.StandardRestore(net.state)
        restored = self._mgr.restore(step,
                                     args=ocp.args.Composite(**items))
        net.params = restored["params"]
        if net.state:
            net.state = restored["state"]
        net.opt_state = restored["opt_state"]
        meta = restored["meta"] or {}
        net.iteration = int(meta.get("iteration", int(step) + 1))
        net.epoch = int(meta.get("epoch", 0))
        return net
