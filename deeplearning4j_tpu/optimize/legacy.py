"""Legacy full-batch solvers (reference ``org.deeplearning4j.optimize.
solvers``: ``LineGradientDescent``, ``ConjugateGradient``, ``LBFGS`` —
SURVEY.md §2.2 "Solver/optimizers (DL4J level)").

TPU-native design: each solver's ENTIRE optimize loop — search direction,
backtracking (Armijo) line search, L-BFGS two-loop recursion over
fixed-size circular history buffers — is one ``lax.while_loop`` compiled
around the model's full-batch loss-of-flat-params function. The reference
iterates these in Java with one JNI round-trip per op; here the loop never
leaves the device.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util import params as params_util


def _flat_loss_fn(net, ds):
    """-> (pure f(flat)->loss, flat0) for either network class.

    The loss is evaluated in EVAL mode (train=False): line searches need a
    deterministic objective (dropout would break the Armijo condition) and
    BatchNorm must use the same running stats the final ``score``/
    ``output`` will — optimizing batch stats while never updating running
    stats would report a loss the saved model can't reproduce."""
    if hasattr(net, "_batch_arrays"):        # MultiLayerNetwork
        features, labels, fmask, lmask = net._batch_arrays(ds)
        conf, like, state = net.conf, net.params, net.state

        def f(flat):
            p = params_util.unflatten_params(conf, flat, like)
            loss, _ = net._loss(p, state, features, labels, fmask, lmask,
                                None, train=False)
            return loss
    else:                                     # ComputationGraph
        features, labels, fmasks, lmasks = net._prep_batch(ds)
        conf, like, state = net.conf, net.params, net.state

        def f(flat):
            p = params_util.unflatten_params(conf, flat, like)
            loss, _ = net._loss(p, state, features, labels, fmasks,
                                lmasks, rng=None, train=False)
            return loss
    return f, jnp.asarray(net.params_flat())


def _line_search(f, x, d, loss, g, step0, c1=1e-4, max_halvings=20):
    """Backtracking Armijo search along ``d`` (reference
    ``BackTrackLineSearch``). Returns (alpha, new_loss)."""
    slope = jnp.vdot(g, d)

    def cond(st):
        alpha, cur, halvings = st
        return jnp.logical_and(halvings < max_halvings,
                               cur > loss + c1 * alpha * slope)

    def body(st):
        alpha, _, halvings = st
        alpha = alpha * 0.5
        return alpha, f(x + alpha * d), halvings + 1

    alpha0 = jnp.asarray(step0, x.dtype)
    alpha, new_loss, _ = jax.lax.while_loop(
        cond, body, (alpha0, f(x + alpha0 * d), jnp.asarray(0)))
    # a failed search (still above the Armijo bound) must not move uphill
    take = new_loss <= loss
    return jnp.where(take, alpha, 0.0), jnp.where(take, new_loss, loss)


@dataclasses.dataclass
class _BaseLegacySolver:
    """Shared optimize() driver: minimize the full-batch loss, write the
    result back through ``set_params_flat``."""

    max_iterations: int = 100
    tolerance: float = 1e-6
    step_size: float = 1.0

    def optimize(self, net, ds):
        f, x0 = _flat_loss_fn(net, ds)
        x, loss = self._minimize(f, x0)
        net.set_params_flat(np.asarray(x))
        return float(loss)


class LineGradientDescent(_BaseLegacySolver):
    """Steepest descent + line search (reference class of the same name)."""

    def _minimize(self, f, x0):
        vg = jax.value_and_grad(f)
        tol, step0 = self.tolerance, self.step_size

        def cond(st):
            k, _, _, g, done = st
            return jnp.logical_and(
                jnp.logical_and(k < self.max_iterations, ~done),
                jnp.linalg.norm(g) > tol)

        def body(st):
            k, x, loss, g, _ = st
            alpha, new_loss = _line_search(f, x, -g, loss, g, step0)
            x2 = x - alpha * g
            _, g2 = vg(x2)
            done = jnp.abs(loss - new_loss) < tol
            return k + 1, x2, new_loss, g2, done

        loss0, g0 = vg(x0)
        _, x, loss, _, _ = jax.jit(lambda s: jax.lax.while_loop(
            cond, body, s))((jnp.asarray(0), x0, loss0, g0,
                             jnp.asarray(False)))
        return x, loss


class ConjugateGradient(_BaseLegacySolver):
    """Polak-Ribiere nonlinear CG with automatic restart (reference class
    of the same name)."""

    def _minimize(self, f, x0):
        vg = jax.value_and_grad(f)
        tol, step0 = self.tolerance, self.step_size

        def cond(st):
            k, _, _, g, _, done = st
            return jnp.logical_and(
                jnp.logical_and(k < self.max_iterations, ~done),
                jnp.linalg.norm(g) > tol)

        def body(st):
            k, x, loss, g, d, _ = st
            alpha, new_loss = _line_search(f, x, d, loss, g, step0)
            x2 = x + alpha * d
            _, g2 = vg(x2)
            beta = jnp.maximum(
                jnp.vdot(g2, g2 - g) / jnp.maximum(jnp.vdot(g, g), 1e-30),
                0.0)  # PR+ : restart (beta=0) when the curvature turns
            d2 = -g2 + beta * d
            # a non-descent direction falls back to steepest descent
            d2 = jnp.where(jnp.vdot(d2, g2) < 0, d2, -g2)
            done = jnp.abs(loss - new_loss) < tol
            return k + 1, x2, new_loss, g2, d2, done

        loss0, g0 = vg(x0)
        _, x, loss, _, _, _ = jax.jit(lambda s: jax.lax.while_loop(
            cond, body, s))((jnp.asarray(0), x0, loss0, g0, -g0,
                             jnp.asarray(False)))
        return x, loss


class LBFGS(_BaseLegacySolver):
    """Limited-memory BFGS (reference class of the same name). History of
    ``m`` (s, y) pairs in circular device buffers; the two-loop recursion
    runs as ``fori_loop`` passes inside the compiled solver loop."""

    m: int = 10

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6,
                 step_size: float = 1.0, m: int = 10):
        super().__init__(max_iterations, tolerance, step_size)
        self.m = int(m)

    def _minimize(self, f, x0):
        vg = jax.value_and_grad(f)
        n = x0.shape[0]
        m, tol, step0 = self.m, self.tolerance, self.step_size

        def direction(g, S, Y, rho, k):
            """Two-loop recursion; entries >= k (not yet written) have
            rho=0 and contribute nothing."""
            q = g

            def bwd(i, carry):
                q, alphas = carry
                idx = (k - 1 - i) % m
                a = rho[idx] * jnp.vdot(S[idx], q)
                a = jnp.where(i < jnp.minimum(k, m), a, 0.0)
                return q - a * Y[idx], alphas.at[idx].set(a)

            q, alphas = jax.lax.fori_loop(
                0, m, bwd, (q, jnp.zeros((m,), x0.dtype)))
            # initial Hessian scaling gamma = s.y / y.y of the newest pair
            newest = (k - 1) % m
            sy = jnp.vdot(S[newest], Y[newest])
            yy = jnp.vdot(Y[newest], Y[newest])
            gamma = jnp.where(k > 0, sy / jnp.maximum(yy, 1e-30), 1.0)
            r = gamma * q

            def fwd(i, r):
                idx = (k - jnp.minimum(k, m) + i) % m
                b = rho[idx] * jnp.vdot(Y[idx], r)
                upd = S[idx] * (alphas[idx] - b)
                return r + jnp.where(i < jnp.minimum(k, m), upd, 0.0)

            r = jax.lax.fori_loop(0, m, fwd, r)
            return -r

        def cond(st):
            k, _, _, g, _, _, _, done = st
            return jnp.logical_and(
                jnp.logical_and(k < self.max_iterations, ~done),
                jnp.linalg.norm(g) > tol)

        def body(st):
            k, x, loss, g, S, Y, rho, _ = st
            d = direction(g, S, Y, rho, k)
            d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
            alpha, new_loss = _line_search(f, x, d, loss, g, step0)
            x2 = x + alpha * d
            _, g2 = vg(x2)
            s, y = x2 - x, g2 - g
            sy = jnp.vdot(s, y)
            idx = k % m
            ok = sy > 1e-10  # curvature condition; else skip the pair
            S = jnp.where(ok, S.at[idx].set(s), S)
            Y = jnp.where(ok, Y.at[idx].set(y), Y)
            rho = jnp.where(ok, rho.at[idx].set(1.0 / sy), rho)
            done = jnp.abs(loss - new_loss) < tol
            return k + 1, x2, new_loss, g2, S, Y, rho, done

        loss0, g0 = vg(x0)
        st0 = (jnp.asarray(0), x0, loss0, g0,
               jnp.zeros((m, n), x0.dtype), jnp.zeros((m, n), x0.dtype),
               jnp.zeros((m,), x0.dtype), jnp.asarray(False))
        _, x, loss, _, _, _, _, _ = jax.jit(
            lambda s: jax.lax.while_loop(cond, body, s))(st0)
        return x, loss
