"""SameDiff training (reference ``TrainingConfig`` + ``TrainingSession`` —
SURVEY.md §3.3).

Where the reference's ``TrainingSession#trainingIteration`` executes the
graph op-by-op then applies regularization + ``GradientUpdater`` per
variable (one JNI crossing each), here one jitted ``train_step`` fuses
forward + ``jax.grad`` backward + regularization + updater into a single
XLA program, compiled once and reused across batches/epochs.
"""

from __future__ import annotations

import dataclasses
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.conf.updaters import IUpdater, Sgd


@dataclasses.dataclass
class TrainingConfig:
    """Reference ``org.nd4j.autodiff.samediff.TrainingConfig``."""
    updater: IUpdater = dataclasses.field(default_factory=Sgd)
    data_set_feature_mapping: tp.Sequence[str] = ()
    data_set_label_mapping: tp.Sequence[str] = ()
    data_set_feature_mask_mapping: tp.Sequence[str] = ()
    data_set_label_mask_mapping: tp.Sequence[str] = ()
    loss_variables: tp.Sequence[str] = ()
    regularization: tp.Sequence = ()  # conf.regularization.* instances
    minimize: bool = True

    class Builder:
        def __init__(self):
            self._cfg = TrainingConfig()

        def updater(self, u):
            self._cfg.updater = u
            return self

        def data_set_feature_mapping(self, *names):
            self._cfg.data_set_feature_mapping = list(names)
            return self

        def data_set_label_mapping(self, *names):
            self._cfg.data_set_label_mapping = list(names)
            return self

        def data_set_feature_mask_mapping(self, *names):
            self._cfg.data_set_feature_mask_mapping = list(names)
            return self

        def data_set_label_mask_mapping(self, *names):
            self._cfg.data_set_label_mask_mapping = list(names)
            return self

        def loss_variables(self, *names):
            self._cfg.loss_variables = [
                n if isinstance(n, str) else n.name for n in names]
            return self

        def regularization(self, *regs):
            self._cfg.regularization = list(regs)
            return self

        def minimize(self, m=True):
            self._cfg.minimize = m
            return self

        def build(self):
            return self._cfg

    @staticmethod
    def builder() -> "TrainingConfig.Builder":
        return TrainingConfig.Builder()


class History:
    """Reference ``org.nd4j.autodiff.listeners.records.History`` (thin).

    Losses accumulate as device scalars and materialize to floats on read
    — a per-step ``float()`` would force a full host sync per iteration
    (~100ms on the axon tunnel). The pending list self-flushes past
    ``_FLUSH_AT`` so a long unobserved run doesn't pin one device buffer
    per step (one stacked transfer, not a sync per scalar)."""

    _FLUSH_AT = 512

    def __init__(self):
        self._pending: list = []
        self._curve: list[float] = []

    def append(self, loss):
        self._pending.append(loss)
        if len(self._pending) >= self._FLUSH_AT:
            self._flush()

    def _flush(self):
        if self._pending:
            self._curve.extend(
                np.asarray(jnp.stack(self._pending)).tolist())
            self._pending.clear()

    @property
    def loss_curve(self) -> list[float]:
        self._flush()
        return self._curve


def make_train_step(sd, cfg: TrainingConfig):
    """Build the pure jitted step:
    (trainables, opt_state, t, placeholders) -> (trainables', opt_state',
    loss). Regularization mirrors the reference's apply-before/after-updater
    split (``Regularization.ApplyStep``)."""
    loss_names = tuple(cfg.loss_variables or sd.loss_variables)
    if not loss_names:
        raise ValueError("TrainingConfig has no loss variables and none "
                         "were marked on the graph")
    trainable_names = tuple(sd.trainable_variables())
    fn = sd.make_function(loss_names)
    updater = cfg.updater
    regs = tuple(cfg.regularization)
    sign = 1.0 if cfg.minimize else -1.0

    def loss_fn(trainables, frozen, placeholders):
        merged = dict(frozen)
        merged.update(trainables)
        outs = fn(merged, placeholders)
        return sign * sum(jnp.sum(v) for v in outs.values())

    from deeplearning4j_tpu.telemetry import health

    mode = health.graph_mode()

    def train_step(trainables, frozen, opt_state, t, placeholders):
        loss, grads = jax.value_and_grad(loss_fn)(trainables, frozen,
                                                  placeholders)
        lr = updater.current_lr(t, 0)
        new_params, new_state = {}, {}
        for n in trainable_names:
            g, p = grads[n], trainables[n]
            for r in regs:
                g = r.apply_before_updater(g, p, lr)
            upd, new_state[n] = updater.update_leaf(g, opt_state[n], lr, t,
                                                    param=p)
            for r in regs:
                upd = r.apply_after_updater(upd, p, lr)
            new_params[n] = p - upd
        if mode:
            vec = health.guard_vector(loss, grads, params=trainables,
                                      new_params=new_params)
            if mode == "skip":
                new_params, new_state = health.apply_skip(
                    vec, (new_params, new_state), (trainables, opt_state))
            return new_params, new_state, loss, vec
        return new_params, new_state, loss

    from deeplearning4j_tpu.optimize import aot_cache

    # the executable bakes in the updater, regularization, minimize sign
    # and the loss-variable subset — they MUST be part of the key, or two
    # TrainingConfigs over the same graph would share one compiled step
    # with the first config's lr/sign/loss frozen in (the health guard
    # mode joins the key the same way via cache_tag)
    cfg_key = aot_cache.graph_signature(
        (repr(updater), tuple(map(repr, regs)), sign, loss_names),
        fallback=cfg)
    # donate trainables + opt state (argnums 0, 2): every step's outputs
    # reuse the previous step's buffers instead of allocating a second
    # copy of the model — the same aliasing contract the network train
    # steps carry (PRG201). fit() stages per-fit copies so ``sd.arrays``
    # never aliases a donated buffer.
    step = aot_cache.wrap(jax.jit(train_step, donate_argnums=(0, 2)),
                          "sd:" + sd.graph_signature(),
                          f"train_step:d02:{cfg_key}{health.cache_tag()}")
    return step, trainable_names, loss_names


def fit(sd, iterator=None, epochs: int = 1, features=None, labels=None):
    """Reference ``SameDiff#fit(DataSetIterator, epochs)``. Also accepts
    raw (features, labels) arrays for single-dataset fitting."""
    cfg = sd.training_config
    if cfg is None:
        raise ValueError("call set_training_config() first")
    # cache the jitted step inside _fn_cache (cleared on graph mutation):
    # rebuilding per fit() call would retrace/recompile every time. Keyed
    # by cfg IDENTITY (the entry holds the cfg, so its id can't be
    # recycled); set_training_config() with a new cfg misses naturally.
    # Mutating a TrainingConfig in place between fits is not supported —
    # call set_training_config with a fresh config.
    from deeplearning4j_tpu.telemetry import flightrec, health

    mode = health.graph_mode()
    cached = sd._fn_cache.get("__train_step__")
    if cached is None or cached[0] is not cfg or cached[1] != mode:
        cached = (cfg, mode, make_train_step(sd, cfg))
        sd._fn_cache["__train_step__"] = cached
    step, trainable_names, _ = cached[2]

    # the step DONATES trainables + opt state, so the loop must own its
    # buffers: stage device COPIES at fit entry (one copy per fit, not
    # per step) — ``sd.arrays`` / ``sd._updater_state`` keep their own
    # live arrays until the final write-back below, and a fit that dies
    # mid-run never leaves the graph pointing at deleted donated buffers
    trainables = {n: jnp.array(sd.arrays[n]) for n in trainable_names}
    frozen = {k: v for k, v in sd.arrays.items()
              if k not in set(trainable_names)}
    if sd._updater_state is None:
        sd._updater_state = {n: cfg.updater.init_state(trainables[n])
                             for n in trainable_names}
    opt_state = jax.tree_util.tree_map(jnp.array, sd._updater_state)
    history = History()

    def batches():
        if iterator is not None:
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                yield ds
        else:
            from deeplearning4j_tpu.datasets.dataset import DataSet
            yield DataSet(features, labels)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn import io as nn_io

    # the dispatch queue persists ACROSS fit() calls: forcing a sync per
    # call would pay the expensive post-program host sync every step for
    # the common one-batch-per-fit pattern; the bounded queue syncs every
    # DISPATCH_DEPTH steps instead, wherever those steps came from
    pending = sd.__dict__.setdefault("_dispatch_pending", [])
    from deeplearning4j_tpu import telemetry

    # health-layer rollback hooks over the loop-local training trees
    # (the functional update below rebinds them, so the restore closure
    # writes back through nonlocal)
    def _snapshot():
        host = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: np.asarray(x), t)
        return (host(trainables), host(opt_state), sd._iteration_count)

    def _restore(snap):
        nonlocal trainables, opt_state
        dev = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: jnp.asarray(x), t)
        trainables = dev(snap[0])
        opt_state = dev(snap[1])
        sd._iteration_count = snap[2]

    guard_keys = health.bucket_keys(trainables) if mode else ()

    with flightrec.flight_recorder():
        for _ in range(epochs):
            for ds in batches():
                with telemetry.span(telemetry.PHASE_INGEST):
                    ph = {}
                    feats = (ds.features
                             if isinstance(ds.features, (list, tuple))
                             else [ds.features])
                    labs = (ds.labels
                            if isinstance(ds.labels, (list, tuple))
                            else [ds.labels])
                    for name, arr in zip(cfg.data_set_feature_mapping,
                                         feats):
                        ph[name] = jnp.asarray(arr)
                    for name, arr in zip(cfg.data_set_label_mapping, labs):
                        ph[name] = jnp.asarray(arr)
                    if cfg.data_set_feature_mask_mapping and \
                            getattr(ds, "features_mask", None) is not None:
                        ph[cfg.data_set_feature_mask_mapping[0]] = \
                            jnp.asarray(ds.features_mask)
                    if cfg.data_set_label_mask_mapping and \
                            getattr(ds, "labels_mask", None) is not None:
                        ph[cfg.data_set_label_mask_mapping[0]] = \
                            jnp.asarray(ds.labels_mask)
                    # write staged arrays back so a reused DataSet
                    # transfers once (reference DataSet#migrate semantics,
                    # matching the networks)
                    if isinstance(ds, DataSet):
                        fmap = list(cfg.data_set_feature_mapping
                                    or [])[:len(feats)]
                        lmap = list(cfg.data_set_label_mapping
                                    or [])[:len(labs)]
                        if len(fmap) == len(feats):
                            staged = [ph[n] for n in fmap]
                            ds.features = (staged if isinstance(
                                ds.features, (list, tuple)) else staged[0])
                        if len(lmap) == len(labs):
                            staged = [ph[n] for n in lmap]
                            ds.labels = (staged if isinstance(
                                ds.labels, (list, tuple)) else staged[0])
                # np scalar stages with the call; a bare python int would
                # take the slow weak-type conversion path (~20ms on the
                # tunnel)
                gvec = None
                with telemetry.span(telemetry.PHASE_COMPUTE) as _sp:
                    out = step(trainables, frozen, opt_state,
                               np.float32(sd._iteration_count), ph)
                    trainables, opt_state, loss = out[:3]
                    if mode:
                        gvec = out[3]
                    _sp.set_result(loss)
                with telemetry.span(telemetry.PHASE_GRAD_SYNC) as _sp:
                    _sp.set_result(trainables)  # single device: ~0
                if telemetry.enabled():
                    rows = getattr(ph.get(next(iter(ph), None), None),
                                   "shape", (0,))
                    telemetry.record_step("samediff",
                                          int(rows[0]) if rows else 0)
                sd._iteration_count += 1
                if mode:
                    health.observe_step(
                        sd, "samediff", sd._iteration_count - 1,
                        sd._epoch_count, loss, gvec, guard_keys,
                        batch=tuple(ph.values()),
                        snapshot=_snapshot, restore=_restore)
                history.append(loss)
                pending.append(loss)
                nn_io.drain(pending)  # bounded async dispatch, no sync
                for lst in sd._listeners:
                    if hasattr(lst, "iteration_done"):
                        lst.iteration_done(sd, sd._iteration_count,
                                           float(loss))
            sd._epoch_count += 1

    sd.arrays.update(trainables)
    sd._updater_state = opt_state
    return history
