"""Op validation harness.

Reference: ``org.nd4j.autodiff.validation.OpValidation`` + ``TestCase`` —
per-op forward value checks, gradient checks, and COVERAGE ACCOUNTING
(the reference fails CI when an op has no validation). Here:

- :class:`TestCase`: expected outputs + gradient checking for one op node.
- :func:`validate`: runs a TestCase (forward compare + f64 central
  differences vs the lowered graph's ``jax.grad``).
- :func:`coverage_report`: which registered ops have been validated in this
  process — tests assert a floor so newly added ops must bring a TestCase.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.samediff import ops as _ops  # noqa: F401  — importing
# populates OP_REGISTRY (namespaces are otherwise lazy; a validate() call
# before any namespace use must still see the full registry)
from deeplearning4j_tpu.samediff.core import OP_REGISTRY, SameDiff

_VALIDATED: set[str] = set()


class TestCase:
    """One op validation case (reference ``TestCase``)."""

    __test__ = False  # not a pytest class, despite the (parity) name

    def __init__(self, sd: SameDiff, inputs: dict, expected: dict,
                 grad_wrt: list | None = None, epsilon: float = 1e-6,
                 max_rel_error: float = 1e-4):
        self.sd = sd
        # float inputs promote to f64 (the reference's double-precision
        # gradient-check protocol); integer/bool inputs keep their dtype
        # (bitwise/scatter-index operands must stay integral)
        self.inputs = {
            k: (np.asarray(v, np.float64)
                if np.issubdtype(np.asarray(v).dtype, np.floating)
                else np.asarray(v))
            for k, v in inputs.items()}
        self.expected = {k: np.asarray(v) for k, v in expected.items()}
        # grad_wrt=[] means "forward-only" (bool/int outputs, non-smooth
        # ops); None defaults to every FLOAT input (integral operands —
        # indices, segment ids — are not differentiable)
        self.grad_wrt = (
            [k for k, v in self.inputs.items()
             if np.issubdtype(v.dtype, np.floating)]
            if grad_wrt is None else list(grad_wrt))
        self.epsilon = float(epsilon)
        self.max_rel_error = float(max_rel_error)


def validate(case: TestCase) -> None:
    """Forward compare + central-difference gradient check in f64
    (``jax.enable_x64``, mirroring the reference's double-precision-only
    gradient checks); records coverage for every op node in the case's
    graph."""
    import jax

    if hasattr(jax, "enable_x64"):
        ctx = jax.enable_x64(True)
    else:  # older jax spells it jax.experimental.enable_x64
        from jax.experimental import enable_x64

        ctx = enable_x64(True)
    with ctx:
        _validate_x64(case)


def _validate_x64(case: TestCase) -> None:
    sd = case.sd
    out_names = tuple(case.expected)

    outs = sd.output(case.inputs, *out_names)
    for name, want in case.expected.items():
        np.testing.assert_allclose(
            np.asarray(outs[name], np.float64), want, rtol=1e-5, atol=1e-6,
            err_msg=f"forward mismatch for output {name!r}")

    if not case.grad_wrt:
        for node in sd.ops.values():
            _VALIDATED.add(node.op_name)
        return

    # gradient of sum(outputs) wrt each requested placeholder
    import jax
    import jax.numpy as jnp

    fn = sd.make_function(out_names)

    def scalar(ph_vals):
        res = fn(dict(sd.arrays), {
            k: (jnp.asarray(v, jnp.float64)
                if np.issubdtype(jnp.asarray(v).dtype, np.floating)
                else jnp.asarray(v))
            for k, v in ph_vals.items()})
        return sum(jnp.sum(v) for v in res.values())

    # differentiate ONLY the requested (float) placeholders — int/bool
    # operands (indices, segment ids, masks) ride along as constants
    fixed = {k: v for k, v in case.inputs.items() if k not in case.grad_wrt}
    analytic = jax.grad(lambda pv: scalar({**fixed, **pv}))(
        {k: jnp.asarray(v) for k, v in case.inputs.items()
         if k in case.grad_wrt})
    for k in case.grad_wrt:
        a = np.asarray(analytic[k], np.float64).ravel()
        x0 = np.asarray(case.inputs[k])
        flat0 = x0.ravel()
        n = flat0.size

        # VMAPPED central differences in chunks: one compiled call per
        # chunk of up/down evaluations instead of two EAGER whole-graph
        # executions per element (the per-element loop dominated the
        # tier-1 op-validation wall time). Tiny inputs (n <= 8) keep the
        # eager loop — a jit+vmap compile costs more than 16 eager evals
        # of a small graph. Same evaluations, same math, either way.
        numeric = np.empty(n, np.float64)
        if n <= 8:
            work = flat0.copy()
            for idx in range(n):
                orig = work[idx]
                work[idx] = orig + case.epsilon
                up = float(scalar({**case.inputs,
                                   k: work.reshape(x0.shape)}))
                work[idx] = orig - case.epsilon
                dn = float(scalar({**case.inputs,
                                   k: work.reshape(x0.shape)}))
                work[idx] = orig
                numeric[idx] = (up - dn) / (2 * case.epsilon)
        else:
            def scalar_k(xk_flat, _k=k, _shape=x0.shape):
                return scalar({**case.inputs, _k: xk_flat.reshape(_shape)})

            fv = jax.jit(jax.vmap(scalar_k))
            chunk = 256
            for start in range(0, n, chunk):
                ii = np.arange(start, min(start + chunk, n))
                pert = np.zeros((len(ii), n), x0.dtype)
                pert[np.arange(len(ii)), ii] = case.epsilon
                up = np.asarray(fv(jnp.asarray(flat0[None] + pert)),
                                np.float64)
                dn = np.asarray(fv(jnp.asarray(flat0[None] - pert)),
                                np.float64)
                numeric[ii] = (up - dn) / (2 * case.epsilon)

        for idx in range(n):
            # central differences bottom out around eps_machine/epsilon —
            # treat both-tiny as matching zero
            if abs(numeric[idx]) < 1e-7 and abs(a[idx]) < 1e-7:
                continue
            denom = max(abs(numeric[idx]), abs(a[idx]), 1e-8)
            rel = abs(numeric[idx] - a[idx]) / denom
            assert rel < case.max_rel_error, (
                f"gradient mismatch for {k}[{idx}]: "
                f"numeric={numeric[idx]:.3e} "
                f"analytic={a[idx]:.3e} rel={rel:.3e}")

    for node in sd.ops.values():
        _VALIDATED.add(node.op_name)


def coverage_report() -> dict:
    """{'validated': n, 'registered': m, 'missing': [...]} for this
    process (reference: OpValidation's coverage accounting)."""
    registered = set(OP_REGISTRY)
    return {
        "validated": len(_VALIDATED & registered),
        "registered": len(registered),
        "missing": sorted(registered - _VALIDATED),
    }
