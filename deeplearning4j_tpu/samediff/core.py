"""SameDiff-equivalent: define-by-graph symbolic autodiff over JAX.

Reference: ``org.nd4j.autodiff.samediff.SameDiff`` / ``SDVariable`` /
``DifferentialFunction`` and the ``InferenceSession``/``TrainingSession``
executors (SURVEY.md §2.2, §3.3).

TPU-first design — deliberately NOT the reference architecture:

- The reference builds a graph of ``DifferentialFunction`` objects and
  executes it **op-by-op** from Java (one JNI crossing per op), deriving
  gradients by a graph-to-graph transform (per-op ``doDiff``).
- Here the graph is a lightweight recipe (ops from a serializable registry),
  *lowered once* to a pure function, and the whole program — forward,
  ``jax.grad`` backward, updater — is a single XLA executable. Gradient
  construction via ``doDiff`` per op collapses into ``jax.grad``.
- Control flow (reference: TF-style Enter/Exit/Switch/Merge frames walked by
  the Java session) is structured instead: ``lax.cond`` / ``lax.while_loop``
  / ``lax.scan`` behind ``sd.cond`` / ``sd.while_loop``, compiler-friendly
  by construction.

Variable taxonomy mirrors the reference exactly (``VariableType``):
VARIABLE (trainable, persisted), CONSTANT (persisted, not trained),
PLACEHOLDER (fed per call), ARRAY (op output, recomputed).
"""

from __future__ import annotations

import collections
import dataclasses
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

# Registry of pure op implementations: op_name -> fn(*arrays, **attrs).
# Every graph node references an entry here, which is what makes graphs
# serializable (serde.py re-links by name on load).
OP_REGISTRY: dict[str, tp.Callable] = {}


def register_op(name: str):
    def deco(fn):
        OP_REGISTRY[name] = fn
        return fn
    return deco


class VariableType:
    VARIABLE = "VARIABLE"
    CONSTANT = "CONSTANT"
    PLACEHOLDER = "PLACEHOLDER"
    ARRAY = "ARRAY"


@dataclasses.dataclass
class VarMeta:
    name: str
    var_type: str
    shape: tuple | None = None
    dtype: str = "float32"
    # producing op name for ARRAY vars; None otherwise
    producer: str | None = None
    output_index: int = 0


@dataclasses.dataclass
class OpNode:
    name: str
    op_name: str
    inputs: tuple
    outputs: tuple
    attrs: dict = dataclasses.field(default_factory=dict)
    # non-serializable callable attrs (control flow bodies); graph with any
    # of these saves config-only UNLESS the callable was traced into a
    # serializable child graph recorded in ``subgraphs`` (same keys)
    fn_attrs: dict = dataclasses.field(default_factory=dict)
    # fn_attr key -> JSON-able child-graph dict (see serde.subgraph_dict)
    subgraphs: dict = dataclasses.field(default_factory=dict)


class SDVariable:
    """Symbolic tensor handle (reference ``SDVariable``). Arithmetic
    operators build graph nodes via the owning ``SameDiff``'s math ops."""

    __array_priority__ = 100  # beat numpy in mixed expressions

    def __init__(self, sd: "SameDiff", name: str):
        self.sd = sd
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    @property
    def var_type(self) -> str:
        return self.sd.variables[self._name].var_type

    @property
    def shape(self):
        return self.sd.variables[self._name].shape

    def rename(self, new_name: str) -> "SDVariable":
        self.sd.rename_variable(self._name, new_name)
        self._name = new_name
        return self

    def eval(self, placeholders=None):
        return self.sd.output(placeholders or {}, self._name)[self._name]

    def get_arr(self):
        """Value of a VARIABLE/CONSTANT (reference ``SDVariable#getArr``)."""
        return self.sd.arrays[self._name]

    def convert_to_variable(self) -> "SDVariable":
        """CONSTANT -> trainable VARIABLE (reference
        ``SDVariable#convertToVariable``) — how imported frozen weights
        become fine-tunable."""
        meta = self.sd.variables[self._name]
        if meta.var_type == VariableType.CONSTANT:
            meta.var_type = VariableType.VARIABLE
            self.sd._fn_cache.clear()
            # the trainable set changed: updater state must re-initialize
            self.sd._updater_state = None
        elif meta.var_type != VariableType.VARIABLE:
            raise ValueError(
                f"{self._name} is {meta.var_type}, not CONSTANT")
        return self

    def convert_to_constant(self) -> "SDVariable":
        """VARIABLE -> frozen CONSTANT (reference
        ``SDVariable#convertToConstant``)."""
        meta = self.sd.variables[self._name]
        if meta.var_type == VariableType.VARIABLE:
            meta.var_type = VariableType.CONSTANT
            self.sd._fn_cache.clear()
            self.sd._updater_state = None
        elif meta.var_type != VariableType.CONSTANT:
            raise ValueError(
                f"{self._name} is {meta.var_type}, not VARIABLE")
        return self

    def set_arr(self, value):
        self.sd.arrays[self._name] = jnp.asarray(value)
        return self

    # ---- operator sugar (delegates to the math namespace) ----
    def _m(self):
        return self.sd.math

    def __add__(self, o):
        return self._m().add(self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._m().sub(self, o)

    def __rsub__(self, o):
        return self._m().rsub(self, o)

    def __mul__(self, o):
        return self._m().mul(self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._m().div(self, o)

    def __rtruediv__(self, o):
        return self._m().rdiv(self, o)

    def __pow__(self, o):
        return self._m().pow(self, o)

    def __neg__(self):
        return self._m().neg(self)

    def __matmul__(self, o):
        return self._m().mmul(self, o)

    def __gt__(self, o):
        return self._m().gt(self, o)

    def __lt__(self, o):
        return self._m().lt(self, o)

    def __ge__(self, o):
        return self._m().gte(self, o)

    def __le__(self, o):
        return self._m().lte(self, o)

    def __getitem__(self, idx):
        return self.sd._op("getitem", [self], index=_encode_index(idx))[0]

    # fluent helpers commonly used on reference SDVariable
    def add(self, o, name=None):
        return self._m().add(self, o, name=name)

    def sub(self, o, name=None):
        return self._m().sub(self, o, name=name)

    def mul(self, o, name=None):
        return self._m().mul(self, o, name=name)

    def div(self, o, name=None):
        return self._m().div(self, o, name=name)

    def mmul(self, o, name=None):
        return self._m().mmul(self, o, name=name)

    def sum(self, *dims, keepdims=False, name=None):
        return self._m().sum(self, dims=dims or None, keepdims=keepdims,
                             name=name)

    def mean(self, *dims, keepdims=False, name=None):
        return self._m().mean(self, dims=dims or None, keepdims=keepdims,
                              name=name)

    def std(self, *dims, keepdims=False, name=None):
        return self._m().std(self, dims=dims or None, keepdims=keepdims,
                             name=name)

    def reshape(self, *shape, name=None):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self.sd.reshape(self, shape, name=name)

    def transpose(self, name=None):
        return self.sd.transpose(self, name=name)

    def permute(self, *dims, name=None):
        return self.sd.permute(self, dims, name=name)

    def cast_to(self, dtype, name=None):
        return self.sd.cast(self, dtype, name=name)

    def __repr__(self):
        m = self.sd.variables[self._name]
        return (f"SDVariable(name={self._name!r}, type={m.var_type}, "
                f"shape={m.shape})")


def _encode_index(idx):
    """Encode a python index expression into a JSON-able attr."""
    def enc(i):
        if isinstance(i, slice):
            return {"slice": [i.start, i.stop, i.step]}
        if i is Ellipsis:
            return {"ellipsis": True}
        if i is None:
            return {"newaxis": True}
        return int(i)
    if isinstance(idx, tuple):
        return {"tuple": [enc(i) for i in idx]}
    return enc(idx)


def _decode_index(enc):
    def dec(e):
        if isinstance(e, dict):
            if "slice" in e:
                return slice(*e["slice"])
            if "ellipsis" in e:
                return Ellipsis
            if "newaxis" in e:
                return None
        return int(e)
    if isinstance(enc, dict) and "tuple" in enc:
        return tuple(dec(e) for e in enc["tuple"])
    return dec(enc)


@register_op("getitem")
def _op_getitem(x, *, index):
    return x[_decode_index(index)]


class SameDiff:
    """The graph container + executor (reference ``SameDiff``).

    Build with ``SameDiff.create()``; define variables/placeholders; call
    namespaced op factories (``sd.math``, ``sd.nn``, ``sd.cnn``, ``sd.rnn``,
    ``sd.loss``, ``sd.random``, ``sd.linalg``, ``sd.image``, ``sd.bitwise``);
    run with ``output()``; train with ``fit()`` after ``set_training_config``.
    """

    def __init__(self):
        self.variables: dict[str, VarMeta] = {}
        self.ops: dict[str, OpNode] = {}  # insertion order == topo order
        self.arrays: dict[str, jnp.ndarray] = {}  # VARIABLE/CONSTANT values
        self._name_counter = collections.Counter()
        self.loss_variables: list[str] = []
        self.training_config = None
        self._updater_state = None
        self._iteration_count = 0
        self._epoch_count = 0
        self._listeners = []
        self._fn_cache: dict = {}
        # lazily-built namespaces (import cycle: ops.py imports core)
        self._ns = {}

    # ---------------- namespaces ----------------
    def _namespace(self, key):
        if key not in self._ns:
            from deeplearning4j_tpu.samediff import ops as _ops
            self._ns[key] = _ops.NAMESPACES[key](self)
        return self._ns[key]

    @property
    def math(self):
        return self._namespace("math")

    @property
    def nn(self):
        return self._namespace("nn")

    @property
    def cnn(self):
        return self._namespace("cnn")

    @property
    def rnn(self):
        return self._namespace("rnn")

    @property
    def loss(self):
        return self._namespace("loss")

    @property
    def random(self):
        return self._namespace("random")

    @property
    def linalg(self):
        return self._namespace("linalg")

    @property
    def image(self):
        return self._namespace("image")

    @property
    def bitwise(self):
        return self._namespace("bitwise")

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # ---------------- variable definition ----------------
    def _unique(self, base: str) -> str:
        if base not in self.variables and base not in self.ops:
            return base
        while True:
            self._name_counter[base] += 1
            cand = f"{base}_{self._name_counter[base]}"
            if cand not in self.variables and cand not in self.ops:
                return cand

    def var(self, name=None, shape=None, weight_init=None, dtype="float32",
            value=None, key=None) -> SDVariable:
        """Trainable VARIABLE. Either ``value`` or (``shape`` +
        ``weight_init``) — default init Xavier like the reference."""
        name = self._unique(name or "variable")
        if value is not None:
            arr = jnp.asarray(value, dtype=dtype)
            shape = arr.shape
        else:
            if shape is None:
                raise ValueError("var() needs shape or value")
            arr = _init_array(shape, weight_init, dtype, key)
        self.variables[name] = VarMeta(name, VariableType.VARIABLE,
                                       tuple(shape), str(dtype))
        self.arrays[name] = arr
        return SDVariable(self, name)

    def constant(self, value, name=None, dtype=None) -> SDVariable:
        name = self._unique(name or "constant")
        arr = jnp.asarray(value, dtype=dtype)
        self.variables[name] = VarMeta(name, VariableType.CONSTANT,
                                       tuple(arr.shape), str(arr.dtype))
        self.arrays[name] = arr
        return SDVariable(self, name)

    def placeholder(self, name, shape=None, dtype="float32") -> SDVariable:
        name = self._unique(name)
        self.variables[name] = VarMeta(
            name, VariableType.PLACEHOLDER,
            tuple(shape) if shape is not None else None, str(dtype))
        return SDVariable(self, name)

    def rename_variable(self, old: str, new: str) -> None:
        if new in self.variables:
            raise ValueError(f"variable {new!r} already exists")
        meta = self.variables.pop(old)
        meta.name = new
        self.variables[new] = meta
        if old in self.arrays:
            self.arrays[new] = self.arrays.pop(old)
        for op in self.ops.values():
            op.inputs = tuple(new if i == old else i for i in op.inputs)
            op.outputs = tuple(new if o == old else o for o in op.outputs)
        self.loss_variables = [new if v == old else v
                               for v in self.loss_variables]
        self._fn_cache.clear()

    # ---------------- graph building ----------------
    def _coerce(self, x) -> str:
        """Turn a non-SDVariable operand into a CONSTANT; return var name."""
        if isinstance(x, SDVariable):
            return x.name
        return self.constant(x).name

    def _op(self, op_name, inputs, n_out=1, name=None, fn_attrs=None,
            subgraphs=None, **attrs) -> list[SDVariable]:
        if op_name not in OP_REGISTRY:
            raise KeyError(f"op {op_name!r} not in registry")
        node_name = self._unique(name or op_name)
        in_names = tuple(self._coerce(x) for x in inputs)
        out_names = tuple(
            node_name if i == 0 and n_out == 1 else f"{node_name}:{i}"
            for i in range(n_out))
        for i, o in enumerate(out_names):
            self.variables[o] = VarMeta(o, VariableType.ARRAY,
                                        producer=node_name, output_index=i)
        self.ops[node_name] = OpNode(node_name, op_name, in_names, out_names,
                                     dict(attrs), dict(fn_attrs or {}),
                                     dict(subgraphs or {}))
        self._fn_cache.clear()
        return [SDVariable(self, o) for o in out_names]

    # ---------------- lowering + execution ----------------
    def _ancestor_ops(self, outputs: tp.Sequence[str]) -> list[OpNode]:
        """Demand-driven subgraph: ops reachable backwards from outputs, in
        original (topological) insertion order. Mirrors the reference's
        ``AbstractSession`` dependency subgraph build — but resolved once at
        trace time, not per step."""
        needed_vars = set(outputs)
        needed_ops = set()
        for op in reversed(list(self.ops.values())):
            if any(o in needed_vars for o in op.outputs):
                needed_ops.add(op.name)
                needed_vars.update(op.inputs)
        return [op for op in self.ops.values() if op.name in needed_ops]

    def make_function(self, outputs: tp.Sequence[str]):
        """Lower the subgraph producing ``outputs`` to a pure function
        ``fn(var_arrays: dict, placeholders: dict) -> dict``. The returned
        function is jit-safe; ``output()``/``fit()`` wrap it in ``jax.jit``.
        """
        outputs = tuple(outputs)
        plan = self._ancestor_ops(outputs)

        def fn(var_arrays, placeholders):
            env = dict(var_arrays)
            env.update(placeholders)
            for op in plan:
                impl = OP_REGISTRY[op.op_name]
                try:
                    args = [env[i] for i in op.inputs]
                except KeyError as e:
                    raise KeyError(
                        f"op {op.name!r} input {e} not available — missing "
                        f"placeholder?") from e
                res = impl(*args, **op.attrs, **op.fn_attrs)
                if len(op.outputs) == 1:
                    env[op.outputs[0]] = res
                else:
                    for o, r in zip(op.outputs, res):
                        env[o] = r
            return {o: env[o] for o in outputs}

        return fn

    def graph_signature(self) -> str:
        """Structural content key of the graph (non-ARRAY variables + op
        topology + attrs) for the AOT executable cache: two SameDiff
        instances holding the same program share compiled step/output
        executables; any graph mutation changes the key. Raw-closure
        control-flow bodies key by identity — never shared, and PINNED
        (aot_cache.pin_id) so a dead graph's recycled addresses cannot
        alias a new graph's key while its executables persist."""
        import hashlib

        from deeplearning4j_tpu.optimize import aot_cache as _aot

        h = hashlib.sha1()
        for v in self.variables.values():
            if v.var_type != VariableType.ARRAY:
                h.update(
                    f"{v.var_type}|{v.name}|{v.shape}|{v.dtype}\n".encode())
        for op in self.ops.values():
            h.update(f"{op.name}|{op.op_name}|{op.inputs}|"
                     f"{op.outputs}|".encode())
            try:
                h.update(repr(sorted(op.attrs.items())).encode())
            except Exception:
                h.update(f"id:{_aot.pin_id(op)}".encode())
            for k in sorted(op.fn_attrs):
                sub = op.subgraphs.get(k)
                if sub is not None:
                    h.update(f"{k}:sub:{repr(sub)}".encode())
                else:
                    h.update(
                        f"{k}:fn:{_aot.pin_id(op.fn_attrs[k])}".encode())
            h.update(b"\n")
        h.update(repr(sorted(self.loss_variables)).encode())
        return h.hexdigest()

    def _jitted(self, outputs: tuple):
        if outputs not in self._fn_cache:
            from deeplearning4j_tpu.optimize import aot_cache

            raw = self.make_function(outputs)
            self._fn_cache[outputs] = aot_cache.wrap(
                jax.jit(raw), "sd:" + self.graph_signature(),
                f"output:{outputs}")
        return self._fn_cache[outputs]

    def output(self, placeholders: dict | None, *outputs) -> dict:
        """Run inference (reference ``SameDiff#output``). ``outputs`` may be
        names or SDVariables; returns {name: array}. Whole subgraph runs as
        one jitted XLA program."""
        names = tuple(o.name if isinstance(o, SDVariable) else o
                      for o in outputs)
        if not names:
            raise ValueError("no outputs requested")
        ph = {k: jnp.asarray(v) for k, v in (placeholders or {}).items()}
        fn = self._jitted(names)
        return dict(fn(self.arrays, ph))

    def batch_output(self, placeholders, *outputs):
        return self.output(placeholders, *outputs)

    # convenience mirrors of reference exec API
    def outputs(self) -> list[str]:
        """Terminal ARRAY variables (consumed by no op)."""
        consumed = {i for op in self.ops.values() for i in op.inputs}
        return [v.name for v in self.variables.values()
                if v.var_type == VariableType.ARRAY and v.name not in consumed]

    def inputs(self) -> list[str]:
        return [v.name for v in self.variables.values()
                if v.var_type == VariableType.PLACEHOLDER]

    def trainable_variables(self) -> list[str]:
        return [v.name for v in self.variables.values()
                if v.var_type == VariableType.VARIABLE]

    # ---------------- gradients ----------------
    def calculate_gradients(self, placeholders: dict | None,
                            *wrt) -> dict:
        """d(sum of loss variables)/d(wrt...) — reference
        ``SameDiff#calculateGradients``. The reference builds a second grad
        graph via per-op ``doDiff``; here ``jax.grad`` differentiates the
        lowered program directly."""
        if not self.loss_variables:
            raise ValueError("no loss variables set; call "
                             "set_loss_variables() or use sd.loss.* ops")
        wrt_names = [w.name if isinstance(w, SDVariable) else w for w in wrt]
        if not wrt_names:
            wrt_names = self.trainable_variables()
        ph = {k: jnp.asarray(v) for k, v in (placeholders or {}).items()}
        fn = self.make_function(tuple(self.loss_variables))

        def scalar_loss(wrt_arrays):
            merged = dict(self.arrays)
            merged.update(wrt_arrays)
            outs = fn(merged, ph)
            return sum(jnp.sum(v) for v in outs.values())

        wrt_arrays = {n: self.arrays[n] for n in wrt_names}
        return jax.grad(scalar_loss)(wrt_arrays)

    calculateGradients = calculate_gradients

    def set_loss_variables(self, *vars_):
        self.loss_variables = [v.name if isinstance(v, SDVariable) else v
                               for v in vars_]

    def mark_loss(self, var):
        name = var.name if isinstance(var, SDVariable) else var
        if name not in self.loss_variables:
            self.loss_variables.append(name)

    # ---------------- training ----------------
    def set_training_config(self, cfg) -> None:
        self.training_config = cfg
        self._updater_state = None
        self._fn_cache.pop("__train_step__", None)

    def fit(self, iterator=None, epochs: int = 1, features=None, labels=None):
        from deeplearning4j_tpu.samediff.training import fit as _fit
        return _fit(self, iterator, epochs, features=features, labels=labels)

    def set_listeners(self, *listeners):
        self._listeners = list(listeners)

    # ---------------- structural ops (on sd, like reference) ----------------
    def reshape(self, x, shape, name=None):
        return self._op("reshape", [x], name=name, shape=tuple(shape))[0]

    def transpose(self, x, name=None):
        return self._op("transpose", [x], name=name)[0]

    def permute(self, x, dims, name=None):
        return self._op("permute", [x], name=name, dims=tuple(dims))[0]

    def concat(self, dim, *xs, name=None):
        return self._op("concat", list(xs), name=name, axis=int(dim))[0]

    def stack(self, axis, *xs, name=None):
        return self._op("stack", list(xs), name=name, axis=int(axis))[0]

    def unstack(self, x, axis, num, name=None):
        return self._op("unstack", [x], n_out=num, name=name,
                        axis=int(axis), num=int(num))

    def squeeze(self, x, axis, name=None):
        return self._op("squeeze", [x], name=name, axis=int(axis))[0]

    def expand_dims(self, x, axis, name=None):
        return self._op("expand_dims", [x], name=name, axis=int(axis))[0]

    def tile(self, x, reps, name=None):
        return self._op("tile", [x], name=name, reps=tuple(reps))[0]

    def cast(self, x, dtype, name=None):
        return self._op("cast", [x], name=name, dtype=str(dtype))[0]

    def slice(self, x, begin, size, name=None):
        return self._op("slice_op", [x], name=name, begin=tuple(begin),
                        size=tuple(size))[0]

    def gather(self, x, indices, axis=0, name=None):
        return self._op("gather", [x, indices], name=name, axis=int(axis))[0]

    def one_hot(self, indices, depth, name=None):
        return self._op("one_hot", [indices], name=name, depth=int(depth))[0]

    # scatter family (reference SDBaseOps scatter*: rows of `ref` selected
    # by `indices` (axis 0) combined with `updates`; duplicates accumulate)
    def scatter_update(self, ref, indices, updates, name=None):
        return self._op("scatter.update", [ref, indices, updates],
                        name=name)[0]

    def scatter_add(self, ref, indices, updates, name=None):
        return self._op("scatter.add", [ref, indices, updates], name=name)[0]

    def scatter_sub(self, ref, indices, updates, name=None):
        return self._op("scatter.sub", [ref, indices, updates], name=name)[0]

    def scatter_mul(self, ref, indices, updates, name=None):
        return self._op("scatter.mul", [ref, indices, updates], name=name)[0]

    def scatter_div(self, ref, indices, updates, name=None):
        return self._op("scatter.div", [ref, indices, updates], name=name)[0]

    def scatter_max(self, ref, indices, updates, name=None):
        return self._op("scatter.max", [ref, indices, updates], name=name)[0]

    def scatter_min(self, ref, indices, updates, name=None):
        return self._op("scatter.min", [ref, indices, updates], name=name)[0]

    def gather_nd(self, x, indices, name=None):
        return self._op("gather_nd", [x, indices], name=name)[0]

    # scatter-nd family (reference scatter_nd / scatter_nd_add /
    # scatter_nd_sub / scatter_nd_update: index TUPLES in the trailing
    # dim select elements; scatter_nd builds from zeros, duplicates sum)
    def scatter_nd(self, indices, updates, shape, name=None):
        return self._op("scatter.nd", [indices, updates], name=name,
                        shape=tuple(int(s) for s in shape))[0]

    def scatter_nd_add(self, ref, indices, updates, name=None):
        return self._op("scatter.ndAdd", [ref, indices, updates],
                        name=name)[0]

    def scatter_nd_sub(self, ref, indices, updates, name=None):
        return self._op("scatter.ndSub", [ref, indices, updates],
                        name=name)[0]

    def scatter_nd_update(self, ref, indices, updates, name=None):
        return self._op("scatter.ndUpdate", [ref, indices, updates],
                        name=name)[0]

    def split_v(self, x, sizes, axis=0, name=None):
        """Unequal-size split (reference split_v); `split` stays the
        equal-parts form."""
        return tuple(self._op("split_v", [x], n_out=len(sizes), name=name,
                              sizes=tuple(int(s) for s in sizes),
                              axis=int(axis)))

    # segment family (reference SDBaseOps segment* / unsortedSegment*: the
    # jax impls don't require sorted ids, so both surfaces share one op.
    # DEVIATION: num_segments is always required — XLA needs static output
    # shapes, so the sorted variants cannot infer it from the ids at run
    # time the way the reference kernels do)
    def _segment(self, kind, data, ids, num_segments, name):
        return self._op(f"segment.{kind}", [data, ids], name=name,
                        num_segments=int(num_segments))[0]

    def segment_sum(self, data, ids, num_segments, name=None):
        return self._segment("sum", data, ids, num_segments, name)

    def segment_mean(self, data, ids, num_segments, name=None):
        return self._segment("mean", data, ids, num_segments, name)

    def segment_max(self, data, ids, num_segments, name=None):
        return self._segment("max", data, ids, num_segments, name)

    def segment_min(self, data, ids, num_segments, name=None):
        return self._segment("min", data, ids, num_segments, name)

    def segment_prod(self, data, ids, num_segments, name=None):
        return self._segment("prod", data, ids, num_segments, name)

    unsorted_segment_sum = segment_sum
    unsorted_segment_mean = segment_mean
    unsorted_segment_max = segment_max
    unsorted_segment_min = segment_min
    unsorted_segment_prod = segment_prod

    def sequence_mask(self, lengths, maxlen, dtype="float32", name=None):
        return self._op("sequence_mask", [lengths], name=name,
                        maxlen=int(maxlen), dtype=str(dtype))[0]

    def shape_of(self, x, name=None):
        return self._op("shape_of", [x], name=name)[0]

    def zeros_like(self, x, name=None):
        return self._op("zeros_like", [x], name=name)[0]

    def ones_like(self, x, name=None):
        return self._op("ones_like", [x], name=name)[0]

    def eye(self, n, name=None):
        return self.constant(jnp.eye(n), name=name)

    def linspace(self, start, stop, num, name=None):
        return self.constant(jnp.linspace(start, stop, num), name=name)

    def range(self, start, stop, step=1, name=None, dtype="int32"):
        return self.constant(jnp.arange(start, stop, step, dtype=dtype),
                             name=name)

    # ---------------- control flow (structured, lax-backed) ----------------
    def _try_trace(self, fn, n_args):
        """Trace ``fn`` symbolically into a fresh child SameDiff by calling
        it on placeholder SDVariables. Returns (child, out_names,
        serializable) when the callable stayed inside SDVariable ops
        (``serializable`` is False if a NESTED control-flow body inside it
        used raw jax — executable, but save() must reject it), or None when
        ``fn`` itself used raw jax/numpy (still executable via the raw
        closure, just never saveable)."""
        child = SameDiff()
        args = [child.placeholder(f"arg{i}") for i in range(n_args)]
        before_ops = set(self.ops)
        before_vars = set(self.variables)
        polluted = False
        try:
            out = fn(*args)
        except (TypeError, AttributeError):
            # raw jax/numpy applied to an SDVariable placeholder fails with
            # one of these (incl. float(v) coercions — TypeError); the set
            # stays NARROW on purpose: a ValueError from a genuine user bug
            # must propagate here, at the cond/while/scan call site, not be
            # silently routed to the raw-closure path to resurface at a
            # distant jit trace. NOTE: the probe CALLS the body once at
            # graph build — side effects run here too (see cond docstring).
            out = None
        finally:
            # a callable mixing parent-graph variables creates stray nodes
            # in the PARENT during the probe — always roll those back
            # (including when a user bug propagates out of the probe)
            if (set(self.ops) != before_ops
                    or set(self.variables) != before_vars):
                polluted = True
                for k in set(self.ops) - before_ops:
                    del self.ops[k]
                for k in set(self.variables) - before_vars:
                    del self.variables[k]
                    self.arrays.pop(k, None)
                self._fn_cache.clear()
        if polluted or out is None:
            return None
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        if not all(isinstance(o, SDVariable) and o.sd is child
                   for o in outs):
            return None
        serializable = True
        for op in child.ops.values():
            if any(i not in child.variables for i in op.inputs):
                return None  # referenced a variable outside the child graph
            if set(op.fn_attrs) - set(op.subgraphs):
                # nested control flow with an untraceable body: the child
                # graph runs fine but would serialize without the inner
                # callables — mark the whole subgraph unsaveable
                serializable = False
        return child, [o.name for o in outs], serializable

    def cond(self, pred, true_fn, false_fn, operands, name=None,
             n_out: int = 1):
        """Structured conditional — replaces the reference's Switch/Merge
        frame machinery with ``lax.cond`` (compiler-friendly; both branches
        traced once). ``true_fn``/``false_fn`` map arrays -> array (or a
        tuple of ``n_out`` arrays — both branches must agree). Returns one
        SDVariable, or a tuple of ``n_out`` of them. When the callables
        stay inside SDVariable ops the graph remains serializable
        (save/load round-trips the branches).

        BUILD-TIME PROBE CONTRACT (also for while_loop/scan): each body is
        CALLED once on symbolic placeholders at graph build to decide
        serializability — side effects in the body run at build time, and
        bodies needing concrete values (``float(v)``, data-dependent
        Python branching) fall back to the raw-closure (unsaveable) path."""
        from deeplearning4j_tpu.samediff import serde as _serde

        n = len(operands)
        single = n_out == 1
        traced_t = self._try_trace(true_fn, n)
        traced_f = self._try_trace(false_fn, n)
        fn_attrs = {"true_fn": true_fn, "false_fn": false_fn}
        subgraphs = {}
        if traced_t is not None and traced_f is not None:
            (ct, ot, st), (cf, of, sf) = traced_t, traced_f
            if len(ot) != n_out or len(of) != n_out:
                raise ValueError(
                    f"cond branches returned {len(ot)}/{len(of)} outputs, "
                    f"expected n_out={n_out}")
            fn_attrs = {"true_fn": subgraph_callable(ct, ot, single=single),
                        "false_fn": subgraph_callable(cf, of, single=single)}
            if st and sf:
                subgraphs = {
                    "true_fn": _serde.subgraph_dict(ct, ot, single=single),
                    "false_fn": _serde.subgraph_dict(cf, of, single=single)}
        outs = self._op("cond", [pred] + list(operands), n_out=n_out,
                        name=name, fn_attrs=fn_attrs, subgraphs=subgraphs)
        return outs[0] if single else tuple(outs)

    def while_loop(self, cond_fn, body_fn, operands, name=None,
                   max_iterations: Optional[int] = None):
        """Structured while — replaces Enter/Exit/NextIteration frames with
        ``lax.while_loop``. ``operands`` is the loop carry (list of vars);
        returns the final carry as a tuple of SDVariables. Serializable
        when the callables stay inside SDVariable ops.

        ``max_iterations``: an upper trip-count bound. When given, the
        loop lowers to a masked ``lax.scan`` of exactly that length —
        results match the unbounded form whenever the loop exits within
        the bound, and the loop becomes REVERSE-DIFFERENTIABLE (training
        can backprop through it; raw ``lax.while_loop`` has no transpose
        rule — the reference's TrainingSession backprops through its loop
        frames, and this is the TPU-native path to the same capability)."""
        from deeplearning4j_tpu.samediff import serde as _serde

        n = len(operands)
        traced_c = self._try_trace(cond_fn, n)
        traced_b = self._try_trace(body_fn, n)
        fn_attrs = {"cond_fn": cond_fn, "body_fn": body_fn}
        subgraphs = {}
        if traced_c is not None and traced_b is not None:
            (cc, oc, sc), (cb, ob, sb) = traced_c, traced_b
            fn_attrs = {"cond_fn": subgraph_callable(cc, oc, single=True),
                        "body_fn": subgraph_callable(cb, ob, single=False)}
            if sc and sb:
                subgraphs = {
                    "cond_fn": _serde.subgraph_dict(cc, oc, single=True),
                    "body_fn": _serde.subgraph_dict(cb, ob, single=False)}
        return self._op("while_loop", list(operands), n_out=n, name=name,
                        fn_attrs=fn_attrs, subgraphs=subgraphs,
                        max_iterations=(None if max_iterations is None
                                        else int(max_iterations)))

    def scan(self, body_fn, init, xs, name=None):
        """``lax.scan`` over leading axis of ``xs``; body maps
        (carry, x) -> (carry, y). Returns (final_carry, ys). Serializable
        when ``body_fn`` stays inside SDVariable ops."""
        from deeplearning4j_tpu.samediff import serde as _serde

        traced = self._try_trace(body_fn, 2)
        fn_attrs = {"body_fn": body_fn}
        subgraphs = {}
        if traced is not None:
            child, outs, ser = traced
            if len(outs) == 2:
                fn_attrs = {"body_fn": subgraph_callable(child, outs,
                                                         single=False)}
                if ser:
                    subgraphs = {"body_fn": _serde.subgraph_dict(
                        child, outs, single=False)}
        return self._op("scan_op", [init, xs], n_out=2, name=name,
                        fn_attrs=fn_attrs, subgraphs=subgraphs)

    # ---------------- persistence ----------------
    def save(self, path, save_updater_state: bool = True):
        from deeplearning4j_tpu.samediff.serde import save as _save
        _save(self, path, save_updater_state)

    @staticmethod
    def load(path):
        from deeplearning4j_tpu.samediff.serde import load as _load
        return _load(path)

    def summary(self) -> str:
        lines = [f"SameDiff: {len(self.variables)} variables, "
                 f"{len(self.ops)} ops"]
        for v in self.variables.values():
            if v.var_type != VariableType.ARRAY:
                lines.append(f"  {v.var_type:<12} {v.name:<24} "
                             f"shape={v.shape}")
        for op in self.ops.values():
            lines.append(f"  OP {op.op_name:<18} {op.name:<24} "
                         f"{op.inputs} -> {op.outputs}")
        return "\n".join(lines)


def subgraph_callable(child: "SameDiff", out_names: list, single: bool):
    """Turn a traced child graph into a plain ``f(*arrays) -> array/tuple``
    suitable for ``lax.cond/while_loop/scan`` bodies."""
    fn = child.make_function(tuple(out_names))
    arg_names = [v.name for v in child.variables.values()
                 if v.var_type == VariableType.PLACEHOLDER]

    def call(*xs):
        res = fn(child.arrays, dict(zip(arg_names, xs)))
        outs = [res[o] for o in out_names]
        return outs[0] if single else tuple(outs)

    return call


def _init_array(shape, weight_init, dtype, key):
    """Init a VARIABLE. Accepts a conf.weights WeightInit or None (Xavier,
    the reference default for SDVariable trainables)."""
    shape = tuple(int(s) for s in shape)
    if key is None:
        key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
    if weight_init is None:
        if len(shape) >= 2:
            fan_in, fan_out = shape[-2], shape[-1]
            std = float(np.sqrt(2.0 / (fan_in + fan_out)))
            return std * jax.random.normal(key, shape, dtype=dtype)
        return jnp.zeros(shape, dtype=dtype)
    if callable(getattr(weight_init, "init", None)):
        fan_in = shape[0] if len(shape) > 1 else 1
        fan_out = shape[-1]
        return weight_init.init(key, shape, fan_in, fan_out).astype(dtype)
    raise TypeError(f"bad weight_init {weight_init!r}")


# ---- structural op impls (registered) ----

@register_op("identity")
def _op_identity(x):
    return x


@register_op("reshape_onnx")
def _op_reshape_onnx(x, *, shape):
    """ONNX Reshape semantics: 0 copies the input dim, -1 infers."""
    shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return x.reshape(shape)


@register_op("unsqueeze_onnx")
def _op_unsqueeze_onnx(x, *, axes):
    """ONNX Unsqueeze: axes are relative to the OUTPUT rank."""
    out_rank = x.ndim + len(axes)
    for a in sorted(a % out_rank for a in axes):
        x = jnp.expand_dims(x, a)
    return x


@register_op("softmax_flattened")
def _op_softmax_flattened(x, *, axis):
    """ONNX opset<13 Softmax: coerce to 2D at ``axis``, softmax the flat
    tail, restore shape."""
    import numpy as _np

    lead = int(_np.prod(x.shape[:axis], dtype=_np.int64)) if axis else 1
    flat = x.reshape(lead, -1)
    return jax.nn.softmax(flat, axis=-1).reshape(x.shape)


@register_op("flatten2d")
def _op_flatten2d(x):
    """[b, ...] -> [b, prod(...)] (ONNX Flatten / Keras Flatten)."""
    return x.reshape(x.shape[0], -1)


@register_op("reshape")
def _op_reshape(x, *, shape):
    return jnp.reshape(x, shape)


@register_op("transpose")
def _op_transpose(x):
    return jnp.transpose(x)


@register_op("permute")
def _op_permute(x, *, dims):
    return jnp.transpose(x, dims)


@register_op("concat")
def _op_concat(*xs, axis):
    return jnp.concatenate(xs, axis=axis)


@register_op("stack")
def _op_stack(*xs, axis):
    return jnp.stack(xs, axis=axis)


@register_op("unstack")
def _op_unstack(x, *, axis, num):
    parts = jnp.split(x, num, axis=axis)
    return tuple(jnp.squeeze(p, axis=axis) for p in parts)


@register_op("split")
def _op_split(x, *, axis, num=None, sizes=None):
    """Even split (``num``) or ragged split (``sizes``, TF SplitV). A
    single ``-1`` size is inferred from the input dim (TF semantics);
    shapes are concrete at trace time."""
    if sizes is not None:
        sizes = [int(s) for s in sizes]
        if sizes.count(-1) > 1:
            raise ValueError("split: at most one size may be -1")
        if -1 in sizes:
            known = sum(s for s in sizes if s >= 0)
            sizes[sizes.index(-1)] = int(x.shape[axis]) - known
        cuts = list(np.cumsum(sizes[:-1]))
        return tuple(jnp.split(x, cuts, axis=axis))
    return tuple(jnp.split(x, num, axis=axis))


@register_op("select_tf")
def _op_select_tf(cond, a, b):
    """TF ``Select`` (v1): a rank-1 condition of length B against rank-N
    operands selects whole leading-dim rows (unlike where's trailing
    broadcast)."""
    c = cond.astype(bool)
    if c.ndim == 1 and a.ndim > 1:
        c = c.reshape((-1,) + (1,) * (a.ndim - 1))
    return jnp.where(c, a, b)


@register_op("strided_slice")
def _op_strided_slice(x, *, begin, end, strides, begin_mask=0, end_mask=0,
                      ellipsis_mask=0, new_axis_mask=0, shrink_axis_mask=0):
    """TF StridedSlice semantics for STATIC begin/end/strides, with the
    common masks (begin/end/shrink). Cite: reference StridedSlice import in
    TFGraphMapper per-op mappings."""
    if ellipsis_mask or new_axis_mask:
        raise NotImplementedError(
            "strided_slice: ellipsis_mask/new_axis_mask not supported")
    idx = []
    for i in range(len(begin)):
        if shrink_axis_mask & (1 << i):
            idx.append(int(begin[i]))
            continue
        b = None if (begin_mask & (1 << i)) else int(begin[i])
        e = None if (end_mask & (1 << i)) else int(end[i])
        idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


@register_op("squeeze")
def _op_squeeze(x, *, axis):
    return jnp.squeeze(x, axis=axis)


@register_op("expand_dims")
def _op_expand_dims(x, *, axis):
    return jnp.expand_dims(x, axis=axis)


@register_op("tile")
def _op_tile(x, *, reps):
    return jnp.tile(x, reps)


@register_op("cast")
def _op_cast(x, *, dtype):
    return x.astype(dtype)


@register_op("slice_op")
def _op_slice(x, *, begin, size):
    return jax.lax.dynamic_slice(x, begin, size)


@register_op("gather")
def _op_gather(x, indices, *, axis):
    return jnp.take(x, indices.astype(jnp.int32), axis=axis)


@register_op("one_hot")
def _op_one_hot(indices, *, depth, axis=-1, dtype="float32"):
    r = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype)
    return jnp.moveaxis(r, -1, axis) if axis != -1 else r


@register_op("shape_of")
def _op_shape_of(x):
    return jnp.asarray(x.shape, dtype=jnp.int32)


@register_op("zeros_like")
def _op_zeros_like(x):
    return jnp.zeros_like(x)


@register_op("ones_like")
def _op_ones_like(x):
    return jnp.ones_like(x)


@register_op("cond")
def _op_cond(pred, *operands, true_fn, false_fn):
    return jax.lax.cond(pred.astype(bool).reshape(()), true_fn, false_fn,
                        *operands)


@register_op("while_loop")
def _op_while_loop(*operands, cond_fn, body_fn, max_iterations=None):
    def as_carry(r):
        # a single-carry body may return a bare array; tuple(r) would
        # wrongly iterate its elements
        return tuple(r) if isinstance(r, (tuple, list)) else (r,)

    if max_iterations is None:
        return jax.lax.while_loop(
            lambda c: cond_fn(*c).astype(bool).reshape(()),
            lambda c: as_carry(body_fn(*c)), tuple(operands))

    # bounded form: a scan over max_iterations steps — identical results
    # whenever the loop exits within the bound, and REVERSE-DIFFERENTIABLE
    # (lax.while_loop has no transpose rule; scan does). The step is a
    # lax.cond, NOT a jnp.where over an always-evaluated body: once the
    # condition goes false the body never runs, so a body that would be
    # undefined past exit (divide-by-zero at the boundary, say) neither
    # poisons the forward nor turns the where-transpose into 0*inf NaNs.
    def step(c):
        new = as_carry(body_fn(*c))
        if len(new) != len(c):
            raise ValueError(
                f"while_loop body returned {len(new)} outputs for a "
                f"{len(c)}-element carry (the unbounded lowering rejects "
                "this too)")
        return new

    def body(c, _):
        pred = cond_fn(*c).astype(bool).reshape(())
        return jax.lax.cond(pred, step, lambda c: c, c), None

    out, _ = jax.lax.scan(body, tuple(operands), None,
                          length=int(max_iterations))
    return out


@register_op("scan_op")
def _op_scan(init, xs, *, body_fn):
    return jax.lax.scan(body_fn, init, xs)
