"""Namespaced op factories for SameDiff (reference
``org.nd4j.autodiff.samediff.ops.SDMath/SDNN/SDCNN/SDRNN/SDLoss/SDRandom/
SDLinalg/SDImage/SDBitwise`` — SURVEY.md §2.2 "SameDiff core").

Every factory records a node referencing a registered pure-jax op impl;
the lowered graph compiles to one XLA program (libnd4j's per-op kernels
collapse into XLA fusion). Where the reference escapes to hand kernels
(cuDNN lstmLayer, attention helpers), the TPU path is ``lax.scan`` /
``lax.conv_general_dilated`` / ``jax.nn`` primitives the compiler tiles
onto the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.samediff.core import register_op


class _Namespace:
    def __init__(self, sd):
        self.sd = sd

    def _op(self, op_name, inputs, n_out=1, name=None, **attrs):
        return self.sd._op(op_name, inputs, n_out=n_out, name=name, **attrs)


def _axes(dims):
    if dims is None:
        return None
    if isinstance(dims, int):
        return (dims,)
    return tuple(int(d) for d in dims)


# ======================= elementwise / reduce impls =======================

_UNARY = {
    "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log, "log1p": jnp.log1p,
    "sqrt": jnp.sqrt, "square": jnp.square, "sin": jnp.sin, "cos": jnp.cos,
    "tan": jnp.tan, "asin": jnp.arcsin, "acos": jnp.arccos,
    "atan": jnp.arctan, "sinh": jnp.sinh, "cosh": jnp.cosh,
    "tanh": jnp.tanh, "asinh": jnp.arcsinh, "acosh": jnp.arccosh,
    "atanh": jnp.arctanh, "floor": jnp.floor, "ceil": jnp.ceil,
    "round": jnp.round, "sign": jnp.sign, "neg": jnp.negative,
    "reciprocal": jnp.reciprocal, "rsqrt": jax.lax.rsqrt,
    "erf": jax.scipy.special.erf, "erfc": jax.scipy.special.erfc,
    "exp2": jnp.exp2, "expm1": jnp.expm1, "log2": jnp.log2,
    "log10": jnp.log10, "isnan": jnp.isnan, "isinf": jnp.isinf,
    "isfinite": jnp.isfinite, "logical_not": jnp.logical_not,
}
for _n, _f in _UNARY.items():
    register_op(f"math.{_n}")(_f)

_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "pow": jnp.power, "floordiv": jnp.floor_divide,
    "mod": jnp.mod, "atan2": jnp.arctan2,
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "eq": lambda a, b: (a == b), "neq": lambda a, b: (a != b),
    "gt": jnp.greater, "gte": jnp.greater_equal,
    "lt": jnp.less, "lte": jnp.less_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "rsub": lambda a, b: b - a, "rdiv": lambda a, b: b / a,
    "squared_difference": lambda a, b: jnp.square(a - b),
}
for _n, _f in _BINARY.items():
    register_op(f"math.{_n}")(_f)

_REDUCE = {
    "sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod, "amax": jnp.max,
    "amin": jnp.min, "norm1": lambda x, axis, keepdims: jnp.sum(
        jnp.abs(x), axis=axis, keepdims=keepdims),
    "norm2": lambda x, axis, keepdims: jnp.sqrt(jnp.sum(
        x * x, axis=axis, keepdims=keepdims)),
    "normmax": lambda x, axis, keepdims: jnp.max(
        jnp.abs(x), axis=axis, keepdims=keepdims),
    "std": jnp.std, "var": jnp.var,
    "countNonZero": lambda x, axis, keepdims: jnp.sum(
        (x != 0).astype(jnp.int32), axis=axis, keepdims=keepdims),
}
for _n, _f in _REDUCE.items():
    register_op(f"reduce.{_n}")(
        lambda x, *, axis, keepdims, _f=_f: _f(x, axis=axis,
                                               keepdims=keepdims))


@register_op("math.clip_by_value")
def _clip(x, *, lo, hi):
    return jnp.clip(x, lo, hi)


@register_op("math.matmul")
def _matmul(a, b, *, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register_op("math.tensordot")
def _tensordot(a, b, *, axes_a, axes_b):
    return jnp.tensordot(a, b, axes=(tuple(axes_a), tuple(axes_b)))


@register_op("math.argmax")
def _argmax(x, *, axis, keepdims):
    r = jnp.argmax(x, axis=axis)
    return jnp.expand_dims(r, axis) if keepdims else r


@register_op("math.argmin")
def _argmin(x, *, axis, keepdims):
    r = jnp.argmin(x, axis=axis)
    return jnp.expand_dims(r, axis) if keepdims else r


@register_op("math.cumsum")
def _cumsum(x, *, axis):
    return jnp.cumsum(x, axis=axis)


@register_op("math.cumprod")
def _cumprod(x, *, axis):
    return jnp.cumprod(x, axis=axis)


@register_op("math.where")
def _where(cond, a, b):
    return jnp.where(cond.astype(bool), a, b)


@register_op("math.whereNonzero")
def _where_nonzero(x):
    """Coordinates of nonzero elements (TF 1-input ``Where``,
    reference Where op) under the BOUNDED-SHAPE convention XLA
    requires: the true output size is data-dependent, so this returns
    ``(indices, count)`` with ``indices`` [size(x), rank] (default int
    dtype; TF's op emits int64, irrelevant to consumers here) —
    row-major coordinates of the nonzero elements in the first
    ``count`` rows, zero-padded after — and ``count`` scalar int32.
    Consumers must mask by ``count``; a GatherNd over the padded tail
    reads element (0,...,0), never out of bounds."""
    flat = x.reshape(-1).astype(bool)
    n = flat.shape[0]
    pos = jnp.arange(n)
    tgt = jnp.where(flat, jnp.cumsum(flat) - 1, n)  # n -> dropped
    lin = jnp.zeros_like(pos).at[tgt].set(pos, mode="drop")
    count = jnp.sum(flat.astype(jnp.int32))
    coords = jnp.stack(jnp.unravel_index(lin, x.shape), axis=-1)
    return coords, count


@register_op("math.reverse")
def _reverse(x, *, dims):
    return jnp.flip(x, axis=dims)


@register_op("math.diag")
def _diag(x):
    return jnp.diag(x)


@register_op("math.trace")
def _trace(x):
    return jnp.trace(x)


class SDMath(_Namespace):
    """Reference ``sd.math()`` — elementwise, reduce, linear algebra glue."""

    def _bin(self, opn, a, b, name=None):
        return self._op(f"math.{opn}", [a, b], name=name)[0]

    def _un(self, opn, x, name=None):
        return self._op(f"math.{opn}", [x], name=name)[0]

    def _red(self, opn, x, dims=None, keepdims=False, name=None):
        return self._op(f"reduce.{opn}", [x], name=name,
                        axis=_axes(dims), keepdims=bool(keepdims))[0]


def _add_simple(cls, names, maker):
    for n in names:
        def m(self, *args, _n=n, name=None, **kw):
            return maker(self, _n, *args, name=name, **kw)
        m.__name__ = n
        setattr(cls, n, m)


_add_simple(SDMath, list(_UNARY), lambda self, n, x, name=None: self._un(
    n, x, name))
_add_simple(SDMath, list(_BINARY), lambda self, n, a, b, name=None: self._bin(
    n, a, b, name))
for _n in _REDUCE:
    def _mk(_n=_n):
        def m(self, x, dims=None, keepdims=False, name=None):
            return self._red(_n, x, dims, keepdims, name)
        m.__name__ = _n
        return m
    setattr(SDMath, _n, _mk())
SDMath.max = SDMath.amax  # reference naming
SDMath.min = SDMath.amin


def _math_extra(self):  # placeholder to keep flake quiet
    pass


def _def(cls, name):
    def deco(fn):
        fn.__name__ = name
        setattr(cls, name, fn)
        return fn
    return deco


@_def(SDMath, "mmul")
def _sd_mmul(self, a, b, transpose_a=False, transpose_b=False, name=None):
    return self._op("math.matmul", [a, b], name=name,
                    transpose_a=bool(transpose_a),
                    transpose_b=bool(transpose_b))[0]


@_def(SDMath, "tensorMmul")
def _sd_tensormmul(self, a, b, axes_a, axes_b, name=None):
    return self._op("math.tensordot", [a, b], name=name,
                    axes_a=_axes(axes_a), axes_b=_axes(axes_b))[0]


@_def(SDMath, "clipByValue")
def _sd_clip(self, x, lo, hi, name=None):
    return self._op("math.clip_by_value", [x], name=name,
                    lo=float(lo), hi=float(hi))[0]


@_def(SDMath, "argmax")
def _sd_argmax(self, x, dim=None, keepdims=False, name=None):
    return self._op("math.argmax", [x], name=name, axis=dim,
                    keepdims=bool(keepdims))[0]


@_def(SDMath, "argmin")
def _sd_argmin(self, x, dim=None, keepdims=False, name=None):
    return self._op("math.argmin", [x], name=name, axis=dim,
                    keepdims=bool(keepdims))[0]


@_def(SDMath, "cumsum")
def _sd_cumsum(self, x, axis=0, name=None):
    return self._op("math.cumsum", [x], name=name, axis=int(axis))[0]


@_def(SDMath, "cumprod")
def _sd_cumprod(self, x, axis=0, name=None):
    return self._op("math.cumprod", [x], name=name, axis=int(axis))[0]


@_def(SDMath, "where")
def _sd_where(self, cond, a, b, name=None):
    return self._op("math.where", [cond, a, b], name=name)[0]


@_def(SDMath, "whereNonzero")
def _sd_where_nonzero(self, x, name=None):
    """-> (indices [size, rank] int, count int32) — bounded-shape
    nonzero coordinates; see math.whereNonzero."""
    idx, count = self._op("math.whereNonzero", [x], n_out=2,
                           name=name)
    return idx, count


@_def(SDMath, "reverse")
def _sd_reverse(self, x, *dims, name=None):
    return self._op("math.reverse", [x], name=name, dims=_axes(dims))[0]


@_def(SDMath, "diag")
def _sd_diag(self, x, name=None):
    return self._op("math.diag", [x], name=name)[0]


@_def(SDMath, "trace")
def _sd_trace(self, x, name=None):
    return self._op("math.trace", [x], name=name)[0]


def _def_reduce3(opn):
    def m(self, x, y, dims=None, keepdims=False, name=None, _n=opn):
        return self._op(f"math.{_n}", [x, y], name=name, axis=_axes(dims),
                        keepdims=bool(keepdims))[0]
    m.__name__ = opn
    setattr(SDMath, opn, m)


for _n in ("euclideanDistance", "manhattanDistance", "cosineSimilarity",
           "cosineDistance", "dot", "hammingDistance", "jaccardDistance"):
    _def_reduce3(_n)

_add_simple(SDMath, ["lgamma", "digamma", "rint"],
            lambda self, n, x, name=None: self._un(n, x, name))


@_def(SDMath, "standardize")
def _sd_standardize(self, x, dims=-1, name=None):
    return self._op("math.standardize", [x], name=name, axis=_axes(dims))[0]


@_def(SDMath, "isMax")
def _sd_is_max(self, x, dims=-1, name=None):
    return self._op("math.isMax", [x], name=name, axis=_axes(dims))[0]


@_def(SDMath, "cross")
def _sd_cross(self, a, b, name=None):
    return self._op("math.cross", [a, b], name=name)[0]


# ======================= nn =======================

_NN_UNARY = {
    "relu": jax.nn.relu, "relu6": jax.nn.relu6, "elu": jax.nn.elu,
    "selu": jax.nn.selu, "gelu": jax.nn.gelu, "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus, "softsign": jax.nn.soft_sign,
    "swish": jax.nn.swish, "silu": jax.nn.silu, "tanh": jnp.tanh,
    "hardSigmoid": jax.nn.hard_sigmoid, "hardTanh": jax.nn.hard_tanh,
    "mish": jax.nn.mish,
}
for _n, _f in _NN_UNARY.items():
    register_op(f"nn.{_n}")(_f)


@register_op("nn.leakyRelu")
def _leaky(x, *, alpha):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


@register_op("nn.softmax")
def _softmax(x, *, axis):
    return jax.nn.softmax(x, axis=axis)


@register_op("nn.logSoftmax")
def _log_softmax(x, *, axis):
    return jax.nn.log_softmax(x, axis=axis)


@register_op("nn.linear")
def _linear(x, w, b):
    return x @ w + b


@register_op("nn.biasAdd")
def _bias_add(x, b):
    return x + b


@register_op("nn.dropout")
def _dropout(x, *, rate, seed, train):
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(jax.random.PRNGKey(seed), keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


@register_op("nn.layerNorm")
def _layer_norm(x, gain, bias, *, axis, eps):
    mu = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    return gain * (x - mu) * jax.lax.rsqrt(var + eps) + bias


@register_op("nn.batchNorm")
def _batch_norm(x, mean, var, gamma, beta, *, axis, eps):
    shape = [1] * x.ndim
    shape[axis] = -1
    rs = lambda a: a.reshape(shape)  # noqa: E731
    return (x - rs(mean)) * jax.lax.rsqrt(rs(var) + eps) * rs(gamma) + rs(beta)


@register_op("nn.dotProductAttention")
def _dpa(q, k, v, mask, *, scaled):
    """Reference ``sd.nn.dotProductAttention`` — [batch, heads?, time, dim].
    mask: [batch, kv_time] 1/0 or all-ones. XLA fuses the softmax chain;
    the matmuls land on the MXU."""
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k)
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(d, scores.dtype))
    neg = jnp.asarray(-1e9, scores.dtype)
    while mask.ndim < scores.ndim:
        mask = mask[:, None, ...]
    scores = jnp.where(mask.astype(bool), scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", w, v)


@register_op("nn.multiHeadDotProductAttention")
def _mhdpa(q, k, v, wq, wk, wv, wo, mask, *, num_heads, scaled):
    """Reference ``sd.nn.multiHeadDotProductAttention``. Inputs [B, T, E];
    projection weights [E, H*D]; output projection [H*D, E]."""
    def split(x, w):
        y = x @ w  # [B,T,H*D]
        b, t, hd = y.shape
        return y.reshape(b, t, num_heads, hd // num_heads).transpose(
            0, 2, 1, 3)  # [B,H,T,D]
    qh, kh, vh = split(q, wq), split(k, wk), split(v, wv)
    out = _dpa(qh, kh, vh, mask, scaled=scaled)  # [B,H,T,D]
    b, h, t, d = out.shape
    out = out.transpose(0, 2, 1, 3).reshape(b, t, h * d)
    return out @ wo


@register_op("nn.pad")
def _pad(x, *, paddings, mode, value):
    return jnp.pad(x, paddings, mode=mode, constant_values=value) \
        if mode == "constant" else jnp.pad(x, paddings, mode=mode)


class SDNN(_Namespace):
    """Reference ``sd.nn()``."""


_add_simple(SDNN, list(_NN_UNARY),
            lambda self, n, x, name=None: self._op(f"nn.{n}", [x],
                                                   name=name)[0])


@_def(SDNN, "leakyRelu")
def _sd_leaky(self, x, alpha=0.01, name=None):
    return self._op("nn.leakyRelu", [x], name=name, alpha=float(alpha))[0]


@_def(SDNN, "softmax")
def _sd_softmax(self, x, dimension=-1, name=None):
    return self._op("nn.softmax", [x], name=name, axis=int(dimension))[0]


@_def(SDNN, "logSoftmax")
def _sd_log_softmax(self, x, dimension=-1, name=None):
    return self._op("nn.logSoftmax", [x], name=name, axis=int(dimension))[0]


@_def(SDNN, "linear")
def _sd_linear(self, x, w, b, name=None):
    return self._op("nn.linear", [x, w, b], name=name)[0]


@_def(SDNN, "biasAdd")
def _sd_bias_add(self, x, b, name=None):
    return self._op("nn.biasAdd", [x, b], name=name)[0]


@_def(SDNN, "dropout")
def _sd_dropout(self, x, rate, seed=0, train=True, name=None):
    return self._op("nn.dropout", [x], name=name, rate=float(rate),
                    seed=int(seed), train=bool(train))[0]


@_def(SDNN, "layerNorm")
def _sd_layer_norm(self, x, gain, bias, axis=-1, eps=1e-5, name=None):
    return self._op("nn.layerNorm", [x, gain, bias], name=name,
                    axis=int(axis), eps=float(eps))[0]


@_def(SDNN, "batchNorm")
def _sd_batch_norm(self, x, mean, var, gamma, beta, axis=-1, eps=1e-5,
                   name=None):
    return self._op("nn.batchNorm", [x, mean, var, gamma, beta], name=name,
                    axis=int(axis), eps=float(eps))[0]


@_def(SDNN, "dotProductAttention")
def _sd_dpa(self, q, k, v, mask=None, scaled=True, name=None):
    if mask is None:
        mask = self.sd.ones_like(self.sd._op(
            "reduce.sum", [k], axis=(-1,), keepdims=False)[0])
    return self._op("nn.dotProductAttention", [q, k, v, mask], name=name,
                    scaled=bool(scaled))[0]


@_def(SDNN, "multiHeadDotProductAttention")
def _sd_mhdpa(self, q, k, v, wq, wk, wv, wo, mask=None, num_heads=1,
              scaled=True, name=None):
    if mask is None:
        mask = self.sd.ones_like(self.sd._op(
            "reduce.sum", [k], axis=(-1,), keepdims=False)[0])
    return self._op("nn.multiHeadDotProductAttention",
                    [q, k, v, wq, wk, wv, wo, mask], name=name,
                    num_heads=int(num_heads), scaled=bool(scaled))[0]


@_def(SDNN, "pad")
def _sd_pad(self, x, paddings, mode="constant", value=0.0, name=None):
    return self._op("nn.pad", [x], name=name,
                    paddings=tuple(tuple(p) for p in paddings),
                    mode=mode, value=float(value))[0]


# ======================= cnn =======================

@register_op("cnn.conv2d")
def _conv2d(x, w, b, *, strides, padding, dilation, fmt="NHWC", groups=1):
    """Default NHWC x HWIO -> NHWC (TPU-native layout). ``fmt="NCHW"``
    supports imported ONNX graphs (weights then OIHW); XLA transposes into
    its preferred layout during compilation either way."""
    dn = (("NCHW", "OIHW", "NCHW") if fmt == "NCHW"
          else ("NHWC", "HWIO", "NHWC"))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if fmt == "NCHW":
        return out + b.reshape(1, -1, 1, 1)
    return out + b


@register_op("cnn.conv1d")
def _conv1d(x, w, b, *, stride, padding):
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=padding,
        dimension_numbers=("NWC", "WIO", "NWC"))
    return out + b


@register_op("cnn.depthwiseConv2d")
def _dwconv2d(x, w, b, *, strides, padding):
    c = x.shape[-1]
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out + b


@register_op("cnn.maxPooling2d")
def _maxpool2d(x, *, k, s, padding, fmt="NHWC"):
    dims = (1, 1, *k) if fmt == "NCHW" else (1, *k, 1)
    strd = (1, 1, *s) if fmt == "NCHW" else (1, *s, 1)
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, dims, strd, padding)


@register_op("cnn.avgPooling2d")
def _avgpool2d(x, *, k, s, padding, fmt="NHWC"):
    dims = (1, 1, *k) if fmt == "NCHW" else (1, *k, 1)
    strd = (1, 1, *s) if fmt == "NCHW" else (1, *s, 1)
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, dims, strd, padding)
    ones = jnp.ones_like(x)
    counts = jax.lax.reduce_window(
        ones, 0.0, jax.lax.add, dims, strd, padding)
    return summed / counts


@register_op("cnn.upsampling2d")
def _upsample2d(x, *, scale):
    return jnp.repeat(jnp.repeat(x, scale, axis=1), scale, axis=2)


class SDCNN(_Namespace):
    """Reference ``sd.cnn()``."""


@_def(SDCNN, "conv2d")
def _sd_conv2d(self, x, w, b=None, strides=(1, 1), padding="SAME",
               dilation=(1, 1), name=None):
    if b is None:
        b = self.sd.constant(jnp.zeros((w.shape[-1],) if w.shape else (1,)))
    return self._op("cnn.conv2d", [x, w, b], name=name,
                    strides=tuple(strides), padding=padding,
                    dilation=tuple(dilation))[0]


@_def(SDCNN, "conv1d")
def _sd_conv1d(self, x, w, b=None, stride=1, padding="SAME", name=None):
    if b is None:
        b = self.sd.constant(jnp.zeros((w.shape[-1],) if w.shape else (1,)))
    return self._op("cnn.conv1d", [x, w, b], name=name, stride=int(stride),
                    padding=padding)[0]


@_def(SDCNN, "depthwiseConv2d")
def _sd_dwconv2d(self, x, w, b=None, strides=(1, 1), padding="SAME",
                 name=None):
    if b is None:
        b = self.sd.constant(jnp.zeros((w.shape[-1] * w.shape[-2],)))
    return self._op("cnn.depthwiseConv2d", [x, w, b], name=name,
                    strides=tuple(strides), padding=padding)[0]


@_def(SDCNN, "maxPooling2d")
def _sd_maxpool(self, x, k=(2, 2), s=(2, 2), padding="VALID", name=None):
    return self._op("cnn.maxPooling2d", [x], name=name, k=tuple(k),
                    s=tuple(s), padding=padding)[0]


@_def(SDCNN, "avgPooling2d")
def _sd_avgpool(self, x, k=(2, 2), s=(2, 2), padding="VALID", name=None):
    return self._op("cnn.avgPooling2d", [x], name=name, k=tuple(k),
                    s=tuple(s), padding=padding)[0]


@_def(SDCNN, "upsampling2d")
def _sd_upsample(self, x, scale=2, name=None):
    return self._op("cnn.upsampling2d", [x], name=name, scale=int(scale))[0]


@_def(SDCNN, "batchNorm")
def _sd_cnn_bn(self, x, mean, var, gamma, beta, axis=-1, eps=1e-5,
               name=None):
    return self._op("nn.batchNorm", [x, mean, var, gamma, beta], name=name,
                    axis=int(axis), eps=float(eps))[0]


# ======================= rnn =======================

@register_op("rnn.lstmLayer")
def _lstm_layer(x, w, r, b, h0, c0):
    """Reference ``sd.rnn.lstmLayer`` (libnd4j lstmLayer / cuDNN helper).
    x [T,B,I] (TNS format), w [I,4H], r [H,4H], b [4H]. Gate order matches
    the reference's c-i-f-o ordering in ``LSTMHelpers``: here i,f,g,o blocks.
    One ``lax.scan`` — the whole sequence is a single fused XLA loop."""
    hidden = r.shape[0]

    def step(hc, xt):
        h, c = hc
        z = xt @ w + h @ r + b
        i, f, g, o = (z[:, :hidden], z[:, hidden:2 * hidden],
                      z[:, 2 * hidden:3 * hidden], z[:, 3 * hidden:])
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (h_f, c_f), ys = jax.lax.scan(step, (h0, c0), x)
    return ys, h_f, c_f


@register_op("rnn.gru")
def _gru(x, w, r, b, h0):
    """x [T,B,I], w [I,3H], r [H,3H], b [3H]; gates r,z,n."""
    hidden = r.shape[0]

    def step(h, xt):
        zx = xt @ w + b
        zh = h @ r
        rg = jax.nn.sigmoid(zx[:, :hidden] + zh[:, :hidden])
        zg = jax.nn.sigmoid(zx[:, hidden:2 * hidden] +
                            zh[:, hidden:2 * hidden])
        ng = jnp.tanh(zx[:, 2 * hidden:] + rg * zh[:, 2 * hidden:])
        h_new = (1 - zg) * ng + zg * h
        return h_new, h_new

    h_f, ys = jax.lax.scan(step, h0, x)
    return ys, h_f


@register_op("rnn.simpleRnn")
def _simple_rnn(x, w, r, b, h0):
    def step(h, xt):
        h_new = jnp.tanh(xt @ w + h @ r + b)
        return h_new, h_new
    h_f, ys = jax.lax.scan(step, h0, x)
    return ys, h_f


class SDRNN(_Namespace):
    """Reference ``sd.rnn()``."""


@_def(SDRNN, "lstmLayer")
def _sd_lstm(self, x, w, r, b, h0, c0, name=None):
    return self._op("rnn.lstmLayer", [x, w, r, b, h0, c0], n_out=3,
                    name=name)


@_def(SDRNN, "gru")
def _sd_gru(self, x, w, r, b, h0, name=None):
    return self._op("rnn.gru", [x, w, r, b, h0], n_out=2, name=name)


@_def(SDRNN, "simpleRnn")
def _sd_simple_rnn(self, x, w, r, b, h0, name=None):
    return self._op("rnn.simpleRnn", [x, w, r, b, h0], n_out=2, name=name)


# ======================= loss =======================

def _apply_reduction(per_ex, reduction):
    if reduction == "MEAN_BY_NONZERO_WEIGHT_COUNT" or reduction == "mean":
        return jnp.mean(per_ex)
    if reduction == "SUM":
        return jnp.sum(per_ex)
    if reduction == "NONE" or reduction == "none":
        return per_ex
    return jnp.mean(per_ex)


@register_op("loss.meanSquaredError")
def _mse(labels, preds, *, reduction):
    per = jnp.mean(jnp.square(preds - labels),
                   axis=tuple(range(1, preds.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.absoluteDifference")
def _l1(labels, preds, *, reduction):
    per = jnp.mean(jnp.abs(preds - labels),
                   axis=tuple(range(1, preds.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.softmaxCrossEntropy")
def _sce(labels, logits, *, reduction, label_smoothing):
    if label_smoothing > 0:
        n = labels.shape[-1]
        labels = labels * (1 - label_smoothing) + label_smoothing / n
    per = -jnp.sum(labels * jax.nn.log_softmax(logits, axis=-1), axis=-1)
    if per.ndim > 1:
        per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _apply_reduction(per, reduction)


def _sparse_ce_per_example(labels, logits):
    """-> (per-example -log p[label], log_softmax(logits)) — shared by
    the reduced and the TF twin-output sparse-CE forms."""
    lp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.take_along_axis(
        lp, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    return per, lp


@register_op("loss.sparseSoftmaxCrossEntropy")
def _ssce(labels, logits, *, reduction):
    per, _ = _sparse_ce_per_example(labels, logits)
    if per.ndim > 1:
        per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.sparseSoftmaxCrossEntropyWithLogits")
def _ssce_with_logits(labels, logits):
    """TF ``SparseSoftmaxCrossEntropyWithLogits`` twin-output form:
    (per-example loss [B], backprop [B, C] = softmax - onehot). The
    backprop output exists so imported TF training graphs that consume
    output :1 keep their hand-wired gradient path."""
    per, lp = _sparse_ce_per_example(labels, logits)
    backprop = jnp.exp(lp) - jax.nn.one_hot(
        labels.astype(jnp.int32), logits.shape[-1], dtype=logits.dtype)
    return per, backprop


@register_op("loss.sigmoidCrossEntropy")
def _bce(labels, logits, *, reduction):
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
    per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.logLoss")
def _log_loss(labels, preds, *, reduction, eps):
    per = -(labels * jnp.log(preds + eps) +
            (1 - labels) * jnp.log(1 - preds + eps))
    per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.huberLoss")
def _huber(labels, preds, *, reduction, delta):
    err = preds - labels
    abs_err = jnp.abs(err)
    quad = jnp.minimum(abs_err, delta)
    per = 0.5 * quad ** 2 + delta * (abs_err - quad)
    per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.hingeLoss")
def _hinge(labels, preds, *, reduction):
    signed = 2 * labels - 1
    per = jnp.mean(jnp.maximum(0.0, 1.0 - signed * preds),
                   axis=tuple(range(1, preds.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.cosineDistance")
def _cosine(labels, preds, *, reduction, axis):
    num = jnp.sum(labels * preds, axis=axis)
    per = 1.0 - num
    if per.ndim > 1:
        per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _apply_reduction(per, reduction)


@register_op("loss.logPoisson")
def _log_poisson(labels, log_preds, *, reduction, full):
    per = jnp.exp(log_preds) - labels * log_preds
    if full:
        per = per + labels * jnp.log(labels + 1e-10) - labels
    per = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _apply_reduction(per, reduction)


class SDLoss(_Namespace):
    """Reference ``sd.loss()`` — every loss marks its output as a loss
    variable (reference behavior: loss ops auto-register)."""

    def _loss(self, opn, inputs, name=None, **attrs):
        out = self._op(f"loss.{opn}", inputs, name=name, **attrs)[0]
        self.sd.mark_loss(out)
        return out

    def meanSquaredError(self, labels, predictions, name=None,
                         reduction="mean"):
        return self._loss("meanSquaredError", [labels, predictions],
                          name=name, reduction=reduction)

    def absoluteDifference(self, labels, predictions, name=None,
                           reduction="mean"):
        return self._loss("absoluteDifference", [labels, predictions],
                          name=name, reduction=reduction)

    def softmaxCrossEntropy(self, labels, logits, name=None,
                            reduction="mean", label_smoothing=0.0):
        return self._loss("softmaxCrossEntropy", [labels, logits], name=name,
                          reduction=reduction,
                          label_smoothing=float(label_smoothing))

    def sparseSoftmaxCrossEntropy(self, labels, logits, name=None,
                                  reduction="mean"):
        return self._loss("sparseSoftmaxCrossEntropy", [labels, logits],
                          name=name, reduction=reduction)

    def sparseSoftmaxCrossEntropyWithLogits(self, labels, logits,
                                            name=None):
        """TF twin-output form: (per-example loss, backprop) — no
        reduction, nothing auto-marked as a loss variable (imported TF
        graphs wire their own downstream reduction)."""
        return tuple(self._op("loss.sparseSoftmaxCrossEntropyWithLogits",
                              [labels, logits], n_out=2, name=name))

    def sigmoidCrossEntropy(self, labels, logits, name=None,
                            reduction="mean"):
        return self._loss("sigmoidCrossEntropy", [labels, logits], name=name,
                          reduction=reduction)

    def logLoss(self, labels, predictions, name=None, reduction="mean",
                eps=1e-7):
        return self._loss("logLoss", [labels, predictions], name=name,
                          reduction=reduction, eps=float(eps))

    def huberLoss(self, labels, predictions, name=None, reduction="mean",
                  delta=1.0):
        return self._loss("huberLoss", [labels, predictions], name=name,
                          reduction=reduction, delta=float(delta))

    def hingeLoss(self, labels, predictions, name=None, reduction="mean"):
        return self._loss("hingeLoss", [labels, predictions], name=name,
                          reduction=reduction)

    def cosineDistance(self, labels, predictions, name=None,
                       reduction="mean", dimension=-1):
        return self._loss("cosineDistance", [labels, predictions], name=name,
                          reduction=reduction, axis=int(dimension))

    def logPoisson(self, labels, log_predictions, name=None,
                   reduction="mean", full=False):
        return self._loss("logPoisson", [labels, log_predictions], name=name,
                          reduction=reduction, full=bool(full))


# ======================= random =======================

@register_op("random.normal")
def _rand_normal(*, seed, shape, mean, stddev):
    return mean + stddev * jax.random.normal(jax.random.PRNGKey(seed),
                                             shape)


@register_op("random.uniform")
def _rand_uniform(*, seed, shape, lo, hi):
    return jax.random.uniform(jax.random.PRNGKey(seed), shape,
                              minval=lo, maxval=hi)


@register_op("random.bernoulli")
def _rand_bernoulli(*, seed, shape, p):
    return jax.random.bernoulli(jax.random.PRNGKey(seed), p,
                                shape).astype(jnp.float32)


class SDRandom(_Namespace):
    """Reference ``sd.random()`` — counter-based RNG (libnd4j RandomBuffer
    role is filled by jax's threefry; seeds are explicit graph attrs so
    results are reproducible and jit-cacheable)."""

    def normal(self, mean, stddev, shape, seed=0, name=None):
        return self._op("random.normal", [], name=name, seed=int(seed),
                        shape=tuple(shape), mean=float(mean),
                        stddev=float(stddev))[0]

    def uniform(self, lo, hi, shape, seed=0, name=None):
        return self._op("random.uniform", [], name=name, seed=int(seed),
                        shape=tuple(shape), lo=float(lo), hi=float(hi))[0]

    def bernoulli(self, p, shape, seed=0, name=None):
        return self._op("random.bernoulli", [], name=name, seed=int(seed),
                        shape=tuple(shape), p=float(p))[0]


# ======================= linalg =======================

for _n, _f in {
    "cholesky": jnp.linalg.cholesky,
    "det": jnp.linalg.det,
    "inv": jnp.linalg.inv,
    "slogdet": jnp.linalg.slogdet,
    "matrixInverse": jnp.linalg.inv,
}.items():
    register_op(f"linalg.{_n}")(_f)


@register_op("linalg.svd")
def _svd(x, *, full_matrices):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


@register_op("linalg.qr")
def _qr(x):
    return tuple(jnp.linalg.qr(x))


@register_op("linalg.solve")
def _solve(a, b):
    return jnp.linalg.solve(a, b)


@register_op("linalg.lstsq")
def _lstsq(a, b):
    return jnp.linalg.lstsq(a, b)[0]


@register_op("linalg.triangularSolve")
def _triangular_solve(a, b, *, lower, adjoint):
    return jax.scipy.linalg.solve_triangular(a, b, lower=lower,
                                             trans=1 if adjoint else 0)


@register_op("linalg.logdet")
def _logdet(x):
    # reference logdet: log(det(x)) for positive-definite input
    return jnp.linalg.slogdet(x)[1]


@register_op("linalg.matrixBandPart")
def _band_part(x, *, num_lower, num_upper):
    n, m = x.shape[-2], x.shape[-1]
    i = jnp.arange(n)[:, None]
    j = jnp.arange(m)[None, :]
    keep_lo = (i - j) <= num_lower if num_lower >= 0 else True
    keep_hi = (j - i) <= num_upper if num_upper >= 0 else True
    return jnp.where(jnp.logical_and(keep_lo, keep_hi), x, 0)


@register_op("linalg.tri")
def _tri(*, rows, cols, k, dtype):
    return jnp.tri(rows, cols, k, dtype=dtype)


@register_op("linalg.triu")
def _triu(x, *, k):
    return jnp.triu(x, k)


@register_op("linalg.tril")
def _tril(x, *, k):
    return jnp.tril(x, k)


@register_op("linalg.eye")
def _eye(*, rows, cols, dtype):
    return jnp.eye(rows, cols, dtype=dtype)


@register_op("linalg.diagPart")
def _diag_part(x):
    return jnp.diagonal(x, axis1=-2, axis2=-1)


class SDLinalg(_Namespace):
    """Reference ``sd.linalg()``."""

    def cholesky(self, x, name=None):
        return self._op("linalg.cholesky", [x], name=name)[0]

    def det(self, x, name=None):
        return self._op("linalg.det", [x], name=name)[0]

    def inv(self, x, name=None):
        return self._op("linalg.inv", [x], name=name)[0]

    matrixInverse = inv

    def svd(self, x, full_matrices=False, name=None):
        return self._op("linalg.svd", [x], n_out=3, name=name,
                        full_matrices=bool(full_matrices))

    def qr(self, x, name=None):
        return self._op("linalg.qr", [x], n_out=2, name=name)

    def solve(self, a, b, name=None):
        return self._op("linalg.solve", [a, b], name=name)[0]

    def lstsq(self, a, b, name=None):
        return self._op("linalg.lstsq", [a, b], name=name)[0]

    def triangularSolve(self, a, b, lower=True, adjoint=False, name=None):
        return self._op("linalg.triangularSolve", [a, b], name=name,
                        lower=bool(lower), adjoint=bool(adjoint))[0]

    def logdet(self, x, name=None):
        return self._op("linalg.logdet", [x], name=name)[0]

    def matrixBandPart(self, x, num_lower, num_upper, name=None):
        return self._op("linalg.matrixBandPart", [x], name=name,
                        num_lower=int(num_lower), num_upper=int(num_upper))[0]

    def tri(self, rows, cols=None, k=0, dtype="float32", name=None):
        return self._op("linalg.tri", [], name=name, rows=int(rows),
                        cols=int(cols if cols is not None else rows),
                        k=int(k), dtype=dtype)[0]

    def triu(self, x, k=0, name=None):
        return self._op("linalg.triu", [x], name=name, k=int(k))[0]

    def tril(self, x, k=0, name=None):
        return self._op("linalg.tril", [x], name=name, k=int(k))[0]

    def eye(self, rows, cols=None, dtype="float32", name=None):
        return self._op("linalg.eye", [], name=name, rows=int(rows),
                        cols=int(cols if cols is not None else rows),
                        dtype=dtype)[0]

    def diagPart(self, x, name=None):
        return self._op("linalg.diagPart", [x], name=name)[0]


# ======================= reduce3 / statistics =======================
# Reference: libnd4j's "reduce3" pairwise-reduction op family
# (euclidean/manhattan/cosine/jaccard/hamming distances, dot) exposed on
# SDMath, plus the entropy/standardize statistics ops.

_EPS3 = 1e-12


def _r3(fn):
    return lambda x, y, *, axis, keepdims: fn(x, y, axis, keepdims)


_REDUCE3 = {
    "euclideanDistance": _r3(lambda x, y, a, k: jnp.sqrt(
        jnp.sum((x - y) ** 2, axis=a, keepdims=k))),
    "manhattanDistance": _r3(lambda x, y, a, k: jnp.sum(
        jnp.abs(x - y), axis=a, keepdims=k)),
    "cosineSimilarity": _r3(lambda x, y, a, k: jnp.sum(
        x * y, axis=a, keepdims=k) / (
        jnp.sqrt(jnp.sum(x * x, axis=a, keepdims=k))
        * jnp.sqrt(jnp.sum(y * y, axis=a, keepdims=k)) + _EPS3)),
    "dot": _r3(lambda x, y, a, k: jnp.sum(x * y, axis=a, keepdims=k)),
    "hammingDistance": _r3(lambda x, y, a, k: jnp.sum(
        (x != y).astype(jnp.int32), axis=a, keepdims=k)),  # exact count
    # (int32 like countZero/countNonZero: f32 accumulation would go
    # inexact past 2^24 mismatches)
    "jaccardDistance": _r3(lambda x, y, a, k: 1.0 - jnp.sum(
        jnp.minimum(x, y), axis=a, keepdims=k) / (jnp.sum(
            jnp.maximum(x, y), axis=a, keepdims=k) + _EPS3)),
}
for _n, _f in _REDUCE3.items():
    register_op(f"math.{_n}")(_f)


@register_op("math.cosineDistance")
def _cosine_distance(x, y, *, axis, keepdims):
    return 1.0 - _REDUCE3["cosineSimilarity"](x, y, axis=axis,
                                              keepdims=keepdims)


_STATS = {
    # entropy family over a distribution along `axis` (reference SDMath)
    "entropy": lambda x, a, k: -jnp.sum(x * jnp.log(x + _EPS3), axis=a,
                                        keepdims=k),
    "logEntropy": lambda x, a, k: jnp.log(-jnp.sum(
        x * jnp.log(x + _EPS3), axis=a, keepdims=k) + _EPS3),
    "shannonEntropy": lambda x, a, k: -jnp.sum(
        x * jnp.log2(x + _EPS3), axis=a, keepdims=k),
    "amean": lambda x, a, k: jnp.mean(jnp.abs(x), axis=a, keepdims=k),
    "asum": lambda x, a, k: jnp.sum(jnp.abs(x), axis=a, keepdims=k),
    "countZero": lambda x, a, k: jnp.sum((x == 0).astype(jnp.int32),
                                         axis=a, keepdims=k),
    "zeroFraction": lambda x, a, k: jnp.mean((x == 0).astype(jnp.float32),
                                             axis=a, keepdims=k),
}
for _n, _f in _STATS.items():
    register_op(f"reduce.{_n}")(
        lambda x, *, axis, keepdims, _f=_f: _f(x, axis, keepdims))
for _n in _STATS:
    def _mk_stat(_n=_n):
        def m(self, x, dims=None, keepdims=False, name=None):
            return self._red(_n, x, dims, keepdims, name)
        m.__name__ = _n
        return m
    setattr(SDMath, _n, _mk_stat())


@register_op("math.standardize")
def _standardize(x, *, axis):
    mu = jnp.mean(x, axis=axis, keepdims=True)
    sd_ = jnp.std(x, axis=axis, keepdims=True)
    return (x - mu) / (sd_ + _EPS3)


@register_op("math.isMax")
def _is_max(x, *, axis):
    """Reference libnd4j IsMax: EXACTLY one 1 per reduction slice (at the
    argmax index), even on ties — a mask of all maxima would break
    downstream one-hot assumptions."""
    if axis is not None and len(axis) != 1:
        raise NotImplementedError("isMax supports a single dimension")
    ax = -1 if axis is None else int(axis[0])
    idx = jnp.argmax(x, axis=ax)
    return jnp.moveaxis(
        jax.nn.one_hot(idx, x.shape[ax], dtype=x.dtype), -1, ax)


@register_op("math.cross")
def _cross(a, b):
    return jnp.cross(a, b, axis=-1)


for _n, _f in {"lgamma": jax.scipy.special.gammaln,
               "digamma": jax.scipy.special.digamma,
               "rint": jnp.rint}.items():
    register_op(f"math.{_n}")(_f)


# ======================= scatter / gather-nd / segment =======================
# Reference: SDBaseOps scatterAdd/Sub/Mul/Div/Max/Min/Update, gatherNd,
# segmentSum/Mean/Max/Min/Prod + unsortedSegment* (libnd4j
# ops/declarable/generic/parity_ops/scatter*.cpp, segment*.cpp). Indices
# select rows on axis 0; duplicate indices accumulate (scatter add/sub)
# or combine by the op, matching the reference kernels.

_SCATTER = {
    "update": lambda ref, i, u: ref.at[i].set(u),
    "add": lambda ref, i, u: ref.at[i].add(u),
    "sub": lambda ref, i, u: ref.at[i].add(-u),
    "mul": lambda ref, i, u: ref.at[i].multiply(u),
    "div": lambda ref, i, u: ref.at[i].divide(u),
    "max": lambda ref, i, u: ref.at[i].max(u),
    "min": lambda ref, i, u: ref.at[i].min(u),
}
for _n, _f in _SCATTER.items():
    register_op(f"scatter.{_n}")(
        lambda ref, idx, upd, _f=_f: _f(ref, idx.astype(jnp.int32), upd))


@register_op("gather_nd")
def _gather_nd(x, idx):
    idx = jnp.moveaxis(idx.astype(jnp.int32), -1, 0)
    return x[tuple(idx)]


def _segment_mean(x, ids, num_segments):
    tot = jax.ops.segment_sum(x, ids, num_segments)
    cnt = jax.ops.segment_sum(jnp.ones(ids.shape, x.dtype), ids,
                              num_segments)
    return tot / jnp.maximum(cnt, 1.0).reshape(
        cnt.shape + (1,) * (tot.ndim - cnt.ndim))


_SEGMENT = {
    "sum": jax.ops.segment_sum,
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
    "prod": jax.ops.segment_prod,
    "mean": _segment_mean,
}
for _n, _f in _SEGMENT.items():
    register_op(f"segment.{_n}")(
        lambda x, ids, *, num_segments, _f=_f: _f(
            x, ids.astype(jnp.int32), num_segments))


@register_op("sequence_mask")
def _sequence_mask(lengths, *, maxlen, dtype):
    m = jnp.arange(maxlen) < lengths.astype(jnp.int32)[..., None]
    return m.astype(dtype)


# ======================= image =======================

@register_op("image.resizeBilinear")
def _resize_bilinear(x, *, height, width):
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, height, width, c), method="bilinear")


@register_op("image.resizeNearest")
def _resize_nearest(x, *, height, width):
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, height, width, c), method="nearest")


@register_op("image.flipLeftRight")
def _flip_lr(x):
    return jnp.flip(x, axis=2)


@register_op("image.flipUpDown")
def _flip_ud(x):
    return jnp.flip(x, axis=1)


@register_op("image.adjustContrast")
def _adjust_contrast(x, *, factor):
    mean = jnp.mean(x, axis=(1, 2), keepdims=True)
    return (x - mean) * factor + mean


@register_op("image.cropAndResize")
def _crop_resize(x, *, y0, x0, h, w, out_h, out_w):
    crop = x[:, y0:y0 + h, x0:x0 + w, :]
    b, _, _, c = crop.shape
    return jax.image.resize(crop, (b, out_h, out_w, c), method="bilinear")


def _rgb_to_hsv_impl(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0))
    h = jnp.where(d == 0, 0.0, h) / 6.0
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], axis=-1)


def _hsv_to_rgb_impl(x):
    h, s, v = x[..., 0] * 6.0, x[..., 1], x[..., 2]
    i = jnp.floor(h)
    f = h - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


register_op("image.rgbToHsv")(_rgb_to_hsv_impl)
register_op("image.hsvToRgb")(_hsv_to_rgb_impl)


@register_op("image.rgbToGrayscale")
def _rgb_to_gray(x):
    w = jnp.asarray([0.2989, 0.5870, 0.1140], x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@register_op("image.adjustHue")
def _adjust_hue(x, *, delta):
    hsv = _rgb_to_hsv_impl(x)
    h = (hsv[..., 0] + delta) % 1.0
    return _hsv_to_rgb_impl(jnp.stack([h, hsv[..., 1], hsv[..., 2]], -1))


@register_op("image.adjustSaturation")
def _adjust_saturation(x, *, factor):
    hsv = _rgb_to_hsv_impl(x)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return _hsv_to_rgb_impl(jnp.stack([hsv[..., 0], s, hsv[..., 2]], -1))


@register_op("image.extractImagePatches")
def _extract_patches(x, *, kh, kw, sh, sw, padding):
    # [B,H,W,C] -> [B,OH,OW,kh*kw*C] (TF extract_image_patches layout)
    b, _, _, c = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # patches come channel-major [.., C*kh*kw]; reorder to kh*kw*C
    oh, ow = patches.shape[1], patches.shape[2]
    patches = patches.reshape(b, oh, ow, c, kh * kw)
    return jnp.swapaxes(patches, -1, -2).reshape(b, oh, ow, kh * kw * c)


@register_op("image.nonMaxSuppression")
def _nms(boxes, scores, *, max_output_size, iou_threshold, score_threshold):
    """Greedy NMS, static output (TF nonMaxSuppressionPadded semantics:
    [max_output_size] selected indices, -1 padded). Boxes [n, 4] as
    (y1, x1, y2, x2)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    bs = boxes[order]
    area = jnp.maximum(bs[:, 2] - bs[:, 0], 0) * jnp.maximum(
        bs[:, 3] - bs[:, 1], 0)
    suppressed = scores[order] < score_threshold

    def body(i, sup):
        yy1 = jnp.maximum(bs[i, 0], bs[:, 0])
        xx1 = jnp.maximum(bs[i, 1], bs[:, 1])
        yy2 = jnp.minimum(bs[i, 2], bs[:, 2])
        xx2 = jnp.minimum(bs[i, 3], bs[:, 3])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        iou = inter / jnp.maximum(area[i] + area - inter, 1e-9)
        kill = (jnp.arange(n) > i) & (iou > iou_threshold) & ~sup[i]
        return sup | kill

    sup = jax.lax.fori_loop(0, n, body, suppressed)
    k = min(max_output_size, n)
    pos = jnp.argsort(sup, stable=True)[:k]
    sel = jnp.where(sup[pos], -1, order[pos]).astype(jnp.int32)
    # static [max_output_size] output even when fewer boxes exist
    return jnp.pad(sel, (0, max_output_size - k), constant_values=-1)


class SDImage(_Namespace):
    """Reference ``sd.image()``."""

    def rgbToHsv(self, x, name=None):
        return self._op("image.rgbToHsv", [x], name=name)[0]

    def hsvToRgb(self, x, name=None):
        return self._op("image.hsvToRgb", [x], name=name)[0]

    def rgbToGrayscale(self, x, name=None):
        return self._op("image.rgbToGrayscale", [x], name=name)[0]

    def adjustHue(self, x, delta, name=None):
        return self._op("image.adjustHue", [x], name=name,
                        delta=float(delta))[0]

    def adjustSaturation(self, x, factor, name=None):
        return self._op("image.adjustSaturation", [x], name=name,
                        factor=float(factor))[0]

    def extractImagePatches(self, x, kh, kw, sh=1, sw=1, padding="VALID",
                            name=None):
        return self._op("image.extractImagePatches", [x], name=name,
                        kh=int(kh), kw=int(kw), sh=int(sh), sw=int(sw),
                        padding=padding)[0]

    def nonMaxSuppression(self, boxes, scores, max_output_size,
                          iou_threshold=0.5, score_threshold=-1e30,
                          name=None):
        return self._op("image.nonMaxSuppression", [boxes, scores],
                        name=name, max_output_size=int(max_output_size),
                        iou_threshold=float(iou_threshold),
                        score_threshold=float(score_threshold))[0]

    def resizeBilinear(self, x, height, width, name=None):
        return self._op("image.resizeBilinear", [x], name=name,
                        height=int(height), width=int(width))[0]

    def resizeNearest(self, x, height, width, name=None):
        return self._op("image.resizeNearest", [x], name=name,
                        height=int(height), width=int(width))[0]

    def flipLeftRight(self, x, name=None):
        return self._op("image.flipLeftRight", [x], name=name)[0]

    def flipUpDown(self, x, name=None):
        return self._op("image.flipUpDown", [x], name=name)[0]

    def adjustContrast(self, x, factor, name=None):
        return self._op("image.adjustContrast", [x], name=name,
                        factor=float(factor))[0]

    def cropAndResize(self, x, y0, x0, h, w, out_h, out_w, name=None):
        return self._op("image.cropAndResize", [x], name=name, y0=int(y0),
                        x0=int(x0), h=int(h), w=int(w), out_h=int(out_h),
                        out_w=int(out_w))[0]


# ======================= bitwise =======================

for _n, _f in {
    "and_": jnp.bitwise_and, "or_": jnp.bitwise_or,
    "xor": jnp.bitwise_xor, "leftShift": jnp.left_shift,
    "rightShift": jnp.right_shift,
}.items():
    register_op(f"bitwise.{_n}")(_f)


def _bit_width(x):
    return jnp.iinfo(x.dtype).bits


@register_op("bitwise.cyclicShiftLeft")
def _rotl(x, s):
    w = _bit_width(x)
    s = s.astype(x.dtype) % w
    # (w - s) % w: a shift equal to the bit width is undefined in XLA
    return (x << s) | _logical_rshift(x, (w - s) % w, w)


@register_op("bitwise.cyclicShiftRight")
def _rotr(x, s):
    w = _bit_width(x)
    s = s.astype(x.dtype) % w
    return _logical_rshift(x, s, w) | (x << ((w - s) % w))


def _logical_rshift(x, s, w):
    # >> on signed ints is arithmetic; rotate needs the logical shift
    ux = x.astype(jnp.dtype(f"uint{w}"))
    return (ux >> s.astype(ux.dtype)).astype(x.dtype)


@register_op("bitwise.toggleBits")
def _toggle_bits(x):
    return jnp.invert(x)


@register_op("bitwise.bitsHammingDistance")
def _hamming(a, b):
    diff = jnp.bitwise_xor(a, b)
    ud = diff.astype(jnp.dtype(f"uint{_bit_width(diff)}"))
    return jnp.sum(jax.lax.population_count(ud).astype(jnp.int32))


class SDBitwise(_Namespace):
    """Reference ``sd.bitwise()``."""

    def cyclicShiftLeft(self, x, shift, name=None):
        return self._op("bitwise.cyclicShiftLeft", [x, shift], name=name)[0]

    def cyclicShiftRight(self, x, shift, name=None):
        return self._op("bitwise.cyclicShiftRight", [x, shift], name=name)[0]

    def toggleBits(self, x, name=None):
        return self._op("bitwise.toggleBits", [x], name=name)[0]

    def bitsHammingDistance(self, a, b, name=None):
        return self._op("bitwise.bitsHammingDistance", [a, b], name=name)[0]

    def and_(self, a, b, name=None):
        return self._op("bitwise.and_", [a, b], name=name)[0]

    def or_(self, a, b, name=None):
        return self._op("bitwise.or_", [a, b], name=name)[0]

    def xor(self, a, b, name=None):
        return self._op("bitwise.xor", [a, b], name=name)[0]

    def leftShift(self, a, b, name=None):
        return self._op("bitwise.leftShift", [a, b], name=name)[0]

    def rightShift(self, a, b, name=None):
        return self._op("bitwise.rightShift", [a, b], name=name)[0]


# ======================= round 3: cnn 3d/transposed family =======================
# Reference: libnd4j declarable ops conv3dnew/deconv2d/deconv3d/sconv2d/
# maxpool3dnew/avgpool3dnew/pooling1d/upsampling1d-3d/space_to_depth/
# depth_to_space/space_to_batch/batch_to_space/lrn/im2col/col2im/dilation2d
# exposed through SDCNN (SURVEY.md §2.1 "Declarable ops library"). Layouts
# are TPU-native channels-last (NWC / NHWC / NDHWC); XLA retiles for the
# MXU during compilation.

@register_op("cnn.conv3d")
def _conv3d(x, w, b, *, strides, padding, dilation):
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return out + b


def _deconv_nd(x, w, b, strides, padding, nd):
    """Transposed conv = gradient-of-conv (scatter-add) semantics, as
    the reference's deconv2d/deconv3d and this repo's Deconvolution2D
    layer define: out[i*s+p, ..., o] += x[i, ..., c] * w[p, ..., c, o].
    Expressed as a direct conv over the stride-dilated input with a
    spatially-flipped kernel (round-3 advisor: plain lax.conv_transpose
    omits the flip and diverges for asymmetric kernels; its "SAME" also
    pads the dilated input one pixel differently from Deconvolution2D —
    so padding is computed explicitly here, matching the layer exactly:
    VALID -> out = (i-1)*s + k, SAME -> out = i*s). Pinned against an
    independent numpy scatter oracle and against the layer in
    test_op_validation.py."""
    k = w.shape[:nd]
    if padding == "SAME":
        pts = [s + kk - 2 for s, kk in zip(strides, k)]
        pad = [(pt // 2, pt - pt // 2) for pt in pts]
    elif padding == "VALID":
        pad = [(kk - 1, kk - 1) for kk in k]
    else:
        raise ValueError(f"deconv: unsupported padding {padding!r}")
    spec = "DHW"[3 - nd:]
    dn = (f"N{spec}C", f"{spec}IO", f"N{spec}C")
    out = jax.lax.conv_general_dilated(
        x, jnp.flip(w, tuple(range(nd))), window_strides=(1,) * nd,
        padding=pad, lhs_dilation=strides, dimension_numbers=dn)
    return out + b


@register_op("cnn.deconv2d")
def _deconv2d(x, w, b, *, strides, padding):
    return _deconv_nd(x, w, b, strides, padding, 2)


@register_op("cnn.deconv3d")
def _deconv3d(x, w, b, *, strides, padding):
    return _deconv_nd(x, w, b, strides, padding, 3)


@register_op("cnn.sconv2d")
def _sconv2d(x, wd, wp, b, *, strides, padding, mult):
    """Separable conv (reference sconv2d): depthwise ``wd`` [kh, kw, 1,
    C*mult] then pointwise ``wp`` [1, 1, C*mult, O]."""
    c = x.shape[-1]
    if wd.shape[-1] != c * mult:
        raise ValueError(
            f"sconv2d: depthwise weights last dim {wd.shape[-1]} != "
            f"channels {c} * depth multiplier {mult}")
    dw = jax.lax.conv_general_dilated(
        x, wd, window_strides=strides, padding=padding,
        feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    out = jax.lax.conv_general_dilated(
        dw, wp, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out + b


def _pool(x, dims, strd, padding, kind):
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strd,
                                     padding)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd, padding)
    counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add, dims,
                                   strd, padding)
    return summed / counts


@register_op("cnn.maxPooling1d")
def _maxpool1d(x, *, k, s, padding):
    return _pool(x, (1, k, 1), (1, s, 1), padding, "max")


@register_op("cnn.avgPooling1d")
def _avgpool1d(x, *, k, s, padding):
    return _pool(x, (1, k, 1), (1, s, 1), padding, "avg")


@register_op("cnn.maxPooling3d")
def _maxpool3d(x, *, k, s, padding):
    return _pool(x, (1, *k, 1), (1, *s, 1), padding, "max")


@register_op("cnn.avgPooling3d")
def _avgpool3d(x, *, k, s, padding):
    return _pool(x, (1, *k, 1), (1, *s, 1), padding, "avg")


@register_op("cnn.upsampling1d")
def _upsample1d(x, *, scale):
    return jnp.repeat(x, scale, axis=1)


@register_op("cnn.upsampling3d")
def _upsample3d(x, *, scale):
    for ax in (1, 2, 3):
        x = jnp.repeat(x, scale, axis=ax)
    return x


@register_op("cnn.spaceToDepth")
def _space_to_depth(x, *, block):
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
        n, h // block, w // block, block * block * c)


@register_op("cnn.depthToSpace")
def _depth_to_space(x, *, block):
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, block, block, c // (block * block))
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
        n, h * block, w * block, c // (block * block))


@register_op("cnn.spaceToBatch")
def _space_to_batch(x, *, block, pads):
    n, h, w, c = x.shape
    x = jnp.pad(x, ((0, 0), tuple(pads[0]), tuple(pads[1]), (0, 0)))
    hp, wp = x.shape[1], x.shape[2]
    x = x.reshape(n, hp // block, block, wp // block, block, c)
    return jnp.transpose(x, (2, 4, 0, 1, 3, 5)).reshape(
        n * block * block, hp // block, wp // block, c)


@register_op("cnn.batchToSpace")
def _batch_to_space(x, *, block, crops):
    nb, h, w, c = x.shape
    n = nb // (block * block)
    x = x.reshape(block, block, n, h, w, c)
    x = jnp.transpose(x, (2, 3, 0, 4, 1, 5)).reshape(
        n, h * block, w * block, c)
    (ct, cb), (cl, cr) = crops
    return x[:, ct:x.shape[1] - cb, cl:x.shape[2] - cr, :]


@register_op("cnn.localResponseNormalization")
def _lrn(x, *, depth, bias, alpha, beta):
    """TF/cuDNN-style across-channel LRN (reference lrn platform helper):
    out = x / (bias + alpha * sum_{c-depth..c+depth} x^2) ** beta."""
    sq = jnp.square(x)
    win = 2 * depth + 1
    ssum = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add, (1, 1, 1, win), (1, 1, 1, 1),
        [(0, 0), (0, 0), (0, 0), (depth, depth)])
    return x / jnp.power(bias + alpha * ssum, beta)


def _im2col_impl(x, k, s, padding):
    return jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@register_op("cnn.im2col")
def _im2col(x, *, k, s, padding):
    """Patches [N, H', W', C*kh*kw] (channel-major within a patch — the
    layout ``conv_general_dilated_patches`` produces for NHWC)."""
    return _im2col_impl(x, k, s, padding)


@register_op("cnn.col2im")
def _col2im(cols, *, shape, k, s, padding):
    """Exact transpose of im2col (scatter-add of patch columns back into
    the image) — implemented AS the transpose: the VJP of the im2col
    primitive, which is precisely col2im's definition."""
    _, vjp = jax.vjp(lambda x: _im2col_impl(x, k, s, padding),
                     jnp.zeros(shape, cols.dtype))
    return vjp(cols)[0]


@register_op("cnn.dilation2d")
def _dilation2d(x, w, *, strides, rates):
    """Morphological (grayscale) dilation, TF semantics:
    out[i,j,c] = max_{di,dj} x[i*s + di*r, j*s + dj*r, c] + w[di, dj, c].
    VALID padding; kernel extents are static so the max unrolls."""
    kh, kw, _ = w.shape
    sh, sw = strides
    rh, rw = rates
    n, h, wd, c = x.shape
    oh = (h - (kh - 1) * rh - 1) // sh + 1
    ow = (wd - (kw - 1) * rw - 1) // sw + 1
    out = jnp.full((n, oh, ow, c), -jnp.inf, x.dtype)
    for di in range(kh):
        for dj in range(kw):
            patch = jax.lax.slice(
                x, (0, di * rh, dj * rw, 0),
                (n, di * rh + (oh - 1) * sh + 1, dj * rw + (ow - 1) * sw + 1,
                 c), (1, sh, sw, 1))
            out = jnp.maximum(out, patch + w[di, dj])
    return out


@_def(SDCNN, "conv3d")
def _sd_conv3d(self, x, w, b=None, strides=(1, 1, 1), padding="SAME",
               dilation=(1, 1, 1), name=None):
    if b is None:
        b = self.sd.constant(jnp.zeros((w.shape[-1],) if w.shape else (1,)))
    return self._op("cnn.conv3d", [x, w, b], name=name,
                    strides=tuple(strides), padding=padding,
                    dilation=tuple(dilation))[0]


@_def(SDCNN, "deconv2d")
def _sd_deconv2d(self, x, w, b=None, strides=(1, 1), padding="SAME",
                 name=None):
    if b is None:
        b = self.sd.constant(jnp.zeros((w.shape[-1],) if w.shape else (1,)))
    return self._op("cnn.deconv2d", [x, w, b], name=name,
                    strides=tuple(strides), padding=padding)[0]


@_def(SDCNN, "deconv3d")
def _sd_deconv3d(self, x, w, b=None, strides=(1, 1, 1), padding="SAME",
                 name=None):
    if b is None:
        b = self.sd.constant(jnp.zeros((w.shape[-1],) if w.shape else (1,)))
    return self._op("cnn.deconv3d", [x, w, b], name=name,
                    strides=tuple(strides), padding=padding)[0]


@_def(SDCNN, "sconv2d")
def _sd_sconv2d(self, x, wd, wp, b=None, strides=(1, 1), padding="SAME",
                mult=1, name=None):
    if b is None:
        b = self.sd.constant(jnp.zeros((wp.shape[-1],) if wp.shape else (1,)))
    return self._op("cnn.sconv2d", [x, wd, wp, b], name=name,
                    strides=tuple(strides), padding=padding,
                    mult=int(mult))[0]


@_def(SDCNN, "maxPooling1d")
def _sd_maxpool1d(self, x, k=2, s=2, padding="VALID", name=None):
    return self._op("cnn.maxPooling1d", [x], name=name, k=int(k), s=int(s),
                    padding=padding)[0]


@_def(SDCNN, "avgPooling1d")
def _sd_avgpool1d(self, x, k=2, s=2, padding="VALID", name=None):
    return self._op("cnn.avgPooling1d", [x], name=name, k=int(k), s=int(s),
                    padding=padding)[0]


@_def(SDCNN, "maxPooling3d")
def _sd_maxpool3d(self, x, k=(2, 2, 2), s=(2, 2, 2), padding="VALID",
                  name=None):
    return self._op("cnn.maxPooling3d", [x], name=name, k=tuple(k),
                    s=tuple(s), padding=padding)[0]


@_def(SDCNN, "avgPooling3d")
def _sd_avgpool3d(self, x, k=(2, 2, 2), s=(2, 2, 2), padding="VALID",
                  name=None):
    return self._op("cnn.avgPooling3d", [x], name=name, k=tuple(k),
                    s=tuple(s), padding=padding)[0]


@_def(SDCNN, "upsampling1d")
def _sd_upsample1d(self, x, scale=2, name=None):
    return self._op("cnn.upsampling1d", [x], name=name, scale=int(scale))[0]


@_def(SDCNN, "upsampling3d")
def _sd_upsample3d(self, x, scale=2, name=None):
    return self._op("cnn.upsampling3d", [x], name=name, scale=int(scale))[0]


@_def(SDCNN, "spaceToDepth")
def _sd_s2d(self, x, block=2, name=None):
    return self._op("cnn.spaceToDepth", [x], name=name, block=int(block))[0]


@_def(SDCNN, "depthToSpace")
def _sd_d2s(self, x, block=2, name=None):
    return self._op("cnn.depthToSpace", [x], name=name, block=int(block))[0]


@_def(SDCNN, "spaceToBatch")
def _sd_s2b(self, x, block=2, pads=((0, 0), (0, 0)), name=None):
    return self._op("cnn.spaceToBatch", [x], name=name, block=int(block),
                    pads=tuple(tuple(int(p) for p in pp) for pp in pads))[0]


@_def(SDCNN, "batchToSpace")
def _sd_b2s(self, x, block=2, crops=((0, 0), (0, 0)), name=None):
    return self._op("cnn.batchToSpace", [x], name=name, block=int(block),
                    crops=tuple(tuple(int(c) for c in cc) for cc in crops))[0]


@_def(SDCNN, "localResponseNormalization")
def _sd_lrn(self, x, depth=2, bias=1.0, alpha=1.0, beta=0.5, name=None):
    return self._op("cnn.localResponseNormalization", [x], name=name,
                    depth=int(depth), bias=float(bias), alpha=float(alpha),
                    beta=float(beta))[0]


@_def(SDCNN, "im2col")
def _sd_im2col(self, x, k=(2, 2), s=(1, 1), padding="VALID", name=None):
    return self._op("cnn.im2col", [x], name=name, k=tuple(k), s=tuple(s),
                    padding=padding)[0]


@_def(SDCNN, "col2im")
def _sd_col2im(self, cols, shape, k=(2, 2), s=(1, 1), padding="VALID",
               name=None):
    return self._op("cnn.col2im", [cols], name=name, shape=tuple(shape),
                    k=tuple(k), s=tuple(s), padding=padding)[0]


@_def(SDCNN, "dilation2d")
def _sd_dilation2d(self, x, w, strides=(1, 1), rates=(1, 1), name=None):
    return self._op("cnn.dilation2d", [x, w], name=name,
                    strides=tuple(strides), rates=tuple(rates))[0]


# ======================= round 3: rnn cells =======================

@register_op("rnn.lstmCell")
def _lstm_cell(x, h, c, w, r, b):
    """One LSTM step (reference sd.rnn.lstmCell): x [B,I], h/c [B,H]."""
    hidden = r.shape[0]
    z = x @ w + h @ r + b
    i, f, g, o = (z[:, :hidden], z[:, hidden:2 * hidden],
                  z[:, 2 * hidden:3 * hidden], z[:, 3 * hidden:])
    c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return h_new, c_new


@register_op("rnn.gruCell")
def _gru_cell(x, h, w, r, b):
    """One GRU step (reference sd.rnn.gruCell). Candidate uses the
    ORIGINAL Cho et al. formulation the reference implements — reset
    gate applied to the state BEFORE the recurrent matmul,
    ng = tanh(x@Wc + (rg*h)@Rc) — not the CuDNN/``reset_after``
    variant tanh(x@Wc + rg*(h@Rc)); the two differ numerically
    (round-3 advisor)."""
    hidden = r.shape[0]
    zx = x @ w + b
    zh = h @ r[:, :2 * hidden]
    rg = jax.nn.sigmoid(zx[:, :hidden] + zh[:, :hidden])
    zg = jax.nn.sigmoid(zx[:, hidden:2 * hidden] + zh[:, hidden:])
    ng = jnp.tanh(zx[:, 2 * hidden:] + (rg * h) @ r[:, 2 * hidden:])
    return (1 - zg) * ng + zg * h


def _sru_step(xt, c, wx, bf, br):
    """One SRU step (Lei et al.; reference sru/sruCell). ``wx`` is the
    precomputed x @ W [B, 3H] block (xtilde, f-gate, r-gate)."""
    hidden = c.shape[-1]
    xt_t = wx[:, :hidden]
    f = jax.nn.sigmoid(wx[:, hidden:2 * hidden] + bf)
    r = jax.nn.sigmoid(wx[:, 2 * hidden:] + br)
    c_new = f * c + (1 - f) * xt_t
    h_new = r * jnp.tanh(c_new) + (1 - r) * xt
    return h_new, c_new


@register_op("rnn.sru")
def _sru(x, w, b, c0):
    """SRU over [T,B,I] with I == H (highway connection); w [I,3H],
    b [2H] = (bf, br). The heavy matmul runs ONCE outside the scan."""
    hidden = c0.shape[-1]
    bf, br = b[:hidden], b[hidden:]
    wx = jnp.einsum("tbi,ih->tbh", x, w)

    def step(c, inp):
        xt, wxt = inp
        h_new, c_new = _sru_step(xt, c, wxt, bf, br)
        return c_new, h_new

    c_f, ys = jax.lax.scan(step, c0, (x, wx))
    return ys, c_f


@register_op("rnn.sruCell")
def _sru_cell(x, c, w, b):
    hidden = c.shape[-1]
    return _sru_step(x, c, x @ w, b[:hidden], b[hidden:])


@_def(SDRNN, "lstmCell")
def _sd_lstm_cell(self, x, h, c, w, r, b, name=None):
    return self._op("rnn.lstmCell", [x, h, c, w, r, b], n_out=2, name=name)


@_def(SDRNN, "gruCell")
def _sd_gru_cell(self, x, h, w, r, b, name=None):
    return self._op("rnn.gruCell", [x, h, w, r, b], name=name)[0]


@_def(SDRNN, "sru")
def _sd_sru(self, x, w, b, c0, name=None):
    return self._op("rnn.sru", [x, w, b, c0], n_out=2, name=name)


@_def(SDRNN, "sruCell")
def _sd_sru_cell(self, x, c, w, b, name=None):
    return self._op("rnn.sruCell", [x, c, w, b], n_out=2, name=name)


# ======================= round 3: math / transforms =======================

@register_op("math.cube")
def _cube(x):
    return x * x * x


@register_op("math.oneMinus")
def _one_minus(x):
    return 1.0 - x


@register_op("math.step")
def _step(x, *, cutoff):
    return (x > cutoff).astype(x.dtype)


@register_op("math.rationalTanh")
def _rational_tanh(x):
    """Reference RationalTanh: 1.7159 * tanh_approx(2x/3) with
    tanh_approx(y) = sign(y) * (1 - 1/(1 + |y| + y^2 + 1.41645 y^4))."""
    y = 2.0 * x / 3.0
    ay = jnp.abs(y)
    approx = 1.0 - 1.0 / (1.0 + ay + y * y + 1.41645 * y ** 4)
    return 1.7159 * jnp.sign(y) * approx


@register_op("math.rectifiedTanh")
def _rectified_tanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


@register_op("math.fmod")
def _fmod(a, b):
    # C-style remainder (sign follows the dividend) — distinct from
    # math.mod's floored modulo, as in the reference's FModOp vs ModOp
    return jnp.fmod(a, b)


@register_op("math.lerp")
def _lerp(a, b, *, weight):
    return a + weight * (b - a)


@register_op("math.isStrictlyIncreasing")
def _is_strictly_increasing(x):
    d = jnp.diff(x.reshape(-1))
    return jnp.all(d > 0).astype(jnp.float32)


@register_op("math.isNonDecreasing")
def _is_non_decreasing(x):
    d = jnp.diff(x.reshape(-1))
    return jnp.all(d >= 0).astype(jnp.float32)


@register_op("math.mergeAdd")
def _merge_add(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_op("math.mergeAvg")
def _merge_avg(*xs):
    return _merge_add(*xs) / float(len(xs))


@register_op("math.mergeMax")
def _merge_max(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = jnp.maximum(out, x)
    return out


@register_op("math.moments")
def _moments(x, *, axis, keepdims):
    return (jnp.mean(x, axis=axis, keepdims=keepdims),
            jnp.var(x, axis=axis, keepdims=keepdims))


@register_op("math.meshgrid")
def _meshgrid(*xs, indexing):
    return tuple(jnp.meshgrid(*xs, indexing=indexing))


@register_op("math.confusionMatrix")
def _confusion_matrix(labels, pred, *, num_classes):
    lo = jax.nn.one_hot(labels.astype(jnp.int32), num_classes)
    po = jax.nn.one_hot(pred.astype(jnp.int32), num_classes)
    return (lo.T @ po).astype(jnp.int32)


@register_op("math.sequenceMask")
def _sequence_mask(lengths, *, maxlen):
    return (jnp.arange(maxlen)[None, :]
            < lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)


@register_op("math.reverseSequence")
def _reverse_sequence(x, seq_lengths, *, seq_axis, batch_axis):
    """Reverse the first ``seq_lengths[b]`` entries of each sequence, the
    tail stays in place (TF/reference ReverseSequence semantics)."""
    x = jnp.moveaxis(x, (batch_axis, seq_axis), (0, 1))
    t = x.shape[1]
    ts = jnp.arange(t)[None, :]
    ln = seq_lengths.astype(jnp.int32)[:, None]
    src = jnp.where(ts < ln, ln - 1 - ts, ts)
    idx = src.reshape(src.shape + (1,) * (x.ndim - 2))
    out = jnp.take_along_axis(x, jnp.broadcast_to(idx, x.shape), axis=1)
    return jnp.moveaxis(out, (0, 1), (batch_axis, seq_axis))


@register_op("math.batchMmul")
def _batch_mmul(a, b):
    return jnp.matmul(a, b)


@register_op("math.zeta")
def _zeta(x, q):
    return jax.scipy.special.zeta(x, q)


@register_op("math.polygamma")
def _polygamma(x, *, n):
    return jax.scipy.special.polygamma(n, x)


@register_op("math.igamma")
def _igamma(a, x):
    return jax.scipy.special.gammainc(a, x)


@register_op("math.igammac")
def _igammac(a, x):
    return jax.scipy.special.gammaincc(a, x)


@register_op("math.betainc")
def _betainc(a, b, x):
    return jax.scipy.special.betainc(a, b, x)


@register_op("math.clipByNorm")
def _clip_by_norm(x, *, clip, axis):
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    return jnp.where(n > clip, x * (clip / jnp.maximum(n, 1e-12)), x)


@register_op("math.clipByAvgNorm")
def _clip_by_avg_norm(x, *, clip, axis):
    cnt = 1
    for a in (axis if axis is not None else range(x.ndim)):
        cnt *= x.shape[a]
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True)) / cnt
    return jnp.where(n > clip, x * (clip / jnp.maximum(n, 1e-30)), x)


@register_op("math.bincount")
def _bincount(x, *, length):
    return jnp.bincount(x.astype(jnp.int32).reshape(-1), length=length)


@register_op("math.dynamicStitch")
def _dynamic_stitch(*arrs, size):
    """TF dynamicStitch: first half of the operands are index vectors,
    second half the matching data slices; later partitions win ties
    (overlapping indices). TF sizes the output max(index)+1 from DATA —
    impossible under jit's static shapes — so ``size`` must be static:
    pass it explicitly for overlapping/sparse indices, or leave the
    default (sum of index lengths — exact for the dominant
    dynamicPartition->dynamicStitch round trip, where the indices
    partition 0..N-1)."""
    n = len(arrs) // 2
    idxs, data = arrs[:n], arrs[n:]
    if size is None:
        size = sum(int(i.shape[0]) for i in idxs)
    out = jnp.zeros((size,) + data[0].shape[1:], data[0].dtype)
    for i, d in zip(idxs, data):
        out = out.at[i.astype(jnp.int32)].set(d)
    return out


@_def(SDMath, "cube")
def _sd_cube(self, x, name=None):
    return self._op("math.cube", [x], name=name)[0]


@_def(SDMath, "oneMinus")
def _sd_one_minus(self, x, name=None):
    return self._op("math.oneMinus", [x], name=name)[0]


@_def(SDMath, "step")
def _sd_step(self, x, cutoff=0.0, name=None):
    return self._op("math.step", [x], name=name, cutoff=float(cutoff))[0]


@_def(SDMath, "rationalTanh")
def _sd_rational_tanh(self, x, name=None):
    return self._op("math.rationalTanh", [x], name=name)[0]


@_def(SDMath, "rectifiedTanh")
def _sd_rectified_tanh(self, x, name=None):
    return self._op("math.rectifiedTanh", [x], name=name)[0]


@_def(SDMath, "fmod")
def _sd_fmod(self, a, b, name=None):
    return self._op("math.fmod", [a, b], name=name)[0]


@_def(SDMath, "lerp")
def _sd_lerp(self, a, b, weight, name=None):
    return self._op("math.lerp", [a, b], name=name, weight=float(weight))[0]


@_def(SDMath, "isStrictlyIncreasing")
def _sd_isi(self, x, name=None):
    return self._op("math.isStrictlyIncreasing", [x], name=name)[0]


@_def(SDMath, "isNonDecreasing")
def _sd_ind(self, x, name=None):
    return self._op("math.isNonDecreasing", [x], name=name)[0]


@_def(SDMath, "mergeAdd")
def _sd_merge_add(self, *xs, name=None):
    return self._op("math.mergeAdd", list(xs), name=name)[0]


@_def(SDMath, "mergeAvg")
def _sd_merge_avg(self, *xs, name=None):
    return self._op("math.mergeAvg", list(xs), name=name)[0]


@_def(SDMath, "mergeMax")
def _sd_merge_max(self, *xs, name=None):
    return self._op("math.mergeMax", list(xs), name=name)[0]


@_def(SDMath, "moments")
def _sd_moments(self, x, dims=None, keepdims=False, name=None):
    return self._op("math.moments", [x], n_out=2, name=name,
                    axis=_axes(dims), keepdims=bool(keepdims))


@_def(SDMath, "meshgrid")
def _sd_meshgrid(self, *xs, indexing="xy", name=None):
    return self._op("math.meshgrid", list(xs), n_out=len(xs), name=name,
                    indexing=indexing)


@_def(SDMath, "confusionMatrix")
def _sd_confusion(self, labels, pred, num_classes, name=None):
    return self._op("math.confusionMatrix", [labels, pred], name=name,
                    num_classes=int(num_classes))[0]


@_def(SDMath, "sequenceMask")
def _sd_seq_mask(self, lengths, maxlen, name=None):
    return self._op("math.sequenceMask", [lengths], name=name,
                    maxlen=int(maxlen))[0]


@_def(SDMath, "reverseSequence")
def _sd_rev_seq(self, x, seq_lengths, seq_axis=1, batch_axis=0, name=None):
    return self._op("math.reverseSequence", [x, seq_lengths], name=name,
                    seq_axis=int(seq_axis), batch_axis=int(batch_axis))[0]


@_def(SDMath, "batchMmul")
def _sd_batch_mmul(self, a, b, name=None):
    return self._op("math.batchMmul", [a, b], name=name)[0]


@_def(SDMath, "zeta")
def _sd_zeta(self, x, q, name=None):
    return self._op("math.zeta", [x, q], name=name)[0]


@_def(SDMath, "polygamma")
def _sd_polygamma(self, x, n=0, name=None):
    return self._op("math.polygamma", [x], name=name, n=int(n))[0]


@_def(SDMath, "igamma")
def _sd_igamma(self, a, x, name=None):
    return self._op("math.igamma", [a, x], name=name)[0]


@_def(SDMath, "igammac")
def _sd_igammac(self, a, x, name=None):
    return self._op("math.igammac", [a, x], name=name)[0]


@_def(SDMath, "betainc")
def _sd_betainc(self, a, b, x, name=None):
    return self._op("math.betainc", [a, b, x], name=name)[0]


@_def(SDMath, "clipByNorm")
def _sd_clip_by_norm(self, x, clip, dims=None, name=None):
    return self._op("math.clipByNorm", [x], name=name, clip=float(clip),
                    axis=_axes(dims))[0]


@_def(SDMath, "clipByAvgNorm")
def _sd_clip_by_avg_norm(self, x, clip, dims=None, name=None):
    return self._op("math.clipByAvgNorm", [x], name=name, clip=float(clip),
                    axis=_axes(dims))[0]


@_def(SDMath, "bincount")
def _sd_bincount(self, x, length, name=None):
    return self._op("math.bincount", [x], name=name, length=int(length))[0]


@_def(SDMath, "dynamicStitch")
def _sd_dynamic_stitch(self, indices, data, size=None, name=None):
    return self._op("math.dynamicStitch", list(indices) + list(data),
                    name=name,
                    size=None if size is None else int(size))[0]


# ======================= round 3: nn activations =======================

@register_op("nn.prelu")
def _prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("nn.crelu")
def _crelu(x):
    return jnp.concatenate([jnp.maximum(x, 0), jnp.maximum(-x, 0)], axis=-1)


@register_op("nn.logSigmoid")
def _log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@register_op("nn.thresholdRelu")
def _threshold_relu(x, *, cutoff):
    return jnp.where(x > cutoff, x, 0.0)


@register_op("nn.preciseGelu")
def _precise_gelu(x):
    # exact erf-based GELU (nn.gelu is the tanh approximation, as the
    # reference's GELU/PreciseGELU pair distinguishes)
    return jax.nn.gelu(x, approximate=False)


@_def(SDNN, "prelu")
def _sd_prelu(self, x, alpha, name=None):
    return self._op("nn.prelu", [x, alpha], name=name)[0]


@_def(SDNN, "crelu")
def _sd_crelu(self, x, name=None):
    return self._op("nn.crelu", [x], name=name)[0]


@_def(SDNN, "logSigmoid")
def _sd_log_sigmoid(self, x, name=None):
    return self._op("nn.logSigmoid", [x], name=name)[0]


@_def(SDNN, "thresholdRelu")
def _sd_threshold_relu(self, x, cutoff=0.0, name=None):
    return self._op("nn.thresholdRelu", [x], name=name,
                    cutoff=float(cutoff))[0]


@_def(SDNN, "preciseGelu")
def _sd_precise_gelu(self, x, name=None):
    return self._op("nn.preciseGelu", [x], name=name)[0]


# ======================= round 3: random =======================

@register_op("random.exponential")
def _rand_exponential(*, seed, shape, lam):
    return jax.random.exponential(jax.random.PRNGKey(seed), shape) / lam


@register_op("random.gamma")
def _rand_gamma(*, seed, shape, alpha, beta):
    return jax.random.gamma(jax.random.PRNGKey(seed), alpha, shape) / beta


@register_op("random.poisson")
def _rand_poisson(*, seed, shape, lam):
    return jax.random.poisson(jax.random.PRNGKey(seed), lam,
                              shape).astype(jnp.float32)


@register_op("random.logNormal")
def _rand_log_normal(*, seed, shape, mean, stddev):
    return jnp.exp(mean + stddev * jax.random.normal(
        jax.random.PRNGKey(seed), shape))


@register_op("random.truncatedNormal")
def _rand_truncated_normal(*, seed, shape, mean, stddev):
    return mean + stddev * jax.random.truncated_normal(
        jax.random.PRNGKey(seed), -2.0, 2.0, shape)


@register_op("random.shuffle")
def _rand_shuffle(x, *, seed):
    return jax.random.permutation(jax.random.PRNGKey(seed), x, axis=0)


@_def(SDRandom, "exponential")
def _sd_rand_exp(self, lam, shape, seed=0, name=None):
    return self._op("random.exponential", [], name=name, seed=int(seed),
                    shape=tuple(shape), lam=float(lam))[0]


@_def(SDRandom, "gamma")
def _sd_rand_gamma(self, alpha, beta, shape, seed=0, name=None):
    return self._op("random.gamma", [], name=name, seed=int(seed),
                    shape=tuple(shape), alpha=float(alpha),
                    beta=float(beta))[0]


@_def(SDRandom, "poisson")
def _sd_rand_poisson(self, lam, shape, seed=0, name=None):
    return self._op("random.poisson", [], name=name, seed=int(seed),
                    shape=tuple(shape), lam=float(lam))[0]


@_def(SDRandom, "logNormal")
def _sd_rand_lognormal(self, mean, stddev, shape, seed=0, name=None):
    return self._op("random.logNormal", [], name=name, seed=int(seed),
                    shape=tuple(shape), mean=float(mean),
                    stddev=float(stddev))[0]


@_def(SDRandom, "truncatedNormal")
def _sd_rand_truncnormal(self, mean, stddev, shape, seed=0, name=None):
    return self._op("random.truncatedNormal", [], name=name, seed=int(seed),
                    shape=tuple(shape), mean=float(mean),
                    stddev=float(stddev))[0]


@_def(SDRandom, "shuffle")
def _sd_rand_shuffle(self, x, seed=0, name=None):
    return self._op("random.shuffle", [x], name=name, seed=int(seed))[0]


# ======================= round 3: image =======================

_YUV = jnp.array([[0.299, 0.587, 0.114],
                  [-0.14714119, -0.28886916, 0.43601035],
                  [0.61497538, -0.51496512, -0.10001026]])
_YIQ = jnp.array([[0.299, 0.587, 0.114],
                  [0.59590059, -0.27455667, -0.32134392],
                  [0.21153661, -0.52273617, 0.31119955]])


@register_op("image.rgbToYuv")
def _rgb_to_yuv(x):
    return x @ _YUV.T.astype(x.dtype)


@register_op("image.yuvToRgb")
def _yuv_to_rgb(x):
    return x @ jnp.linalg.inv(_YUV).T.astype(x.dtype)


@register_op("image.rgbToYiq")
def _rgb_to_yiq(x):
    return x @ _YIQ.T.astype(x.dtype)


@register_op("image.yiqToRgb")
def _yiq_to_rgb(x):
    return x @ jnp.linalg.inv(_YIQ).T.astype(x.dtype)


@register_op("image.resizeBicubic")
def _resize_bicubic(x, *, height, width):
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, height, width, c), method="cubic")


@register_op("image.imageResize")
def _image_resize(x, *, height, width, method):
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, height, width, c), method=method)


@_def(SDImage, "rgbToYuv")
def _sd_rgb_yuv(self, x, name=None):
    return self._op("image.rgbToYuv", [x], name=name)[0]


@_def(SDImage, "yuvToRgb")
def _sd_yuv_rgb(self, x, name=None):
    return self._op("image.yuvToRgb", [x], name=name)[0]


@_def(SDImage, "rgbToYiq")
def _sd_rgb_yiq(self, x, name=None):
    return self._op("image.rgbToYiq", [x], name=name)[0]


@_def(SDImage, "yiqToRgb")
def _sd_yiq_rgb(self, x, name=None):
    return self._op("image.yiqToRgb", [x], name=name)[0]


@_def(SDImage, "resizeBicubic")
def _sd_resize_bicubic(self, x, height, width, name=None):
    return self._op("image.resizeBicubic", [x], name=name,
                    height=int(height), width=int(width))[0]


@_def(SDImage, "imageResize")
def _sd_image_resize(self, x, height, width, method="bilinear", name=None):
    method = {"bilinear": "linear", "bicubic": "cubic"}.get(method, method)
    return self._op("image.imageResize", [x], name=name, height=int(height),
                    width=int(width), method=method)[0]


# ======================= round 3: linalg =======================

@register_op("linalg.expm")
def _expm(x):
    return jax.scipy.linalg.expm(x)


@register_op("linalg.pinv")
def _pinv(x):
    return jnp.linalg.pinv(x)


@register_op("linalg.matrixSetDiag")
def _matrix_set_diag(x, diag):
    n, m = x.shape[-2], x.shape[-1]
    k = min(n, m)
    eye = jnp.eye(n, m, dtype=bool)
    d = jnp.zeros(x.shape, x.dtype)
    idx = jnp.arange(k)
    d = d.at[..., idx, idx].set(diag[..., :k])
    return jnp.where(eye, d, x)


@_def(SDLinalg, "expm")
def _sd_expm(self, x, name=None):
    return self._op("linalg.expm", [x], name=name)[0]


@_def(SDLinalg, "pinv")
def _sd_pinv(self, x, name=None):
    return self._op("linalg.pinv", [x], name=name)[0]


@_def(SDLinalg, "matrixSetDiag")
def _sd_matrix_set_diag(self, x, diag, name=None):
    return self._op("linalg.matrixSetDiag", [x, diag], name=name)[0]


# ======================= round 3: segment / reduce / loss =======================

@register_op("segment.unsortedSegmentSqrtN")
def _segment_sqrt_n(data, ids, *, num_segments):
    s = jax.ops.segment_sum(data, ids.astype(jnp.int32), num_segments)
    cnt = jax.ops.segment_sum(jnp.ones(ids.shape, data.dtype),
                              ids.astype(jnp.int32), num_segments)
    shape = cnt.shape + (1,) * (s.ndim - cnt.ndim)
    return s / jnp.sqrt(jnp.maximum(cnt, 1.0)).reshape(shape)


@register_op("reduce.logSumExp")
def _log_sum_exp(x, *, axis, keepdims):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)


@_def(SDMath, "logSumExp")
def _sd_logsumexp(self, x, dims=None, keepdims=False, name=None):
    return self._op("reduce.logSumExp", [x], name=name, axis=_axes(dims),
                    keepdims=bool(keepdims))[0]


@register_op("loss.l2Loss")
def _l2_loss(x):
    return jnp.sum(x * x) / 2.0


@register_op("loss.weightedCrossEntropy")
def _weighted_ce(labels, logits, *, weight):
    """TF weighted_cross_entropy_with_logits (reference
    weightedCrossEntropyWithLogits): positive class reweighted by
    ``weight``; numerically-stable log1p(exp(-|x|)) form."""
    q = weight
    per = ((1 - labels) * logits
           + (1 + (q - 1) * labels)
           * (jnp.log1p(jnp.exp(-jnp.abs(logits)))
              + jnp.maximum(-logits, 0.0)))
    return jnp.mean(per)


@_def(SDLoss, "l2Loss")
def _sd_l2_loss(self, x, name=None):
    out = self._op("loss.l2Loss", [x], name=name)[0]
    self.sd.mark_loss(out)
    return out


@_def(SDLoss, "weightedCrossEntropyWithLogits")
def _sd_weighted_ce(self, labels, logits, weight=1.0, name=None):
    out = self._op("loss.weightedCrossEntropy", [labels, logits], name=name,
                   weight=float(weight))[0]
    self.sd.mark_loss(out)
    return out


# ======================= round 3b: einsum / gatherNd / topK =======================
# (TF-import surface: Einsum, GatherNd, TopKV2 — also first-class sd ops)

@register_op("math.einsum")
def _einsum(*arrays, equation):
    return jnp.einsum(equation, *arrays)


@register_op("math.gatherNd")
def _gather_nd(x, indices):
    idx = indices.astype(jnp.int32)
    return x[tuple(jnp.moveaxis(idx, -1, 0))]


@register_op("math.topK")
def _top_k(x, *, k, sorted):
    values, indices = jax.lax.top_k(x, k)
    return values, indices


@_def(SDMath, "einsum")
def _sd_einsum(self, equation, *arrays, name=None):
    return self._op("math.einsum", list(arrays), name=name,
                    equation=str(equation))[0]


@_def(SDMath, "gatherNd")
def _sd_gather_nd(self, x, indices, name=None):
    return self._op("math.gatherNd", [x, indices], name=name)[0]


@_def(SDMath, "topK")
def _sd_top_k(self, x, k, sorted=True, name=None):
    return self._op("math.topK", [x], n_out=2, name=name, k=int(k),
                    sorted=bool(sorted))


NAMESPACES = {
    "math": SDMath, "nn": SDNN, "cnn": SDCNN, "rnn": SDRNN, "loss": SDLoss,
    "random": SDRandom, "linalg": SDLinalg, "image": SDImage,
    "bitwise": SDBitwise,
}


# ======================= round 4: ctc / fft / embedding / s2b_nd =======================
# Reference: libnd4j declarable ops ctc_loss (ops/declarable/generic/loss/
# ctcLoss.cpp), fft/ifft/rfft/irfft (.../fft), embedding_lookup
# (.../embeddings), space_to_batch_nd / batch_to_space_nd (.../tnse —
# SURVEY.md §2.1 declarable-op catalog; named round-3 verdict gaps).

_CTC_NEG = -1e30  # -inf surrogate: safe under logaddexp arithmetic


@register_op("loss.ctcLoss")
def _ctc_loss(target_labels, logits, target_label_lengths,
              logit_input_lengths, *, blank_index):
    """CTC negative log-likelihood per example (reference ctc_loss).

    ``target_labels`` [B, L] int; ``logits`` [B, T, C] unnormalized;
    lengths [B]. Log-space alpha (forward) recursion over the extended
    blank-interleaved label sequence as ONE ``lax.scan`` over time —
    XLA-friendly (static shapes, masked variable lengths; the backward
    is autodiff through the scan, which yields the classic
    soft-alignment-posterior gradient without a hand-written beta pass).
    """
    B, T, C = logits.shape
    L = target_labels.shape[1]
    labels = target_labels.astype(jnp.int32)
    lab_len = target_label_lengths.astype(jnp.int32)
    inp_len = logit_input_lengths.astype(jnp.int32)
    # promote to >=f32 but PRESERVE f64 (the validation harness grad-checks
    # in double precision, reference protocol)
    logp = jax.nn.log_softmax(
        logits.astype(jnp.promote_types(logits.dtype, jnp.float32)),
        axis=-1)
    S = 2 * L + 1
    # extended sequence: blank at even s, label (s-1)//2 at odd s
    ext = jnp.full((B, S), blank_index, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    s_idx = jnp.arange(S)
    valid_s = s_idx[None, :] < (2 * lab_len + 1)[:, None]
    # the s-2 skip transition: s>=2, l'[s] != blank, l'[s] != l'[s-2]
    ext_m2 = jnp.concatenate(
        [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank_index) & (ext != ext_m2)

    def emit(logp_t):  # [B, C] -> [B, S] log p of the extended symbol
        e = jnp.take_along_axis(logp_t, ext, axis=1)
        return jnp.where(valid_s, e, _CTC_NEG)

    alpha = jnp.where(s_idx[None, :] < 2, emit(logp[:, 0]), _CTC_NEG)

    def step(alpha, xs):
        t, logp_t = xs
        a1 = alpha
        a2 = jnp.concatenate(
            [jnp.full((B, 1), _CTC_NEG), alpha[:, :-1]], axis=1)
        a3 = jnp.concatenate(
            [jnp.full((B, 2), _CTC_NEG), alpha[:, :-2]], axis=1)
        a3 = jnp.where(can_skip, a3, _CTC_NEG)
        new = jnp.logaddexp(jnp.logaddexp(a1, a2), a3) + emit(logp_t)
        # freeze finished examples (t beyond their input length)
        new = jnp.where((t < inp_len)[:, None], new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(
        step, alpha, (jnp.arange(1, T), jnp.moveaxis(logp[:, 1:], 1, 0)))
    end_blank = jnp.take_along_axis(alpha, (2 * lab_len)[:, None], axis=1)[:, 0]
    end_label = jnp.where(
        lab_len > 0,
        jnp.take_along_axis(alpha,
                            jnp.maximum(2 * lab_len - 1, 0)[:, None],
                            axis=1)[:, 0],
        _CTC_NEG)
    tot = jnp.logaddexp(end_blank, end_label)
    # infeasible alignment (input shorter than the minimum CTC length:
    # every end state still at the -inf surrogate) -> +inf like the
    # reference, not a huge-but-finite value with garbage gradients
    return jnp.where(tot < 0.5 * _CTC_NEG, jnp.inf, -tot)


@_def(SDLoss, "ctcLoss")
def _sd_ctc_loss(self, target_labels, logit_input, target_label_lengths,
                 logit_input_lengths, blank_index=0, name=None):
    out = self._op("loss.ctcLoss",
                   [target_labels, logit_input, target_label_lengths,
                    logit_input_lengths],
                   name=name, blank_index=int(blank_index))[0]
    self.sd.mark_loss(out)
    return out


# --- fft family (jnp.fft lowers to XLA FFT HLO; TPU executes natively) ---

@register_op("math.fft")
def _fft(x):
    return jnp.fft.fft(x)


@register_op("math.ifft")
def _ifft(x):
    return jnp.fft.ifft(x)


@register_op("math.rfft")
def _rfft(x, *, n):
    return jnp.fft.rfft(x, n=n)


@register_op("math.irfft")
def _irfft(x, *, n):
    return jnp.fft.irfft(x, n=n)


@register_op("math.fft2")
def _fft2(x):
    return jnp.fft.fft2(x)


@register_op("math.ifft2")
def _ifft2(x):
    return jnp.fft.ifft2(x)


@register_op("math.fft3")
def _fft3(x):
    return jnp.fft.fftn(x, axes=(-3, -2, -1))


@register_op("math.ifft3")
def _ifft3(x):
    return jnp.fft.ifftn(x, axes=(-3, -2, -1))


for _n in ("fft", "ifft", "fft2", "ifft2", "fft3", "ifft3"):
    def _sd_fft(self, x, name=None, _n=_n):
        return self._op(f"math.{_n}", [x], name=name)[0]
    _sd_fft.__name__ = _n
    setattr(SDMath, _n, _sd_fft)


@_def(SDMath, "rfft")
def _sd_rfft(self, x, n=None, name=None):
    return self._op("math.rfft", [x], name=name,
                    n=None if n is None else int(n))[0]


@_def(SDMath, "irfft")
def _sd_irfft(self, x, n=None, name=None):
    return self._op("math.irfft", [x], name=name,
                    n=None if n is None else int(n))[0]


@register_op("nn.embeddingLookup")
def _embedding_lookup(weights, ids):
    """Reference embedding_lookup (div/mod partition strategies collapse:
    sharded tables are one logical array under jax.sharding)."""
    return jnp.take(weights, ids.astype(jnp.int32), axis=0)


@_def(SDNN, "embeddingLookup")
def _sd_embedding_lookup(self, weights, ids, name=None):
    return self._op("nn.embeddingLookup", [weights, ids], name=name)[0]


@register_op("cnn.spaceToBatchNd")
def _space_to_batch_nd(x, *, block_shape, paddings):
    """TF-convention SpaceToBatchND: pad spatial dims, move block
    offsets into batch (block index varies slower than input batch)."""
    bs = [int(b) for b in block_shape]
    M = len(bs)
    pads = [(0, 0)] + [tuple(int(q) for q in p) for p in paddings] \
        + [(0, 0)] * (x.ndim - 1 - M)
    x = jnp.pad(x, pads)
    sh = x.shape
    rs = [sh[0]]
    for i in range(M):
        rs += [sh[1 + i] // bs[i], bs[i]]
    rs += list(sh[1 + M:])
    x = x.reshape(rs)
    perm = [2 * i + 2 for i in range(M)] + [0] \
        + [2 * i + 1 for i in range(M)] + list(range(1 + 2 * M, len(rs)))
    x = x.transpose(perm)
    out_b = sh[0]
    for b in bs:
        out_b *= b
    return x.reshape([out_b] + [sh[1 + i] // bs[i] for i in range(M)]
                     + list(sh[1 + M:]))


@register_op("cnn.batchToSpaceNd")
def _batch_to_space_nd(x, *, block_shape, crops):
    """Exact inverse of spaceToBatchNd (then crop)."""
    bs = [int(b) for b in block_shape]
    M = len(bs)
    sh = x.shape
    prod_b = 1
    for b in bs:
        prod_b *= b
    b0 = sh[0] // prod_b
    x = x.reshape(bs + [b0] + list(sh[1:]))
    # inverse permutation of [b_1..b_M, B, S'_1..S'_M, rest]
    perm = [M]
    for i in range(M):
        perm += [M + 1 + i, i]
    perm += list(range(2 * M + 1, x.ndim))
    x = x.transpose(perm)
    x = x.reshape([b0] + [sh[1 + i] * bs[i] for i in range(M)]
                  + list(sh[1 + M:]))
    sl = [slice(None)]
    for i in range(M):
        c0, c1 = (int(q) for q in crops[i])
        sl.append(slice(c0, x.shape[1 + i] - c1))
    return x[tuple(sl)]


@_def(SDCNN, "spaceToBatchNd")
def _sd_s2b_nd(self, x, block_shape, paddings, name=None):
    return self._op("cnn.spaceToBatchNd", [x], name=name,
                    block_shape=tuple(int(b) for b in block_shape),
                    paddings=tuple(tuple(int(q) for q in p)
                                   for p in paddings))[0]


@_def(SDCNN, "batchToSpaceNd")
def _sd_b2s_nd(self, x, block_shape, crops, name=None):
    return self._op("cnn.batchToSpaceNd", [x], name=name,
                    block_shape=tuple(int(b) for b in block_shape),
                    crops=tuple(tuple(int(q) for q in p) for p in crops))[0]


# ======================= round 4b: math / reduce / structural tail =======================
# Reference: libnd4j ops/declarable/generic/parity_ops + transforms —
# roll, fill, linspace, range, repeat, broadcast_to, stop_gradient,
# invert_permutation, nth_element, in_top_k, histogram(+fixed_width),
# unique(+with_counts), listdiff, dynamic_partition, clip_by_global_norm,
# compare_and_bitpack, divnonan/x*y, assign, equals_with_eps,
# merge_max_index, first/last_index, match_condition, axpy,
# sufficient_statistics / normalize_moments, choose, check_numerics.
# Bounded-shape convention (XLA static shapes): ops whose reference output
# size is data-dependent (unique, listdiff, choose, dynamic_partition)
# return max-size zero-padded arrays + an explicit count output, exactly
# like math.whereNonzero above.

@register_op("math.stopGradient")
def _stop_gradient(x):
    return jax.lax.stop_gradient(x)


@register_op("math.broadcastTo")
def _broadcast_to(x, *, shape):
    return jnp.broadcast_to(x, tuple(shape))


@register_op("math.fill")
def _fill(*, shape, value, dtype):
    return jnp.full(tuple(shape), value, dtype=dtype)


@register_op("math.linspace")
def _linspace(*, start, stop, num):
    return jnp.linspace(start, stop, num)


@register_op("math.range")
def _range(*, start, limit, delta):
    return jnp.arange(start, limit, delta)


@register_op("math.repeat")
def _repeat(x, *, repeats, axis):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("math.roll")
def _roll(x, *, shift, axis):
    return jnp.roll(x, shift, axis=axis)


@register_op("math.invertPermutation")
def _invert_permutation(x):
    n = x.shape[-1]
    return jnp.zeros_like(x).at[..., x.astype(jnp.int32)].set(
        jnp.arange(n, dtype=x.dtype)) if x.ndim == 1 else \
        jax.vmap(lambda p: jnp.zeros_like(p).at[p.astype(jnp.int32)].set(
            jnp.arange(n, dtype=p.dtype)))(x)


@register_op("math.nthElement")
def _nth_element(x, *, n, reverse):
    s = jnp.sort(x, axis=-1)
    idx = x.shape[-1] - 1 - n if reverse else n
    return s[..., idx]


@register_op("math.inTopK")
def _in_top_k(predictions, targets, *, k):
    t = targets.astype(jnp.int32)
    target_score = jnp.take_along_axis(
        predictions, t[:, None], axis=-1)[:, 0]
    # TF semantics: count of strictly-greater scores < k
    n_better = jnp.sum(predictions > target_score[:, None], axis=-1)
    return n_better < k


def _bin_counts(x, lo, hi, nbins):
    """Shared histogram body; a degenerate (zero-width) range puts all
    mass in bin 0 instead of dividing by zero."""
    w = (hi - lo) / nbins
    idx = jnp.clip(((x - lo) / jnp.where(w == 0, 1.0, w)).astype(jnp.int32),
                   0, nbins - 1)
    return jax.ops.segment_sum(jnp.ones(x.size, jnp.int32),
                               idx.reshape(-1), nbins)


@register_op("math.histogram")
def _histogram(x, *, nbins):
    return _bin_counts(x, jnp.min(x), jnp.max(x), nbins)


@register_op("math.histogramFixedWidth")
def _histogram_fixed_width(x, *, lo, hi, nbins):
    return _bin_counts(x, lo, hi, nbins)


def _unique_parts(x):
    n = x.size
    xf = x.reshape(-1)
    u, inv = jnp.unique(xf, size=n, return_inverse=True, fill_value=0)
    inv = inv.reshape(-1)
    # first-occurrence position of each sorted-unique slot (n = "never")
    first = jnp.full(n, n, jnp.int32).at[inv].min(
        jnp.arange(n, dtype=jnp.int32))
    order = jnp.argsort(first)  # padded slots (first=n) sort last
    rank = jnp.argsort(order)
    values = u[order]
    indices = rank[inv]
    counts = jnp.zeros(n, jnp.int32).at[inv].add(1)[order]
    count = jnp.sum(first < n)
    return values, indices.astype(jnp.int32), counts, count


@register_op("math.unique")
def _unique(x):
    """First-occurrence-ordered unique values (TF convention), bounded
    shape: (values zero-padded to x.size, inverse indices, count)."""
    values, indices, _, count = _unique_parts(x)
    return values, indices, count


@register_op("math.uniqueWithCounts")
def _unique_with_counts(x):
    values, indices, counts, count = _unique_parts(x)
    return values, indices, counts, count


@register_op("math.listDiff")
def _list_diff(x, y):
    """Elements of x not present in y (order kept), bounded shape:
    (values padded to x.size, their indices in x, count)."""
    keep = ~jnp.isin(x, y)
    n = x.size
    (idx,) = jnp.nonzero(keep, size=n, fill_value=0)
    count = jnp.sum(keep)
    valid = jnp.arange(n) < count
    return (jnp.where(valid, x[idx], 0), 
            jnp.where(valid, idx, 0).astype(jnp.int32), count)


@register_op("math.dynamicPartition")
def _dynamic_partition(x, partitions, *, num_partitions):
    """Bounded shape: each partition padded to len(x) rows; the LAST
    output is the per-partition counts [num_partitions].

    Divergence from the reference/TF op (documented, round-4 advisor):
    rows whose partition id is outside [0, num_partitions) — including
    negative ids — are silently DROPPED here, where TF raises. Static
    shapes forbid a data-dependent throw under jit; eagerly we validate
    and raise to match the reference."""
    p = partitions.astype(jnp.int32)
    if not isinstance(p, jax.core.Tracer):
        bad = jnp.logical_or(p < 0, p >= num_partitions)
        if bool(jnp.any(bad)):
            raise ValueError(
                f"dynamicPartition: partition ids must be in "
                f"[0, {num_partitions}); got "
                f"{int(p.min())}..{int(p.max())}")
    n = x.shape[0]
    outs = []
    counts = []
    for i in range(num_partitions):
        keep = p == i
        (idx,) = jnp.nonzero(keep, size=n, fill_value=0)
        cnt = jnp.sum(keep)
        valid = (jnp.arange(n) < cnt)
        sel = x[idx]
        sel = jnp.where(valid.reshape((n,) + (1,) * (x.ndim - 1)), sel, 0)
        outs.append(sel)
        counts.append(cnt)
    return tuple(outs) + (jnp.stack(counts).astype(jnp.int32),)


@register_op("math.clipByGlobalNorm")
def _clip_by_global_norm(*arrays, clip_norm):
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(a)) for a in arrays))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))
    out = tuple(a * scale for a in arrays)
    return out if len(out) > 1 else out[0]


@register_op("math.compareAndBitpack")
def _compare_and_bitpack(x, *, threshold):
    bits = (x > threshold).astype(jnp.uint8)
    b = bits.reshape(x.shape[:-1] + (x.shape[-1] // 8, 8))
    weights = (2 ** jnp.arange(7, -1, -1)).astype(jnp.uint8)
    return jnp.sum(b * weights, axis=-1).astype(jnp.uint8)


@register_op("math.divNoNan")
def _div_no_nan(x, y):
    return jnp.where(y == 0, 0.0, x / jnp.where(y == 0, 1.0, y))


@register_op("math.xdivy")
def _xdivy(x, y):
    return jnp.where(x == 0, 0.0, x / jnp.where(x == 0, 1.0, y))


@register_op("math.xlogy")
def _xlogy(x, y):
    return jax.scipy.special.xlogy(x, y)


@register_op("math.truncatediv")
def _truncatediv(x, y):
    if jnp.issubdtype(x.dtype, jnp.integer):
        q = jnp.abs(x) // jnp.abs(y)
        return (jnp.sign(x) * jnp.sign(y) * q).astype(x.dtype)
    return jnp.trunc(x / y)


@register_op("math.assign")
def _assign(x, y):
    """Reference assign: y broadcast onto x's shape (x supplies shape and
    dtype only — whole-graph compilation has no in-place aliasing)."""
    return jnp.broadcast_to(y, x.shape).astype(x.dtype)


@register_op("math.relativeError")
def _relative_error(x, y):
    """Reference relative_error: |x-y| / max(|x|, |y|), 0 where both 0."""
    denom = jnp.maximum(jnp.abs(x), jnp.abs(y))
    return jnp.where(denom == 0, 0.0, jnp.abs(x - y)
                     / jnp.where(denom == 0, 1.0, denom))


@register_op("math.equalsWithEps")
def _equals_with_eps(x, y, *, eps):
    return jnp.all(jnp.abs(x - y) <= eps)


@register_op("math.mergeMaxIndex")
def _merge_max_index(*arrays):
    return jnp.argmax(jnp.stack(arrays), axis=0).astype(jnp.int32)


@register_op("math.firstIndex")
def _first_index(x, *, condition, value):
    mask = _COND_FNS[condition](x, value)
    any_ = jnp.any(mask)
    return jnp.where(any_, jnp.argmax(mask), -1)


@register_op("math.lastIndex")
def _last_index(x, *, condition, value):
    mask = _COND_FNS[condition](x, value)
    any_ = jnp.any(mask)
    n = mask.size
    return jnp.where(any_, n - 1 - jnp.argmax(mask.reshape(-1)[::-1]), -1)


_COND_FNS = {
    "gt": lambda x, v: x > v, "gte": lambda x, v: x >= v,
    "lt": lambda x, v: x < v, "lte": lambda x, v: x <= v,
    "eq": lambda x, v: x == v, "neq": lambda x, v: x != v,
    "abs_gt": lambda x, v: jnp.abs(x) > v,
    "abs_lt": lambda x, v: jnp.abs(x) < v,
}


@register_op("math.matchCondition")
def _match_condition(x, *, condition, value):
    """Reference MatchCondition reduce: COUNT of matching elements."""
    return jnp.sum(_COND_FNS[condition](x, value)).astype(jnp.int64)


@register_op("math.choose")
def _choose(x, *, condition, value):
    """Reference choose: matching elements compacted (bounded shape:
    padded to x.size + count)."""
    mask = _COND_FNS[condition](x, value).reshape(-1)
    n = x.size
    (idx,) = jnp.nonzero(mask, size=n, fill_value=0)
    count = jnp.sum(mask)
    valid = jnp.arange(n) < count
    return jnp.where(valid, x.reshape(-1)[idx], 0), count


@register_op("math.axpy")
def _axpy(x, y, *, alpha):
    return alpha * x + y


@register_op("math.sufficientStatistics")
def _sufficient_statistics(x, *, axis, shift):
    axes = tuple(axis)
    import math as _math

    count = jnp.asarray(
        _math.prod(x.shape[a] for a in axes), x.dtype)
    xs = x - shift if shift is not None else x
    return (count, jnp.sum(xs, axis=axes), jnp.sum(xs * xs, axis=axes))


@register_op("math.normalizeMoments")
def _normalize_moments(counts, mean_ss, var_ss, *, shift):
    mean = mean_ss / counts
    var = var_ss / counts - mean * mean
    if shift is not None:
        mean = mean + shift
    return mean, var


@register_op("math.checkNumerics")
def _check_numerics(x, *, message):
    """Reference check_numerics throws on NaN/Inf; under whole-graph jit
    there is no host exception path, so this validates EAGERLY (concrete
    arrays — e.g. SameDiff.output on real inputs executes op-by-op only
    when debugging). When traced (checkify.check cannot stage under
    plain jit in this JAX), it (a) emits a ONE-TIME warning that the
    hard-throw guarantee is eager-only, and (b) where the backend
    supports host callbacks, installs a ``jax.debug.callback`` that
    LOGS every non-finite event at runtime (logging, not
    ``warnings.warn`` — the default warning filter would swallow every
    event after the first) — round-4 advisor finding closed. The axon
    PJRT plugin rejects host send/recv callbacks outright, so on that
    backend the op stays a traced identity after the one-time warning
    rather than crashing every jitted graph that contains it."""
    if not isinstance(x, jax.core.Tracer):
        if not bool(jnp.all(jnp.isfinite(x))):
            raise FloatingPointError(f"check_numerics: {message}")
        return x
    import warnings

    global _CHECK_NUMERICS_WARNED
    if not _CHECK_NUMERICS_WARNED:
        _CHECK_NUMERICS_WARNED = True
        warnings.warn(
            "math.checkNumerics inside jit cannot raise host "
            "exceptions; non-finite values are reported via a runtime "
            "log message instead (traced identity on backends without "
            "host-callback support). Call eagerly for the hard "
            "throw-on-NaN guarantee.", RuntimeWarning, stacklevel=3)
    if not _host_callbacks_supported():
        return x

    def _report(ok):
        if not bool(ok):
            import logging

            logging.getLogger(__name__).warning(
                "check_numerics: %s (non-finite values in jitted graph)",
                message)

    jax.debug.callback(_report, jnp.all(jnp.isfinite(x)))
    return x


_CHECK_NUMERICS_WARNED = False
_HOST_CALLBACKS_OK = None


def _host_callbacks_supported():
    """One-time capability probe: the axon PJRT plugin registers as
    platform 'tpu' but rejects host send/recv callbacks with
    UNIMPLEMENTED, so the only reliable gate is executing one."""
    global _HOST_CALLBACKS_OK
    if _HOST_CALLBACKS_OK is None:
        # metadata gate, NOT an execution probe: _check_numerics calls
        # this INSIDE an active trace, where any probe jit would inline
        # its callback into the caller's graph (jit-under-trace inlines)
        # and crash the very program the gate is protecting
        try:
            _HOST_CALLBACKS_OK = ("axon" not in jax.devices()[0]
                                  .client.platform_version)
        except Exception:
            _HOST_CALLBACKS_OK = False
    return _HOST_CALLBACKS_OK


@register_op("math.rank")
def _rank(x):
    return jnp.asarray(x.ndim, jnp.int32)


@register_op("math.sizeOp")
def _size_op(x):
    return jnp.asarray(x.size, jnp.int64)


@register_op("split_v")
def _split_v(x, *, sizes, axis):
    total = x.shape[axis]
    sizes = list(sizes)
    if sizes.count(-1) > 1:
        raise ValueError("split_v: at most one -1 size")
    if -1 in sizes:
        rest = total - sum(s for s in sizes if s != -1)
        if rest < 0:
            raise ValueError(f"split_v: sizes {sizes} exceed axis {total}")
        sizes[sizes.index(-1)] = rest
    if sum(sizes) != total:
        raise ValueError(
            f"split_v: sizes {sizes} must sum to axis length {total}")
    outs = []
    off = 0
    for s in sizes:
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(off, off + s)
        outs.append(x[tuple(sl)])
        off += s
    return tuple(outs)


@register_op("reduce.all")
def _reduce_all(x, *, axis, keepdims):
    return jnp.all(x, axis=axis, keepdims=keepdims)


@register_op("reduce.any")
def _reduce_any(x, *, axis, keepdims):
    return jnp.any(x, axis=axis, keepdims=keepdims)


@register_op("reduce.percentile")
def _percentile(x, *, q, axis, keepdims, interpolation):
    return jnp.percentile(x, q, axis=axis, keepdims=keepdims,
                          method=interpolation)


@register_op("reduce.median")
def _median(x, *, axis, keepdims):
    return jnp.median(x, axis=axis, keepdims=keepdims)


@register_op("reduce.squaredNorm")
def _squared_norm(x, *, axis, keepdims):
    return jnp.sum(x * x, axis=axis, keepdims=keepdims)


def _single_axis(axis):
    if isinstance(axis, (tuple, list)):
        assert len(axis) == 1, "iamax/iamin take one axis (reference iamax)"
        return axis[0]
    return axis


@register_op("reduce.iamax")
def _iamax(x, *, axis, keepdims):
    ax = _single_axis(axis)
    r = jnp.argmax(jnp.abs(x), axis=ax)
    return jnp.expand_dims(r, ax) if keepdims and ax is not None else r


@register_op("reduce.iamin")
def _iamin(x, *, axis, keepdims):
    ax = _single_axis(axis)
    r = jnp.argmin(jnp.abs(x), axis=ax)
    return jnp.expand_dims(r, ax) if keepdims and ax is not None else r


# ======================= round 4c: nn / cnn / linalg / loss / quant tail =======================

@register_op("nn.reluLayer")
def _relu_layer(x, w, b):
    return jax.nn.relu(x @ w + b)


@register_op("nn.mirrorPad")
def _mirror_pad(x, *, paddings, mode):
    return jnp.pad(x, [tuple(p) for p in paddings],
                   mode="reflect" if mode == "REFLECT" else "symmetric")


@register_op("cnn.pnormPool2d")
def _pnorm_pool2d(x, *, kernel, stride, padding, p):
    s = jax.lax.reduce_window(
        jnp.abs(x) ** p, 0.0, jax.lax.add,
        (1, kernel[0], kernel[1], 1), (1, stride[0], stride[1], 1), padding)
    return s ** (1.0 / p)


@register_op("cnn.maxPoolWithArgmax")
def _max_pool_with_argmax(x, *, kernel, stride, padding):
    """Values + TF-convention argmax (flat index into [H*W*C] per batch).
    Windows enumerated by static strided slices (kernel is small), the
    argmax over the window axis — no dynamic shapes."""
    b, h, w, c = x.shape
    kh, kw = kernel
    sh, sw = stride
    if padding == "SAME":
        oh, ow = -(-h // sh), -(-w // sw)
        ph = max((oh - 1) * sh + kh - h, 0)
        pw = max((ow - 1) * sw + kw - w, 0)
        pt, pl = ph // 2, pw // 2
        xp = jnp.pad(x, ((0, 0), (pt, ph - pt), (pl, pw - pl), (0, 0)),
                     constant_values=-jnp.inf)
        row0, col0 = -pt, -pl
    else:
        oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        xp, row0, col0 = x, 0, 0
    vals, flat = [], []
    for ki in range(kh):
        for kj in range(kw):
            v = xp[:, ki:ki + sh * (oh - 1) + 1:sh,
                   kj:kj + sw * (ow - 1) + 1:sw, :]
            vals.append(v)
            ri = row0 + ki + sh * jnp.arange(oh)
            cj = col0 + kj + sw * jnp.arange(ow)
            f = (ri[:, None] * w + cj[None, :])[None, :, :, None] * c \
                + jnp.arange(c)[None, None, None, :]
            flat.append(jnp.broadcast_to(f, v.shape))
    stacked = jnp.stack(vals)
    am = jnp.argmax(stacked, axis=0)
    values = jnp.max(stacked, axis=0)
    indices = jnp.take_along_axis(jnp.stack(flat), am[None], axis=0)[0]
    return values, indices.astype(jnp.int64)


@register_op("linalg.lu")
def _lu(x):
    """LU factorization, LAPACK convention: packed LU + pivot indices
    (reference lu op returns the same pair)."""
    lu, piv = jax.scipy.linalg.lu_factor(x)
    return lu, piv.astype(jnp.int32)


@register_op("linalg.matrixDiag")
def _matrix_diag(x):
    n = x.shape[-1]
    return x[..., :, None] * jnp.eye(n, dtype=x.dtype)


@register_op("loss.softmaxCrossEntropyWithLogits")
def _sce_with_logits(labels, logits):
    """TF twin-output form: (per-example loss, backprop = softmax -
    labels) — dense-label sibling of sparseSoftmaxCrossEntropyWithLogits."""
    lp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.sum(labels * lp, axis=-1)
    return per, jnp.exp(lp) - labels


@register_op("loss.meanPairwiseSquaredError")
def _mpse(labels, preds, *, reduction):
    """Reference mean_pairwssqerr_loss: mean over ordered pairs (i, j) of
    ((d_i - d_j)^2)/2 per example, d = preds - labels."""
    d = (preds - labels).reshape(preds.shape[0], -1)
    n = d.shape[-1]
    s1 = jnp.sum(d, axis=-1)
    s2 = jnp.sum(d * d, axis=-1)
    # sum_{i<j} (d_i-d_j)^2 = n*s2 - s1^2 ; pairs = n*(n-1)/2; TF divides
    # by pairs and halves via the ordered-pair double count
    pairs = n * (n - 1)
    per = jnp.where(pairs > 0, (n * s2 - s1 * s1) * 2.0 / pairs, 0.0)
    return _apply_reduction(per, reduction)


def _fake_quant(x, lo, hi, num_bits, narrow_range):
    qmin = 1.0 if narrow_range else 0.0
    qmax = float(2 ** num_bits - 1)
    # TF nudged-range formula
    scale = (hi - lo) / (qmax - qmin)
    zp_float = qmin - lo / scale
    zp = jnp.clip(jnp.round(zp_float), qmin, qmax)
    nudged_lo = (qmin - zp) * scale
    nudged_hi = (qmax - zp) * scale
    xc = jnp.clip(x, nudged_lo, nudged_hi)
    q = jnp.round((xc - nudged_lo) / scale) * scale + nudged_lo
    # straight-through estimator, the TF/reference gradient: 1 inside
    # the nudged range (via clip), 0 outside; round contributes nothing
    return xc + jax.lax.stop_gradient(q - xc)


@register_op("math.fakeQuantWithMinMaxArgs")
def _fake_quant_args(x, *, lo, hi, num_bits, narrow_range):
    return _fake_quant(x, lo, hi, num_bits, narrow_range)


@register_op("math.fakeQuantWithMinMaxVars")
def _fake_quant_vars(x, lo, hi, *, num_bits, narrow_range):
    return _fake_quant(x, lo, hi, num_bits, narrow_range)


@register_op("math.fakeQuantWithMinMaxVarsPerChannel")
def _fake_quant_per_channel(x, lo, hi, *, num_bits, narrow_range):
    return _fake_quant(x, lo, hi, num_bits, narrow_range)


@register_op("bitwise.bitcast")
def _bitcast(x, *, dtype):
    return jax.lax.bitcast_convert_type(x, jnp.dtype(dtype))


@register_op("image.resizeArea")
def _resize_area(x, *, height, width):
    """Area (box-filter) resize for INTEGER downscale factors — exact
    block mean, the common data-pipeline case; other ratios raise (the
    reference's general kernel is out of scope until needed)."""
    b, h, w, c = x.shape
    if h % height or w % width:
        raise NotImplementedError(
            "image.resizeArea: non-integer scale factors unsupported "
            f"({h}x{w} -> {height}x{width})")
    fh, fw = h // height, w // width
    return jnp.mean(
        x.reshape(b, height, fh, width, fw, c), axis=(2, 4))


@register_op("image.randomCrop")
def _random_crop(x, *, seed, height, width):
    key = jax.random.PRNGKey(seed)
    kh, kw = jax.random.split(key)
    h0 = jax.random.randint(kh, (), 0, x.shape[1] - height + 1)
    w0 = jax.random.randint(kw, (), 0, x.shape[2] - width + 1)
    return jax.lax.dynamic_slice(
        x, (0, h0, w0, 0), (x.shape[0], height, width, x.shape[3]))


@register_op("random.multinomial")
def _multinomial(logits, *, seed, num_samples):
    s = jax.random.categorical(
        jax.random.PRNGKey(seed), logits, axis=-1,
        shape=(num_samples, logits.shape[0]))  # sample dim leads, then T
    return s.T.astype(jnp.int64)


@register_op("scatter.nd")
def _scatter_nd(indices, updates, *, shape):
    idx = indices.astype(jnp.int32)
    return jnp.zeros(tuple(shape), updates.dtype).at[
        tuple(jnp.moveaxis(idx, -1, 0))].add(updates, mode="drop")


@register_op("scatter.ndAdd")
def _scatter_nd_add(ref, indices, updates):
    idx = indices.astype(jnp.int32)
    return ref.at[tuple(jnp.moveaxis(idx, -1, 0))].add(updates, mode="drop")


@register_op("scatter.ndSub")
def _scatter_nd_sub(ref, indices, updates):
    idx = indices.astype(jnp.int32)
    return ref.at[tuple(jnp.moveaxis(idx, -1, 0))].add(-updates, mode="drop")


@register_op("scatter.ndUpdate")
def _scatter_nd_update(ref, indices, updates):
    idx = indices.astype(jnp.int32)
    return ref.at[tuple(jnp.moveaxis(idx, -1, 0))].set(updates, mode="drop")


@register_op("rnn.ctcGreedyDecoder")
def _ctc_greedy_decoder(logits, seq_lengths, *, blank_index, merge_repeated):
    """Greedy (beam-width-1) CTC decode, bounded shape: best path argmax
    per step, repeats merged, blanks removed -> (decoded [B, T] padded
    with -1, lengths [B], neg-sum-logit score [B])."""
    B, T, C = logits.shape
    lp = jax.nn.log_softmax(logits, axis=-1)
    path = jnp.argmax(lp, axis=-1).astype(jnp.int32)          # [B, T]
    score = -jnp.sum(jnp.max(lp, axis=-1) * (
        jnp.arange(T)[None, :] < seq_lengths.astype(jnp.int32)[:, None]),
        axis=-1)
    t_idx = jnp.arange(T)[None, :]
    in_len = t_idx < seq_lengths.astype(jnp.int32)[:, None]
    prev = jnp.concatenate(
        [jnp.full((B, 1), -1, jnp.int32), path[:, :-1]], axis=1)
    keep = (path != blank_index) & in_len
    if merge_repeated:
        keep &= (path != prev)
    # stable compaction of kept symbols to the front: dropped symbols
    # scatter to the out-of-bounds index T and are discarded
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full((B, T), -1, jnp.int32)
    bidx = jnp.repeat(jnp.arange(B)[:, None], T, axis=1)
    out = out.at[bidx, jnp.where(keep, pos, T)].set(path, mode="drop")
    lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
    return out, lengths, score


# --- round-4 tail: namespace surface -----------------------------------------

def _def_simple_math(opn, n_in=1, n_out=1, **fixed):
    def m(self, *xs, name=None, _n=opn, **kw):
        args = list(xs[:n_in])
        attrs = {**fixed, **kw}
        r = self._op(f"math.{_n}", args, n_out=n_out, name=name, **attrs)
        return r[0] if n_out == 1 else tuple(r)
    m.__name__ = opn
    setattr(SDMath, opn, m)


_def_simple_math("stopGradient")
_def_simple_math("xdivy", n_in=2)
_def_simple_math("xlogy", n_in=2)
_def_simple_math("divNoNan", n_in=2)
_def_simple_math("truncatediv", n_in=2)
_def_simple_math("assign", n_in=2)
_def_simple_math("invertPermutation")
_def_simple_math("unique", n_out=3)
_def_simple_math("uniqueWithCounts", n_out=4)
_def_simple_math("listDiff", n_in=2, n_out=3)
_def_simple_math("rank")
_def_simple_math("sizeOp")


@_def(SDMath, "broadcastTo")
def _sd_broadcast_to(self, x, shape, name=None):
    return self._op("math.broadcastTo", [x], name=name,
                    shape=tuple(int(s) for s in shape))[0]


@_def(SDMath, "fill")
def _sd_fill(self, shape, value, dtype="float32", name=None):
    return self._op("math.fill", [], name=name,
                    shape=tuple(int(s) for s in shape),
                    value=float(value), dtype=str(dtype))[0]


@_def(SDMath, "linspace")
def _sd_linspace(self, start, stop, num, name=None):
    return self._op("math.linspace", [], name=name, start=float(start),
                    stop=float(stop), num=int(num))[0]


@_def(SDMath, "range")
def _sd_range(self, start, limit, delta=1, name=None):
    return self._op("math.range", [], name=name, start=start, limit=limit,
                    delta=delta)[0]


@_def(SDMath, "repeat")
def _sd_repeat(self, x, repeats, axis, name=None):
    return self._op("math.repeat", [x], name=name, repeats=int(repeats),
                    axis=int(axis))[0]


@_def(SDMath, "roll")
def _sd_roll(self, x, shift, axis=None, name=None):
    return self._op("math.roll", [x], name=name, shift=shift,
                    axis=axis if axis is None else int(axis))[0]


@_def(SDMath, "nthElement")
def _sd_nth_element(self, x, n, reverse=False, name=None):
    return self._op("math.nthElement", [x], name=name, n=int(n),
                    reverse=bool(reverse))[0]


@_def(SDMath, "inTopK")
def _sd_in_top_k(self, predictions, targets, k, name=None):
    return self._op("math.inTopK", [predictions, targets], name=name,
                    k=int(k))[0]


@_def(SDMath, "histogram")
def _sd_histogram(self, x, nbins, name=None):
    return self._op("math.histogram", [x], name=name, nbins=int(nbins))[0]


@_def(SDMath, "histogramFixedWidth")
def _sd_histogram_fw(self, x, lo, hi, nbins, name=None):
    return self._op("math.histogramFixedWidth", [x], name=name,
                    lo=float(lo), hi=float(hi), nbins=int(nbins))[0]


@_def(SDMath, "dynamicPartition")
def _sd_dynamic_partition(self, x, partitions, num_partitions, name=None):
    return tuple(self._op("math.dynamicPartition", [x, partitions],
                          n_out=int(num_partitions) + 1, name=name,
                          num_partitions=int(num_partitions)))


@_def(SDMath, "clipByGlobalNorm")
def _sd_clip_by_global_norm(self, arrays, clip_norm, name=None):
    arrays = list(arrays)
    r = self._op("math.clipByGlobalNorm", arrays, n_out=len(arrays),
                 name=name, clip_norm=float(clip_norm))
    return tuple(r)


@_def(SDMath, "compareAndBitpack")
def _sd_compare_and_bitpack(self, x, threshold, name=None):
    return self._op("math.compareAndBitpack", [x], name=name,
                    threshold=float(threshold))[0]


@_def(SDMath, "relativeError")
def _sd_relative_error(self, x, y, name=None):
    return self._op("math.relativeError", [x, y], name=name)[0]


@_def(SDMath, "equalsWithEps")
def _sd_equals_with_eps(self, x, y, eps=1e-5, name=None):
    return self._op("math.equalsWithEps", [x, y], name=name,
                    eps=float(eps))[0]


@_def(SDMath, "mergeMaxIndex")
def _sd_merge_max_index(self, *arrays, name=None):
    return self._op("math.mergeMaxIndex", list(arrays), name=name)[0]


@_def(SDMath, "firstIndex")
def _sd_first_index(self, x, condition, value, name=None):
    return self._op("math.firstIndex", [x], name=name,
                    condition=str(condition), value=float(value))[0]


@_def(SDMath, "lastIndex")
def _sd_last_index(self, x, condition, value, name=None):
    return self._op("math.lastIndex", [x], name=name,
                    condition=str(condition), value=float(value))[0]


@_def(SDMath, "matchCondition")
def _sd_match_condition(self, x, condition, value, name=None):
    return self._op("math.matchCondition", [x], name=name,
                    condition=str(condition), value=float(value))[0]


@_def(SDMath, "choose")
def _sd_choose(self, x, condition, value, name=None):
    return tuple(self._op("math.choose", [x], n_out=2, name=name,
                          condition=str(condition), value=float(value)))


@_def(SDMath, "axpy")
def _sd_axpy(self, x, y, alpha, name=None):
    return self._op("math.axpy", [x, y], name=name, alpha=float(alpha))[0]


@_def(SDMath, "sufficientStatistics")
def _sd_sufficient_statistics(self, x, dims, shift=None, name=None):
    return tuple(self._op("math.sufficientStatistics", [x], n_out=3,
                          name=name, axis=_axes(dims),
                          shift=None if shift is None else float(shift)))


@_def(SDMath, "normalizeMoments")
def _sd_normalize_moments(self, counts, mean_ss, var_ss, shift=None,
                          name=None):
    return tuple(self._op("math.normalizeMoments",
                          [counts, mean_ss, var_ss], n_out=2, name=name,
                          shift=None if shift is None else float(shift)))


@_def(SDMath, "checkNumerics")
def _sd_check_numerics(self, x, message="", name=None):
    return self._op("math.checkNumerics", [x], name=name,
                    message=str(message))[0]


for _n in ("fakeQuantWithMinMaxVars", "fakeQuantWithMinMaxVarsPerChannel"):
    def _sd_fq(self, x, lo, hi, num_bits=8, narrow_range=False, name=None,
               _n=_n):
        return self._op(f"math.{_n}", [x, lo, hi], name=name,
                        num_bits=int(num_bits),
                        narrow_range=bool(narrow_range))[0]
    _sd_fq.__name__ = _n
    setattr(SDMath, _n, _sd_fq)


@_def(SDMath, "fakeQuantWithMinMaxArgs")
def _sd_fq_args(self, x, lo=-6.0, hi=6.0, num_bits=8, narrow_range=False,
                name=None):
    return self._op("math.fakeQuantWithMinMaxArgs", [x], name=name,
                    lo=float(lo), hi=float(hi), num_bits=int(num_bits),
                    narrow_range=bool(narrow_range))[0]


def _def_reduce4(opn):
    def m(self, x, dims=None, keepdims=False, name=None, _n=opn):
        return self._op(f"reduce.{_n}", [x], name=name, axis=_axes(dims),
                        keepdims=bool(keepdims))[0]
    m.__name__ = opn
    setattr(SDMath, opn, m)


for _n in ("all", "any", "median", "squaredNorm", "iamax", "iamin"):
    _def_reduce4(_n)


@_def(SDMath, "percentile")
def _sd_percentile(self, x, q, dims=None, keepdims=False,
                   interpolation="linear", name=None):
    return self._op("reduce.percentile", [x], name=name, q=float(q),
                    axis=_axes(dims), keepdims=bool(keepdims),
                    interpolation=str(interpolation))[0]


@_def(SDNN, "reluLayer")
def _sd_relu_layer(self, x, w, b, name=None):
    return self._op("nn.reluLayer", [x, w, b], name=name)[0]


@_def(SDNN, "mirrorPad")
def _sd_mirror_pad(self, x, paddings, mode="REFLECT", name=None):
    return self._op("nn.mirrorPad", [x], name=name,
                    paddings=tuple(tuple(int(q) for q in p)
                                   for p in paddings), mode=str(mode))[0]


@_def(SDCNN, "pnormPool2d")
def _sd_pnorm_pool2d(self, x, kernel, stride, p=2.0, padding="VALID",
                     name=None):
    return self._op("cnn.pnormPool2d", [x], name=name,
                    kernel=(int(kernel[0]), int(kernel[1])),
                    stride=(int(stride[0]), int(stride[1])),
                    padding=str(padding), p=float(p))[0]


@_def(SDCNN, "maxPoolWithArgmax")
def _sd_max_pool_with_argmax(self, x, kernel, stride, padding="VALID",
                             name=None):
    return tuple(self._op("cnn.maxPoolWithArgmax", [x], n_out=2, name=name,
                          kernel=(int(kernel[0]), int(kernel[1])),
                          stride=(int(stride[0]), int(stride[1])),
                          padding=str(padding)))


@_def(SDLinalg, "lu")
def _sd_lu(self, x, name=None):
    return tuple(self._op("linalg.lu", [x], n_out=2, name=name))


@_def(SDLinalg, "matrixDiag")
def _sd_matrix_diag(self, x, name=None):
    return self._op("linalg.matrixDiag", [x], name=name)[0]


@_def(SDLoss, "softmaxCrossEntropyWithLogits")
def _sd_sce_with_logits(self, labels, logits, name=None):
    return tuple(self._op("loss.softmaxCrossEntropyWithLogits",
                          [labels, logits], n_out=2, name=name))


@_def(SDLoss, "meanPairwiseSquaredError")
def _sd_mpse(self, labels, predictions, name=None, reduction="mean"):
    out = self._op("loss.meanPairwiseSquaredError", [labels, predictions],
                   name=name, reduction=reduction)[0]
    self.sd.mark_loss(out)
    return out


@_def(SDBitwise, "bitcast")
def _sd_bitcast(self, x, dtype, name=None):
    return self._op("bitwise.bitcast", [x], name=name, dtype=str(dtype))[0]


@_def(SDImage, "resizeArea")
def _sd_resize_area(self, x, height, width, name=None):
    return self._op("image.resizeArea", [x], name=name, height=int(height),
                    width=int(width))[0]


@_def(SDImage, "randomCrop")
def _sd_random_crop(self, x, height, width, seed=0, name=None):
    return self._op("image.randomCrop", [x], name=name, seed=int(seed),
                    height=int(height), width=int(width))[0]


@_def(SDRandom, "multinomial")
def _sd_multinomial(self, logits, num_samples, seed=0, name=None):
    return self._op("random.multinomial", [logits], name=name,
                    seed=int(seed), num_samples=int(num_samples))[0]


@_def(SDRNN, "ctcGreedyDecoder")
def _sd_ctc_greedy_decoder(self, logits, seq_lengths, blank_index=0,
                           merge_repeated=True, name=None):
    return tuple(self._op("rnn.ctcGreedyDecoder", [logits, seq_lengths],
                          n_out=3, name=name, blank_index=int(blank_index),
                          merge_repeated=bool(merge_repeated)))
