"""SameDiff-equivalent: symbolic define-by-graph autodiff lowered to
whole-graph XLA programs (reference ``org.nd4j.autodiff.samediff``)."""

from deeplearning4j_tpu.samediff.core import (OP_REGISTRY, SDVariable,
                                              SameDiff, VariableType,
                                              register_op)
from deeplearning4j_tpu.samediff.training import History, TrainingConfig

__all__ = ["SameDiff", "SDVariable", "VariableType", "TrainingConfig",
           "History", "OP_REGISTRY", "register_op"]
