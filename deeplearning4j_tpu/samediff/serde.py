"""SameDiff persistence (reference: FlatBuffers save/load via
``SameDiff#asFlatBuffers/save/load`` + ``FlatBuffersMapper`` — SURVEY.md
§2.2 "SameDiff serialization").

Format here: one ``.sdz`` zip = ``graph.json`` (variables + op nodes with
registry names and JSON attrs) + ``arrays.npz`` (VARIABLE/CONSTANT values)
+ optional ``updater_state.npz``. The op registry is the schema — loading
re-links each node to its pure-jax impl by name, so a loaded graph compiles
to the identical XLA program. Graphs containing control-flow callables
(``cond``/``while_loop``/``scan``) carry non-serializable closures and are
rejected with a clear error, matching the spirit of the reference's
unsupported-op FlatBuffers failures.
"""

from __future__ import annotations

import io
import json
import zipfile

import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 1


def save(sd, path, save_updater_state: bool = True) -> None:
    for op in sd.ops.values():
        if op.fn_attrs:
            raise ValueError(
                f"op {op.name!r} ({op.op_name}) holds python callables "
                "(control flow); such graphs are not serializable")
    graph = {
        "format_version": FORMAT_VERSION,
        "variables": [
            {"name": v.name, "var_type": v.var_type,
             "shape": list(v.shape) if v.shape is not None else None,
             "dtype": v.dtype, "producer": v.producer,
             "output_index": v.output_index}
            for v in sd.variables.values()
        ],
        "ops": [
            {"name": o.name, "op_name": o.op_name,
             "inputs": list(o.inputs), "outputs": list(o.outputs),
             "attrs": _jsonable_attrs(o.attrs)}
            for o in sd.ops.values()
        ],
        "loss_variables": list(sd.loss_variables),
        "iteration_count": sd._iteration_count,
        "epoch_count": sd._epoch_count,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("graph.json", json.dumps(graph, indent=1))
        z.writestr("arrays.npz", _npz_bytes(
            {k: np.asarray(v) for k, v in sd.arrays.items()}))
        if save_updater_state and sd._updater_state is not None:
            flat = {}
            for var, st in sd._updater_state.items():
                for k, v in (st or {}).items():
                    flat[f"{var}//{k}"] = np.asarray(v)
            z.writestr("updater_state.npz", _npz_bytes(flat))


def load(path):
    from deeplearning4j_tpu.samediff.core import (OpNode, SameDiff, VarMeta)

    with zipfile.ZipFile(path, "r") as z:
        graph = json.loads(z.read("graph.json"))
        arrays = dict(np.load(io.BytesIO(z.read("arrays.npz"))))
        updater_state = None
        if "updater_state.npz" in z.namelist():
            flat = dict(np.load(io.BytesIO(z.read("updater_state.npz"))))
            updater_state = {}
            for key, v in flat.items():
                var, k = key.rsplit("//", 1)
                updater_state.setdefault(var, {})[k] = jnp.asarray(v)

    sd = SameDiff()
    for v in graph["variables"]:
        sd.variables[v["name"]] = VarMeta(
            v["name"], v["var_type"],
            tuple(v["shape"]) if v["shape"] is not None else None,
            v["dtype"], v.get("producer"), v.get("output_index", 0))
    for o in graph["ops"]:
        sd.ops[o["name"]] = OpNode(
            o["name"], o["op_name"], tuple(o["inputs"]),
            tuple(o["outputs"]), _restore_attrs(o["attrs"]))
    sd.arrays = {k: jnp.asarray(v) for k, v in arrays.items()}
    sd.loss_variables = list(graph.get("loss_variables", []))
    sd._iteration_count = graph.get("iteration_count", 0)
    sd._epoch_count = graph.get("epoch_count", 0)
    sd._updater_state = updater_state
    return sd


def _jsonable_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, tuple):
            out[k] = {"__tuple__": [_jsonable_attrs({"v": x})["v"]
                                    for x in v]}
        elif isinstance(v, (str, int, float, bool, type(None), dict, list)):
            out[k] = v
        else:
            raise TypeError(f"attr {k}={v!r} not JSON-serializable")
    return out


def _restore_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__tuple__" in v:
            out[k] = tuple(
                _restore_attrs({"v": x})["v"] for x in v["__tuple__"])
        else:
            out[k] = v
    return out


def _npz_bytes(arrs: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrs)
    return buf.getvalue()
