"""SameDiff persistence (reference: FlatBuffers save/load via
``SameDiff#asFlatBuffers/save/load`` + ``FlatBuffersMapper`` — SURVEY.md
§2.2 "SameDiff serialization").

Format here: one ``.sdz`` zip = ``graph.json`` (variables + op nodes with
registry names and JSON attrs) + ``arrays.npz`` (VARIABLE/CONSTANT values)
+ optional ``updater_state.npz``. The op registry is the schema — loading
re-links each node to its pure-jax impl by name, so a loaded graph compiles
to the identical XLA program. Control flow (``cond``/``while_loop``/
``scan``) serializes too when its callables were traced into child graphs
(see ``SameDiff._try_trace``) — the child graph rides along as JSON and
the callable is rebuilt at load, the role of the reference's FlatBuffers
control-flow frames. Only bodies written against raw jax (not SDVariable
ops) are unserializable and rejected with a clear error.
"""

from __future__ import annotations

import io
import json
import zipfile

import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 1


def _check_serializable_ops(ops, where=""):
    for op in ops:
        missing = set(op.fn_attrs) - set(op.subgraphs)
        if missing:
            raise ValueError(
                f"op {op.name!r} ({op.op_name}){where} holds python "
                f"callables {sorted(missing)} that were not traceable as "
                "SDVariable subgraphs (they use raw jax/numpy); such "
                "graphs are not serializable")


def save(sd, path, save_updater_state: bool = True) -> None:
    _check_serializable_ops(sd.ops.values())
    graph = {
        "format_version": FORMAT_VERSION,
        "variables": _var_dicts(sd),
        "ops": _op_dicts(sd),
        "loss_variables": list(sd.loss_variables),
        "iteration_count": sd._iteration_count,
        "epoch_count": sd._epoch_count,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("graph.json", json.dumps(graph, indent=1))
        z.writestr("arrays.npz", _npz_bytes(
            {k: np.asarray(v) for k, v in sd.arrays.items()}))
        if save_updater_state and sd._updater_state is not None:
            flat = {}
            for var, st in sd._updater_state.items():
                for k, v in (st or {}).items():
                    flat[f"{var}//{k}"] = np.asarray(v)
            z.writestr("updater_state.npz", _npz_bytes(flat))


def load(path):
    from deeplearning4j_tpu.samediff.core import (OpNode, SameDiff, VarMeta)

    with zipfile.ZipFile(path, "r") as z:
        graph = json.loads(z.read("graph.json"))
        arrays = dict(np.load(io.BytesIO(z.read("arrays.npz"))))
        updater_state = None
        if "updater_state.npz" in z.namelist():
            flat = dict(np.load(io.BytesIO(z.read("updater_state.npz"))))
            updater_state = {}
            for key, v in flat.items():
                var, k = key.rsplit("//", 1)
                updater_state.setdefault(var, {})[k] = jnp.asarray(v)

    sd = SameDiff()
    for v in graph["variables"]:
        sd.variables[v["name"]] = VarMeta(
            v["name"], v["var_type"],
            tuple(v["shape"]) if v["shape"] is not None else None,
            v["dtype"], v.get("producer"), v.get("output_index", 0))
    for o in graph["ops"]:
        subgraphs = o.get("subgraphs", {})
        fn_attrs = {k: callable_from_subgraph(d)
                    for k, d in subgraphs.items()}
        sd.ops[o["name"]] = OpNode(
            o["name"], o["op_name"], tuple(o["inputs"]),
            tuple(o["outputs"]), _restore_attrs(o["attrs"]),
            fn_attrs, subgraphs)
    sd.arrays = {k: jnp.asarray(v) for k, v in arrays.items()}
    sd.loss_variables = list(graph.get("loss_variables", []))
    sd._iteration_count = graph.get("iteration_count", 0)
    sd._epoch_count = graph.get("epoch_count", 0)
    sd._updater_state = updater_state
    return sd


def _var_dicts(sd) -> list:
    return [
        {"name": v.name, "var_type": v.var_type,
         "shape": list(v.shape) if v.shape is not None else None,
         "dtype": v.dtype, "producer": v.producer,
         "output_index": v.output_index}
        for v in sd.variables.values()
    ]


def _op_dicts(sd) -> list:
    out = []
    for o in sd.ops.values():
        d = {"name": o.name, "op_name": o.op_name,
             "inputs": list(o.inputs), "outputs": list(o.outputs),
             "attrs": _jsonable_attrs(o.attrs)}
        if o.subgraphs:
            d["subgraphs"] = o.subgraphs
        out.append(d)
    return out


def subgraph_dict(child, out_names: list, single: bool) -> dict:
    """JSON-able form of a traced control-flow child graph (the role of the
    reference's FlatBuffers control-flow frames). Arrays (constants created
    inside the body, e.g. the ``2.0`` in ``lambda v: v * 2.0``) are inlined
    as nested lists — they are scalars/small by construction. Nested
    control flow recurses through ``_op_dicts``' subgraphs field."""
    return {
        "variables": _var_dicts(child),
        "ops": _op_dicts(child),
        "arrays": {k: {"data": np.asarray(v).tolist(),
                       "dtype": str(np.asarray(v).dtype)}
                   for k, v in child.arrays.items()},
        "outputs": list(out_names),
        "single": bool(single),
    }


def callable_from_subgraph(d: dict):
    """Rebuild the lax-body callable from its serialized child graph."""
    from deeplearning4j_tpu.samediff.core import (OpNode, SameDiff, VarMeta,
                                                  subgraph_callable)

    child = SameDiff()
    for v in d["variables"]:
        child.variables[v["name"]] = VarMeta(
            v["name"], v["var_type"],
            tuple(v["shape"]) if v["shape"] is not None else None,
            v["dtype"], v.get("producer"), v.get("output_index", 0))
    for o in d["ops"]:
        subgraphs = o.get("subgraphs", {})
        child.ops[o["name"]] = OpNode(
            o["name"], o["op_name"], tuple(o["inputs"]),
            tuple(o["outputs"]), _restore_attrs(o["attrs"]),
            {k: callable_from_subgraph(sg) for k, sg in subgraphs.items()},
            subgraphs)
    child.arrays = {k: jnp.asarray(v["data"], dtype=v["dtype"])
                    for k, v in d["arrays"].items()}
    return subgraph_callable(child, list(d["outputs"]), bool(d["single"]))


def _jsonable_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, tuple):
            out[k] = {"__tuple__": [_jsonable_attrs({"v": x})["v"]
                                    for x in v]}
        elif isinstance(v, (str, int, float, bool, type(None), dict, list)):
            out[k] = v
        else:
            raise TypeError(f"attr {k}={v!r} not JSON-serializable")
    return out


def _restore_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__tuple__" in v:
            out[k] = tuple(
                _restore_attrs({"v": x})["v"] for x in v["__tuple__"])
        else:
            out[k] = v
    return out


def _npz_bytes(arrs: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrs)
    return buf.getvalue()
