"""ctypes bindings for the native host-runtime library (``native/src``).

Reference parity: the JVM reference reaches its C++ runtime through JavaCPP
presets over the libnd4j C ABI (SURVEY.md §2.1); here the host-side kernels
(gradient codecs, CSV ETL, ubyte conversion, batch gather) live in
``libdl4j_native.so`` reached through ctypes — no JNI-style per-op overhead
matters since these are coarse host calls.

The library is compiled on first use with the baked-in g++ (``-O3 -fopenmp``)
and cached next to the source. Everything degrades to numpy fallbacks when
compilation is unavailable (``DL4J_TPU_DISABLE_NATIVE=1`` forces that).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_ABI_VERSION = 2  # must match dl4j_native_version() in dl4j_native.cpp
_SRC = Path(__file__).resolve().parents[2] / "native" / "src" / "dl4j_native.cpp"
# the ABI version is part of the artifact name: an incompatible cached .so
# from an older source tree can never be picked up by a newer wrapper
# (mtime staleness alone can miss restored/copied build dirs)
_OUT = (Path(__file__).resolve().parents[2] / "native" / "build"
        / f"libdl4j_native_v{_ABI_VERSION}.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    _OUT.parent.mkdir(parents=True, exist_ok=True)
    cmd = ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", "-std=c++17",
           str(_SRC), "-o", str(_OUT)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, i32, f32 = ctypes.c_int64, ctypes.c_int32, ctypes.c_float
    P = ctypes.POINTER
    lib.dl4j_encode_threshold.restype = i64
    lib.dl4j_encode_threshold.argtypes = [P(f32), i64, f32, P(i32), i64]
    lib.dl4j_decode_threshold.restype = None
    lib.dl4j_decode_threshold.argtypes = [P(i32), i64, f32, P(f32)]
    lib.dl4j_encode_bitmap.restype = i64
    lib.dl4j_encode_bitmap.argtypes = [P(f32), i64, f32,
                                       P(ctypes.c_uint64)]
    lib.dl4j_decode_bitmap.restype = None
    lib.dl4j_decode_bitmap.argtypes = [P(ctypes.c_uint64), i64, f32, P(f32)]
    lib.dl4j_csv_dims.restype = i64
    lib.dl4j_csv_dims.argtypes = [ctypes.c_char_p, i64, ctypes.c_char, i64,
                                  P(i64), P(i64)]
    lib.dl4j_parse_csv.restype = i64
    lib.dl4j_parse_csv.argtypes = [ctypes.c_char_p, i64, ctypes.c_char, i64,
                                   P(f32), i64, i64]
    lib.dl4j_u8_to_f32.restype = None
    lib.dl4j_u8_to_f32.argtypes = [P(ctypes.c_uint8), i64, f32, f32, P(f32)]
    lib.dl4j_gather_rows.restype = None
    lib.dl4j_gather_rows.argtypes = [ctypes.c_char_p, P(i64), i64, i64,
                                     ctypes.c_char_p]
    lib.dl4j_w2v_pairs.restype = i64
    lib.dl4j_w2v_pairs.argtypes = [P(i32), P(i64), i64, i64,
                                   P(ctypes.c_uint64), P(i32), i64]
    lib.dl4j_native_version.restype = ctypes.c_int
    lib.dl4j_native_threads.restype = ctypes.c_int
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first call; None if
    unavailable or disabled."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried or os.environ.get("DL4J_TPU_DISABLE_NATIVE") == "1":
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            stale = (not _OUT.exists()
                     or _OUT.stat().st_mtime < _SRC.stat().st_mtime)
            if stale and not _build():
                return None
            lib = _bind(ctypes.CDLL(str(_OUT)))
            if lib.dl4j_native_version() != _ABI_VERSION:
                if stale:
                    # we JUST built from current source and it still
                    # mismatches: wrapper/source version skew — a rebuild
                    # cannot help, fail fast (cached via _tried)
                    return None
                # old artifact under the right filename: delete and
                # rebuild ONCE from current source
                _OUT.unlink(missing_ok=True)
                if not _build():
                    return None
                lib = _bind(ctypes.CDLL(str(_OUT)))
                if lib.dl4j_native_version() != _ABI_VERSION:
                    return None
            _lib = lib
        except Exception:
            _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


# ---------------------------------------------------------------------------
# Host-side codec (numpy). The on-device jax codec lives in
# parallel/compression.py; this one serves host messaging/checkpoint
# compression (reference: EncodingHandler on the Java side).
# ---------------------------------------------------------------------------

def encode_threshold(g: np.ndarray, tau: float) -> np.ndarray:
    """-> int32 array of signed 1-based indices (+i: +tau flip, -i: -tau)."""
    g = np.ascontiguousarray(g, np.float32).ravel()
    lib = get_lib()
    if lib is None:
        pos = np.flatnonzero(g >= tau) + 1
        neg = -(np.flatnonzero(g <= -tau) + 1)
        enc = np.concatenate([pos, neg]).astype(np.int32)
        order = np.argsort(np.abs(enc), kind="stable")
        return enc[order]
    cap = max(int(g.size), 16)
    out = np.empty(cap, np.int32)
    cnt = lib.dl4j_encode_threshold(
        _fptr(g), g.size, tau, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int32)), cap)
    return out[:cnt].copy()


def decode_threshold(enc: np.ndarray, tau: float, n: int,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    """Accumulate ±tau flips into ``out`` (allocated zero if None)."""
    if out is None:
        out = np.zeros(n, np.float32)
    enc = np.ascontiguousarray(enc, np.int32)
    if enc.size:
        amax = int(np.abs(enc).max())
        if amax > n:
            raise ValueError(
                f"corrupt threshold message: index magnitude {amax} outside "
                f"[1, {n}] (truncated or mis-framed payload?)")
        nzero = int((enc == 0).sum())
        if nzero:
            raise ValueError(
                f"corrupt threshold message: {nzero} zero entries "
                f"(indices are signed and 1-based; 0 is not a valid code)")
    lib = get_lib()
    if lib is None:
        idx = np.abs(enc) - 1
        np.add.at(out, idx, np.where(enc > 0, tau, -tau).astype(np.float32))
        return out
    lib.dl4j_decode_threshold(
        enc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), enc.size, tau,
        _fptr(out))
    return out


def encode_bitmap(g: np.ndarray, tau: float) -> tuple[np.ndarray, int]:
    """-> (uint64 words with 2 bits/elem, nnz)."""
    g = np.ascontiguousarray(g, np.float32).ravel()
    words = np.zeros((g.size + 31) // 32, np.uint64)
    lib = get_lib()
    if lib is None:
        nnz = 0
        for i, v in enumerate(g):
            if v >= tau:
                words[i // 32] |= np.uint64(1) << np.uint64((i % 32) * 2)
                nnz += 1
            elif v <= -tau:
                words[i // 32] |= np.uint64(2) << np.uint64((i % 32) * 2)
                nnz += 1
        return words, nnz
    nnz = lib.dl4j_encode_bitmap(
        _fptr(g), g.size, tau,
        words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return words, int(nnz)


def decode_bitmap(words: np.ndarray, tau: float, n: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
    if out is None:
        out = np.zeros(n, np.float32)
    words = np.ascontiguousarray(words, np.uint64)
    if n > words.size * 32:
        raise ValueError(f"bitmap of {words.size} words covers "
                         f"{words.size * 32} elements < n={n}")
    lib = get_lib()
    if lib is None:
        for i in range(n):
            s = (int(words[i // 32]) >> ((i % 32) * 2)) & 3
            if s == 1:
                out[i] += tau
            elif s == 2:
                out[i] -= tau
        return out
    lib.dl4j_decode_bitmap(
        words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n, tau,
        _fptr(out))
    return out


# ---------------------------------------------------------------------------
# ETL fast paths
# ---------------------------------------------------------------------------

def parse_numeric_csv(text: bytes | str, delimiter: str = ",",
                      skip_lines: int = 0) -> np.ndarray:
    """Parse an all-numeric CSV buffer to a float32 matrix."""
    if isinstance(text, str):
        text = text.encode()
    lib = get_lib()
    if lib is None:
        rows = [r.split(delimiter) for r in text.decode().splitlines()
                if r.strip()][skip_lines:]
        if not rows:
            return np.zeros((0, 0), np.float32)
        return np.asarray([[float(c) for c in r] for r in rows], np.float32)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    d = ctypes.c_char(delimiter.encode())
    lib.dl4j_csv_dims(text, len(text), d, skip_lines,
                      ctypes.byref(rows), ctypes.byref(cols))
    out = np.empty((rows.value, cols.value), np.float32)
    errs = lib.dl4j_parse_csv(text, len(text), d, skip_lines, _fptr(out),
                              rows.value, cols.value)
    if errs:
        raise ValueError(f"{errs} non-numeric cells in CSV "
                         f"(use CSVRecordReader + TransformProcess for "
                         f"mixed-type data)")
    return out


def u8_to_f32(src: np.ndarray, scale: float = 1.0 / 255.0,
              shift: float = 0.0) -> np.ndarray:
    """ubyte image buffer -> float32 (NativeImageLoader's normalize role)."""
    src = np.ascontiguousarray(src, np.uint8)
    lib = get_lib()
    if lib is None:
        return src.astype(np.float32) * scale + shift
    dst = np.empty(src.shape, np.float32)
    lib.dl4j_u8_to_f32(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size,
        scale, shift, _fptr(dst))
    return dst


def gather_rows(src: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Shuffled minibatch assembly: ``src[indices]`` with OpenMP memcpy.
    Non-contiguous sources fall back to numpy fancy-indexing rather than
    paying a full-array copy per batch."""
    src = np.asarray(src)
    idx = np.ascontiguousarray(indices, np.int64)
    n = src.shape[0] if src.ndim else 0
    # numpy fancy-index semantics for BOTH paths: negatives wrap, OOB raises
    if idx.size and ((idx < -n).any() or (idx >= n).any()):
        bad = idx[(idx < -n) | (idx >= n)][0]
        raise IndexError(f"index {bad} out of bounds for axis 0 with "
                         f"size {n}")
    idx = np.where(idx < 0, idx + n, idx)
    lib = get_lib()
    if (lib is None or src.ndim == 0
            or not src.flags["C_CONTIGUOUS"]):
        return src[idx]
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    dst = np.empty((idx.size,) + src.shape[1:], src.dtype)
    lib.dl4j_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), idx.size,
        row_bytes, dst.ctypes.data_as(ctypes.c_char_p))
    return dst


_XORSHIFT_INIT = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


def _xorshift64_stream(seed: int):
    """The SAME xorshift64 stream the C side uses — keeps native and
    fallback paths bit-identical for a given seed."""
    st = (seed or _XORSHIFT_INIT) & _MASK64
    while True:
        st = (st ^ (st << 13)) & _MASK64
        st ^= st >> 7
        st = (st ^ (st << 17)) & _MASK64
        yield st


# chunk bound for the worst-case pair buffer: tokens*2*window int32 pairs
_W2V_CHUNK_TOKENS = 1 << 20


def w2v_pairs(sentences, window: int, seed: int = 1):
    """Skip-gram (center, context) pairs with word2vec.c dynamic windows
    (reference: the nd4j SkipGram native op's pair walk). ``sentences``:
    list of int32 arrays of token indices. Returns int32 [n, 2]. The
    numpy fallback replays the identical RNG stream, so results are
    bit-equal with or without the native lib."""
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    sents = [np.ascontiguousarray(s, np.int32) for s in sentences if len(s)]
    lib = get_lib()
    if lib is None:
        rng = _xorshift64_stream(int(seed))
        pairs = []
        for sent in sents:
            n = len(sent)
            if n < 2:
                # the C walk still consumes no RNG for n<2 sentences
                continue
            for i in range(n):
                b = 1 + (next(rng) % window)
                lo, hi = max(0, i - b), min(n, i + b + 1)
                for j in range(lo, hi):
                    if j != i:
                        pairs.append((sent[i], sent[j]))
        return (np.asarray(pairs, np.int32) if pairs
                else np.zeros((0, 2), np.int32))
    # chunk sentences so the worst-case buffer stays bounded (~8MB*window
    # per chunk instead of corpus-sized)
    chunks = []
    cur, cur_tokens = [], 0
    for sent in sents:
        cur.append(sent)
        cur_tokens += len(sent)
        if cur_tokens >= _W2V_CHUNK_TOKENS:
            chunks.append(cur)
            cur, cur_tokens = [], 0
    if cur:
        chunks.append(cur)
    results = []
    # the C walk reads its RNG state from io_state and writes the final
    # state back, so chunking continues ONE stream with no host-side
    # replay. Seed 0 maps to the same init constant as the fallback, and
    # xorshift64 never reaches state 0 from nonzero — bit-parity holds
    # for every seed.
    io_state = ctypes.c_uint64((int(seed) or _XORSHIFT_INIT) & _MASK64)
    for chunk in chunks:
        tokens = np.concatenate(chunk)
        offsets = np.zeros(len(chunk) + 1, np.int64)
        np.cumsum([len(s) for s in chunk], out=offsets[1:])
        cap = max(int(tokens.size) * 2 * int(window), 16)
        out = np.empty((cap, 2), np.int32)
        cnt = lib.dl4j_w2v_pairs(
            tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(chunk), int(window), ctypes.byref(io_state),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
        if cnt < 0:
            raise RuntimeError(f"native w2v_pairs failed: {cnt}")
        results.append(out[:cnt].copy())
    return (np.concatenate(results) if results
            else np.zeros((0, 2), np.int32))


def native_threads() -> int:
    lib = get_lib()
    return int(lib.dl4j_native_threads()) if lib is not None else 0
