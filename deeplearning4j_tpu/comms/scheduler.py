"""Collective scheduler: one planner for bucket layout, issue order, and
collective choice.

Three bucketed primitives (``bucketed_psum``, ``bucketed_psum_scatter``,
``bucketed_all_gather``) used to hand-coordinate bucket partitioning and
``optimization_barrier`` issue chains privately. This module is the single
owner of all three decisions:

- **layout** — :func:`bucket_partition`: reverse-topological,
  size-targeted buckets (the last layers' gradients — the first ones
  backprop produces — land in bucket 0), shared by every intent;
- **order** — one ``optimization_barrier`` chain ties bucket k+1's
  operands to bucket k's result, so XLA cannot merge or reorder the
  collectives and bucket k's exchange overlaps the backward pass still
  producing bucket k+1 (arXiv:1905.04035: collective performance during
  gradient accumulation dominates DP scaling; arXiv:2112.01075:
  decomposing one transfer into scheduled chunks);
- **choice** — per bucket:

  =============== ==========================================================
  ``variadic``    one variadic collective over the bucket's leaves (the
                  default; a single-bucket plan is the fused
                  single-exchange baseline — the ``:b0`` shape)
  ``densify``     the bucket's many small same-dtype leaves are flattened
                  into ONE dense buffer for a single ``psum`` and split
                  back after — densified accumulation (arXiv:1905.04035:
                  per-leaf sparse exchange loses to one dense buffer when
                  leaves are tiny); ``psum`` is elementwise, so the result
                  is bitwise the per-leaf exchange
  ``all_gather``  native ``lax.all_gather`` — chosen when
                  :data:`NATIVE_ALL_GATHER` shows a vma-capable jax whose
                  type system can express the gathered output's
                  replication (probe-gated like
                  ``mesh.EFFICIENT_PSUM_TRANSPOSE``); moves the ring
                  all-gather's (n-1)/n payload
  ``masked_psum`` the pre-vma fallback: each shard deposits its slice
                  into a zeros vector and a ``psum`` reassembles —
                  bitwise-exact and statically-replicated for check_rep
                  jax (this container's 0.4.37), at ~2x native all-gather
                  bandwidth on the wire
  =============== ==========================================================

Every plan is content-addressed: :attr:`CollectivePlan.digest` hashes the
(intent, layout, choices, leaf sizes/dtypes) and joins the AOT-cache step
key (``plan:<digest>`` tokens), so a changed layout or choice can never
silently reuse a stale executable — and the PRG205 collective audit looks
the digest up via :func:`lookup_plan` to verify the compiled module's
collective sequence matches what the plan promised.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from typing import Dict, Optional, Tuple

import numpy as np

# this module sits BELOW parallel/ in the import graph (parallel.
# compression re-exports from here), so it cannot import parallel.mesh
# at module scope; the axis-name constant and the capability probe are
# restated with their authorities cross-referenced
DATA_AXIS = "data"   # parallel.mesh.DATA_AXIS


def _probe_vma() -> bool:
    import jax

    # the SAME feature probe as parallel.mesh.EFFICIENT_PSUM_TRANSPOSE
    # (jax.typeof + lax.pcast = the vma type system), restated here to
    # keep comms importable without the parallel package
    return hasattr(jax, "typeof") and hasattr(jax.lax, "pcast")


# capability probe: a native lax.all_gather's output is replicated in
# VALUE but only the vma type system can SAY so — pre-vma check_rep
# shard_map rejects out_specs claiming replication of a gathered result,
# so the masked-psum fallback stays active on this container's 0.4.37.
# Tests exercise the native branch through this seam (monkeypatch +
# varying out_specs).
NATIVE_ALL_GATHER = _probe_vma()

INTENTS = ("all_reduce", "reduce_scatter", "all_gather")

# densified accumulation thresholds: a bucket of >= MIN_LEAVES leaves,
# every one at most MAX_LEAF_BYTES and all one dtype, exchanges as one
# dense concatenated buffer instead of a variadic per-leaf collective
# (launch overhead amortizes; psum is elementwise so numerics are
# bitwise unchanged)
DENSIFY_MIN_LEAVES = 8
DENSIFY_MAX_LEAF_BYTES = 16 << 10   # 16 KiB


# --------------------------------------------------------------------------
# layout (the single shared implementation — parallel.compression and
# sharding.zero re-export / delegate here)
# --------------------------------------------------------------------------

def bucket_partition(sizes, bucket_bytes: int):
    """Partition leaf indices into size-targeted buckets, walking the
    leaves in REVERSE order (reverse-topological: backprop computes the
    deepest layers' grads first). Returns a list of index lists; every
    index appears exactly once. A leaf larger than ``bucket_bytes`` gets
    its own bucket."""
    buckets, cur, acc = [], [], 0
    for i in reversed(range(len(sizes))):
        if cur and acc + sizes[i] > bucket_bytes:
            buckets.append(cur)
            cur, acc = [], 0
        cur.append(i)
        acc += sizes[i]
    if cur:
        buckets.append(cur)
    return buckets


def bucket_layout(tree, bucket_bytes=None):
    """Host-side preview of an all-reduce schedule for a pytree of
    (possibly abstract) arrays: per-bucket payload bytes, in issue order.
    ``bucket_bytes=None`` returns one bucket holding the whole tree."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return []
    sizes = [l.size * np.dtype(l.dtype).itemsize for l in leaves]
    if bucket_bytes is None or len(leaves) <= 1:
        return [sum(sizes)]
    return [sum(sizes[i] for i in bucket)
            for bucket in bucket_partition(sizes, int(bucket_bytes))]


# --------------------------------------------------------------------------
# plans
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CollectivePlan:
    """One resolved exchange schedule: what moves, in which buckets, in
    which order, through which collective. Immutable and content-
    addressed — ``digest`` is the AOT-cache key component."""

    intent: str
    axis: str
    bucket_bytes: Optional[int]
    buckets: Tuple[Tuple[int, ...], ...]   # leaf indices, issue order
    choices: Tuple[str, ...]               # one per bucket
    leaf_sizes: Tuple[int, ...]            # payload bytes per leaf
    leaf_dtypes: Tuple[str, ...]
    digest: str = ""

    def bytes_moved(self) -> int:
        """Logical per-shard payload of one exchange (the masked-psum
        gather fallback costs ~2x this on the wire — the counters record
        the logical payload either way)."""
        return int(sum(self.leaf_sizes))

    def launches(self) -> int:
        """Collectives issued per exchange (1 = fused single exchange)."""
        return len(self.buckets)

    def bucket_bytes_list(self):
        return [sum(self.leaf_sizes[i] for i in b) for b in self.buckets]

    def choice_summary(self) -> str:
        return "+".join(sorted(set(self.choices)))

    def key_token(self) -> str:
        """The AOT-cache step-key component: ``plan:<digest>``."""
        return f"plan:{self.digest}"

    def summary(self) -> dict:
        """JSON-ready record (PRG205 audit / UI surfaces)."""
        return {
            "intent": self.intent,
            "axis": self.axis,
            "digest": self.digest,
            "buckets": self.launches(),
            "choices": list(self.choices),
            "bytes": self.bytes_moved(),
            "bucket_bytes": [int(b) for b in self.bucket_bytes_list()],
        }


def _leaf_meta(leaves, intent, full_sizes):
    """-> (payload bytes per leaf, dtype strs). For ``all_gather`` the
    payload is the GATHERED vector (``full_sizes``), matching the layout
    the masked-psum contributions actually bucket on."""
    dtypes = [str(np.dtype(l.dtype)) for l in leaves]
    if intent == "all_gather":
        if full_sizes is None:
            raise ValueError("all_gather plans need full_sizes")
        sizes = [int(f) * np.dtype(l.dtype).itemsize
                 for f, l in zip(full_sizes, leaves)]
    else:
        sizes = [int(l.size) * np.dtype(l.dtype).itemsize for l in leaves]
    return sizes, dtypes


def _choose(intent, idxs, sizes, dtypes):
    """Per-bucket collective choice (see the module table)."""
    if intent == "all_reduce":
        if (len(idxs) >= DENSIFY_MIN_LEAVES
                and max(sizes[i] for i in idxs) <= DENSIFY_MAX_LEAF_BYTES
                and len({dtypes[i] for i in idxs}) == 1):
            return "densify"
        return "variadic"
    if intent == "reduce_scatter":
        # densification would re-cut the scattered slices (the scatter
        # of a concatenated buffer hands each shard a block of the
        # CONCATENATION, not per-leaf slices) — layout-changing, so
        # reduce-scatter always exchanges per-leaf
        return "variadic"
    if intent == "all_gather":
        return "all_gather" if NATIVE_ALL_GATHER else "masked_psum"
    raise ValueError(f"unknown intent {intent!r}; expected one of "
                     f"{INTENTS}")


class _Stats:
    def __init__(self):
        self.plans_built = 0
        self.plan_cache_hits = 0


_STATS = _Stats()
_PLAN_CACHE: Dict[tuple, CollectivePlan] = {}
_BY_DIGEST: Dict[str, CollectivePlan] = {}
_LOCK = threading.Lock()


def stats() -> dict:
    """Process-global planner counters (bench_collectives.py record)."""
    with _LOCK:
        return {"plans_built": _STATS.plans_built,
                "plan_cache_hits": _STATS.plan_cache_hits,
                "registered": len(_BY_DIGEST)}


def lookup_plan(digest: str) -> Optional[CollectivePlan]:
    """Digest -> plan, for consumers holding only the AOT-cache key
    (the PRG205 collective audit). None when this process never built
    the plan (e.g. a key minted by an earlier run)."""
    with _LOCK:
        return _BY_DIGEST.get(digest)


def reset() -> None:
    """Test hook: drop cached plans and counters."""
    with _LOCK:
        _PLAN_CACHE.clear()
        _BY_DIGEST.clear()
        _STATS.plans_built = 0
        _STATS.plan_cache_hits = 0


class CollectiveScheduler:
    """The planner: takes a gradient/param pytree plus an intent and
    emits a :class:`CollectivePlan`. Stateless apart from the process-
    global plan cache — two schedulers over the same tree/intent emit
    the identical (same-digest) plan, on any process."""

    def __init__(self, axis_name: str = DATA_AXIS,
                 bucket_bytes: Optional[int] = None):
        self.axis_name = axis_name
        self.bucket_bytes = (None if bucket_bytes is None
                             else int(bucket_bytes))

    def plan(self, tree, intent: str,
             full_sizes=None) -> CollectivePlan:
        """Resolve the exchange schedule for ``tree`` (arrays, avals or
        ShapeDtypeStructs — only ``.size``/``.dtype`` are read)."""
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
        sizes, dtypes = _leaf_meta(leaves, intent, full_sizes)
        key = (intent, self.axis_name, self.bucket_bytes, tuple(sizes),
               tuple(dtypes),
               NATIVE_ALL_GATHER if intent == "all_gather" else None)
        with _LOCK:
            cached = _PLAN_CACHE.get(key)
            if cached is not None:
                _STATS.plan_cache_hits += 1
                return cached
        if not leaves:
            buckets = ()
        elif self.bucket_bytes is None or len(leaves) <= 1:
            buckets = (tuple(range(len(leaves))),)
        else:
            buckets = tuple(
                tuple(b) for b in bucket_partition(sizes,
                                                   self.bucket_bytes))
        choices = tuple(_choose(intent, b, sizes, dtypes)
                        for b in buckets)
        digest = hashlib.sha1(repr(
            (intent, self.axis_name, buckets, choices, tuple(sizes),
             tuple(dtypes))).encode()).hexdigest()[:16]
        plan = CollectivePlan(
            intent=intent, axis=self.axis_name,
            bucket_bytes=self.bucket_bytes, buckets=buckets,
            choices=choices, leaf_sizes=tuple(sizes),
            leaf_dtypes=tuple(dtypes), digest=digest)
        with _LOCK:
            # re-check under the lock: a concurrent planner of the same
            # layout may have won the build race — one logical plan must
            # count (and record its telemetry) exactly once
            raced = _PLAN_CACHE.get(key)
            if raced is not None:
                _STATS.plan_cache_hits += 1
                return raced
            _PLAN_CACHE[key] = plan
            _BY_DIGEST[digest] = plan
            _STATS.plans_built += 1
        _record_plan(plan)
        return plan

    # --- execution (traced: runs inside jitted steps) ----------------------
    def execute(self, plan: CollectivePlan, tree, index=None,
                full_sizes=None):
        """Run one exchange under ``plan``. ``all_gather`` plans take the
        per-shard slice tree plus ``index`` (this shard's ``axis_index``,
        masked-psum fallback only) and ``full_sizes`` (per-leaf gathered
        lengths)."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree
        if plan.intent == "all_gather":
            leaves = _gather_operands(plan, leaves, index, full_sizes)
        out = [None] * len(leaves)
        pin = None
        for bucket, choice in zip(plan.buckets, plan.choices):
            vals = tuple(leaves[i] for i in bucket)
            if pin is not None:
                # order pin: this bucket's collective is scheduled after
                # the previous bucket's — a pure scheduling edge, no math
                pinned = jax.lax.optimization_barrier(vals + (pin,))
                vals = tuple(pinned[:-1])
            red = _run_bucket(plan, choice, vals)
            pin = red[0]
            for i, r in zip(bucket, red):
                out[i] = r
        return jax.tree_util.tree_unflatten(treedef, out)


def _gather_operands(plan, slices, index, full_sizes):
    """The all-gather operand transform. Masked-psum fallback: each
    shard deposits its slice at ``[index*m, (index+1)*m)`` of a zeros
    vector — adding zeros is float-exact AND the psum output is
    statically replicated for check_rep jax. Native path: the raw
    slices feed ``lax.all_gather`` directly."""
    import jax
    import jax.numpy as jnp

    if full_sizes is None:
        raise ValueError("all_gather execution needs full_sizes")
    if all(c == "all_gather" for c in plan.choices):
        return list(slices)
    if index is None:
        raise ValueError("masked-psum all_gather needs the shard index")
    out = []
    for sl, full in zip(slices, full_sizes):
        m = sl.shape[0]
        out.append(jax.lax.dynamic_update_slice(
            jnp.zeros((int(full),), sl.dtype), sl, (index * m,)))
    return out


def _run_bucket(plan, choice, vals):
    import jax
    import jax.numpy as jnp

    axis = plan.axis
    if choice == "variadic":
        if plan.intent == "reduce_scatter":
            return jax.lax.psum_scatter(vals, axis, scatter_dimension=0,
                                        tiled=True)
        return jax.lax.psum(vals, axis)
    if choice == "densify":
        # one dense fused buffer: flatten + concat, a single psum, split
        # back — elementwise reduction, bitwise the per-leaf exchange
        shapes = [v.shape for v in vals]
        counts = [int(np.prod(s)) if s else 1 for s in shapes]
        cat = jnp.concatenate([jnp.reshape(v, (-1,)) for v in vals])
        red = jax.lax.psum(cat, axis)
        out, off = [], 0
        for shape, n in zip(shapes, counts):
            out.append(jnp.reshape(
                jax.lax.slice_in_dim(red, off, off + n), shape))
            off += n
        return tuple(out)
    if choice == "masked_psum":
        # operands are the position-masked full-size contributions
        return jax.lax.psum(vals, axis)
    if choice == "all_gather":
        return tuple(jax.lax.all_gather(v, axis, axis=0, tiled=True)
                     for v in vals)
    raise ValueError(f"unknown collective choice {choice!r}")


# --------------------------------------------------------------------------
# module-level conveniences (the thin-wrapper surface compression uses)
# --------------------------------------------------------------------------

def plan_for(tree, intent: str, axis_name: str = DATA_AXIS,
             bucket_bytes=None, full_sizes=None) -> CollectivePlan:
    """Build (or fetch) the plan for one exchange without running it —
    key digests for ``aot_cache.wrap`` callsites, layouts for telemetry."""
    return CollectiveScheduler(axis_name, bucket_bytes).plan(
        tree, intent, full_sizes=full_sizes)


def exchange(tree, intent: str, axis_name: str = DATA_AXIS,
             bucket_bytes=None, index=None, full_sizes=None):
    """Plan + execute one exchange (the ``bucketed_*`` primitives'
    engine). Traced: call from inside jitted/shard_mapped steps."""
    sched = CollectiveScheduler(axis_name, bucket_bytes)
    plan = sched.plan(tree, intent, full_sizes=full_sizes)
    return sched.execute(plan, tree, index=index, full_sizes=full_sizes)


def _record_plan(plan: CollectivePlan) -> None:
    """Telemetry on each fresh plan: the per-(intent, choice) counter and
    the bytes/launches gauges feeding the UI System tab collective panel.
    Control-plane cadence (once per unique plan per process — plans are
    resolved at trace time, never per step), so recording is
    unconditional like the analysis/resilience events."""
    try:
        from deeplearning4j_tpu import telemetry

        telemetry.record_collective_plan(
            plan.intent, plan.choice_summary(), plan.bytes_moved(),
            plan.launches())
    except Exception:
        pass  # observability must never break a trace
