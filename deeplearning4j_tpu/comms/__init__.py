"""Unified collective communication layer (ROADMAP item 4).

Two pillars:

- :mod:`deeplearning4j_tpu.comms.scheduler` — the
  :class:`CollectiveScheduler`: ONE planner owning bucket layout, issue
  order, and per-bucket collective choice for every explicit exchange in
  the tree (``parallel.compression``'s ``bucketed_psum`` /
  ``bucketed_psum_scatter`` / ``bucketed_all_gather`` are thin wrappers
  over scheduler plans). Every plan carries a content digest that joins
  the AOT step-executable cache key, so a changed layout can never
  silently reuse a stale executable.
- :mod:`deeplearning4j_tpu.comms.reshard` — portable cross-mesh
  resharding (arXiv:2112.01075 shape: per-device slice intersection →
  minimal exchange → reassemble) for live-state hand-offs: restore
  across mesh shapes without the host gather/scatter round-trip, and
  ``publish_to_engine`` for zero-copy train→serve publishing.

docs/collectives.md has the guided tour.
"""

from deeplearning4j_tpu.comms.scheduler import (  # noqa: F401
    CollectivePlan,
    CollectiveScheduler,
    bucket_layout,
    bucket_partition,
    exchange,
    lookup_plan,
    plan_for,
    stats,
)
from deeplearning4j_tpu.comms.reshard import (  # noqa: F401
    commit_compiled,
    publish_to_engine,
    recut_flat,
    reshard,
    reshard_flat,
    reshard_training_state,
)
