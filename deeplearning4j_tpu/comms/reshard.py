"""Portable cross-mesh resharding (arXiv:2112.01075).

``reshard(tree, sharding)`` moves committed device arrays between
placements — different specs, different mesh shapes, different device
counts — by the paper's three-phase shape: **per-device slice
intersection** (each target shard's index box is intersected with the
source shards that hold it), **minimal exchange** (only the intersecting
bytes move, and a block already resident on its target device moves
nothing), **reassemble** (``jax.make_array_from_single_device_arrays``
stitches the blocks under the target sharding). No host round-trip: the
data path is device-to-device.

Consumers:

- **restore across mesh shapes** — ``sharding.zero.ZeroSpec.scatter``
  routes device-resident trees (a restored checkpoint's arrays, a live
  wrapper's state) through :func:`reshard_flat` instead of the
  numpy gather/scatter round-trip, and
  :func:`reshard_training_state` hands a live wrapper's full training
  state (params/state/opt, ZeRO slices included) to a wrapper on a
  DIFFERENT mesh bitwise-identically to the host route;
- **zero-copy train→serve** — :func:`publish_to_engine` reshards a live
  wrapper's params onto the serving engine's placement and swaps them in
  between launches, so a training loop publishes fresh weights without
  gathering to host.

Multi-process route: the eager slice intersection works over
*addressable* shards only, so process-SPANNING arrays route through a
COMPILED identity with the target sharding pinned
(:func:`commit_compiled`) — XLA emits the minimal cross-host exchange
over ICI/DCN, which is exactly the portable-collective discipline of
the paper applied by the compiler instead of by hand. Host values stage
through ``jax.make_array_from_callback`` (each process materializes
only its own addressable index boxes). ``jax.device_put`` remains the
final portability valve. Flat ZeRO layouts whose padded lengths differ
re-cut through :func:`recut_flat` (the pod checkpoint
restore-across-pod-shapes path — ``resilience.pod``).
"""

from __future__ import annotations

import numpy as np


def _bounds(index, shape):
    """Normalize a shard's index (tuple of slices, possibly ``slice(None)``)
    to per-dim ``(start, stop)`` pairs."""
    out = []
    for d, dim in enumerate(shape):
        sl = index[d] if d < len(index) else slice(None)
        out.append((sl.start or 0, dim if sl.stop is None else sl.stop))
    return tuple(out)


def _assemble(pieces, d, ndim):
    """Stitch ``[(bounds, array)]`` blocks covering one box back into a
    single array, concatenating dimension by dimension."""
    import jax.numpy as jnp

    if len(pieces) == 1:
        return pieces[0][1]
    if d >= ndim:
        raise ValueError("overlapping reshard blocks")  # replicated dup
    starts = sorted({b[d][0] for b, _ in pieces})
    if len(starts) == 1:
        return _assemble(pieces, d + 1, ndim)
    runs = [_assemble([p for p in pieces if p[0][d][0] == st], d + 1, ndim)
            for st in starts]
    return jnp.concatenate(runs, axis=d)


def _sharding_token(sharding) -> str:
    """Short digest of a target placement for compiled-route cache keys:
    the ORDERED device identities + spec (+ mesh axis sizes when
    named). Two targets that differ in any of those — including the
    same axis sizes over a different or permuted device set — must
    never share an executable, or the cached program would commit the
    result under the wrong placement."""
    import hashlib

    mesh = getattr(sharding, "mesh", None)
    spec = getattr(sharding, "spec", None)
    if mesh is not None:
        axes = tuple((a, int(mesh.shape[a])) for a in mesh.axis_names)
        dev_ids = tuple(int(d.id) for d in mesh.devices.flat)
    else:
        axes = ()
        # device_set is unordered; sorting still distinguishes SETS
        # (the exotic-sharding valve — NamedSharding covers every
        # in-repo caller with the ordered mesh above)
        dev_ids = tuple(sorted(
            int(getattr(d, "id", -1))
            for d in getattr(sharding, "device_set", ()) or ()))
    payload = repr((dev_ids, str(spec), axes))
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


def commit_compiled(x, target):
    """The REAL multi-process route: recommit a committed global array
    under ``target`` through a compiled identity with ``out_shardings``
    pinned — XLA plans the cross-host exchange (collective-permute /
    all-gather as needed), each process executing only its addressable
    part. AOT-cached under the ``reshard_commit`` kind so repeated
    restores / train→serve hand-offs on a pod never re-lower.
    Non-donating: callers (``publish_to_engine``, restore paths) keep
    the source alive — and cross-placement per-device buffers could not
    alias anyway."""
    import jax

    from deeplearning4j_tpu.optimize import aot_cache

    step = aot_cache.wrap(
        jax.jit(lambda a: a, out_shardings=target),
        "reshard", f"reshard_commit:{_sharding_token(target)}")
    return step(x)


def _reshard_leaf(x, target):
    import jax

    if not isinstance(x, jax.Array):
        # host value: every process stages ONLY its own addressable
        # index boxes (device_put of a full host array is fine single-
        # process and wrong on a pod, where remote shards are not ours
        # to place)
        if jax.process_count() > 1:
            arr = np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, target, lambda idx: arr[idx])
        return jax.device_put(x, target)
    if x.sharding == target:
        return x
    if jax.process_count() > 1 \
            or not getattr(x, "is_fully_addressable", True):
        # process-spanning arrays: the eager intersection below can only
        # see addressable shards — route through the compiled exchange
        try:
            return commit_compiled(x, target)
        except Exception:
            return jax.device_put(x, target)  # portability valve
    try:
        return _intersect_exchange(x, target)
    except Exception:
        # portability valve: an exotic sharding/layout this pass cannot
        # decompose still reshard correctly through jax's own transfer
        return jax.device_put(x, target)


def _intersect_exchange(x, target):
    import jax

    shape = x.shape
    if not shape:  # scalars: one block, broadcast to every target device
        import jax.numpy as jnp

        # jnp.copy per target: device_put returns the INPUT object when
        # it already lives on the target device, and wrapping a source
        # shard's own buffer would let a later donation of the resharded
        # array delete the source (tensor blocks are slices — always
        # fresh buffers — so only scalars need this)
        s0 = x.addressable_shards[0].data
        per_dev = [jax.device_put(jnp.copy(s0), d)
                   for d in target.addressable_devices_indices_map(
                       shape)]
        return jax.make_array_from_single_device_arrays(
            shape, target, per_dev)
    # dedup replicated source shards by index box, preferring the copy
    # already on a device the target uses least exchange from
    srcs = {}
    for s in x.addressable_shards:
        srcs.setdefault(_bounds(s.index, shape), []).append(s)
    arrays = []
    for dev, tidx in target.addressable_devices_indices_map(shape).items():
        tb = _bounds(tidx, shape)
        pieces = []
        for sb, copies in srcs.items():
            inter = tuple((max(a, sa), min(b, sb_))
                          for (a, b), (sa, sb_) in zip(tb, sb))
            if any(lo >= hi for lo, hi in inter):
                continue
            src = next((c for c in copies if c.device == dev), copies[0])
            sa = [s[0] for s in _bounds(src.index, shape)]
            cut = tuple(slice(lo - a0, hi - a0)
                        for (lo, hi), a0 in zip(inter, sa))
            block = src.data[cut]
            if src.device != dev:
                block = jax.device_put(block, dev)  # the minimal exchange
            rel = tuple((lo - t0, hi - t0)
                        for (lo, hi), (t0, _) in zip(inter, tb))
            pieces.append((rel, block))
        if not pieces:
            raise ValueError("target shard not covered by source shards")
        arrays.append(_assemble(pieces, 0, len(shape)))
    return jax.make_array_from_single_device_arrays(shape, target, arrays)


def reshard(tree, sharding):
    """Recommit ``tree`` under ``sharding`` — a single ``Sharding``
    applied to every leaf, or a matching pytree of shardings — via
    slice-intersection exchange (host-free for single-process device
    trees; ``device_put`` otherwise)."""
    import jax
    from jax.sharding import Sharding

    if isinstance(sharding, Sharding):
        return jax.tree_util.tree_map(
            lambda x: _reshard_leaf(x, sharding), tree)
    return jax.tree_util.tree_map(
        lambda s, x: _reshard_leaf(x, s), sharding, tree,
        is_leaf=lambda v: isinstance(v, Sharding))


def reshard_flat(x, logical_size, target_padded, target_sharding):
    """Reshard one FLAT vector between ZeRO layouts whose padded lengths
    differ (shard counts n_src != n_dst pad the same logical payload to
    different totals). Source positions beyond the source padding — and
    target positions beyond ``logical_size`` not covered by the source —
    are zeros by the ZeroSpec contract, so the target pad tail is zero-
    filled on its own device and only ``[0, logical_size)`` exchanges."""
    import jax
    import jax.numpy as jnp

    if not isinstance(x, jax.Array):
        flat = np.zeros((int(target_padded),),
                        np.asarray(x).dtype if not hasattr(x, "dtype")
                        else np.dtype(x.dtype))
        src = np.asarray(x).reshape(-1)
        n = min(src.size, int(logical_size))
        flat[:n] = src[:n]
        if jax.process_count() > 1:
            # each pod host stages only its addressable slices
            return jax.make_array_from_callback(
                flat.shape, target_sharding, lambda idx: flat[idx])
        return jax.device_put(flat, target_sharding)
    if jax.process_count() > 1 \
            or not getattr(x, "is_fully_addressable", True):
        # process-spanning flat vector: compiled re-cut (XLA owns the
        # cross-host exchange) — same route the pod checkpoint restore
        # takes between pod shapes
        return recut_flat(x, logical_size, target_padded,
                          target_sharding)
    src_len = x.shape[0]
    if src_len == int(target_padded):
        return _reshard_leaf(x, target_sharding)
    arrays = []
    srcs = {}
    for s in x.addressable_shards:
        srcs.setdefault(_bounds(s.index, x.shape)[0], []).append(s)
    for dev, tidx in target_sharding.addressable_devices_indices_map(
            (int(target_padded),)).items():
        a, b = _bounds(tidx, (int(target_padded),))[0]
        pieces = []
        for (sa, sb), copies in sorted(srcs.items()):
            lo, hi = max(a, sa), min(b, sb, src_len)
            if lo >= hi:
                continue
            src = next((c for c in copies if c.device == dev), copies[0])
            block = src.data[lo - sa:hi - sa]
            if src.device != dev:
                block = jax.device_put(block, dev)
            pieces.append(block)
        covered = sum(int(p.shape[0]) for p in pieces)
        if covered < b - a:  # target pad tail beyond the source's length
            pieces.append(jax.device_put(
                jnp.zeros((b - a - covered,), x.dtype), dev))
        arrays.append(pieces[0] if len(pieces) == 1
                      else jax.numpy.concatenate(pieces))
    return jax.make_array_from_single_device_arrays(
        (int(target_padded),), target_sharding, arrays)


def recut_flat(x, logical_size, target_padded, target_sharding):
    """COMPILED re-cut of one flat vector between ZeRO/pod layouts whose
    padded lengths differ: keep ``[0, logical_size)``, zero-fill the
    target pad tail, and commit under ``target_sharding`` — XLA plans
    the exchange, so the route works across processes (each host
    executes its addressable part) exactly like :func:`commit_compiled`.
    This is the restore-across-pod-shapes path of the pod checkpoint
    layer (``resilience.pod``): shards saved by an n-host pod restore
    onto an m-host pod through this executable, bitwise the snapshot
    (pinned by test_pod). AOT-cached under the ``pod_recut`` kind.
    Non-donating by necessity: source and target layouts have
    different per-device buffer sizes, which XLA cannot alias — the
    one reshard family exempt from the PRG201 donation expectation
    (see analysis/program.py)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.optimize import aot_cache

    logical = int(logical_size)
    target_padded = int(target_padded)
    src_len = int(x.shape[0])
    keep = min(logical, src_len)

    def recut(a):
        a = a[:keep]
        if target_padded > keep:
            a = jnp.concatenate(
                [a, jnp.zeros((target_padded - keep,), a.dtype)])
        return a

    step = aot_cache.wrap(
        jax.jit(recut, out_shardings=target_sharding),
        "reshard",
        f"pod_recut:s{src_len}:l{logical}:t{target_padded}"
        f":{_sharding_token(target_sharding)}")
    return step(x)


# --------------------------------------------------------------------------
# live-state consumers
# --------------------------------------------------------------------------

def _replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def reshard_training_state(src, dst) -> None:
    """Hand a live :class:`~deeplearning4j_tpu.parallel.wrapper.
    ParallelWrapper`'s training state to ``dst`` — a wrapper on a
    possibly different mesh shape — device-to-device, replacing the host
    gather (``sync_model``) / re-scatter (``_setup``) round-trip.
    Bitwise: the values are recommitted, never recomputed (pinned by
    test_comms against the host route on the 8-device mesh).

    ``dst`` must wrap the same network configuration and use the exact
    SHARED_GRADIENTS family (plain/bucketed SPMD, ZeRO, or a partition-
    rules plan — the modes whose state is params/state/opt trees;
    AVERAGING replica stacks and threshold residuals don't transfer
    across worker counts)."""
    from deeplearning4j_tpu.parallel.wrapper import TrainingMode

    if src._params is None:
        raise ValueError("source wrapper has no staged training state "
                         "(fit or _setup first)")
    for w, role in ((src, "source"), (dst, "destination")):
        if (w.training_mode is not TrainingMode.SHARED_GRADIENTS
                or w.threshold_algorithm is not None or w.expert_parallel):
            raise ValueError(
                f"{role} wrapper must use the exact SHARED_GRADIENTS "
                f"family (AVERAGING replica stacks / threshold residuals "
                f"do not reshard across worker counts)")
    rep = _replicated(dst.mesh)
    # params go straight to the destination placement (plan shardings or
    # replicated) — one slice-intersection pass, never materializing the
    # full tree per-device as a replicated intermediate
    if dst._plan is not None:
        pspecs = dst._plan.param_specs(src.model.params)
        params = reshard(src._params, dst._plan.shardings(pspecs))
    else:
        params = reshard(src._params, rep)
    state = reshard(src._state, rep)
    # optimizer state: re-cut source ZeRO slices into the destination's
    # layout without materializing the dense tree on host
    if getattr(dst, "_zero", False):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.parallel.wrapper import DATA
        from deeplearning4j_tpu.sharding.zero import ZeroSpec

        dst._zero_pspec = ZeroSpec(src.model.params, dst.workers)
        dst._zero_ospec = ZeroSpec(src.model.opt_state, dst.workers)
        zsh = NamedSharding(dst.mesh, P(DATA))
        if getattr(src, "_zero", False):
            sleaves = jax.tree_util.tree_flatten(src._opt)[0]
            spec = dst._zero_ospec
            out = [reshard_flat(leaf, size, padded, zsh)
                   for leaf, size, padded in zip(
                       sleaves, spec.sizes, spec.padded_sizes)]
            opt = jax.tree_util.tree_unflatten(spec.treedef, out)
        else:
            opt = dst._zero_ospec.scatter(src._opt, dst.mesh, DATA)
    elif getattr(src, "_zero", False):
        # scattered flat slices -> full replicated tree, device-side
        import jax
        import jax.numpy as jnp

        spec = src._zero_ospec
        leaves = jax.tree_util.tree_flatten(src._opt)[0]
        full = [jnp.reshape(_reshard_leaf(l, rep)[:size], shape)
                for l, size, shape in zip(leaves, spec.sizes, spec.shapes)]
        opt = jax.tree_util.tree_unflatten(spec.treedef, full)
        if dst._plan is not None:
            opt = reshard(opt, dst._plan.shardings(
                dst._plan.opt_specs(src.model.params,
                                    src.model.opt_state)))
    elif dst._plan is not None:
        opt = reshard(src._opt, dst._plan.shardings(
            dst._plan.opt_specs(src.model.params, src.model.opt_state)))
    else:
        opt = reshard(src._opt, rep)
    # donation safety: a leaf whose placement already matched the target
    # came back as the SOURCE array object (reshard's identity
    # fast-path); the destination's train step donates its inputs, so
    # copy exactly those leaves to keep the source wrapper's live state
    # intact (cross-mesh hand-offs never hit this — every leaf recommits)
    import jax
    import jax.numpy as jnp

    src_ids = {id(l) for l in jax.tree_util.tree_leaves(
        (src._params, src._state, src._opt))}
    params, state, opt = jax.tree_util.tree_map(
        lambda l: jnp.copy(l) if id(l) in src_ids else l,
        (params, state, opt))
    dst.model = src.model
    dst._prestaged = (params, state, opt)
    src._synced = False  # the model's host arrays lag the handed-off state


def publish_to_engine(wrapper, engine):
    """Zero-copy train→serve hand-off: reshard the wrapper's LIVE device
    params/state onto a replicated placement and publish them into a
    running :class:`~deeplearning4j_tpu.parallel.batcher.InferenceEngine`
    (``engine.publish`` re-runs its construction-time inference-graph
    pass on the device trees and swaps models between launches). The
    training loop keeps ownership of its buffers — the engine serves a
    donation-safe copy — and nothing crosses the host.

    Falls back to the model's host arrays when the wrapper has not
    staged yet (pre-first-fit publish still works)."""
    from deeplearning4j_tpu.parallel.wrapper import TrainingMode

    import jax
    import jax.numpy as jnp

    m = wrapper.model
    if wrapper._params is None:
        params, state = m.params, m.state
    elif wrapper.training_mode is TrainingMode.AVERAGING:
        # replica-stacked params: the published model is the replica
        # MEAN, exactly what fit()'s final write-back publishes
        params = wrapper._collect(wrapper._params)
        state = wrapper._collect(wrapper._state)
    else:
        rep = _replicated(wrapper.mesh)
        params = reshard(wrapper._params, rep)
        state = reshard(wrapper._state, rep)
    # donation safety: already-replicated leaves come back as the
    # wrapper's LIVE array objects (reshard's identity fast-path), and a
    # graph_opt=False engine publishes them without the inference pass's
    # copy — the wrapper's next donated train dispatch would then delete
    # the buffers the engine is serving from. Copy exactly those leaves,
    # and only for graph_opt=False engines (the fold pass / clone() both
    # copy params themselves — copying here too would double the work on
    # the default hot-publish path).
    if not getattr(engine, "_graph_opt", True):
        live_ids = {id(l) for l in jax.tree_util.tree_leaves(
            (wrapper._params, wrapper._state))}
        params, state = jax.tree_util.tree_map(
            lambda l: jnp.copy(l) if id(l) in live_ids else l,
            (params, state))
    return engine.publish(m, params=params, state=state)
