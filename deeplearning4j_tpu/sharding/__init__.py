"""Declarative sharding subsystem (ROADMAP open item 1).

- :mod:`~deeplearning4j_tpu.sharding.rules` — regex-over-param-path ->
  ``PartitionSpec`` rule tables (``match_partition_rules``) and
  optimizer-state spec cloning (``create_opt_spec``);
- :mod:`~deeplearning4j_tpu.sharding.plan` — :class:`ShardingPlan`:
  a rule table bound to a composed DP×TP mesh, with ``NamedSharding``
  placement, AOT-cache sharding keys, ``explain()`` debugging and
  per-device byte accounting;
- :mod:`~deeplearning4j_tpu.sharding.zero` — the flatten/pad/scatter
  layout behind ``ParallelWrapper(zero_optimizer=True)``'s ZeRO-style
  optimizer-state sharding.

docs/sharding.md has the guided tour; ``deeplearning4j_tpu.zoo.rules``
ships rule tables for the built-in nets.
"""

from deeplearning4j_tpu.sharding.plan import (  # noqa: F401
    ShardingPlan,
    active_plans,
    plans_summary,
)
from deeplearning4j_tpu.sharding.rules import (  # noqa: F401
    bytes_per_device,
    create_opt_spec,
    match_partition_rules,
    named_paths,
)
from deeplearning4j_tpu.sharding.zero import ZeroSpec  # noqa: F401
